"""Dynamic micro-batching for online vector search.

TPU-KNN (arxiv 2206.14286) gets peak MXU utilisation only at fixed,
saturating query-batch shapes; online traffic arrives as a trickle of
small, differently-shaped requests. This module is the bridge — the
batching front-end of the shape-bucketed-kernel serving pattern
(Ragged Paged Attention, arxiv 2604.15464):

  coalesce   pending requests merge (row-concatenated) up to
             `max_batch` rows, waiting at most `max_wait_ms` after the
             first request so a lone caller is never parked behind an
             empty queue;
  bucket     the merged row count pads up to a small LADDER of bucket
             shapes (`buckets`, e.g. 8/32/128/512) — XLA compiles one
             program per (bucket, k) and every batch reuses one of
             them, the same padding discipline as
             `neighbors/batch_loader.py`'s uniform blocks;
  scatter    the merged `(values, ids)` rows slice back to per-request
             replies, delivered through `PendingResult` futures.

Only same-`k` requests merge (k is a static shape of the select
kernels), and only same-`recall_target` requests merge (the target
resolves to one probe-budget plan per batch); mixed traffic simply
splits across consecutive batches.
Expired requests are dropped at collection time — see
`serve.admission` — and `faults` sites `serve.submit` / `serve.batch`
let the chaos suite inject slow/flaky serving paths.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.core import faults
from raft_tpu.obs import trace as _trace
from raft_tpu.serve.admission import (
    AdmissionConfig,
    AdmissionController,
    DeadlineExceeded,
    ServerClosed,
)
from raft_tpu.serve.metrics import ServerMetrics

SUBMIT_SITE = "serve.submit"


class SearchReply(NamedTuple):
    """Per-request result: best-first `(values, ids)` rows plus the
    degraded-mode shard `coverage` (1.0 when every shard answered —
    mirrors `comms.resilience.DegradedSearchResult`)."""

    values: np.ndarray
    ids: np.ndarray
    coverage: float


class PendingResult:
    """Future handed back by `submit`: one event, one slot."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[SearchReply] = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> SearchReply:
        """Block until delivery; raises the request's failure
        (`DeadlineExceeded`, `ServerClosed`, a searcher error) or
        `TimeoutError` if `timeout` elapses first."""
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        if self._exc is not None:
            raise self._exc
        assert self._value is not None
        return self._value

    def _set(self, value: SearchReply) -> None:
        self._value = value
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()


@dataclasses.dataclass
class _Request:
    queries: np.ndarray  # (n, dim) f32, host-resident until merge
    k: int
    n: int
    deadline: Optional[float]  # absolute monotonic, None = no deadline
    submit_t: float
    reply: PendingResult
    recall_target: Optional[float] = None  # adaptive-probing SLO knob
    trace: Optional[_trace.TraceCtx] = None  # request-scope trace (obs on)


@dataclasses.dataclass
class Batch:
    """One collected micro-batch (all requests share `k` AND
    `recall_target` — the target resolves to one probe-budget plan per
    batch, so mixed targets split across consecutive batches exactly
    like mixed k)."""

    requests: List[_Request]
    k: int
    recall_target: Optional[float] = None

    @property
    def rows(self) -> int:
        return sum(r.n for r in self.requests)


def bucket_for(rows: int, buckets: Sequence[int]) -> int:
    """Smallest ladder bucket >= rows (rows is bounded by buckets[-1]
    because max_batch == buckets[-1])."""
    for b in buckets:
        if rows <= b:
            return int(b)
    raise ValueError(f"{rows} rows exceed the largest bucket {buckets[-1]}")


def merge(batch: Batch, dim: int, bucket: int, dtype=np.float32) -> Tuple[np.ndarray, int]:
    """Row-concatenate the batch's queries and zero-pad to `bucket`
    rows; returns (padded (bucket, dim) array, valid rows). Zero rows
    are real queries to the kernels — their results are sliced away by
    `scatter`, never delivered."""
    valid = batch.rows
    out = np.zeros((bucket, dim), dtype)
    lo = 0
    for req in batch.requests:
        out[lo:lo + req.n] = req.queries
        lo += req.n
    return out, valid


def scatter(batch: Batch, values: np.ndarray, ids: np.ndarray,
            coverage: float) -> List[Tuple[_Request, SearchReply]]:
    """Slice merged result rows back to per-request replies (row order
    is the merge order)."""
    out = []
    lo = 0
    for req in batch.requests:
        reply = SearchReply(values[lo:lo + req.n], ids[lo:lo + req.n],
                            float(coverage))
        out.append((req, reply))
        lo += req.n
    return out


class MicroBatcher:
    """The request queue: admission-gated `submit` on the caller side,
    `collect` on the worker side. One condition variable serialises
    both; `collect` holds the lock only while scanning/popping — device
    execution happens outside (in the engine), so submitters are never
    blocked behind a running batch."""

    def __init__(
        self,
        buckets: Sequence[int],
        max_wait_ms: float,
        admission: AdmissionController,
        metrics: ServerMetrics,
        dim: int,
    ):
        buckets = tuple(sorted(int(b) for b in buckets))
        if not buckets or buckets[0] <= 0:
            raise ValueError(f"need positive bucket sizes, got {buckets!r}")
        self.buckets = buckets
        self.max_batch = buckets[-1]
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.admission = admission
        self.metrics = metrics
        self.dim = int(dim)
        self._dq: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._pending_rows = 0
        self._closed = False

    # -- caller side ---------------------------------------------------

    @property
    def pending_rows(self) -> int:
        # monitoring fast path: single GIL-atomic int read, stale-by-one
        # is fine for a gauge (taking the lock here would let a slow
        # scraper contend with the submit path)
        return self._pending_rows  # raftlint: disable=lock-discipline

    @property
    def closed(self) -> bool:
        # GIL-atomic bool read; close() is one-way, a stale False only
        # delays the caller until the locked check in submit()
        return self._closed  # raftlint: disable=lock-discipline

    def submit(self, queries, k: int,
               deadline_s: Optional[float] = None,
               recall_target: Optional[float] = None) -> PendingResult:
        """Enqueue one request; returns its future. Validates shape
        here (fail fast, in the caller's thread, with the caller's
        stack) and applies admission policy under the queue lock.
        `recall_target` (0, 1]: the request's recall SLO — resolved by
        the searcher to per-query probe budgets (adaptive probing);
        only same-target requests coalesce into one batch."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.ndim != 2 or q.shape[0] == 0:
            raise ValueError(f"queries must be (n, dim) with n >= 1, got {q.shape}")
        if q.shape[1] != self.dim:
            raise ValueError(f"query dim {q.shape[1]} != index dim {self.dim}")
        if q.shape[0] > self.max_batch:
            raise ValueError(
                f"{q.shape[0]} query rows exceed the largest bucket "
                f"({self.max_batch}); split the request (batch_loader helps)"
            )
        k = int(k)
        if k <= 0:
            raise ValueError("k must be positive")
        if recall_target is not None:
            recall_target = float(recall_target)
            if not (0.0 < recall_target <= 1.0):
                raise ValueError(
                    f"recall_target must be in (0, 1], got {recall_target}")
        # chaos site: slow/flaky ingress (an overloaded frontend, a
        # flaky RPC hop) — no-op without an installed FaultPlan
        faults.fault_point(SUBMIT_SITE)
        req = _Request(
            queries=q,
            k=k,
            n=int(q.shape[0]),
            deadline=self.admission.deadline_for(deadline_s),
            submit_t=time.monotonic(),
            reply=PendingResult(),
            recall_target=recall_target,
            trace=_trace.begin(),
        )
        if req.trace is not None:
            req.trace.stamp("admitted", rows=req.n, k=k)
        with self._cond:
            if self._closed:
                raise ServerClosed("server is stopped")
            try:
                # the two lambdas are evaluated by Condition.wait_for
                # with this same lock RE-ACQUIRED (we are inside `with
                # self._cond`), not lock-free as they lexically appear
                self.admission.admit(
                    req.n,
                    lambda: self._pending_rows,  # raftlint: disable=lock-discipline
                    self._cond,
                    lambda: self._closed,  # raftlint: disable=lock-discipline
                )
            except Exception:
                self.metrics.observe_reject()
                _trace.complete(req.trace, outcome="rejected")
                raise
            self._dq.append(req)
            self._pending_rows += req.n
            self.metrics.observe_submit()
            self.metrics.set_queue_depth(self._pending_rows)
            self._cond.notify_all()
        return req.reply

    # -- worker side ---------------------------------------------------

    def _expire(self, req: _Request) -> None:
        wait_s = time.monotonic() - req.submit_t
        req.reply._set_exception(DeadlineExceeded(
            f"deadline passed after {wait_s:.3f}s "
            "in queue; request was dropped without executing"
        ))
        # queue-wait-until-drop: admission tuning must see the requests
        # it killed, not just the survivors' latencies
        self.metrics.observe_expired(wait_s=wait_s)
        _trace.complete(req.trace, outcome="expired")

    def _take_locked(self, now: float) -> List[_Request]:
        """Pop one batch's worth of live same-(k, recall_target)
        requests (FIFO by the oldest live request's key — the target
        resolves to ONE probe-budget plan per device batch); expired
        requests encountered on the way are failed and removed. Lock
        held by caller."""
        taken: List[_Request] = []
        keep: List[_Request] = []
        key0 = None
        rows = 0
        expired = 0
        for req in self._dq:
            if self.admission.expired(req.deadline, now):
                self._pending_rows -= req.n
                self._expire(req)
                expired += 1
                continue
            if key0 is None:
                key0 = (req.k, req.recall_target)
            if ((req.k, req.recall_target) == key0
                    and rows + req.n <= self.max_batch):
                taken.append(req)
                rows += req.n
            else:
                keep.append(req)
        self._dq = collections.deque(keep)
        for req in taken:
            self._pending_rows -= req.n
            if req.trace is not None:
                req.trace.stamp("coalesced")
        self.metrics.set_queue_depth(self._pending_rows)
        if taken or expired:
            # rows left the queue (pops or expiries): wake any blocked
            # submitters — including when EVERYTHING expired and both
            # taken and keep are empty
            self._cond.notify_all()
        return taken

    def collect(self, timeout_s: Optional[float] = None) -> Optional[Batch]:
        """Gather the next micro-batch: wait up to `timeout_s` for a
        first request, then linger `max_wait_ms` (from that request's
        arrival) for more to coalesce — returning early once the merged
        rows reach the largest bucket. None when idle past the timeout
        or closed-and-drained."""
        with self._cond:
            deadline = None if timeout_s is None else time.monotonic() + timeout_s
            while not self._dq:
                if self._closed:
                    return None
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(timeout=remaining):
                        return None
            # linger window anchored at the oldest pending arrival so a
            # request never waits more than max_wait_ms for company
            linger_until = self._dq[0].submit_t + self.max_wait_s
            while (self._pending_rows < self.max_batch
                   and not self._closed):
                remaining = linger_until - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            taken = self._take_locked(time.monotonic())
        if not taken:
            return None
        return Batch(requests=taken, k=taken[0].k,
                     recall_target=taken[0].recall_target)

    def drain_expired(self) -> int:
        """Fail every expired queued request now (periodic hygiene for
        idle servers); returns the number dropped."""
        now = time.monotonic()
        dropped = 0
        with self._cond:
            keep = []
            for req in self._dq:
                if self.admission.expired(req.deadline, now):
                    self._pending_rows -= req.n
                    self._expire(req)
                    dropped += 1
                else:
                    keep.append(req)
            self._dq = collections.deque(keep)
            self.metrics.set_queue_depth(self._pending_rows)
            if dropped:
                self._cond.notify_all()
        return dropped

    def close(self) -> int:
        """Stop admitting; fail every queued request with
        `ServerClosed`. Returns the number failed."""
        with self._cond:
            self._closed = True
            failed = 0
            while self._dq:
                req = self._dq.popleft()
                self._pending_rows -= req.n
                req.reply._set_exception(ServerClosed(
                    "server stopped before the request was served"))
                failed += 1
            self._pending_rows = 0
            self.metrics.set_queue_depth(0)
            self._cond.notify_all()
        return failed
