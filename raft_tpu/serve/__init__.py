"""Online vector-search serving: dynamic micro-batching, admission
control, and latency/QPS metrics over any built raft_tpu index.

The subsystem between the kernels and real traffic (no RAFT analogue —
the reference stops at library calls): `SearchServer` coalesces
per-caller `submit(queries, k)` futures into shape-bucketed device
batches (`batcher`), sheds and degrades load before it wastes device
time (`admission`), and accounts for every request (`metrics`). See
docs/serving.md for the architecture and ops guidance.

    from raft_tpu import serve

    with serve.SearchServer(index, serve.ServerConfig(warmup_k=10)) as s:
        reply = s.submit(queries, k=10).result(timeout=1.0)
"""

from raft_tpu.serve.admission import (
    AdmissionConfig,
    AdmissionController,
    DeadlineExceeded,
    RejectedError,
    ServerClosed,
)
from raft_tpu.serve.batcher import (
    MicroBatcher,
    PendingResult,
    SearchReply,
    bucket_for,
)
from raft_tpu.serve.engine import (
    BruteForceSearcher,
    IvfFlatSearcher,
    IvfPqSearcher,
    IvfRabitqSearcher,
    MnmgSearcher,
    Searcher,
    SearchServer,
    ServerConfig,
    as_searcher,
)
from raft_tpu.serve.metrics import ServerMetrics

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "BruteForceSearcher",
    "DeadlineExceeded",
    "IvfFlatSearcher",
    "IvfPqSearcher",
    "IvfRabitqSearcher",
    "MicroBatcher",
    "MnmgSearcher",
    "PendingResult",
    "RejectedError",
    "SearchReply",
    "Searcher",
    "SearchServer",
    "ServerClosed",
    "ServerConfig",
    "ServerMetrics",
    "as_searcher",
    "bucket_for",
]
