"""The serving engine: worker loop + searcher adapters + lifecycle.

`SearchServer` turns any built raft_tpu index into an online service:
callers `submit(queries, k)` and get futures; a single worker thread
pulls micro-batches from the `MicroBatcher`, pads them onto the bucket
ladder, runs ONE device search per batch, and scatters rows back.
Execution is deliberately single-worker: XLA owns device streams, so
one dispatching thread keeps programs ordered while `device_put` /
dispatch async overlap still happens inside XLA (same stance as
`batch_loader`'s double buffering).

Searcher adapters normalise the three index families (plus the MNMG
distributed pair) to one call: `search(queries, k, probe_scale)` ->
`(values, ids, coverage)`. Auto-resolving engine/score modes resolve by
batch shape, which would make a request's numerics depend on who it was
batched with — the adapters therefore PIN the engine at construction
(flat defaults to the exact "query" engine, PQ to "recon8"), keeping
the serve invariant: merged batched results are bit-identical to the
same request served alone.

Degraded mode rides the PR 1 resilience path: construct with `health=`
(a `comms.resilience.RankHealth`) or swap one in live via
`set_health()` — replies then carry `coverage < 1.0` instead of
hanging on a sick rank. Fault site "serve.batch" lets the chaos suite
slow/flake the execution path itself.

Deterministic test mode: skip `start()` and call `step()` — it
collects (without lingering) and executes exactly one batch on the
calling thread.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence, Tuple

import numpy as np
import jax

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.tracing import trace_range
from raft_tpu.obs import flight as _flight
from raft_tpu.obs import trace as _trace
from raft_tpu.serve.admission import (
    AdmissionConfig,
    AdmissionController,
    DeadlineExceeded,
    ServerClosed,
)
from raft_tpu.serve.batcher import (
    Batch,
    MicroBatcher,
    PendingResult,
    SearchReply,
    bucket_for,
    merge,
    scatter,
)
from raft_tpu.serve.metrics import ServerMetrics

BATCH_SITE = "serve.batch"


# ---------------------------------------------------------------------------
# searcher adapters
# ---------------------------------------------------------------------------

class Searcher:
    """Protocol: one device search per padded batch.

    `search(queries, k, probe_scale, recall_target)` returns
    `(values, ids, coverage)` with `coverage` = served-shard fraction
    (1.0 for local indexes). `probe_scale` in (0, 1] is the admission
    controller's overload degradation knob — adapters with probes apply
    it to n_probes (floor 1); exact searches ignore it. `recall_target`
    is the per-request adaptive-probing knob (neighbors/probe_budget):
    probed adapters resolve it to per-query budgets through the tuned
    `adaptive_probe_policy`, WITHIN the probe_scale-capped n_probes —
    overload composes as a cap on top of adaptivity. None keeps the
    searcher's configured behavior; exact searchers ignore it.
    """

    dim: int

    def search(self, queries: np.ndarray, k: int,
               probe_scale: float = 1.0,
               recall_target: Optional[float] = None,
               ) -> Tuple[jax.Array, jax.Array, float]:
        raise NotImplementedError

    def probe_key(self, probe_scale: float = 1.0,
                  recall_target: Optional[float] = None):
        """Hashable token for how `probe_scale` / `recall_target` shape
        the COMPILED program — the compile-cache key component. Exact
        searchers ignore both (one program per (bucket, k)); probed
        searchers return the derived n_probes plus the resolved
        adaptive-plan token (tau itself is a traced operand, so only
        the plan's STRUCTURE keys programs), so two requests that
        resolve to the same compiled program correctly share one cache
        entry."""
        return None

    # -- live mutation (zero-dip swap-in) ------------------------------

    #: committed-mutation queue (neighbors/mutation.MutationFeed); None
    #: = a static index, the mutation path adds zero work per batch
    _mutation_feed = None

    def attach_mutations(self, feed) -> None:
        """Subscribe this searcher to a `neighbors.mutation.MutationFeed`:
        committed batches published to the feed are applied BETWEEN
        device batches by the serving loop (`_heal_between_batches`),
        never on the request path."""
        self._mutation_feed = feed

    def maybe_apply_mutations(self) -> int:
        """Drain the attached feed and swap in the mutated index — one
        reference assignment, so any in-flight device batch keeps
        scanning the OLD object end to end (zero-dip: coverage never
        drops, and a query untouched by the mutations is bit-identical
        before and after the swap). Called by the server off the
        request path; returns the number of batches applied."""
        feed = self._mutation_feed
        index = getattr(self, "index", None)
        if feed is None or index is None:
            return 0  # static serving, or an exact searcher (no index)
        batches = feed.drain()
        if not batches:
            return 0
        from raft_tpu.neighbors import mutation

        for batch in batches:
            index = mutation.apply_batch(index, batch)
        self.index = index
        return len(batches)

    # -- online integrity (scrub/quarantine/repair off the request path)

    #: integrity.IntegrityWatchdog; None = no scrubbing, the integrity
    #: path adds zero work per batch
    _integrity = None

    def attach_integrity(self, watchdog) -> None:
        """Subscribe this searcher to an `integrity.IntegrityWatchdog`:
        the serving loop runs one bounded scrub slice BETWEEN device
        batches (`_heal_between_batches`), and a detected-bad list is
        quarantined (masked dead) / repaired by reference swap — the
        same zero-dip discipline as mutations."""
        self._integrity = watchdog

    def maybe_scrub(self) -> None:
        """One watchdog tick: scrub slice, quarantine-on-mismatch,
        verified repair. Any index change lands as one reference
        assignment; in-flight batches keep the old object. Called by
        the server off the request path."""
        wd = self._integrity
        index = getattr(self, "index", None)
        if wd is None or index is None:
            return  # static serving, or an exact searcher (no index)
        out = wd.step(index)
        if out is not index:
            self.index = out

    def _coverage(self) -> float:
        """Served-list fraction for local adapters: 1.0 until the
        watchdog quarantines something, then honestly less — dispatch
        marks such replies degraded, exactly like MNMG shard loss."""
        wd = self._integrity
        return 1.0 if wd is None else float(wd.coverage())


def _scaled_probes(n_probes: int, probe_scale: float) -> int:
    """The ONE overload-degradation rule: floor(n_probes * scale),
    never below 1. Documented as floor (not round) so budget
    composition is deterministic: a scale of 0.25 always yields
    floor(n_probes / 4) — round() used to land ABOVE the
    min_probe_scale floor's intent at small n_probes (n_probes=6,
    scale=0.25 -> round(1.5) = 2, not the floor's 1). Pinned by
    tests/test_serve.py::test_scaled_probes_floor_rule."""
    return max(1, int(n_probes * float(probe_scale)))


def _request_params(params, probe_scale: float, recall_target):
    """One request's effective SearchParams: the admission controller's
    probe_scale CAPS n_probes first (floor-with-min-1), then a
    per-request recall_target resolves to per-query budgets WITHIN that
    cap — overload can only shrink work, adaptivity redistributes it."""
    import dataclasses as _dc

    changes = {}
    if probe_scale < 1.0:
        changes["n_probes"] = _scaled_probes(params.n_probes, probe_scale)
    if recall_target is not None:
        changes["recall_target"] = float(recall_target)
    return _dc.replace(params, **changes) if changes else params


def _probed_key(params, probe_scale: float, recall_target):
    """Compile-cache token for a probed searcher: the derived n_probes
    plus the resolved adaptive-plan structure (probe_budget.policy_token
    — tau/min_probes are traced operands, so only adaptive-vs-fixed and
    the bounds structure distinguish compiled programs)."""
    from raft_tpu.neighbors import probe_budget

    p = _request_params(params, probe_scale, recall_target)
    n = _scaled_probes(params.n_probes, probe_scale)
    return (n, probe_budget.policy_token(p, n))


class BruteForceSearcher(Searcher):
    """Exact k-NN over a host/device dataset (`brute_force.knn`);
    probe_scale and recall_target are no-ops (there is nothing
    approximate to shed — every request already gets recall 1.0)."""

    def __init__(self, dataset, **knn_kwargs):
        import jax.numpy as jnp

        self.dataset = jnp.asarray(dataset)
        self.knn_kwargs = knn_kwargs
        self.dim = int(self.dataset.shape[1])

    def search(self, queries, k, probe_scale=1.0, recall_target=None):
        from raft_tpu.neighbors import brute_force

        vals, ids = brute_force.knn(self.dataset, queries, k, **self.knn_kwargs)
        return vals, ids, 1.0


class IvfFlatSearcher(Searcher):
    def __init__(self, index, search_params=None):
        from raft_tpu.neighbors import ivf_flat

        self.index = index
        self.params = search_params or ivf_flat.SearchParams()
        if self.params.engine == "auto":
            raise ValueError(
                "engine='auto' resolves per batch shape, which would make "
                "a request's numerics depend on its batch-mates; pin an "
                "engine in SearchParams for serving"
            )
        self.dim = int(index.dim)

    def search(self, queries, k, probe_scale=1.0, recall_target=None):
        from raft_tpu.neighbors import ivf_flat

        p = _request_params(self.params, probe_scale, recall_target)
        vals, ids = ivf_flat.search(p, self.index, queries, k)
        return vals, ids, self._coverage()

    def probe_key(self, probe_scale: float = 1.0, recall_target=None):
        return _probed_key(self.params, probe_scale, recall_target)


class IvfPqSearcher(Searcher):
    def __init__(self, index, search_params=None):
        from raft_tpu.neighbors import ivf_pq

        self.index = index
        self.params = search_params or ivf_pq.SearchParams(score_mode="recon8")
        if self.params.score_mode == "auto":
            raise ValueError(
                "score_mode='auto' resolves per batch shape, which would "
                "make a request's numerics depend on its batch-mates; pin "
                "a score_mode in SearchParams for serving"
            )
        self.dim = int(index.dim)

    def search(self, queries, k, probe_scale=1.0, recall_target=None):
        from raft_tpu.neighbors import ivf_pq

        p = _request_params(self.params, probe_scale, recall_target)
        vals, ids = ivf_pq.search(p, self.index, queries, k)
        return vals, ids, self._coverage()

    def probe_key(self, probe_scale: float = 1.0, recall_target=None):
        return _probed_key(self.params, probe_scale, recall_target)


class IvfRabitqSearcher(Searcher):
    """IVF-RaBitQ adapter: the binary-code scan is query-major (per-row
    results are independent of batch-mates) and the rerank depth/query
    bits resolve from process-stable tuned state, never from batch
    shape — so merged batched results stay bit-identical to unbatched
    without pinning anything beyond the params object."""

    def __init__(self, index, search_params=None):
        from raft_tpu.neighbors import ivf_rabitq

        self.index = index
        self.params = search_params or ivf_rabitq.SearchParams()
        self.dim = int(index.dim)

    def search(self, queries, k, probe_scale=1.0, recall_target=None):
        from raft_tpu.neighbors import ivf_rabitq

        p = _request_params(self.params, probe_scale, recall_target)
        vals, ids = ivf_rabitq.search(p, self.index, queries, k)
        return vals, ids, self._coverage()

    def probe_key(self, probe_scale: float = 1.0, recall_target=None):
        return _probed_key(self.params, probe_scale, recall_target)


class MnmgSearcher(Searcher):
    """Distributed IVF (flat or PQ) with the PR 1 degraded-mode path and
    the replication-era heal loop: searches carry the current
    `RankHealth` mask, replies carry its coverage. `set_health` swaps
    masks atomically between batches (the mask is an array ARGUMENT to
    the SPMD program — no retrace). On a replicated index
    (`mnmg.replicate_index` / build `replication=`), a degraded mask
    fails over losslessly — in-flight traffic keeps coverage 1.0 — and
    the server calls `maybe_heal()` BETWEEN batches, so the
    repair-then-rejoin loop (comms/recovery.py) runs off the request
    path and a healed rank's primary serves again without any caller
    ever seeing a degraded reply.

    `heal_checkpoint` optionally names a checkpoint to rehydrate from
    when a shard has no surviving replica copy (beyond r-1 failures)."""

    def __init__(self, index, kind: str, n_probes: int = 20,
                 engine: Optional[str] = None, health=None,
                 heal_checkpoint: Optional[str] = None,
                 auto_heal: bool = True):
        self.index = index
        self.kind = kind  # "ivf_flat" | "ivf_pq" | "ivf_rabitq"
        self.n_probes = int(n_probes)
        if kind == "ivf_rabitq":
            # ivf_rabitq has ONE engine (the binary-code scan): an
            # explicit engine= is a config error — reject it loudly
            # rather than silently serving different semantics than the
            # caller pinned (the flat/PQ wrong-name reject, moved up
            # here because there is no search-side engine kwarg to
            # forward it to)
            if engine is not None:
                raise ValueError(
                    f"engine={engine!r} is meaningless for ivf_rabitq: "
                    "the binary-code scan is the only engine")
        elif engine is None:
            # per-kind list-major serving default (the engine vocabularies
            # differ: flat's is "list", PQ's is "recon8_list"); an
            # EXPLICIT wrong name still reaches the search's loud reject
            engine = "list" if kind == "ivf_flat" else "recon8_list"
        self.engine = engine
        self.heal_checkpoint = heal_checkpoint
        self.auto_heal = bool(auto_heal)
        self._health = health
        self._health_lock = threading.Lock()
        # the distributed indexes have no `dim` property: flat centers
        # are (n_lists, dim), the PQ/RaBitQ rotation is (rot_dim, dim)
        self.dim = int(index.centers.shape[1] if kind == "ivf_flat"
                       else index.rotation.shape[1])

    def set_health(self, health) -> None:
        with self._health_lock:
            self._health = health

    @property
    def health(self):
        with self._health_lock:
            return self._health

    def search(self, queries, k, probe_scale=1.0, recall_target=None):
        from raft_tpu.comms import mnmg

        health = self.health
        n_probes = _scaled_probes(self.n_probes, probe_scale)
        ad = dict(recall_target=recall_target) if recall_target is not None \
            else {}
        if self.kind == "ivf_rabitq":
            out = mnmg.ivf_rabitq_search(
                self.index, queries, k, n_probes=n_probes,
                query_mode="replicated", health=health, **ad)
        else:
            fn = (mnmg.ivf_flat_search if self.kind == "ivf_flat"
                  else mnmg.ivf_pq_search)
            out = fn(self.index, queries, k, n_probes=n_probes,
                     engine=self.engine, query_mode="replicated",
                     health=health, **ad)
        if isinstance(out, tuple) and len(out) == 2:
            vals, ids = out
            return vals, ids, 1.0
        return out.values, out.ids, float(out.coverage)

    def probe_key(self, probe_scale: float = 1.0, recall_target=None):
        n = _scaled_probes(self.n_probes, probe_scale)
        # distributed adaptive plans are budgets-only (bounds stay off),
        # so the plan structure token is fixed whenever a target is set
        return (n, ("adaptive", False) if recall_target is not None else None)

    def maybe_heal(self) -> bool:
        """One heal-loop turn, called by the server between batches (off
        the request path): when the mask is degraded and the index
        carries replicas (or a heal checkpoint is configured), repair
        the dead ranks' shards and rejoin them behind a verified
        barrier, then publish the healthy mask. Returns True when a
        heal ran. Never raises into the serving loop — an unhealable
        mesh (no copies, barrier timeout) keeps its degraded mask and
        the failover/degraded path keeps answering."""
        if not self.auto_heal:
            return False
        health = self.health
        if health is None or not health.degraded:
            return False
        from raft_tpu.comms import recovery

        if (getattr(self.index, "replicas", None) is None
                and self.heal_checkpoint is None):
            return False  # nothing to heal from; stay degraded
        try:
            with obs.span("serve.heal"):
                index, healed = recovery.heal(
                    self.index.comms, health, self.index,
                    checkpoint=self.heal_checkpoint)
        except Exception as e:  # noqa: BLE001 — the loop must survive
            obs.event("heal_failed", error=repr(e))
            return False
        self.index = index
        # publish compare-and-swap: a prober may have installed a NEWER
        # mask (another rank died) while the repair/barrier ran —
        # clobbering it would un-mask a dead rank until the next probe.
        # The newer mask stays; the next between-batches turn heals it.
        with self._health_lock:
            if self._health is health:
                self._health = healed
        return True

    def maybe_apply_mutations(self) -> int:
        """RankHealth-aware variant: while the mesh is degraded the feed
        stays queued (applying against a partial mesh would leave dead
        ranks' shards stale — replication mirrors must re-derive from
        every touched primary), and the heal loop runs first. Batches
        apply through `comms.mnmg_mutation` so rank-local stores AND
        their replica mirrors mutate coherently."""
        feed = self._mutation_feed
        if feed is None:
            return 0
        health = self.health
        if health is not None and health.degraded:
            return 0  # defer — drain nothing, the feed keeps the batches
        batches = feed.drain()
        if not batches:
            return 0
        from raft_tpu.comms import mnmg_mutation

        index = self.index
        for batch in batches:
            index = mnmg_mutation.apply_batch(index, self.kind, batch)
        self.index = index
        return len(batches)


def as_searcher(index, *, search_params=None, health=None,
                n_probes: int = 20, engine: Optional[str] = None,
                heal_checkpoint: Optional[str] = None,
                auto_heal: bool = True,
                **knn_kwargs) -> Searcher:
    """Coerce `index` to a `Searcher`:

    - an existing `Searcher` passes through,
    - `ivf_flat.Index` / `ivf_pq.Index` / `ivf_rabitq.Index` ->
      pinned-engine adapters (`search_params` forwarded),
    - MNMG `DistributedIvfFlat` / `DistributedIvfPq` /
      `DistributedIvfRabitq` -> `MnmgSearcher` (`health`, `n_probes`,
      `engine`, `heal_checkpoint`, `auto_heal` forwarded),
    - a 2-D array (numpy or jax) -> exact `BruteForceSearcher`
      (`knn_kwargs` forwarded to `brute_force.knn`).
    """
    if isinstance(index, Searcher):
        return index
    from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq

    if isinstance(index, ivf_flat.Index):
        return IvfFlatSearcher(index, search_params)
    if isinstance(index, ivf_pq.Index):
        return IvfPqSearcher(index, search_params)
    if isinstance(index, ivf_rabitq.Index):
        return IvfRabitqSearcher(index, search_params)
    # distributed indexes only exist if comms was imported to build them
    kind = type(index).__name__
    mnmg_kinds = {"DistributedIvfFlat": "ivf_flat",
                  "DistributedIvfPq": "ivf_pq",
                  "DistributedIvfRabitq": "ivf_rabitq"}
    if kind in mnmg_kinds:
        return MnmgSearcher(
            index, mnmg_kinds[kind],
            n_probes=n_probes, engine=engine, health=health,
            heal_checkpoint=heal_checkpoint, auto_heal=auto_heal,
        )
    arr = np.asarray(index) if not hasattr(index, "ndim") else index
    if getattr(arr, "ndim", 0) == 2:
        return BruteForceSearcher(arr, **knn_kwargs)
    raise TypeError(f"cannot serve from {type(index).__name__!r}")


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Serving knobs.

    buckets        the shape ladder; merged batches pad to the smallest
                   bucket that fits, so XLA compiles once per
                   (bucket, k) and reuses it forever. The largest
                   bucket is also `max_batch`.
    max_wait_ms    linger window: how long the oldest pending request
                   waits for batch-mates before dispatch.
    admission      backpressure / deadline / degradation policy.
    warmup_k       when set, `start()` pre-compiles every bucket at
                   this k before serving (cold-compile happens at
                   startup, not on the first unlucky caller).
    latency_window ring size for the latency/QPS percentiles.
    idle_poll_s    worker wake-up period when the queue is empty (also
                   bounds how long `stop()` waits for the worker).
    """

    buckets: Tuple[int, ...] = (8, 32, 128, 512)
    max_wait_ms: float = 2.0
    admission: AdmissionConfig = dataclasses.field(default_factory=AdmissionConfig)
    warmup_k: Optional[int] = None
    latency_window: int = 4096
    idle_poll_s: float = 0.05


class SearchServer:
    """Online vector-search server over one searcher/index.

    Threaded mode::

        server = SearchServer(index, config)
        server.start()                      # or `with SearchServer(...) as s:`
        fut = server.submit(queries, k=10)  # from any thread
        reply = fut.result(timeout=1.0)     # .values / .ids / .coverage
        server.metrics.snapshot()["qps"]
        server.stop()

    Deterministic single-thread test mode: never `start()`; call
    `step()` to collect+execute exactly one batch on the calling
    thread.
    """

    def __init__(self, index, config: Optional[ServerConfig] = None, *,
                 metrics: Optional[ServerMetrics] = None, **searcher_kwargs):
        self.config = config or ServerConfig()
        self.searcher = as_searcher(index, **searcher_kwargs)
        self.metrics = metrics or ServerMetrics(self.config.latency_window)
        self.admission = AdmissionController(self.config.admission)
        self.batcher = MicroBatcher(
            buckets=self.config.buckets,
            max_wait_ms=self.config.max_wait_ms,
            admission=self.admission,
            metrics=self.metrics,
            dim=self.searcher.dim,
        )
        self._worker: Optional[threading.Thread] = None
        self._running = False
        # host mirror of XLA's program cache for the serve path, keyed
        # the way the bucket ladder compiles: (bucket, k, probe token) —
        # the token is the searcher's DERIVED probe count (probe_key),
        # not the raw scale, so two overload scales that floor to the
        # same n_probes key as the one program XLA actually caches.
        # warmup() pre-populates it; _dispatch() classifies each batch
        # as a compile-cache hit (program already built) or miss.
        # warmup runs on the caller's thread and may overlap a live
        # worker (re-warm after a mutation/heal), so the set carries
        # its own lock (threadcheck shared-state-race)
        self._compiled_lock = threading.Lock()
        self._compiled: set = set()

    # -- caller surface ------------------------------------------------

    def submit(self, queries, k: int,
               deadline_s: Optional[float] = None,
               recall_target: Optional[float] = None) -> PendingResult:
        """Enqueue one request; thread-safe. See `MicroBatcher.submit`.
        `recall_target` (0, 1]: the request's recall SLO, resolved to
        per-query probe budgets by the searcher (adaptive probing;
        1.0 = the saturated, bit-exact fixed-probe plan)."""
        return self.batcher.submit(queries, k, deadline_s=deadline_s,
                                   recall_target=recall_target)

    def search(self, queries, k: int, timeout: Optional[float] = None,
               deadline_s: Optional[float] = None,
               recall_target: Optional[float] = None) -> SearchReply:
        """Synchronous convenience: submit + wait. In single-thread test
        mode (no worker running) it also drives `step()` itself."""
        fut = self.submit(queries, k, deadline_s=deadline_s,
                          recall_target=recall_target)
        if not self._running:
            while not fut.done():
                if self.step() == 0:
                    break
        return fut.result(timeout)

    def set_health(self, health) -> None:
        """Swap the distributed searcher's liveness mask (no-op route to
        `MnmgSearcher.set_health`; raises for local searchers, which
        have no rank to degrade)."""
        if not hasattr(self.searcher, "set_health"):
            raise TypeError(
                f"{type(self.searcher).__name__} has no health mask")
        self.searcher.set_health(health)

    def attach_mutations(self, feed) -> None:
        """Subscribe the searcher to a committed-mutation feed
        (`neighbors.mutation.MutationFeed`); batches drain between
        device batches — see `Searcher.maybe_apply_mutations`."""
        self.searcher.attach_mutations(feed)

    def attach_integrity(self, watchdog) -> None:
        """Subscribe the searcher to an `integrity.IntegrityWatchdog`:
        one scrub slice runs between device batches, quarantine/repair
        swap in off the request path — see `Searcher.maybe_scrub`."""
        self.searcher.attach_integrity(watchdog)

    def attach_watchtower(self, watchtower) -> None:
        """Attach an `obs.slo.Watchtower` judging this server's traffic
        (terminal outcomes, latencies, coverage, occupancy) — see
        `ServerMetrics.attach_watchtower`."""
        self.metrics.attach_watchtower(watchtower)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "SearchServer":
        if self._running:
            return self
        if self.batcher.closed:
            raise ServerClosed(
                "SearchServer is one-shot: a stopped server failed its "
                "queued futures and cannot resume — construct a new one"
            )
        if self.config.warmup_k is not None:
            self.warmup(self.config.warmup_k)
        self._running = True
        self._worker = threading.Thread(
            target=self._run, name="raft-tpu-serve-worker", daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        """Stop serving and fail every queued request with
        `ServerClosed`. Terminal: the server cannot be restarted."""
        self._running = False
        self.batcher.close()
        if self._worker is not None:
            self._worker.join(timeout=max(5.0, 10 * self.config.idle_poll_s))
            self._worker = None

    def __enter__(self) -> "SearchServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def warmup(self, k: int, ks: Sequence[int] = ()) -> int:
        """Compile every bucket shape for `k` (and any extra `ks`) by
        running throwaway searches; returns the number of (bucket, k)
        programs touched. Serving then never pays a cold XLA compile."""
        import time as _time

        compiled = 0
        with trace_range("raft_tpu.serve.warmup"), obs.span("serve.warmup"):
            for kk in sorted({int(k), *(int(x) for x in ks)}):
                for bucket in self.batcher.buckets:
                    q = np.zeros((bucket, self.searcher.dim), np.float32)
                    t0 = _time.monotonic()
                    vals, ids, _ = self.searcher.search(q, kk)
                    jax.block_until_ready((vals, ids))
                    dur = _time.monotonic() - t0
                    with self._compiled_lock:
                        self._compiled.add(
                            (bucket, kk, self.searcher.probe_key(1.0)))
                    compiled += 1
                    if obs.enabled():
                        # per-bucket warmup compile time: the cold-start
                        # cost the ladder pays so callers never do
                        obs.histogram("serve.warmup_compile_s").observe(dur)
                        obs.event("compile", phase="warmup", bucket=bucket,
                                  k=kk, dur_s=dur)
        return compiled

    # -- execution -----------------------------------------------------

    def _run(self) -> None:
        while self._running:
            try:
                batch = self.batcher.collect(timeout_s=self.config.idle_poll_s)
                if batch is None:
                    self._heal_between_batches()
                    continue
                self._execute(batch)
                self._heal_between_batches()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # `_execute` never raises, so anything landing here is a
                # batcher/heal bug: record the last seconds of timeline
                # (the post-mortem a dead worker would otherwise take
                # with it) and keep serving — a dead worker strands
                # every queued caller.
                obs.event("serve_worker_error", error=repr(e))
                _flight.maybe_dump("serve.worker.exception", error=repr(e))
        # drain: anything still queued fails with ServerClosed in close()

    def _heal_between_batches(self) -> None:
        """Off-request-path maintenance hook: a degraded MNMG searcher
        repairs and rejoins its dead ranks BETWEEN batches (replica
        failover keeps in-flight traffic at coverage 1.0 meanwhile) —
        see `MnmgSearcher.maybe_heal` — and committed mutation batches
        swap in here too (`Searcher.maybe_apply_mutations`), so a live
        upsert/delete never touches the request path. Heal runs first:
        mutations defer while the mesh is degraded. The integrity
        watchdog ticks last (`Searcher.maybe_scrub`) so its slice hashes
        the index the NEXT batch will actually serve."""
        mh = getattr(self.searcher, "maybe_heal", None)
        if mh is not None:
            mh()
        self.searcher.maybe_apply_mutations()
        self.searcher.maybe_scrub()

    def step(self, timeout_s: float = 0.0) -> int:
        """Single-thread test mode: collect one batch (no linger beyond
        `timeout_s`) and execute it on the calling thread. Returns the
        number of requests answered (delivered, expired, or failed)."""
        expired_before = self.metrics.expired  # int read; no ring copy
        batch = self.batcher.collect(timeout_s=timeout_s)
        served = self.metrics.expired - expired_before  # collect-time drops
        if batch is not None:
            served += self._execute(batch)
            self._heal_between_batches()
        return int(served)

    def _execute(self, batch: Batch) -> int:
        """Run one merged batch on the device and deliver per-request
        replies; never raises — any failure (searcher error, injected
        chaos, even a batching bug) is delivered through the futures so
        the worker survives and no caller is stranded. Returns the
        number of requests answered (delivered, expired, or failed)."""
        total = len(batch.requests)
        try:
            self._dispatch(batch)
        except Exception as e:
            undelivered = [r for r in batch.requests if not r.reply.done()]
            for req in undelivered:
                req.reply._set_exception(e)
                _trace.complete(req.trace, outcome="failed", error=repr(e))
            self.metrics.observe_failed(len(undelivered))
        return total

    def _dispatch(self, batch: Batch) -> None:
        import time as _time

        # chaos site: a slow/flaky device dispatch (the serving analogue
        # of a straggling rank); no-op without an installed plan
        faults.fault_point(BATCH_SITE)
        now = _time.monotonic()
        live = []
        for req in batch.requests:
            # a request can expire between collection and dispatch (e.g.
            # behind an injected slow batch) — still cheaper to drop now
            # than to deliver a result its caller already abandoned
            if self.admission.expired(req.deadline, now):
                self.batcher._expire(req)
            else:
                live.append(req)
        if not live:
            return
        batch = Batch(requests=live, k=batch.k,
                      recall_target=batch.recall_target)
        bucket = bucket_for(batch.rows, self.batcher.buckets)
        padded, valid = merge(batch, self.searcher.dim, bucket)
        scale = self.admission.probe_scale(self.batcher.pending_rows)
        key = (bucket, batch.k,
               self.searcher.probe_key(scale, batch.recall_target))
        with self._compiled_lock:
            cached = key in self._compiled
        if obs.enabled():
            obs.counter("serve.compile_cache.hit" if cached
                        else "serve.compile_cache.miss").inc()
            obs.event("compile", phase="serve", bucket=bucket, k=batch.k,
                      cached=cached)
        for req in batch.requests:
            if req.trace is not None:
                req.trace.stamp("dispatched", bucket=bucket, k=batch.k,
                                cached=cached, probe=repr(key[2]))
        with trace_range("raft_tpu.serve.batch"), \
                obs.span("serve.batch", bucket=bucket, k=batch.k,
                         rows=valid, pad_rows=bucket - valid,
                         cached=cached):
            vals, ids, coverage = self.searcher.search(
                padded, batch.k, probe_scale=scale,
                recall_target=batch.recall_target)
            vals, ids = jax.block_until_ready((vals, ids))
        # mark compiled only after the program actually ran: a failed
        # dispatch must not fake a cache hit for the next batch
        with self._compiled_lock:
            self._compiled.add(key)
        for req in batch.requests:
            if req.trace is not None:
                req.trace.stamp("fenced")
        vals = np.asarray(vals)
        ids = np.asarray(ids)
        done_t = _time.monotonic()
        outcome = "degraded" if float(coverage) < 1.0 else "ok"
        latencies = []
        for req, reply in scatter(batch, vals, ids, coverage):
            if req.trace is not None:
                req.trace.stamp("scattered", coverage=float(coverage))
                _trace.complete(req.trace, outcome=outcome)
            req.reply._set(reply)
            latencies.append(done_t - req.submit_t)
        self.metrics.observe_batch(
            n_requests=len(batch.requests),
            valid_rows=valid,
            bucket_rows=bucket,
            latencies_s=latencies,
            coverage=coverage,
        )
