"""Serving metrics: QPS, queue depth, batch occupancy, latency
percentiles, degraded-coverage — the observability half of the serving
engine.

No reference analogue (RAFT ships kernels, not a server); the design
follows the usual online-serving metric set: monotone counters for
admission outcomes, gauges for instantaneous state, and a fixed-size
ring buffer of per-request latencies from which `snapshot()` derives
p50/p90/p99 (a ring keeps memory constant over unbounded runs and makes
the percentiles reflect RECENT traffic, not the all-time mix). QPS
comes from the same ring's completion timestamps, so it too is a
sliding-window rate.

Deduped onto `raft_tpu.obs` (this file predates the obs subsystem and
carried its own counters + exposition formatter): the scalar counters
and gauges are now `obs.registry.Counter`/`Gauge` instruments in a
PER-INSTANCE `obs.Registry` (two servers must never collide on
"submitted"), `render_text()` delegates to the shared Prometheus
formatter in `obs.export`, and — when library observability is enabled
— each instance registers a named collector on the global registry so
`obs.snapshot()` / the run report include serving state without a
second scrape path. The latency/occupancy rings stay here: percentile
windows are this module's job (obs histograms are deterministic
aggregates, not reservoirs).

Thread-safety: instruments carry their own locks; the rings and
derived-window math stay under this class's one lock, observations
remain O(1) appends, and percentile math is deferred to `snapshot()`.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Optional, Sequence

import numpy as np

from raft_tpu import obs
from raft_tpu.obs.export import render_prometheus
from raft_tpu.obs.registry import Registry

_COUNTERS = ("submitted", "completed", "rejected", "expired", "failed",
             "batches")
_instance_ids = itertools.count(1)


class ServerMetrics:
    """Lock-safe registry for one `SearchServer`.

    Counters (monotone): `submitted`, `completed`, `rejected`,
    `expired`, `failed`, `batches` — readable as int attributes, backed
    by per-instance obs instruments.
    Gauges: `queue_depth` (rows waiting), `coverage_last`/`coverage_min`
    (degraded-mode shard coverage, 1.0 == every shard answered).
    Windows: per-request latency ring (`latency_window` entries) and its
    completion timestamps; per-batch occupancy ring (valid rows /
    dispatched bucket rows — the padding tax the bucket ladder pays for
    one-compile-per-bucket).
    """

    def __init__(self, latency_window: int = 4096,
                 registry: Optional[Registry] = None):
        if latency_window <= 0:
            raise ValueError("latency_window must be positive")
        self._window = int(latency_window)
        self._lock = threading.Lock()
        # optional obs.slo.Watchtower: fed per terminal request under
        # its own lock (the Watchtower is not thread-safe by itself)
        self._watchtower = None
        self._wt_lock = threading.Lock()
        self._reg = registry if registry is not None else Registry()
        for name in _COUNTERS:
            self._reg.counter(name)
        self.reset()
        if obs.enabled():
            # join the global snapshot under a stable per-instance name;
            # weakref so a dropped server doesn't pin its metrics alive,
            # and a finalizer so its section doesn't outlive it either
            ref = weakref.ref(self)
            name = f"serve#{next(_instance_ids)}"

            def _collect(ref=ref):
                inst = ref()
                return inst.snapshot() if inst is not None else {}

            obs.registry().add_collector(name, _collect)
            weakref.finalize(self, obs.registry().remove_collector, name)

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.monotonic()
            # reset only the instruments this class OWNS — a caller may
            # have passed a shared registry, whose other instruments and
            # collectors are not ours to wipe
            for name in _COUNTERS:
                self._reg.counter(name).reset()
            self._rows_valid = 0
            self._rows_dispatched = 0
            self._lat_s = np.zeros(self._window, np.float64)
            self._done_t = np.zeros(self._window, np.float64)
            self._lat_i = 0
            self._lat_n = 0
            self._occ = np.zeros(min(self._window, 1024), np.float64)
            self._occ_i = 0
            self._occ_n = 0
            self._queue_depth = 0
            self._coverage_last = 1.0
            self._coverage_min = 1.0

    # -- counter attribute views (engine/tests read these as ints) ------

    @property
    def submitted(self) -> int:
        return self._reg.counter("submitted").value

    @property
    def completed(self) -> int:
        return self._reg.counter("completed").value

    @property
    def rejected(self) -> int:
        return self._reg.counter("rejected").value

    @property
    def expired(self) -> int:
        return self._reg.counter("expired").value

    @property
    def failed(self) -> int:
        return self._reg.counter("failed").value

    @property
    def batches(self) -> int:
        return self._reg.counter("batches").value

    # -- observations (called by batcher/engine) -----------------------

    def observe_submit(self) -> None:
        self._reg.counter("submitted").inc()

    def attach_watchtower(self, watchtower) -> None:
        """Attach an `obs.slo.Watchtower`: every terminal request
        outcome (ok/degraded/expired/rejected) and batch occupancy
        feeds its objective windows, and `evaluate()` runs after each
        feed so breach/recover transitions publish promptly. Detach
        with None. Zero cost when unattached or obs is disabled."""
        with self._wt_lock:
            self._watchtower = watchtower

    def _feed_watchtower(self, requests=(), occupancy=None) -> None:
        if not obs.enabled():
            return
        with self._wt_lock:
            wt = self._watchtower
            if wt is None:
                return
            for kw in requests:
                wt.observe_request(**kw)
            if occupancy is not None:
                wt.observe_batch(occupancy=occupancy)
            wt.evaluate()

    def observe_reject(self) -> None:
        self._reg.counter("rejected").inc()
        if obs.enabled():
            obs.counter("serve.outcome.rejected").inc()
            self._feed_watchtower(requests=({"outcome": "rejected"},))

    def observe_expired(self, n: int = 1,
                        wait_s: Optional[float] = None) -> None:
        """`wait_s` = queue wait until the drop (fed to the
        `serve.drop_wait_s` histogram) — the latency story of the
        requests admission killed, which the survivor percentiles by
        construction cannot show."""
        self._reg.counter("expired").inc(int(n))
        if obs.enabled():
            obs.counter("serve.outcome.expired").inc(int(n))
            if wait_s is not None:
                obs.histogram("serve.drop_wait_s").observe(float(wait_s))
            self._feed_watchtower(
                requests=({"outcome": "expired"},) * int(n))

    def observe_failed(self, n: int = 1) -> None:
        self._reg.counter("failed").inc(int(n))
        if obs.enabled():
            self._feed_watchtower(
                requests=({"outcome": "failed"},) * int(n))

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self._queue_depth = int(rows)

    def observe_batch(
        self,
        n_requests: int,
        valid_rows: int,
        bucket_rows: int,
        latencies_s: Sequence[float],
        coverage: Optional[float] = None,
    ) -> None:
        """One executed batch: `latencies_s` are the per-request
        submit->deliver wall seconds (one entry per merged request)."""
        now = time.monotonic()
        degraded = coverage is not None and float(coverage) < 1.0
        if obs.enabled():
            # the library-wide bucketed latency histogram: real
            # `_bucket{le=...}` series on the Prometheus surface, so a
            # scrape can chart latency quantiles over time (the
            # percentile *windows* stay in this instance's ring)
            hist = obs.histogram("serve.latency_s")
            for lat in latencies_s:
                hist.observe(float(lat))
            # terminal-outcome counters: with expired/rejected these
            # four account for every request that left the system
            obs.counter("serve.outcome.degraded" if degraded
                        else "serve.outcome.ok").inc(int(n_requests))
        with self._lock:
            # counters move under the ring lock so a concurrent
            # snapshot() never sees batches/completed ahead of the ring
            # entries they belong to (the pre-obs atomicity invariant)
            self._reg.counter("batches").inc()
            self._reg.counter("completed").inc(int(n_requests))
            self._rows_valid += int(valid_rows)
            self._rows_dispatched += int(bucket_rows)
            for lat in latencies_s:
                self._lat_s[self._lat_i] = float(lat)
                self._done_t[self._lat_i] = now
                self._lat_i = (self._lat_i + 1) % self._window
                self._lat_n = min(self._lat_n + 1, self._window)
            if bucket_rows > 0:
                self._occ[self._occ_i] = valid_rows / bucket_rows
                self._occ_i = (self._occ_i + 1) % self._occ.size
                self._occ_n = min(self._occ_n + 1, self._occ.size)
            if coverage is not None:
                self._coverage_last = float(coverage)
                self._coverage_min = min(self._coverage_min, float(coverage))
        if obs.enabled():
            outcome = "degraded" if degraded else "ok"
            self._feed_watchtower(
                requests=tuple({"latency_s": float(lat), "outcome": outcome,
                                "coverage": (float(coverage)
                                             if coverage is not None else None)}
                               for lat in latencies_s),
                occupancy=(valid_rows / bucket_rows if bucket_rows > 0
                           else None))

    # -- derived views --------------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time dict of every metric; percentiles/QPS derive
        from the ring windows (NaN when no request completed yet, so a
        dashboard can tell "no traffic" from "0 ms")."""
        with self._lock:
            counts = {name: self._reg.counter(name).value for name in _COUNTERS}
            lat = self._lat_s[: self._lat_n].copy()
            done = self._done_t[: self._lat_n].copy()
            occ = self._occ[: self._occ_n].copy()
            snap = {
                "uptime_s": time.monotonic() - self._t0,
                **counts,
                "queue_depth": self._queue_depth,
                "coverage_last": self._coverage_last,
                "coverage_min": self._coverage_min,
            }
        if lat.size:
            q = np.percentile(lat, [50.0, 90.0, 99.0]) * 1e3
            snap["latency_ms_p50"] = float(q[0])
            snap["latency_ms_p90"] = float(q[1])
            snap["latency_ms_p99"] = float(q[2])
            snap["latency_ms_mean"] = float(lat.mean() * 1e3)
            snap["latency_ms_max"] = float(lat.max() * 1e3)
            # sliding-window rate: completions in the ring over the span
            # from the oldest ringed completion to now (not t0 — the ring
            # must forget idle history the same way it forgets latencies)
            span = max(time.monotonic() - float(done.min()), 1e-9)
            snap["qps"] = float(lat.size / span)
        else:
            for key in ("latency_ms_p50", "latency_ms_p90", "latency_ms_p99",
                        "latency_ms_mean", "latency_ms_max", "qps"):
                snap[key] = float("nan")
        snap["batch_occupancy"] = float(occ.mean()) if occ.size else float("nan")
        snap["requests_per_batch"] = (
            snap["completed"] / snap["batches"] if snap["batches"] else float("nan")
        )
        return snap

    def render_text(self) -> str:
        """Prometheus exposition text of `snapshot()` (the shared
        `obs.export` formatter — one formatter for every scrape surface
        in the library)."""
        return render_prometheus(self.snapshot(), prefix="raft_tpu_serve_")
