"""Serving metrics: QPS, queue depth, batch occupancy, latency
percentiles, degraded-coverage — the observability half of the serving
engine.

No reference analogue (RAFT ships kernels, not a server); the design
follows the usual online-serving metric set: monotone counters for
admission outcomes, gauges for instantaneous state, and a fixed-size
ring buffer of per-request latencies from which `snapshot()` derives
p50/p90/p99 (a ring keeps memory constant over unbounded runs and makes
the percentiles reflect RECENT traffic, not the all-time mix). QPS
comes from the same ring's completion timestamps, so it too is a
sliding-window rate.

Thread-safety: every mutation takes one lock. Observations are O(1)
appends — percentile math is deferred to `snapshot()`, which copies the
valid window under the lock and computes outside contention-sensitive
paths (callers poll snapshots at human rates, not per request).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import numpy as np


class ServerMetrics:
    """Lock-safe registry for one `SearchServer`.

    Counters (monotone): `submitted`, `completed`, `rejected`,
    `expired`, `failed`, `batches`.
    Gauges: `queue_depth` (rows waiting), `coverage_last`/`coverage_min`
    (degraded-mode shard coverage, 1.0 == every shard answered).
    Windows: per-request latency ring (`latency_window` entries) and its
    completion timestamps; per-batch occupancy ring (valid rows /
    dispatched bucket rows — the padding tax the bucket ladder pays for
    one-compile-per-bucket).
    """

    def __init__(self, latency_window: int = 4096):
        if latency_window <= 0:
            raise ValueError("latency_window must be positive")
        self._window = int(latency_window)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.monotonic()
            self.submitted = 0
            self.completed = 0
            self.rejected = 0
            self.expired = 0
            self.failed = 0
            self.batches = 0
            self._rows_valid = 0
            self._rows_dispatched = 0
            self._lat_s = np.zeros(self._window, np.float64)
            self._done_t = np.zeros(self._window, np.float64)
            self._lat_i = 0
            self._lat_n = 0
            self._occ = np.zeros(min(self._window, 1024), np.float64)
            self._occ_i = 0
            self._occ_n = 0
            self._queue_depth = 0
            self._coverage_last = 1.0
            self._coverage_min = 1.0

    # -- observations (called by batcher/engine) -----------------------

    def observe_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def observe_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def observe_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += int(n)

    def observe_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += int(n)

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self._queue_depth = int(rows)

    def observe_batch(
        self,
        n_requests: int,
        valid_rows: int,
        bucket_rows: int,
        latencies_s: Sequence[float],
        coverage: Optional[float] = None,
    ) -> None:
        """One executed batch: `latencies_s` are the per-request
        submit->deliver wall seconds (one entry per merged request)."""
        now = time.monotonic()
        with self._lock:
            self.batches += 1
            self.completed += int(n_requests)
            self._rows_valid += int(valid_rows)
            self._rows_dispatched += int(bucket_rows)
            for lat in latencies_s:
                self._lat_s[self._lat_i] = float(lat)
                self._done_t[self._lat_i] = now
                self._lat_i = (self._lat_i + 1) % self._window
                self._lat_n = min(self._lat_n + 1, self._window)
            if bucket_rows > 0:
                self._occ[self._occ_i] = valid_rows / bucket_rows
                self._occ_i = (self._occ_i + 1) % self._occ.size
                self._occ_n = min(self._occ_n + 1, self._occ.size)
            if coverage is not None:
                self._coverage_last = float(coverage)
                self._coverage_min = min(self._coverage_min, float(coverage))

    # -- derived views --------------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time dict of every metric; percentiles/QPS derive
        from the ring windows (NaN when no request completed yet, so a
        dashboard can tell "no traffic" from "0 ms")."""
        with self._lock:
            lat = self._lat_s[: self._lat_n].copy()
            done = self._done_t[: self._lat_n].copy()
            occ = self._occ[: self._occ_n].copy()
            snap = {
                "uptime_s": time.monotonic() - self._t0,
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "batches": self.batches,
                "queue_depth": self._queue_depth,
                "coverage_last": self._coverage_last,
                "coverage_min": self._coverage_min,
            }
        if lat.size:
            q = np.percentile(lat, [50.0, 90.0, 99.0]) * 1e3
            snap["latency_ms_p50"] = float(q[0])
            snap["latency_ms_p90"] = float(q[1])
            snap["latency_ms_p99"] = float(q[2])
            snap["latency_ms_mean"] = float(lat.mean() * 1e3)
            snap["latency_ms_max"] = float(lat.max() * 1e3)
            # sliding-window rate: completions in the ring over the span
            # from the oldest ringed completion to now (not t0 — the ring
            # must forget idle history the same way it forgets latencies)
            span = max(time.monotonic() - float(done.min()), 1e-9)
            snap["qps"] = float(lat.size / span)
        else:
            for key in ("latency_ms_p50", "latency_ms_p90", "latency_ms_p99",
                        "latency_ms_mean", "latency_ms_max", "qps"):
                snap[key] = float("nan")
        snap["batch_occupancy"] = float(occ.mean()) if occ.size else float("nan")
        snap["requests_per_batch"] = (
            snap["completed"] / snap["batches"] if snap["batches"] else float("nan")
        )
        return snap

    def render_text(self) -> str:
        """Flat `name value` lines (Prometheus exposition style) — the
        form a scrape endpoint or a log tail wants."""
        snap = self.snapshot()
        lines = []
        for key in sorted(snap):
            val = snap[key]
            if isinstance(val, float):
                lines.append(f"raft_tpu_serve_{key} {val:.6g}")
            else:
                lines.append(f"raft_tpu_serve_{key} {val}")
        return "\n".join(lines) + "\n"
