"""raft_tpu.integrity — online integrity for live indexes.

Silent corruption of live HBM/host tables is only caught by the
checkpoint CRC at the NEXT load — after it has been served. This
package closes that window with four composed pieces:

- **digests** (`integrity.digest`): per-list / per-table CRC-32C
  sidecars over every serialized array of the three local index kinds,
  computed at build/extend time, kept incrementally fresh by the
  mutation ops (only touched lists re-digest), and carried through
  save/load as first-class `CKPT_SCHEMA` fields.
- **scrubbing** (`integrity.scrub`, `jobs.resumable_scrub`): a bounded
  re-hash walker that runs between serve batches (or as a supervised,
  SIGKILL-resumable job stage), emitting `integrity.scan/mismatch` obs
  events.
- **quarantine + repair** (`integrity.watchdog`): a detected-bad list
  is masked through the existing tombstone/`valid` path (serving
  degrades honestly — `coverage()` < 1.0 — instead of returning
  garbage), then repaired zero-dip: replica mirror under MNMG
  (`repair_ranks`), checkpoint replay locally (`checkpoint_repairer`),
  always digest-verified before swap-in.
- **point-in-time recovery** (`integrity.restore`):
  `restore(root, seq)` = newest verifiable retained snapshot + bounded
  mutation-log replay, byte-identical to the checkpoint a crash-free
  run would have committed at that seq; `Mutator(retain=K)` keeps the
  snapshot window and keys payload GC off the oldest retained cursor.

Chaos sites: ``integrity.table.rot`` (seeded live-table rot, the HBM
analogue of ``ckpt.corrupt_file``) and ``integrity.scrub.crash``
(SIGKILL after a scrub-cursor commit). Drills: tests/test_integrity.py.

Layer contract (tools/raftlint/rules/layers.py): module scope touches
only core/obs; neighbors and comms resolve lazily at call time — the
same posture as neighbors/mutation. The serve layer reaches DOWN into
this package (`Searcher.attach_integrity`), never the reverse.
"""

from raft_tpu.integrity.digest import (  # noqa: F401
    DIGEST_FIELDS,
    IntegrityError,
    attach,
    check_fresh,
    compute,
    refresh,
    verify,
)
from raft_tpu.integrity.restore import (  # noqa: F401
    prune,
    restore,
    retained,
    snapshot_path,
)
from raft_tpu.integrity.scrub import (  # noqa: F401
    ROT_SITE,
    SCRUB_CRASH_SITE,
    Scrubber,
    maybe_rot,
    rot_list,
)
from raft_tpu.integrity.watchdog import (  # noqa: F401
    IntegrityWatchdog,
    checkpoint_repairer,
    maybe_rot_mnmg,
    mnmg_digests,
    quarantine,
    repair_ranks,
    rot_rank,
    verify_mnmg,
)

__all__ = [
    "DIGEST_FIELDS",
    "IntegrityError",
    "IntegrityWatchdog",
    "ROT_SITE",
    "SCRUB_CRASH_SITE",
    "Scrubber",
    "attach",
    "check_fresh",
    "checkpoint_repairer",
    "compute",
    "maybe_rot",
    "maybe_rot_mnmg",
    "mnmg_digests",
    "prune",
    "quarantine",
    "refresh",
    "repair_ranks",
    "restore",
    "retained",
    "rot_list",
    "rot_rank",
    "snapshot_path",
    "verify",
    "verify_mnmg",
]
