"""Quarantine + repair: the containment half of the integrity story.

`IntegrityWatchdog` wraps a `Scrubber` with the serve-loop contract:
one bounded slice per `step(index)` call, and when a slice names a bad
list the watchdog immediately masks it through the existing
tombstone/`valid` path (every engine already skips dead cells — the
quarantined index serves bit-identically to one that never held those
rows, and `coverage()` reports the loss honestly instead of returning
garbage), then repairs zero-dip between batches through a pluggable
`repair` callable — checkpoint replay locally (`checkpoint_repairer`),
a replica mirror under MNMG (`repair_ranks`). A repaired index is
digest-verified (`digest.check_fresh`) before it replaces the
quarantined one; a repair that fails verification is rejected and the
quarantine stands.

Quarantine deliberately masks EVERY cell of the bad list, not just the
live ones: the rot may sit in `slot_rows` itself, so occupancy cannot
be trusted — and masking unoccupied/dead cells is a no-op to the scan.

MNMG rot is per-rank, not per-list (the sharded primaries are
rank-major blocks): `mnmg_digests` snapshots one digest per (attr,
rank), `verify_mnmg` names rotted ranks, and `repair_ranks` reuses the
PR-4 election + patched-view machinery (`comms.recovery.heal`) to
restore them from their ring mirrors.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.integrity import digest
from raft_tpu.integrity.scrub import ROT_SITE, Scrubber


def quarantine(index, list_id: int, kind: Optional[str] = None):
    """Mask every cell of `list_id` dead on a CLONE (zero-dip swap
    semantics: in-flight scans keep the old object). Returns the new
    index; its tombstones digest rows refresh through the normal
    incremental path, the rotted payload rows intentionally keep their
    stale (mismatching) digests — the scrubber skips quarantined lists
    instead of re-flagging them."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import mutation

    kind = kind or digest.kind_of(index)
    mask = mutation._tomb_mask(index).copy()
    mask[int(list_id), :] = True
    out = mutation._clone(index)
    out.tombstones = jnp.asarray(mask)
    for name in mutation._DERIVED_ATTRS:
        if getattr(out, name, None) is not None:
            setattr(out, name, None)
    digest.refresh(out, index, kind)
    if obs.enabled():
        obs.counter("integrity.quarantines").inc()
        obs.event("integrity.quarantine", list=int(list_id))
    return out


def checkpoint_repairer(root: str) -> Callable:
    """Repair callable for local serving: rebuild the index from the
    mutation root's checkpoint + log replay (integrity.restore) to the
    log's committed state — a serve loop applying uncommitted feed
    batches should commit before repair so the restored state matches
    what it serves. The restored object is digest-verified by the
    watchdog before swap-in like any other repair."""
    def _repair(index):
        import importlib

        # importlib, not `from ... import restore`: the package re-binds
        # `restore` to the FUNCTION, shadowing the module
        restore_mod = importlib.import_module("raft_tpu.integrity.restore")
        restored, _ = restore_mod.restore(root, verify=True)
        return restored

    return _repair


class IntegrityWatchdog:
    """Serve-side integrity driver. `step(index)` runs one scrub slice
    and handles any mismatch; it returns the index to serve next —
    usually the one passed in, a quarantined clone on detection, a
    verified repair when one succeeds. `coverage()` in [0, 1] is the
    fraction of lists not quarantined (1.0 = full coverage), which the
    serve adapters surface as the result coverage so degradation is
    visible at the dispatch layer."""

    def __init__(self, kind: Optional[str] = None, *, budget_lists: int = 8,
                 repair: Optional[Callable] = None):
        self.scrubber = Scrubber(kind, budget_lists=budget_lists)
        self.repair = repair
        self.quarantined: Set[int] = set()
        self.table_alarms: Set[str] = set()
        self.repairs = 0
        self.failed_repairs = 0

    def coverage(self) -> float:
        if not self.quarantined:
            return 1.0
        n = max(int(self._n_lists), 1)
        return max(0.0, 1.0 - len(self.quarantined) / n)

    _n_lists = 0

    def step(self, index):
        """One watchdog tick (call between serve batches)."""
        kind = self.scrubber.kind or digest.kind_of(index)
        self._n_lists = int(index.n_lists)
        bad = self.scrubber.slice_scan(index, skip=self.quarantined)
        for field, lid in bad:
            if lid < 0:
                # table-granularity rot has no smaller containment
                # mask than "repair": remember the alarm, degrade-free
                # serving resumes only after a verified repair
                self.table_alarms.add(field)
                continue
            if lid in self.quarantined:
                continue
            index = quarantine(index, lid, kind)
            self.quarantined.add(lid)
        if (self.quarantined or self.table_alarms) and self.repair is not None:
            index = self._try_repair(index, kind)
        return index

    def _try_repair(self, index, kind: str):
        try:
            repaired = self.repair(index)
            if repaired is None:
                return index
            digest.check_fresh(repaired, kind)
        except Exception as e:  # noqa: BLE001 — quarantine must outlive
            # a failed repair: serving stays degraded-but-honest
            self.failed_repairs += 1
            if obs.enabled():
                obs.counter("integrity.failed_repairs").inc()
                obs.event("integrity.repair", ok=False, error=str(e)[:200])
            return index
        self.repairs += 1
        n_lists = int(repaired.n_lists)
        if obs.enabled():
            obs.counter("integrity.repairs").inc()
            obs.event("integrity.repair", ok=True,
                      lists=sorted(self.quarantined),
                      tables=sorted(self.table_alarms))
        self.quarantined.clear()
        self.table_alarms.clear()
        self._n_lists = n_lists
        return repaired


# ---------------------------------------------------------------------------
# MNMG: per-rank shard digests + mirror repair
# ---------------------------------------------------------------------------


def mnmg_digests(index) -> Dict[str, np.ndarray]:
    """One CRC-32C per (replicated attr, rank) over the rank-major
    primary shards — the MNMG sidecar (per-rank because that is the
    repair granularity the mirrors provide)."""
    from raft_tpu.comms.replication import _replicated_attrs

    out: Dict[str, np.ndarray] = {}
    for name in _replicated_attrs(index):
        arr = np.ascontiguousarray(np.asarray(getattr(index, name)))
        out[name] = np.asarray(
            [digest.crc32c(arr[r]) for r in range(arr.shape[0])], np.uint32)
    return out


def verify_mnmg(index, baseline: Dict[str, np.ndarray]) -> List[int]:
    """Re-hash the shards against a `mnmg_digests` baseline; returns
    the sorted rotted ranks (any attr mismatching convicts the rank)."""
    bad: Set[int] = set()
    current = mnmg_digests(index)
    for name, want in baseline.items():
        got = current.get(name)
        if got is None or got.shape != np.asarray(want).shape:
            bad.update(range(int(index.comms.get_size())))
            continue
        bad.update(int(r) for r in np.flatnonzero(got != np.asarray(want)))
    if obs.enabled():
        obs.counter("integrity.scans").inc()
        for r in sorted(bad):
            obs.counter("integrity.mismatches").inc()
            obs.event("integrity.mismatch", field="shard", rank=int(r))
    return sorted(bad)


def rot_rank(index, rank: int, *, frac: float = 0.05, seed: int = 0) -> None:
    """Rot one rank's primary payload shard in place (MNMG drill
    helper; the FaultPlan-driven flavor seeds through `maybe_rot_mnmg`)."""
    import jax.numpy as jnp

    from raft_tpu.comms.replication import _replicated_attrs

    name = _replicated_attrs(index)[0]  # the payload table
    arr = np.ascontiguousarray(np.asarray(getattr(index, name))).copy()
    rng = np.random.default_rng(seed)
    cells = arr[int(rank)].reshape(-1)
    n = max(1, int(frac * cells.size))
    sel = rng.choice(cells.size, size=min(n, cells.size), replace=False)
    view = cells.view(np.uint8).reshape(cells.size, arr.itemsize)
    view[sel, 0] ^= 0xFF
    setattr(index, name, jnp.asarray(arr))
    if obs.enabled():
        obs.counter("integrity.rot_injected").inc()
        obs.event("integrity.rot", field=name, rank=int(rank))


def maybe_rot_mnmg(index, *, salt: int = 0) -> List[int]:
    """FaultPlan-driven MNMG shard rot at ``integrity.table.rot``
    (`corrupt_shard` faults; `rank` picks the victim, -1 draws one
    seeded). Returns the rotted ranks."""
    from raft_tpu.core import faults

    plan = faults.active_plan()
    if plan is None:
        return []
    hits = plan.matching(ROT_SITE, "corrupt_shard")
    if not hits:
        return []
    world = int(index.comms.get_size())
    rotted: List[int] = []
    for fi, f in enumerate(hits):
        rng = np.random.default_rng((plan.site_seed(ROT_SITE), salt, fi))
        rank = int(f.rank) if f.rank >= 0 else int(rng.integers(world))
        rot_rank(index, rank, frac=max(float(f.fraction), 1e-3),
                 seed=int(rng.integers(1 << 31)))
        rotted.append(rank)
    return sorted(set(rotted))


def repair_ranks(index, ranks, checkpoint: Optional[str] = None,
                 timeout_s: float = 30.0):
    """Mirror repair for rotted ranks: synthesize a RankHealth with the
    convicted ranks unhealthy and run the PR-4 heal loop (replica
    patch ppermute, checkpoint rehydration fallback, one verified
    barrier). Returns the repaired index."""
    from raft_tpu.comms import recovery
    from raft_tpu.comms.resilience import RankHealth

    health = RankHealth.all_healthy(int(index.comms.get_size()))
    for r in ranks:
        health.mark_unhealthy(int(r))
    index, _ = recovery.heal(index.comms, health, index,
                             checkpoint=checkpoint, timeout_s=timeout_s)
    if obs.enabled():
        obs.counter("integrity.repairs").inc()
        obs.event("integrity.repair", ok=True, ranks=sorted(int(r) for r in ranks))
    return index
