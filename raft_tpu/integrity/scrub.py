"""Online scrubbing: bounded re-hash slices over a live index, plus the
seeded table-rot chaos injector the drills exercise it with.

The scrubber is deliberately read-only — it names bad (field, list)
pairs and keeps a resumable cursor; containment (quarantine) and repair
are the watchdog's job (integrity/watchdog), and running it as a
supervised job stage is jobs.resumable_scrub. Layer contract: module
scope touches only core/obs (raftlint layers); neighbors resolve
lazily at call time, the mutation-module posture.

Chaos sites:

- ``integrity.table.rot`` — seeded in-memory rot of a live payload
  list: the HBM/host analogue of ``ckpt.corrupt_file``. Injected by
  `maybe_rot` under a `corrupt_shard` fault; the low byte of a seeded
  fraction of the victim row's elements flips (finite for floats —
  the containment drill's bit-identity claim must not ride on NaN
  propagation quirks), and no digest refreshes: rot, by definition,
  bypasses the mutation protocol.
- ``integrity.scrub.crash`` — SIGKILL window after a scrub-cursor
  commit (jobs.resumable_scrub), proving mid-scrub death resumes from
  the cursor instead of restarting the walk.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.integrity import digest

#: chaos sites (core.faults.FAULT_SITES)
ROT_SITE = "integrity.table.rot"
SCRUB_CRASH_SITE = "integrity.scrub.crash"

#: fields maybe_rot picks victims from, per kind: the payload tables.
#: (slot_rows/tombstones rot is detectable the same way — unit tests
#: rot them explicitly via rot_list — but the seeded injector models
#: payload rot, the overwhelmingly larger surface.)
_ROT_FIELDS = {
    "ivf_flat": ("list_data",),
    "ivf_pq": ("codes",),
    "ivf_rabitq": ("codes", "aux"),
}


def _flip_low_bytes(arr: np.ndarray, row: int, frac: float,
                    rng: np.random.Generator) -> np.ndarray:
    """Return a copy of `arr` with the low byte of a seeded `frac` of
    row `row`'s elements XOR-flipped (little-endian: byte 0 of each
    element — mantissa LSBs for floats, value bits for ints)."""
    out = np.ascontiguousarray(np.asarray(arr)).copy()
    cells = out[row].reshape(-1)
    n = max(1, int(frac * cells.size))
    sel = rng.choice(cells.size, size=min(n, cells.size), replace=False)
    view = cells.view(np.uint8).reshape(cells.size, out.itemsize)
    view[sel, 0] ^= 0xFF
    return out


def rot_list(index, list_id: int, field: str, *, frac: float = 1.0,
             seed: int = 0):
    """Rot one list of one field in place on `index` (direct drill
    helper; `maybe_rot` is the FaultPlan-driven flavor). Derived lazy
    stores are dropped so the rotted bytes are what scans actually
    read."""
    arr = getattr(index, field)
    rng = np.random.default_rng(seed)
    import jax.numpy as jnp

    rotted = _flip_low_bytes(arr, int(list_id), frac, rng)
    setattr(index, field, jnp.asarray(rotted))
    _drop_derived(index)
    if obs.enabled():
        obs.counter("integrity.rot_injected").inc()
        obs.event("integrity.rot", field=field, list=int(list_id))


def _drop_derived(index) -> None:
    from raft_tpu.neighbors import mutation

    for name in mutation._DERIVED_ATTRS:
        if getattr(index, name, None) is not None:
            setattr(index, name, None)


def maybe_rot(index, kind: Optional[str] = None, *, salt: int = 0
              ) -> List[Tuple[str, int]]:
    """Seeded in-memory table rot, driven by the active FaultPlan: each
    `corrupt_shard` fault matching ``integrity.table.rot`` rots `count`
    seeded (payload field, list) victims at `fraction` of the row's
    elements. Returns the victim pairs (the drill's ground truth).
    Victim choice keys off the plan's per-site seed + `salt`, so the
    3-seed chaos matrix rots different lists."""
    plan = faults.active_plan()
    if plan is None:
        return []
    hits = plan.matching(ROT_SITE, "corrupt_shard")
    if not hits:
        return []
    kind = kind or digest.kind_of(index)
    n_lists = int(index.n_lists)
    victims: List[Tuple[str, int]] = []
    for fi, f in enumerate(hits):
        rng = np.random.default_rng(
            (plan.site_seed(ROT_SITE), int(salt), fi))
        for _ in range(max(1, int(f.count))):
            field = _ROT_FIELDS[kind][int(rng.integers(
                len(_ROT_FIELDS[kind])))]
            lid = int(rng.integers(n_lists))
            rot_list(index, lid, field, frac=float(f.fraction),
                     seed=int(rng.integers(1 << 31)))
            victims.append((field, lid))
    return victims


class Scrubber:
    """Bounded-slice re-hash walker: each `slice_scan` call verifies up
    to `budget_lists` lists against the sidecar and advances a cursor;
    a full lap additionally re-hashes the table-granularity fields.
    The cursor is plain state (`cursor` int attr) so a supervising job
    can persist/restore it (jobs.resumable_scrub) and a serve loop can
    run one slice between batches without ever blocking traffic."""

    def __init__(self, kind: Optional[str] = None, *, budget_lists: int = 8):
        if budget_lists < 1:
            raise ValueError(f"budget_lists must be >= 1, got {budget_lists}")
        self.kind = kind
        self.budget_lists = int(budget_lists)
        self.cursor = 0
        self.lists_scanned = 0
        self.laps = 0
        self.mismatches = 0

    def slice_scan(self, index, skip=()) -> List[Tuple[str, int]]:
        """One bounded slice. Returns mismatches as (field, list_id)
        pairs; table-field mismatches (checked at lap boundaries)
        report list_id -1. Lists in `skip` (already quarantined) are
        not re-flagged."""
        kind = self.kind or digest.kind_of(index)
        if getattr(index, "list_digests", None) is None:
            # legacy index: first contact attaches a fresh sidecar —
            # nothing to verify against yet, coverage starts next slice
            digest.attach(index, kind)
            if obs.enabled():
                obs.event("integrity.scan", lists=0, cursor=0,
                          attached=True)
            return []
        n_lists = int(index.n_lists)
        start = self.cursor if self.cursor < n_lists else 0
        end = min(start + self.budget_lists, n_lists)
        ids = [i for i in range(start, end) if i not in set(skip)]
        bad = digest.verify_lists(index, ids, kind)
        if end >= n_lists:
            bad.extend((f, -1) for f in digest.verify_tables(index, kind))
            self.cursor = 0
            self.laps += 1
        else:
            self.cursor = end
        self.lists_scanned += len(ids)
        self.mismatches += len(bad)
        if obs.enabled():
            obs.counter("integrity.scans").inc()
            obs.counter("integrity.lists_scanned").inc(len(ids))
            obs.event("integrity.scan", lists=len(ids), cursor=self.cursor)
            for field, lid in bad:
                obs.counter("integrity.mismatches").inc()
                obs.event("integrity.mismatch", field=field, list=lid)
        return bad

    def full_scan(self, index, skip=()) -> List[Tuple[str, int]]:
        """Every list + the tables, as repeated slices (one lap from
        wherever the cursor stands)."""
        kind = self.kind or digest.kind_of(index)
        if getattr(index, "list_digests", None) is None:
            digest.attach(index, kind)
            return []
        bad: List[Tuple[str, int]] = []
        n_lists = int(index.n_lists)
        self.cursor = 0
        for _ in range(-(-n_lists // self.budget_lists) + 1):
            bad.extend(self.slice_scan(index, skip=skip))
            if self.cursor == 0:
                break
        return bad
