"""Content digests for live indexes: per-list / per-table CRC-32C
sidecars over the payload of the three local index kinds.

The checkpoint CRC (core/serialize) only proves bytes survived the
*disk* round trip; these sidecars cover the tables while they are live
— computed at build/extend time, kept incrementally fresh by the
mutation ops (only touched lists re-digest), carried through save/load
as first-class `CKPT_SCHEMA` fields, and re-checked online by the
scrubber (integrity/scrub) between serve batches.

Granularity is the containment unit: "list" fields digest per IVF list
row (one uint32 per list — a mismatch names the list to quarantine),
"table" fields digest whole (one uint32 — a mismatch means
repair-from-mirror/checkpoint, there is no smaller mask).

DIGEST_FIELDS is a PURE LITERAL: tools/raftlint AST-parses it (like
CKPT_SCHEMA) and fails closed if it stops being one. The lint rule
`integrity-digest-registry` pins it against CKPT_SCHEMA — every array
field of a digestable kind must carry a digest row, so a new
serialized table cannot silently ship without scrub coverage.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.core.serialize import crc32c

# kind -> {serialized array field -> digest granularity}.
# The sidecar fields themselves ("list_digests" array, "table_digests"
# meta) are exempt — a digest of the digests adds detection power only
# against rot of the sidecar, which a mismatch already surfaces.
DIGEST_FIELDS = {
    "ivf_flat": {
        "centers": "table",
        "list_data": "list",
        "slot_rows": "list",
        "list_sizes": "table",
        "source_ids": "table",
        "list_radii": "table",
        "tombstones": "list",
    },
    "ivf_pq": {
        "rotation": "table",
        "centers": "table",
        "pq_centers": "table",
        "codes": "list",
        "slot_rows": "list",
        "list_sizes": "table",
        "source_ids": "table",
        "list_radii": "table",
        "tombstones": "list",
    },
    "ivf_rabitq": {
        "rotation": "table",
        "centers": "table",
        "codes": "list",
        "aux": "list",
        "slot_rows": "list",
        "list_sizes": "table",
        "source_ids": "table",
        "tombstones": "list",
    },
}


class IntegrityError(RuntimeError):
    """A digest check failed where the caller required a clean result
    (verified restore, post-repair verification)."""


def kind_of(index) -> str:
    """Local index kind from the payload attrs (the mutation-layer
    convention: pq carries pq_centers, rabitq aux without list_data)."""
    if getattr(index, "pq_centers", None) is not None:
        return "ivf_pq"
    if hasattr(index, "aux") and not hasattr(index, "list_data"):
        return "ivf_rabitq"
    if hasattr(index, "list_data"):
        return "ivf_flat"
    raise TypeError(f"not a digestable local index: {type(index).__name__}")


def _canon(field: str, arr) -> np.ndarray:
    # digest the SERIALIZED representation: tombstones live as bool in
    # memory but ship as u8 (ivf_*.save) — canonicalizing here keeps a
    # digest computed before save valid against one recomputed after
    # load, and the sidecar meaningful across the boundary
    a = np.asarray(arr)
    if field == "tombstones":
        a = a.astype(np.uint8)
    return np.ascontiguousarray(a)


def _row_digests(field: str, arr, rows) -> np.ndarray:
    a = _canon(field, arr)
    out = np.empty(len(rows), np.uint32)
    for j, i in enumerate(rows):
        out[j] = crc32c(a[int(i)])
    return out


def _table_digest(field: str, arr) -> int:
    return int(crc32c(_canon(field, arr)))


def compute(index, kind: Optional[str] = None
            ) -> Tuple[Dict[str, np.ndarray], Dict[str, int]]:
    """Full digest pass. Returns (lists, tables): lists maps each
    present list-granularity field to a (n_lists,) uint32 row-digest
    vector, tables maps each present table-granularity field to one
    digest. Absent (None) optional fields simply have no entry — the
    invariant `set(lists) == present list fields` is what lets the
    packed sidecar round-trip save/load without a field manifest."""
    kind = kind or kind_of(index)
    spec = DIGEST_FIELDS[kind]
    n_lists = int(index.n_lists)
    all_rows = range(n_lists)
    lists: Dict[str, np.ndarray] = {}
    tables: Dict[str, int] = {}
    for field, gran in spec.items():
        arr = getattr(index, field, None)
        if arr is None:
            continue
        if gran == "table":
            tables[field] = _table_digest(field, arr)
        else:
            lists[field] = _row_digests(field, arr, all_rows)
    return lists, tables


def attach(index, kind: Optional[str] = None) -> None:
    """Compute and attach the sidecar in place (build-time hook)."""
    lists, tables = compute(index, kind)
    index.list_digests = lists
    index.table_digests = tables


def refresh(out, old, kind: Optional[str] = None) -> None:
    """Incrementally refresh `out`'s sidecar after a mutation that
    derived it from `old` (extend / tombstone / compact / rebalance).
    No-op when `old` carries no sidecar (legacy index).

    Touched-row detection leans on the mutation protocol's shape: every
    legitimate op moves `slot_rows` (appends, compaction) and/or the
    tombstone mask (deletes) for exactly the lists it touched, and a
    geometry change (regrow, rebalance) invalidates everything. Rot
    does neither — which is precisely why it stays detectable: nothing
    here ever re-digests a list no op legitimately touched."""
    if old is None or getattr(old, "list_digests", None) is None:
        return
    kind = kind or kind_of(out)
    spec = DIGEST_FIELDS[kind]
    n_lists = int(out.n_lists)
    old_sr = _canon("slot_rows", old.slot_rows)
    new_sr = _canon("slot_rows", out.slot_rows)
    if old_sr.shape != new_sr.shape or int(old.n_lists) != n_lists:
        attach(out, kind)  # geometry changed: every slot moved
        return
    touched = np.flatnonzero((old_sr != new_sr).any(axis=1))
    ot, nt = getattr(old, "tombstones", None), getattr(out, "tombstones", None)
    if (ot is None) != (nt is None):
        tomb_touched = np.arange(n_lists)
    elif nt is None:
        tomb_touched = np.zeros(0, np.int64)
    else:
        om, nm = _canon("tombstones", ot), _canon("tombstones", nt)
        if om.shape != nm.shape:
            tomb_touched = np.arange(n_lists)
        else:
            tomb_touched = np.flatnonzero((om != nm).any(axis=1))
    lists = dict(old.list_digests)
    tables = dict(getattr(old, "table_digests", None) or {})
    for field, gran in spec.items():
        arr = getattr(out, field, None)
        oarr = getattr(old, field, None)
        if arr is None:
            lists.pop(field, None)
            tables.pop(field, None)
            continue
        if gran == "table":
            if oarr is None or arr is not oarr or field not in tables:
                tables[field] = _table_digest(field, arr)
            continue
        rows = tomb_touched if field == "tombstones" else touched
        prev = lists.get(field)
        if oarr is None or prev is None or prev.shape[0] != n_lists:
            lists[field] = _row_digests(field, arr, range(n_lists))
        elif arr is not oarr and len(rows):
            d = prev.copy()
            d[rows] = _row_digests(field, arr, rows)
            lists[field] = d
        # identical object (clone shared the ref) -> digests still hold
    out.list_digests = lists
    out.table_digests = tables


def verify_lists(index, list_ids, kind: Optional[str] = None
                 ) -> List[Tuple[str, int]]:
    """Re-hash the given lists against the sidecar. Returns
    [(field, list_id), ...] mismatches (empty = clean slice)."""
    kind = kind or kind_of(index)
    sidecar = getattr(index, "list_digests", None)
    if not sidecar:
        return []
    bad: List[Tuple[str, int]] = []
    for field, want in sidecar.items():
        arr = getattr(index, field, None)
        if arr is None:
            continue
        got = _row_digests(field, arr, list_ids)
        for j, i in enumerate(list_ids):
            if got[j] != want[int(i)]:
                bad.append((field, int(i)))
    return bad


def verify_tables(index, kind: Optional[str] = None) -> List[str]:
    """Re-hash the table-granularity fields. Returns mismatched field
    names (empty = clean)."""
    kind = kind or kind_of(index)
    sidecar = getattr(index, "table_digests", None)
    if not sidecar:
        return []
    return [f for f, want in sidecar.items()
            if getattr(index, f, None) is not None
            and _table_digest(f, getattr(index, f)) != int(want)]


def verify(index, kind: Optional[str] = None) -> List[Tuple[str, int]]:
    """Full verification pass: every list of every list field plus all
    tables. Table mismatches report list id -1."""
    kind = kind or kind_of(index)
    bad = verify_lists(index, range(int(index.n_lists)), kind)
    bad.extend((f, -1) for f in verify_tables(index, kind))
    return bad


def check_fresh(index, kind: Optional[str] = None) -> None:
    """Raise IntegrityError unless the attached sidecar matches the
    content exactly (the verified-restore / post-repair gate)."""
    kind = kind or kind_of(index)
    if getattr(index, "list_digests", None) is None:
        raise IntegrityError(f"{kind}: no digest sidecar attached")
    bad = verify(index, kind)
    if bad:
        raise IntegrityError(f"{kind}: digest mismatch at {bad[:8]!r}"
                             f" ({len(bad)} total)")


# ---------------------------------------------------------------------------
# checkpoint packing: the per-list vectors ride as ONE (n_fields,
# n_lists) uint32 array field; per-table digests ride in the meta JSON
# ---------------------------------------------------------------------------


def _packed_order(index, kind: str) -> List[str]:
    # deterministic row order WITHOUT a manifest: sorted list-field
    # names, restricted to fields present on the index. Save-side
    # presence (digest entry exists) and load-side presence (attr is
    # not None) agree by the compute/refresh invariant.
    spec = DIGEST_FIELDS[kind]
    return [f for f in sorted(spec) if spec[f] == "list"
            and getattr(index, f, None) is not None]


def pack_lists(index, kind: str) -> Optional[np.ndarray]:
    """Sidecar -> one stacked uint32 array for serialization (None when
    no sidecar is attached)."""
    sidecar = getattr(index, "list_digests", None)
    if sidecar is None:
        return None
    order = _packed_order(index, kind)
    if not all(f in sidecar for f in order):
        return None  # stale sidecar: do not serialize a partial one
    if not order:
        return np.zeros((0, int(index.n_lists)), np.uint32)
    return np.stack([np.asarray(sidecar[f], np.uint32) for f in order])


def unpack_lists(index, kind: str, packed, table_meta) -> None:
    """Load-side inverse of pack_lists: attach the sidecar from the
    checkpoint fields, or leave it absent (None) when the file predates
    digests or the packed shape no longer matches the field set."""
    index.list_digests = None
    index.table_digests = None
    if packed is None:
        return
    order = _packed_order(index, kind)
    packed = np.asarray(packed, np.uint32)
    if packed.ndim != 2 or packed.shape[0] != len(order) \
            or packed.shape[1] != int(index.n_lists):
        return  # foreign/old field layout: degrade to no sidecar
    index.list_digests = {f: packed[i].copy() for i, f in enumerate(order)}
    index.table_digests = {str(k): int(v)
                           for k, v in (table_meta or {}).items()}
