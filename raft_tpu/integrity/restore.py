"""Point-in-time recovery: base checkpoint + bounded mutation-log
replay to a target committed seq, digest-verified before anyone serves
the result.

The mutation log (neighbors/mutation) already proves replay
determinism — a SIGKILL resume is exactly "load the committed
checkpoint, replay the log tail". PITR generalizes the same machinery
to ANY committed seq: the `Mutator(retain=K)` keeps the K newest
commit checkpoints as cursor-stamped snapshots (`pitr_<cursor>.ckpt`,
byte-for-byte copies of the commit's `index.ckpt`), the payload sweep
floor drops to the oldest retained cursor (so every retained base can
replay forward), and `restore(root, seq)` picks the newest verifiable
base at-or-below the target and replays `[base.cursor, seq)`. A base
that fails its digest check is skipped for the next older one — a
rotted snapshot costs replay time, not the restore.

Retention/GC is keyed off the log's committed cursor: snapshots are
only ever written at commits, pruning keeps the newest K, and payload
containers below the oldest retained cursor are the only ones swept.

Layer contract: module scope touches only core/obs; the mutation and
index modules resolve lazily at call time.
"""

from __future__ import annotations

import glob
import os
import re
from typing import List, Optional, Tuple

from raft_tpu import obs
from raft_tpu.integrity import digest

#: cursor-stamped commit snapshots under the mutation root
SNAPSHOT_PREFIX = "pitr_"
_SNAPSHOT_RE = re.compile(r"pitr_(\d+)\.ckpt$")


def snapshot_path(root: str, cursor: int) -> str:
    return os.path.join(os.fspath(root), f"{SNAPSHOT_PREFIX}{int(cursor):06d}.ckpt")


def retained(root: str) -> List[Tuple[int, str]]:
    """The retained snapshots as (cursor, path), oldest first."""
    out = []
    for p in glob.glob(os.path.join(os.fspath(root), f"{SNAPSHOT_PREFIX}*.ckpt")):
        m = _SNAPSHOT_RE.search(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def prune(root: str, keep: int) -> List[int]:
    """Drop all but the newest `keep` snapshots; returns the surviving
    cursors (oldest first). keep <= 0 removes every snapshot."""
    snaps = retained(root)
    drop = snaps[:-keep] if keep > 0 else snaps
    for _, p in drop:
        try:
            os.remove(p)
        except OSError:
            pass  # a lingering snapshot is wasted disk, not corruption
    return [c for c, _ in (snaps[-keep:] if keep > 0 else [])]


def _bases(root: str) -> List[Tuple[int, str]]:
    """Candidate replay bases, oldest first: the retained snapshots
    plus the live committed checkpoint (its cursor is read lazily —
    only when it is actually considered)."""
    from raft_tpu.neighbors.mutation import CKPT_NAME

    out = retained(root)
    live = os.path.join(os.fspath(root), CKPT_NAME)
    if os.path.exists(live):
        from raft_tpu.core.serialize import peek_meta

        try:
            out.append((int(peek_meta(live).get("mut_cursor", 0)), live))
        except Exception:  # noqa: BLE001 — a torn live ckpt is just
            pass           # not a candidate; the snapshots still are
    return sorted(out)


def restore(root: str, seq: Optional[int] = None, *,
            out: Optional[str] = None, verify: bool = True,
            base_cursor: Optional[int] = None):
    """Reconstruct the committed state at `seq` (default: the log's
    full committed length). Returns (index, out_path-or-None); with
    `out` set the result is also saved — byte-identical to the
    checkpoint a crash-free run would have committed at that seq (the
    replay path IS the resume path, plus the deterministic save).

    `verify=True` digest-checks both the chosen base (falling back to
    older bases on mismatch) and the final state; `base_cursor` pins a
    specific base (the drills use it to force a real replay instead of
    a snapshot copy)."""
    from raft_tpu.neighbors import mutation

    mod = mutation._index_module  # resolved per kind below
    log = mutation.MutationLog(root)
    entries = log.entries()
    seq = len(entries) if seq is None else int(seq)
    if seq < 0 or seq > len(entries):
        raise digest.IntegrityError(
            f"restore target seq {seq} outside the committed log "
            f"(0..{len(entries)})")
    candidates = [(c, p) for c, p in _bases(root) if c <= seq]
    if base_cursor is not None:
        candidates = [(c, p) for c, p in candidates if c == int(base_cursor)]
    if not candidates:
        raise digest.IntegrityError(
            f"no base checkpoint at or below seq {seq} under {root}")
    last_err: Optional[Exception] = None
    for cursor, path in reversed(candidates):
        from raft_tpu.core.serialize import peek_meta

        try:
            # peek inside the try: a snapshot rotted in its HEADER must
            # fall back to an older base like any other bad candidate
            kind = peek_meta(path)["kind"]
            idx = mod(kind).load(path)
            if verify and getattr(idx, "list_digests", None) is not None:
                digest.check_fresh(idx, kind)
        except Exception as e:  # noqa: BLE001 — rotted/torn base:
            last_err = e       # fall back to the next older snapshot
            if obs.enabled():
                obs.event("integrity.restore", base=cursor, ok=False,
                          error=str(e)[:200])
            continue
        index = _replay(mutation, kind, idx, log, entries, seq)
        if getattr(index, "list_digests", None) is None:
            digest.attach(index, kind)
        if verify:
            digest.check_fresh(index, kind)
        out_path = None
        if out is not None:
            out_path = os.fspath(out)
            mod(kind).save(out_path, index)
        if obs.enabled():
            obs.counter("integrity.restores").inc()
            obs.event("integrity.restore", base=cursor, seq=seq, ok=True)
        return index, out_path
    raise digest.IntegrityError(
        f"every base checkpoint at or below seq {seq} failed to "
        f"load/verify: {last_err!r}")


def _replay(mutation, kind: str, idx, log, entries, seq: int):
    """Replay entries [idx.mut_cursor, seq) — the Mutator resume path,
    bounded at `seq` — and stamp the commit-equivalent cursor/slack."""
    slack = int(idx.append_slack)
    if slack:
        idx = mutation.ensure_append_slack(idx, slack)
    start = int(idx.mut_cursor)
    if start > seq:
        raise digest.IntegrityError(
            f"base cursor {start} beyond restore target {seq}")
    for e in entries[start:seq]:
        op = e["op"]
        if op == "rebalance":
            idx, _ = mutation.rebalance(idx, slack=slack or None)
            continue
        op2, _, ids, vectors = mutation._load_batch(
            log.payload_path(e["seq"]))
        if op2 != op:
            raise mutation.MutationLogError(
                f"payload op {op2!r} != log op {op!r} at seq {e['seq']}")
        if op == "upsert":
            idx = mutation.upsert(idx, vectors, ids)
        elif op == "delete":
            idx = mutation.delete(idx, ids)
        else:
            raise mutation.MutationLogError(f"unknown logged op {op!r}")
    final = mutation._clone(idx)
    final.mut_cursor = seq
    final.append_slack = slack
    return final
