"""Distance metric enum + name resolution.

Reference parity: `raft::distance::DistanceType` (distance/distance_types.hpp:23-67,
20 metrics + Precomputed) and pylibraft's string→enum mapping
(distance/pairwise_distance.pyx DISTANCE_TYPES / PAIRWISE_DISTANCE_METRICS).
"""

from __future__ import annotations

import enum


class DistanceType(enum.IntEnum):
    # Values match distance_types.hpp:23-67 for interop/debuggability.
    L2Expanded = 0
    L2SqrtExpanded = 1
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7
    Canberra = 8
    LpUnexpanded = 9
    CorrelationExpanded = 10
    JaccardExpanded = 11
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19
    Precomputed = 100


# pylibraft-compatible metric names (pairwise_distance.pyx DISTANCE_TYPES)
DISTANCE_TYPES = {
    "l2": DistanceType.L2SqrtExpanded,
    "sqeuclidean": DistanceType.L2Expanded,
    "euclidean": DistanceType.L2SqrtExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "taxicab": DistanceType.L1,
    "inner_product": DistanceType.InnerProduct,
    "chebyshev": DistanceType.Linf,
    "linf": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "cosine": DistanceType.CosineExpanded,
    "lp": DistanceType.LpUnexpanded,
    "minkowski": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "kldivergence": DistanceType.KLDivergence,
    "russellrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
    "sqeuclidean_unexpanded": DistanceType.L2Unexpanded,
    "euclidean_unexpanded": DistanceType.L2SqrtUnexpanded,
}

# Metrics for which smaller is better=closer. InnerProduct is a similarity.
SIMILARITY_METRICS = frozenset({DistanceType.InnerProduct})


def resolve_metric(metric) -> DistanceType:
    """Accept a DistanceType, its int value, or a pylibraft metric string."""
    if isinstance(metric, DistanceType):
        return metric
    if isinstance(metric, int):
        return DistanceType(metric)
    name = str(metric).lower()
    try:
        return DISTANCE_TYPES[name]
    except KeyError:
        raise ValueError(
            f"unsupported metric {metric!r}; supported: {sorted(DISTANCE_TYPES)}"
        ) from None
