"""Pairwise distances, fused 1-NN, masked NN, kernel (gram) matrices.

TPU-native equivalent of `cpp/include/raft/distance/` (survey §2.7).
"""

from raft_tpu.distance.distance_types import (
    DistanceType,
    DISTANCE_TYPES,
    resolve_metric,
)
from raft_tpu.distance.pairwise import pairwise_distance, distance, set_matmul_precision
from raft_tpu.distance.fused_l2_nn import fused_l2_nn, fused_l2_nn_argmin
from raft_tpu.distance.masked_nn import masked_l2_nn
from raft_tpu.distance.kernels import (
    KernelType,
    KernelParams,
    GramMatrix,
    kernel_factory,
    gram_matrix,
)

__all__ = [
    "DistanceType",
    "DISTANCE_TYPES",
    "resolve_metric",
    "pairwise_distance",
    "distance",
    "set_matmul_precision",
    "fused_l2_nn",
    "fused_l2_nn_argmin",
    "masked_l2_nn",
    "KernelType",
    "KernelParams",
    "GramMatrix",
    "kernel_factory",
    "gram_matrix",
]
