"""Pairwise distances — TPU-native implementation.

Reference parity: `raft::distance::pairwise_distance` (distance/distance.cuh:241)
with the 20-metric enum; per-metric accumulate/epilogue functors
(distance/detail/distance_ops/*.cuh); the shared GEMM-like tiling engine
(linalg/detail/contractions.cuh, detail/pairwise_matrix/*).

TPU design (not a port):
  - *Expanded* metrics (L2, cosine, correlation, hellinger, russelrao,
    jaccard, dice, inner product) reduce to ONE big matmul on the MXU plus
    rank-1 norm epilogues — `x @ y.T` with f32 accumulation. This is where
    the benchmark TFLOPS come from; XLA tiles it optimally.
  - *Unexpanded* metrics (L1, Linf, Canberra, Lp, Bray-Curtis, Hamming,
    Jensen-Shannon, KL) are VPU-bound elementwise-pair reductions. They run
    through one generic row-blocked engine (`_tiled_rowwise`) parameterized
    by a per-metric term function — mirroring how all reference metrics share
    the `pairwise_matrix` engine with op functors. Blocking bounds the
    materialized (bm, n, k) broadcast so it fits comfortably on-chip.

Everything is jit-compiled with static metric; block sizes are computed from
static shapes at trace time.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.core.config import auto_convert_output

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


# Matmul precision for the expanded-distance inner products. TPU MXUs run
# f32 matmuls as bf16 passes unless told otherwise; distances built from
# norm-cancellation need the HIGHEST (6-pass) mode for f32 parity with the
# CUDA reference. Callers chasing TFLOPS can drop to "default"/bf16 inputs
# via set_matmul_precision.
_MATMUL_PRECISION = lax.Precision.HIGHEST

def set_matmul_precision(precision) -> None:
    """Set the MXU precision for f32 distance matmuls.

    Default is `lax.Precision.HIGHEST` (six bf16 passes — f32 parity with
    the reference's cuBLAS path, needed by the expanded-form norm trick's
    cancellation). `lax.Precision.DEFAULT` runs one bf16 pass: ~6x the
    matmul throughput at ~1e-3 relative error — usually fine for k-means
    assignment and ANN probing, not for tight distance parity tests.

    Call BEFORE the first distance computation of a given shape/dtype:
    the precision is captured at trace time and jit-cached executables
    are not invalidated by later changes."""
    global _MATMUL_PRECISION
    _MATMUL_PRECISION = precision


def _dot(x: jax.Array, y: jax.Array, precision=None) -> jax.Array:
    """x (m,k) @ y.T (k,n) with f32 accumulation on the MXU.

    `precision` overrides the module default for this call (bf16 inputs
    always run single-pass)."""
    if precision is None:
        precision = None if x.dtype == jnp.bfloat16 else _MATMUL_PRECISION
    return lax.dot_general(
        x,
        y,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def _row_norms_sq(x: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    return jnp.sum(xf * xf, axis=1)


def _block_rows(m: int, n: int, k: int, budget_elems: int = 1 << 22) -> int:
    """Pick a row-block size so the (bm, n, k) broadcast stays ~16MB f32."""
    bm = max(1, budget_elems // max(1, n * k))
    bm = min(bm, m)
    if bm >= 8:
        bm = bm // 8 * 8
    return max(1, bm)


def _tiled_rowwise(
    x: jax.Array,
    y: jax.Array,
    row_fn: Callable[[jax.Array, jax.Array], jax.Array],
    budget_elems: int = 1 << 22,
) -> jax.Array:
    """Apply row_fn((bm,k), (n,k)) -> (bm,n) over row blocks of x.

    The TPU analogue of the reference's grid-strided tiling: each block's
    intermediate broadcast lives only for that block, so peak memory is
    bounded regardless of m·n·k.
    """
    m, k = x.shape
    n = y.shape[0]
    bm = _block_rows(m, n, k, budget_elems)
    nblocks = -(-m // bm)
    pad = nblocks * bm - m
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    blocks = xp.reshape(nblocks, bm, k)
    out = lax.map(lambda xb: row_fn(xb, y), blocks)
    out = out.reshape(nblocks * bm, n)
    return out[:m] if pad else out


# ---------------------------------------------------------------------------
# expanded (MXU) family
# ---------------------------------------------------------------------------


def _l2_expanded(x, y, sqrt: bool):
    d = _dot(x, y)
    xn = _row_norms_sq(x)[:, None]
    yn = _row_norms_sq(y)[None, :]
    out = jnp.maximum(xn + yn - 2.0 * d, 0.0)
    # Exact zeros on the diagonal-style matches (x_i == y_j) are preserved by
    # the clamp; sqrt afterwards for the Sqrt variant.
    return jnp.sqrt(out) if sqrt else out


def _cosine(x, y):
    d = _dot(x, y)
    xn = jnp.sqrt(_row_norms_sq(x))[:, None]
    yn = jnp.sqrt(_row_norms_sq(y))[None, :]
    denom = jnp.maximum(xn * yn, jnp.finfo(jnp.float32).tiny)
    return 1.0 - d / denom


def _correlation(x, y):
    xc = x - jnp.mean(x.astype(jnp.float32), axis=1, keepdims=True)
    yc = y - jnp.mean(y.astype(jnp.float32), axis=1, keepdims=True)
    return _cosine(xc, yc)


def _inner_product(x, y):
    return _dot(x, y)


def _hellinger(x, y):
    # d = sqrt(1 - sum(sqrt(x_i * y_i)))  (distance_ops/hellinger.cuh)
    d = _dot(jnp.sqrt(jnp.abs(x)), jnp.sqrt(jnp.abs(y)))
    return jnp.sqrt(jnp.maximum(1.0 - d, 0.0))


def _russelrao(x, y):
    k = x.shape[1]
    d = _dot(x, y)
    return (k - d) / k


def _jaccard(x, y):
    # binary semantics: 1 - |x∩y| / |x∪y|; counts via dot / row sums
    d = _dot(x, y)
    sx = jnp.sum(x.astype(jnp.float32), axis=1)[:, None]
    sy = jnp.sum(y.astype(jnp.float32), axis=1)[None, :]
    union = jnp.maximum(sx + sy - d, jnp.finfo(jnp.float32).tiny)
    return 1.0 - d / union


def _dice(x, y):
    d = _dot(x, y)
    sx = jnp.sum(x.astype(jnp.float32), axis=1)[:, None]
    sy = jnp.sum(y.astype(jnp.float32), axis=1)[None, :]
    denom = jnp.maximum(sx + sy, jnp.finfo(jnp.float32).tiny)
    return 1.0 - 2.0 * d / denom


# ---------------------------------------------------------------------------
# unexpanded (VPU) family — generic engine + per-metric term functions
# ---------------------------------------------------------------------------


def _sum_terms(term_fn, finalize=None):
    def row_fn(xb, y):
        t = term_fn(xb[:, None, :].astype(jnp.float32), y[None, :, :].astype(jnp.float32))
        s = jnp.sum(t, axis=-1)
        return finalize(s) if finalize is not None else s

    return row_fn


def _l1_row(xb, y):
    return jnp.sum(jnp.abs(xb[:, None, :] - y[None, :, :]).astype(jnp.float32), axis=-1)


def _linf_row(xb, y):
    return jnp.max(jnp.abs(xb[:, None, :] - y[None, :, :]).astype(jnp.float32), axis=-1)


def _canberra_term(a, b):
    num = jnp.abs(a - b)
    den = jnp.abs(a) + jnp.abs(b)
    return jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)


def _hamming_row(xb, y):
    k = y.shape[-1]
    return jnp.sum((xb[:, None, :] != y[None, :, :]).astype(jnp.float32), axis=-1) / k


def _kl_term(a, b):
    # sum x*log(x/y) over x>0 (distance_ops/kl_divergence.cuh)
    safe = (a > 0) & (b > 0)
    ratio = jnp.where(safe, a / jnp.where(safe, b, 1.0), 1.0)
    return jnp.where(safe, a * jnp.log(ratio), 0.0)


def _js_term(a, b):
    m = 0.5 * (a + b)
    pos_m = m > 0
    logm = jnp.where(pos_m, jnp.log(jnp.where(pos_m, m, 1.0)), 0.0)
    ta = jnp.where(a > 0, a * (jnp.log(jnp.where(a > 0, a, 1.0)) - logm), 0.0)
    tb = jnp.where(b > 0, b * (jnp.log(jnp.where(b > 0, b, 1.0)) - logm), 0.0)
    return ta + tb


def _braycurtis_row(xb, y):
    a = xb[:, None, :].astype(jnp.float32)
    b = y[None, :, :].astype(jnp.float32)
    num = jnp.sum(jnp.abs(a - b), axis=-1)
    den = jnp.sum(jnp.abs(a + b), axis=-1)
    return jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)


def _haversine(x, y):
    # 2-d (lat, lon) in radians (spatial/knn haversine semantics)
    lat1, lon1 = x[:, 0][:, None], x[:, 1][:, None]
    lat2, lon2 = y[:, 0][None, :], y[:, 1][None, :]
    sdlat = jnp.sin(0.5 * (lat2 - lat1))
    sdlon = jnp.sin(0.5 * (lon2 - lon1))
    h = sdlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sdlon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


# DistanceType -> Pallas engine metric key (raft_tpu.ops.pairwise_pallas).
_PALLAS_METRICS = {
    DistanceType.L1: "l1",
    DistanceType.Linf: "linf",
    DistanceType.L2Unexpanded: "l2_unexpanded",
    DistanceType.L2SqrtUnexpanded: "l2_sqrt_unexpanded",
    DistanceType.Canberra: "canberra",
    DistanceType.KLDivergence: "kl_divergence",
    DistanceType.HammingUnexpanded: "hamming",
}


def _try_pallas_pairwise(x, y, metric: DistanceType):
    """Pallas tiled engine for unexpanded metrics on TPU; None if not taken.

    All decisions are static at trace time (metric, shapes, backend), so this
    composes with the jit around `_pairwise_impl`.
    """
    from raft_tpu import ops
    from raft_tpu.ops import pairwise_pallas

    key = _PALLAS_METRICS.get(metric)
    if key is None or not ops.use_pallas():
        return None
    m, k = x.shape
    n = y.shape[0]
    if not pairwise_pallas.fits_pallas(m, n, k):
        return None
    return pairwise_pallas.pairwise_tiled(
        x, y, key, interpret=ops.interpret_mode()
    )


@functools.partial(jax.jit, static_argnums=(2,), static_argnames=("metric_arg",))
def _pairwise_impl(x: jax.Array, y: jax.Array, metric: DistanceType, *, metric_arg: float = 2.0):
    # Pallas engine first: covers the unexpanded family for ALL callers
    # (brute_force, epsilon_neighborhood, ball_cover, sparse adapters, ...).
    pallas_out = _try_pallas_pairwise(x, y, metric)
    if pallas_out is not None:
        return pallas_out
    D = DistanceType
    if metric == D.L2Expanded:
        return _l2_expanded(x, y, sqrt=False)
    if metric == D.L2SqrtExpanded:
        return _l2_expanded(x, y, sqrt=True)
    if metric == D.CosineExpanded:
        return _cosine(x, y)
    if metric == D.CorrelationExpanded:
        return _correlation(x, y)
    if metric == D.InnerProduct:
        return _inner_product(x, y)
    if metric == D.HellingerExpanded:
        return _hellinger(x, y)
    if metric == D.RusselRaoExpanded:
        return _russelrao(x, y)
    if metric == D.JaccardExpanded:
        return _jaccard(x, y)
    if metric == D.DiceExpanded:
        return _dice(x, y)
    if metric == D.L1:
        return _tiled_rowwise(x, y, _l1_row)
    if metric == D.Linf:
        return _tiled_rowwise(x, y, _linf_row)
    if metric == D.L2Unexpanded:
        return _tiled_rowwise(x, y, _sum_terms(lambda a, b: (a - b) ** 2))
    if metric == D.L2SqrtUnexpanded:
        return _tiled_rowwise(x, y, _sum_terms(lambda a, b: (a - b) ** 2, jnp.sqrt))
    if metric == D.Canberra:
        return _tiled_rowwise(x, y, _sum_terms(_canberra_term))
    if metric == D.LpUnexpanded:
        p = metric_arg
        return _tiled_rowwise(
            x, y, _sum_terms(lambda a, b: jnp.abs(a - b) ** p, lambda s: s ** (1.0 / p))
        )
    if metric == D.HammingUnexpanded:
        return _tiled_rowwise(x, y, _hamming_row)
    if metric == D.KLDivergence:
        return _tiled_rowwise(x, y, _sum_terms(_kl_term))
    if metric == D.JensenShannon:
        return _tiled_rowwise(x, y, _sum_terms(_js_term, lambda s: jnp.sqrt(0.5 * s)))
    if metric == D.BrayCurtis:
        return _tiled_rowwise(x, y, _braycurtis_row)
    if metric == D.Haversine:
        return _haversine(x, y)
    raise ValueError(f"metric {metric} not implemented")


@auto_convert_output
def pairwise_distance(
    X,
    Y,
    out: Optional[jax.Array] = None,
    metric="euclidean",
    p: float = 2.0,
    resources=None,
) -> jax.Array:
    """Compute the full m×n pairwise distance matrix.

    pylibraft-compatible signature (distance/pairwise_distance.pyx). `out`
    is accepted for API parity; a new array is always returned (functional
    semantics — XLA owns buffers).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance
    >>> x = np.array([[0.0, 0.0], [3.0, 4.0]])
    >>> d = pairwise_distance(x, x, metric="euclidean")
    >>> np.asarray(d).round(3).tolist()
    [[0.0, 5.0], [5.0, 0.0]]
    """
    from raft_tpu.core.validation import check_matrix, check_same_cols

    x = check_matrix(X, name="X")
    y = check_matrix(Y, name="Y")
    m = resolve_metric(metric)
    if m == DistanceType.Precomputed:
        return x
    if m == DistanceType.Haversine and x.shape[1] != 2:
        raise ValueError("haversine requires 2-d (lat, lon) inputs")
    check_same_cols(x, y, "X", "Y")
    result = _pairwise_impl(x, y, m, metric_arg=float(p))
    if resources is not None:
        resources.track(result)
    if out is not None:
        # API parity: fill the caller's buffer shape-check, return result.
        if tuple(out.shape) != (x.shape[0], y.shape[0]):
            raise ValueError("out has wrong shape")
    return result


distance = pairwise_distance  # raft::distance::distance() alias
