"""Fused L2 nearest-neighbor (1-NN) — the k-means inner loop.

Reference parity: `raft::distance::fused_l2_nn` / `fused_l2_nn_min_reduce`
(distance/fused_l2_nn.cuh; kernel detail/fused_l2_nn.cuh:129) computes, for
each row of x, the index (and optionally distance) of the closest row of y
WITHOUT materializing the full m×n distance matrix, using a fused
distance+argmin kernel with atomic KeyValuePair reductions.

TPU design: the expanded-L2 trick makes the inner product the only O(m·n·k)
term — an MXU matmul. We block over rows of x; each block computes its
(bm, n) distance tile and reduces it to (bm,) argmin immediately, so only a
tile ever exists. XLA fuses the add-norms + argmin epilogue into the matmul
consumer, giving the same effect as the reference's fused kernel with zero
atomics (deterministic by construction).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from raft_tpu.core.config import auto_convert_output

def _block_rows(m: int, n: int, budget_elems: int = 1 << 22) -> int:
    bm = max(1, budget_elems // max(1, n))
    bm = min(bm, m)
    if bm >= 8:
        bm = bm // 8 * 8
    return max(1, bm)


def _fused_l2_nn(x: jax.Array, y: jax.Array, *, sqrt: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Dispatch: Pallas fused kernel on TPU, XLA-fused blocked path otherwise."""
    from raft_tpu import ops
    from raft_tpu.ops import fused_l2_argmin

    m, k = x.shape
    n = y.shape[0]
    if ops.use_pallas() and fused_l2_argmin.fits_pallas(m, n, k):
        from raft_tpu.distance import pairwise as _pw

        return fused_l2_argmin.fused_l2_argmin_pallas(
            x,
            y,
            sqrt=sqrt,
            interpret=ops.interpret_mode(),
            precision=_pw._MATMUL_PRECISION,  # honor set_matmul_precision
        )
    return _fused_l2_nn_xla(x, y, sqrt=sqrt)


@functools.partial(jax.jit, static_argnames=("sqrt",))
def _fused_l2_nn_xla(x: jax.Array, y: jax.Array, *, sqrt: bool = False) -> Tuple[jax.Array, jax.Array]:
    m, k = x.shape
    n = y.shape[0]
    yn = jnp.sum(y.astype(jnp.float32) ** 2, axis=1)  # (n,)
    bm = _block_rows(m, n)
    nblocks = -(-m // bm)
    pad = nblocks * bm - m
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    blocks = xp.reshape(nblocks, bm, k)

    def body(xb):
        from raft_tpu.distance.pairwise import _dot

        d = _dot(xb, y)
        xn = jnp.sum(xb.astype(jnp.float32) ** 2, axis=1)[:, None]
        dist = jnp.maximum(xn + yn[None, :] - 2.0 * d, 0.0)
        idx = jnp.argmin(dist, axis=1).astype(jnp.int32)
        best = jnp.min(dist, axis=1)
        return best, idx

    best, idx = lax.map(body, blocks)
    best = best.reshape(-1)[:m]
    idx = idx.reshape(-1)[:m]
    if sqrt:
        best = jnp.sqrt(best)
    return best, idx


@auto_convert_output
def fused_l2_nn_argmin(X, Y, sqrt: bool = False, resources=None) -> jax.Array:
    """Index of the nearest row of Y for each row of X (L2).

    pylibraft-compatible (distance/fused_l2_nn.pyx `fused_l2_nn_argmin`).
    """
    from raft_tpu.core.validation import check_matrix, check_same_cols

    x = check_matrix(X, name="X")
    y = check_matrix(Y, name="Y")
    check_same_cols(x, y, "X", "Y")
    _, idx = _fused_l2_nn(x, y, sqrt=sqrt)
    if resources is not None:
        resources.track(idx)
    return idx


def fused_l2_nn(X, Y, sqrt: bool = False, resources=None) -> Tuple[jax.Array, jax.Array]:
    """(min_distance, argmin) pairs — the KeyValuePair variant
    (`MinAndDistanceReduceOp`, detail/fused_l2_nn.cuh:42)."""
    from raft_tpu.core.validation import check_matrix, check_same_cols

    x = check_matrix(X, name="X")
    y = check_matrix(Y, name="Y")
    check_same_cols(x, y, "X", "Y")
    out = _fused_l2_nn(x, y, sqrt=sqrt)
    if resources is not None:
        resources.track(*out)
    return out
