"""Masked L2 nearest neighbors.

Reference parity: `raft::distance::masked_l2_nn` (distance/masked_nn.cuh,
detail/masked_distance_base.cuh, detail/compress_to_bits.cuh) — fused L2
argmin where each x-row only considers y-rows belonging to ALLOWED groups
(adjacency (m, n_groups) × group membership (n,)), the HDBSCAN workload.

TPU design: the reference compresses the mask to bitfields to skip tiles;
XLA prefers dense math with predication — we stream x row-blocks, compute
the (bm, n) distance tile on the MXU, apply the expanded group mask, and
argmin. Skipping is a bandwidth optimization the MXU rarely needs here
because the mask multiply fuses into the matmul epilogue.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def _masked_l2_nn(x, y, adj, group_of_y) -> Tuple[jax.Array, jax.Array]:
    m, k = x.shape
    n = y.shape[0]
    yn = jnp.sum(y.astype(jnp.float32) ** 2, axis=1)
    bm = max(1, min(m, (1 << 21) // max(1, n)))
    nblocks = -(-m // bm)
    pad = nblocks * bm - m
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    adjp = jnp.pad(adj, ((0, pad), (0, 0))) if pad else adj

    from raft_tpu.distance.pairwise import _dot

    def block(inp):
        xb, ab = inp  # (bm, k), (bm, n_groups)
        d = _dot(xb, y)
        xn = jnp.sum(xb.astype(jnp.float32) ** 2, axis=1)[:, None]
        dist = jnp.maximum(xn + yn[None, :] - 2.0 * d, 0.0)
        allowed = ab[:, group_of_y]  # (bm, n)
        dist = jnp.where(allowed, dist, jnp.inf)
        return jnp.min(dist, axis=1), jnp.argmin(dist, axis=1).astype(jnp.int32)

    dmin, idx = lax.map(block, (xp.reshape(nblocks, bm, k), adjp.reshape(nblocks, bm, -1)))
    return dmin.reshape(-1)[:m], idx.reshape(-1)[:m]


def masked_l2_nn(X, Y, adj, group_ids, sqrt: bool = False):
    """For each row of X, the nearest row of Y whose group is allowed by
    `adj[i]`. Returns (distances, indices); rows with no allowed group get
    (inf, -1). (masked_nn.cuh masked_l2_nn semantics.)"""
    x = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(Y, jnp.float32)
    a = jnp.asarray(adj, bool)
    g = jnp.asarray(group_ids).astype(jnp.int32)
    if a.shape[0] != x.shape[0]:
        raise ValueError("adj must have one row per X row")
    if g.shape[0] != y.shape[0]:
        raise ValueError("group_ids must have one entry per Y row")
    d, i = _masked_l2_nn(x, y, a, g)
    i = jnp.where(jnp.isfinite(d), i, -1)
    if sqrt:
        d = jnp.sqrt(d)
    return d, i
