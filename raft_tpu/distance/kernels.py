"""Kernel (Gram) matrices for SVM-style algorithms.

Reference parity: `raft::distance::kernels` (distance/kernels.cuh,
detail/kernels/{gram_matrix,kernel_matrices,kernel_factory}.cuh): linear,
polynomial, RBF, tanh kernels with a factory over `KernelParams`.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


class KernelType(enum.IntEnum):
    LINEAR = 0
    POLYNOMIAL = 1
    RBF = 2
    TANH = 3


@dataclasses.dataclass
class KernelParams:
    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def _dotm(x, y):
    from raft_tpu.distance.pairwise import _dot

    return _dot(jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32))


class GramMatrix:
    """GramMatrixBase parity: callable computing K(x1, x2)."""

    def __init__(self, params: KernelParams):
        self.params = params

    def __call__(self, x1, x2) -> jax.Array:
        p = self.params
        if p.kernel == KernelType.LINEAR:
            return _dotm(x1, x2)
        if p.kernel == KernelType.POLYNOMIAL:
            return (p.gamma * _dotm(x1, x2) + p.coef0) ** p.degree
        if p.kernel == KernelType.TANH:
            return jnp.tanh(p.gamma * _dotm(x1, x2) + p.coef0)
        if p.kernel == KernelType.RBF:
            x = jnp.asarray(x1, jnp.float32)
            y = jnp.asarray(x2, jnp.float32)
            d = _dotm(x, y)
            sq = (
                jnp.sum(x * x, axis=1)[:, None]
                + jnp.sum(y * y, axis=1)[None, :]
                - 2.0 * d
            )
            return jnp.exp(-p.gamma * jnp.maximum(sq, 0.0))
        raise ValueError(p.kernel)


def kernel_factory(params: KernelParams) -> GramMatrix:
    """KernelFactory::create parity."""
    return GramMatrix(params)


def gram_matrix(x1, x2, params: Optional[KernelParams] = None) -> jax.Array:
    return GramMatrix(params or KernelParams())(x1, x2)
