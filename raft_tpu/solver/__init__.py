"""Solvers: linear assignment (LAP).

TPU-native equivalent of `cpp/include/raft/solver/linear_assignment.cuh`
(survey §2.12; legacy alias lap/lap.cuh). The reference implements a
date–Hungarian augmenting-path GPU solver; on TPU the natural massively-
parallel formulation is Bertsekas' AUCTION algorithm with ε-scaling: every
unassigned row bids simultaneously (vectorized top-2 over its value row),
conflicts resolve with a dense argmax per object — all inside one
lax.while_loop, no sequential augmenting paths.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("maximize", "n_phases"))
def _auction(cost: jax.Array, maximize: bool = False, eps_start: float = 1.0,
             scaling: float = 0.2, n_phases: int = 6):
    """Auction LAP on an (n, n) cost matrix; returns col assignment per row."""
    n = cost.shape[0]
    benefit = (cost if maximize else -cost).astype(jnp.float32)
    neg = jnp.float32(-1e30)

    def phase(prices, eps):
        # row_of[j] = row owning object j (-1 none). col_of derived from it.
        row_of = jnp.full((n,), -1, jnp.int32)

        def col_of_fn(row_of):
            co = jnp.full((n,), -1, jnp.int32)
            valid = row_of >= 0
            return co.at[jnp.where(valid, row_of, 0)].set(
                jnp.where(valid, jnp.arange(n, dtype=jnp.int32), co[jnp.where(valid, row_of, 0)])
            )

        def cond(state):
            row_of, prices, it = state
            return jnp.any(col_of_fn(row_of) < 0) & (it < 50 * n + 200)

        def body(state):
            row_of, prices, it = state
            col_of = col_of_fn(row_of)
            unassigned = col_of < 0
            values = benefit - prices[None, :]
            v2, idx = lax.top_k(values, 2)
            best_j = idx[:, 0]
            bid = prices[best_j] + (v2[:, 0] - v2[:, 1]) + eps
            # (n_rows, n_objs) bid matrix; winner = argmax row per object
            onehot = jax.nn.one_hot(best_j, n, dtype=jnp.bool_)
            bids_mat = jnp.where(unassigned[:, None] & onehot, bid[:, None], neg)
            win_bid = jnp.max(bids_mat, axis=0)
            winner = jnp.argmax(bids_mat, axis=0).astype(jnp.int32)
            has = win_bid > neg
            prices = jnp.where(has, win_bid, prices)
            row_of = jnp.where(has, winner, row_of)
            return row_of, prices, it + 1

        row_of, prices, _ = lax.while_loop(
            cond, body, (row_of, prices, jnp.zeros((), jnp.int32))
        )
        return prices, row_of

    eps_seq = eps_start * (scaling ** jnp.arange(n_phases, dtype=jnp.float32))
    prices, row_ofs = lax.scan(phase, jnp.zeros((n,), jnp.float32), eps_seq)
    row_of = row_ofs[-1]
    # invert object->row into row->object
    col = jnp.full((n,), -1, jnp.int32)
    valid = row_of >= 0
    col = col.at[jnp.where(valid, row_of, 0)].set(
        jnp.where(valid, jnp.arange(n, dtype=jnp.int32), col[jnp.where(valid, row_of, 0)])
    )
    return col


def linear_assignment(cost, maximize: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Solve the LAP; returns (row_indices, col_assignment) minimizing
    sum(cost[i, col[i]]) (LinearAssignmentProblem.solve parity)."""
    c = jnp.asarray(cost, jnp.float32)
    if c.ndim != 2 or c.shape[0] != c.shape[1]:
        raise ValueError("cost must be square (n, n)")
    n = c.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.int32)
    spread = float(jnp.max(c) - jnp.min(c))
    col = _auction(c, maximize, eps_start=max(spread, 1e-3) / 2.0)
    return jnp.arange(n, dtype=jnp.int32), col


lap = linear_assignment  # legacy lap/lap.cuh alias
