"""Spectral clustering & embedding.

TPU-native equivalent of `cpp/include/raft/spectral/` (survey §2.12):
`partition` (spectral/partition.cuh:49 — Laplacian → Lanczos eigenvectors →
k-means on the embedding), `modularity_maximization.cuh`, `analyze_*`
quality metrics, and the solver wrappers (`eigen_solvers.cuh`
lanczos_solver_t, `cluster_solvers.cuh` kmeans_solver_t), plus
`sparse/linalg/spectral.cuh`'s `fit_embedding`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.sparse.formats import CsrMatrix, CooMatrix, coo_to_csr
from raft_tpu.sparse.linalg import laplacian_matvec, spmv
from raft_tpu.sparse.solver import lanczos


@dataclasses.dataclass
class EigenSolverConfig:
    """lanczos_solver_t config (spectral/eigen_solvers.hpp)."""

    n_eigenvecs: int = 2
    ncv: Optional[int] = None
    seed: int = 0


class LanczosSolver:
    """spectral::lanczos_solver_t parity."""

    def __init__(self, config: EigenSolverConfig):
        self.config = config

    def solve_smallest(self, matvec, n: int):
        return lanczos(
            matvec, n, self.config.n_eigenvecs, "smallest",
            ncv=self.config.ncv, seed=self.config.seed,
        )

    def solve_largest(self, matvec, n: int):
        return lanczos(
            matvec, n, self.config.n_eigenvecs, "largest",
            ncv=self.config.ncv, seed=self.config.seed,
        )


class KmeansSolver:
    """spectral::kmeans_solver_t parity."""

    def __init__(self, n_clusters: int, max_iter: int = 100, seed: int = 0):
        self.n_clusters = n_clusters
        self.max_iter = max_iter
        self.seed = seed

    def solve(self, embedding) -> jax.Array:
        from raft_tpu.cluster import kmeans

        centers, _, _ = kmeans.fit(
            embedding, n_clusters=self.n_clusters, max_iter=self.max_iter, seed=self.seed
        )
        return kmeans.predict(embedding, centers)


def fit_embedding(adj: CsrMatrix, n_components: int = 2, seed: int = 0,
                  normalized: bool = True) -> jax.Array:
    """Spectral embedding: smallest nontrivial Laplacian eigenvectors
    (sparse/linalg/spectral.cuh fit_embedding). Returns (n, n_components)."""
    mv = laplacian_matvec(adj, normalized=normalized)
    # drop the trivial constant eigenvector: compute k+1, skip the first
    vals, vecs = lanczos(mv, adj.shape[0], n_components + 1, "smallest", seed=seed)
    return vecs[:, 1:]


def partition(
    adj,
    n_clusters: int,
    n_eigenvecs: Optional[int] = None,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Spectral graph partition (spectral/partition.cuh:49).

    Returns (labels, eigenvalues, eigenvectors)."""
    if isinstance(adj, CooMatrix):
        adj = coo_to_csr(adj)
    k = n_eigenvecs or n_clusters
    mv = laplacian_matvec(adj, normalized=True)
    # Use the first k eigenvectors INCLUDING the smallest (partition.cuh
    # passes all nEigVecs to kmeans): for connected graphs the first is a
    # harmless constant; for disconnected graphs the Krylov null-space
    # mixture it carries is exactly the component indicator information.
    vals, vecs = lanczos(mv, adj.shape[0], k, "smallest", seed=seed)
    emb = vecs[:, :k]
    # row-normalize the embedding (standard normalized spectral clustering)
    emb = emb / jnp.maximum(jnp.linalg.norm(emb, axis=1, keepdims=True), 1e-12)
    labels = KmeansSolver(n_clusters, seed=seed).solve(emb)
    return labels, vals[:k], emb


def modularity_maximization(
    adj,
    n_clusters: int,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cluster by top eigenvectors of the modularity matrix
    (spectral/modularity_maximization.cuh): B = A - d d^T / (2m)."""
    if isinstance(adj, CooMatrix):
        adj = coo_to_csr(adj)
    n = adj.shape[0]
    deg = spmv(adj, jnp.ones((n,), jnp.float32))
    two_m = jnp.maximum(jnp.sum(deg), 1e-12)

    def mv(v):
        return spmv(adj, v) - deg * (jnp.dot(deg, v) / two_m)

    vals, vecs = lanczos(mv, n, n_clusters, "largest", seed=seed)
    emb = vecs / jnp.maximum(jnp.linalg.norm(vecs, axis=1, keepdims=True), 1e-12)
    labels = KmeansSolver(n_clusters, seed=seed).solve(emb)
    return labels, vals, emb


def analyze_partition(adj, labels, n_clusters: int) -> Tuple[float, float]:
    """(edge_cut, cost) of a partition (spectral/partition.cuh analyzePartition)."""
    if isinstance(adj, CooMatrix):
        adj = coo_to_csr(adj)
    import numpy as np

    from raft_tpu.sparse.formats import csr_to_coo

    coo = csr_to_coo(adj)
    l = np.asarray(labels)
    r, c, v = np.asarray(coo.rows), np.asarray(coo.cols), np.asarray(coo.vals)
    cut = float(v[l[r] != l[c]].sum()) / 2.0
    sizes = np.bincount(l, minlength=n_clusters).astype(np.float64)
    cost = float((sizes**2).sum())
    return cut, cost


def modularity(adj, labels) -> float:
    """Modularity Q of a labeling (analyze_modularity)."""
    if isinstance(adj, CooMatrix):
        adj = coo_to_csr(adj)
    import numpy as np

    from raft_tpu.sparse.formats import csr_to_coo

    coo = csr_to_coo(adj)
    l = np.asarray(labels)
    r, c, v = np.asarray(coo.rows), np.asarray(coo.cols), np.asarray(coo.vals)
    two_m = v.sum()
    intra = v[l[r] == l[c]].sum()
    deg = np.zeros(adj.shape[0])
    np.add.at(deg, r, v)
    k = np.bincount(l, weights=deg)
    return float(intra / two_m - ((k / two_m) ** 2).sum())
