"""Host-side utilities (reference `cpp/include/raft/util/`, survey §2.2).

Most of the reference's util layer (warp shuffles, vectorized loads, device
atomics, bitonic sort) is subsumed by XLA/Pallas on TPU; what remains useful
on the host is the power-of-two tiling math (`util/pow2_utils.cuh`), integer
helpers (`util/integer_utils.hpp`), the LRU cache (`util/cache.cuh:34` — an
associative device cache; here a host-side LRU used to memoize expensive
host artifacts such as packed slot tables), and the prime sieve
(`util/seive.hpp`).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Hashable, Iterator, Optional

__all__ = [
    "Pow2",
    "ceil_div",
    "round_up_safe",
    "round_down_safe",
    "is_pow2",
    "next_pow2",
    "prev_pow2",
    "log2_int",
    "LRUCache",
    "Sieve",
]


def ceil_div(a: int, b: int) -> int:
    """ceil(a/b) for non-negative ints (util/integer_utils.hpp ceildiv)."""
    if b <= 0:
        raise ValueError("divisor must be positive")
    return -(-a // b)


def round_up_safe(a: int, multiple: int) -> int:
    """Smallest multiple of `multiple` >= a (util/integer_utils.hpp)."""
    return ceil_div(a, multiple) * multiple


def round_down_safe(a: int, multiple: int) -> int:
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    return a // multiple * multiple


def is_pow2(v: int) -> bool:
    return v > 0 and (v & (v - 1)) == 0


def next_pow2(v: int) -> int:
    """Smallest power of two >= v."""
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def prev_pow2(v: int) -> int:
    """Largest power of two <= v."""
    if v < 1:
        raise ValueError("v must be >= 1")
    return 1 << (v.bit_length() - 1)


def log2_int(v: int) -> int:
    if not is_pow2(v):
        raise ValueError(f"{v} is not a power of two")
    return v.bit_length() - 1


class Pow2:
    """Power-of-two alignment math (util/pow2_utils.cuh `Pow2<Value>`).

    The same quotient/remainder/round/align helpers the reference uses for
    warp- and tile-granularity math; on TPU this is the block-shape
    arithmetic used when choosing Pallas grids and padded table sizes.
    """

    def __init__(self, value: int):
        if not is_pow2(value):
            raise ValueError(f"Pow2 value must be a power of two, got {value}")
        self.value = value
        self.mask = value - 1
        self.log2 = log2_int(value)

    def quot(self, x: int) -> int:
        return x >> self.log2

    def rem(self, x: int) -> int:
        return x & self.mask

    def div(self, x: int) -> tuple[int, int]:
        return self.quot(x), self.rem(x)

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0


class LRUCache:
    """Thread-safe host LRU cache (util/cache.cuh:34 `cache::Cache` role).

    The reference caches device buffers keyed by integer ids with
    set-associative eviction; here a plain LRU memoizes host-side artifacts
    (packed slot tables, loaded index files, compiled native handles).
    """

    def __init__(self, capacity: int = 128):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._store: "collections.OrderedDict[Hashable, Any]" = collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            if key in self._store:
                self._store.move_to_end(key)
                self.hits += 1
                return self._store[key]
            self.misses += 1
            return default

    def put(self, key: Hashable, value: Any) -> None:
        with self._lock:
            if key in self._store:
                self._store.move_to_end(key)
            self._store[key] = value
            while len(self._store) > self.capacity:
                self._store.popitem(last=False)

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._store

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)


class Sieve:
    """Prime sieve (util/seive.hpp) — odd-only bitset of primes up to n."""

    def __init__(self, n: int):
        self.n = n
        size = max(0, (n + 1) // 2)
        self._odd = bytearray([1]) * size if size else bytearray()
        if size:
            self._odd[0] = 0  # 1 is not prime
        i = 3
        while i * i <= n:
            if self._odd[i // 2]:
                for j in range(i * i, n + 1, 2 * i):
                    self._odd[j // 2] = 0
            i += 2

    def is_prime(self, v: int) -> bool:
        if v == 2:
            return self.n >= 2
        if v < 2 or v % 2 == 0 or v > self.n:
            return False
        return bool(self._odd[v // 2])

    def primes(self) -> Iterator[int]:
        if self.n >= 2:
            yield 2
        for v in range(3, self.n + 1, 2):
            if self._odd[v // 2]:
                yield v
