"""select_k: batched top-k selection — the ANN performance spine.

Reference parity: `raft::matrix::select_k` (matrix/select_k.cuh:78) selects
the k smallest (or largest) elements per row with their indices. The CUDA
implementation dispatches between warp-level bitonic queues
(detail/select_warpsort.cuh) and multi-pass radix select
(detail/select_radix.cuh) based on k/len/batch (detail/select_k.cuh:67-88).

TPU design: `jax.lax.top_k` lowers to an XLA sort-based TopK that is already
heavily tuned for TPU for moderate len. For very large rows we use a
two-phase selection mirroring the reference's strategy split: partition each
row into chunks, take a per-chunk top-k on-chip (phase 1, bandwidth-bound
streaming pass), then merge the per-chunk candidates with a final top-k
(phase 2) — the same shape as warpsort's per-warp queues + block merge.
Selecting the smallest is implemented by negation (top_k selects largest).

This module is the ONE dispatch layer for every top-k decision:

  - `select_k(values, ...)` — matrix input, strategies "topk" /
    "two_phase" / "counting" (explicit or promoted by the tuned
    `select_k_strategy` key measured by bench_select_k_strategies).
  - `scan_select_k(queries, dataset, ...)` — OPERAND input: the scores
    are a derived quantity, so the "fused" strategy can hand the whole
    scan+select to the fused Pallas kernel (ops/fused_scan.py) and the
    (n_queries, n_rows) score matrix never materializes in HBM — the
    TPU-KNN fusion (arxiv 2206.14286) behind ROADMAP item 1. The
    "two_phase" strategy is the materializing reference path the fused
    kernel must bit-agree with (tests/test_fused_scan.py).

Engines (brute_force, ivf_flat, ivf_pq, refine) ask this layer for
top-k and never pick kernels; `select_k_strategy` is resolved via
`core/tuned.py` exactly like `flat_auto_engine`.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Rows longer than this go through the two-phase chunked path.
_CHUNK_THRESHOLD = 1 << 16
_CHUNK = 1 << 14

# dtypes whose values embed exactly in f32 — the one list both the
# explicit strategy="counting" validation and the tuned auto-promotion
# gate consult (int32+ and f64 would silently lose precision)
_COUNTING_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16,
                    jnp.int8, jnp.int16, jnp.uint8, jnp.uint16)


def _two_phase_largest(vals: jax.Array, k: int,
                       chunk: int = _CHUNK) -> Tuple[jax.Array, jax.Array]:
    """Two-phase chunked top-k (warpsort-queues + block-merge shape):
    per-chunk top-k (streaming pass), then a merge top-k over candidates.
    Exposed separately so the strategy bench can race it against plain
    lax.top_k / approx_max_k at any shape."""
    batch = vals.shape[:-1]
    n = vals.shape[-1]
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:
        vals = jnp.pad(vals, [(0, 0)] * len(batch) + [(0, pad)], constant_values=-jnp.inf)
    chunked = vals.reshape(*batch, nchunks, chunk)
    cvals, cidx = lax.top_k(chunked, min(k, chunk))  # (..., nchunks, kc)
    base = (jnp.arange(nchunks, dtype=cidx.dtype) * chunk)[:, None]
    cidx = cidx + base  # chunk-local -> row-global indices
    # phase 2: merge candidates
    cand_vals = cvals.reshape(*batch, -1)
    cand_idx = cidx.reshape(*batch, -1)
    mvals, midx = lax.top_k(cand_vals, k)
    out_idx = jnp.take_along_axis(cand_idx, midx, axis=-1)
    return mvals, out_idx


#: matrix-input strategies the tuned `select_k_strategy` key may name
#: ("fused" is operand-level only — a materialized matrix can't fuse)
_MATRIX_STRATEGIES = ("topk", "two_phase", "counting")


def _tuned_strategy():
    """The measured `select_k_strategy` winner (bench --apply writes it),
    or None. The ONE tuned policy every top-k call site consults; an
    out-of-set value degrades to None (heuristics), never crashes."""
    from raft_tpu.core import tuned

    t = tuned.get("select_k_strategy")
    return t if t in _MATRIX_STRATEGIES + ("fused",) else None


def _tuned_chunk_threshold():
    """Validated on-chip-measured chunk threshold, or None. A hand-merged
    or corrupt tuned value must degrade to the built-in heuristic, not
    crash the ANN spine (ivf_pq/ivf_flat guard their tuned keys the same
    way)."""
    from raft_tpu.core import tuned

    t = tuned.get("select_k_chunk_threshold")
    if not isinstance(t, (int, float)) or isinstance(t, bool) or t <= 0:
        return None
    return int(t)


def _top_k_largest(vals: jax.Array, k: int,
                   chunk_threshold: int = None,
                   forced: str = None) -> Tuple[jax.Array, jax.Array]:
    """top-k largest per row; two-phase for long rows. The length
    threshold is measured on-chip (bench_select_k_strategies --apply
    writes it into the tuned defaults). Public select_k reads it OUTSIDE
    jit and threads it through as a static argument (reload-aware); the
    internal ANN-spine callers reach here inside their own traces with
    chunk_threshold=None, so the tuned value is read at trace time — a
    later tuned.reload() only affects newly-traced shapes, which is fine:
    the --apply writers run in fresh processes per on-chip queue step."""
    n = vals.shape[-1]
    # an explicit caller strategy, else the measured tuned winner,
    # overrides the length heuristic (but the two-phase guards stay: a
    # row that fits one chunk, or a k too large for the per-chunk
    # phase, degenerates to plain top_k anyway)
    if forced is None:
        forced = _tuned_strategy()
    if forced == "topk":
        return lax.top_k(vals, k)
    if forced == "two_phase":
        if n > 2 * _CHUNK and k <= _CHUNK // 4:
            return _two_phase_largest(vals, k)
        return lax.top_k(vals, k)
    if chunk_threshold is None:
        chunk_threshold = _tuned_chunk_threshold()
    thresh = _CHUNK_THRESHOLD if chunk_threshold is None else int(chunk_threshold)
    if n <= thresh or n <= 2 * _CHUNK or k > _CHUNK // 4:
        return lax.top_k(vals, k)
    return _two_phase_largest(vals, k)


def _counting_promoted(vals, k: int) -> bool:
    """Trace-time gate for the measured counting-engine promotion,
    shared by the public API and `_select_k_impl` so internal hot paths
    (the brute-force per-tile select, IVF merges) also benefit from an
    on-chip strategy win. Exact engine — the flip is purely perf."""
    from raft_tpu.core import tuned
    from raft_tpu.core.config import is_tpu_backend

    promoted = (tuned.get("select_k_auto_strategy") == "counting"
                or _tuned_strategy() == "counting")
    if (
        not promoted
        or not is_tpu_backend()  # Mosaic kernel, chip-measured: CPU would
        # interpret (orders slower), GPU would fail to lower
        or vals.ndim != 2
        or vals.dtype not in _COUNTING_DTYPES
    ):
        return False
    from raft_tpu.ops.select_counting import fits_counting

    padded = vals.shape[-1] + (-vals.shape[-1]) % 128
    return bool(fits_counting(vals.shape[0], padded, int(k)))


@functools.partial(
    jax.jit, static_argnames=("k", "select_min", "chunk_threshold", "forced")
)
def _select_k_impl(vals: jax.Array, k: int, select_min: bool,
                   chunk_threshold: int = None, forced: str = None):
    if forced is None and _counting_promoted(vals, k):
        return _select_k_counting(vals, k, select_min)
    if select_min:
        # negate; NaNs/infs: -inf stays worst under negation of +inf
        v, i = _top_k_largest(-vals, k, chunk_threshold, forced)
        return -v, i
    return _top_k_largest(vals, k, chunk_threshold, forced)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "interpret"))
def _select_k_counting(vals: jax.Array, k: int, select_min: bool,
                       interpret: bool = False):
    """Pallas counting-select engine (ops/select_counting.py): exact
    threshold via in-VMEM bit-fixing, then a tiny (B, k) sort for the
    best-first output contract. Opt-in (strategy="counting") until the
    on-chip strategy bench decides where it wins."""
    from raft_tpu.ops.select_counting import counting_select_min

    n = vals.shape[-1]
    pad = (-n) % 128
    # cast BEFORE negating: integer negation wraps (int8 -128 -> -128,
    # unsigned mod 2^n), f32 negation is exact for every admitted dtype
    v = vals.astype(jnp.float32)
    if not select_min:
        v = -v
    if pad:
        v = jnp.pad(v, ((0, 0), (0, pad)), constant_values=jnp.inf)
    cv, ci = counting_select_min(v, k, interpret=interpret)
    # finish: best-first order over the k survivors (tiny)
    sv, order = lax.top_k(-cv, k)
    iv = jnp.take_along_axis(ci, order, axis=-1)
    out = -sv if select_min else sv
    # match every other strategy's contract: values keep the input dtype
    # (exact: all admitted dtypes embed in f32)
    return out.astype(vals.dtype), iv


def select_k(
    values,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
    resources=None,
    strategy: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (default) or largest values per row.

    Returns (values, indices), both shaped (batch, k), sorted best-first —
    matching matrix/select_k.cuh semantics. `indices`, when given, maps
    row-local positions to caller ids (the reference's `in_idx` optional
    input used by tile merging).

    `strategy`: None/"auto" picks the measured default (the tuned
    `select_k_strategy` winner when set, else lax.top_k or the
    two-phase chunked path by shape); "topk" forces plain top_k;
    "two_phase" forces the chunked warpsort-shaped path; "counting"
    opts into the Pallas counting-select engine
    (ops/select_counting.py), the radix-select analogue aimed at large
    rows — all exact, raced by bench/bench_select_k_strategies.py.
    For top-k over operands (queries x dataset) use `scan_select_k`,
    which adds the "fused" strategy.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.matrix import select_k
    >>> v, i = select_k(np.array([[3.0, 1.0, 2.0], [0.5, 4.0, 0.25]]), 2)
    >>> np.asarray(i).tolist()
    [[1, 2], [2, 0]]
    >>> np.asarray(v).tolist()
    [[1.0, 2.0], [0.25, 0.5]]
    """
    from raft_tpu.core.validation import as_array

    vals = as_array(values)
    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[None, :]
    if not (0 < k <= vals.shape[-1]):
        raise ValueError(f"k={k} out of range for row length {vals.shape[-1]}")
    if strategy not in (None, "auto", "topk", "two_phase", "counting"):
        raise ValueError(f"unknown select_k strategy {strategy!r}")
    if strategy in (None, "auto"):
        # a measured on-chip winner can promote the counting engine for
        # the shapes it fits (shared gate with _select_k_impl, so
        # internal hot paths get the same flip). The kernel is strictly
        # 2-D; higher-rank batches keep the ndim-agnostic default path.
        if _counting_promoted(vals, k):
            strategy = "counting"
    if strategy == "counting":
        # the engine works on the f32 order image; only dtypes that embed
        # exactly in f32 keep the documented exact-selection contract
        if vals.dtype not in _COUNTING_DTYPES:
            raise ValueError(
                f"strategy='counting' requires an f32-embeddable dtype, got {vals.dtype}"
            )
        interp = jax.default_backend() == "cpu"  # Mosaic needs TPU
        v, i = _select_k_counting(vals, int(k), bool(select_min), interp)
    else:
        v, i = _select_k_impl(
            vals, int(k), bool(select_min), _tuned_chunk_threshold(),
            forced=strategy if strategy in ("topk", "two_phase") else None,
        )
    if indices is not None:
        idx = as_array(indices)
        if idx.ndim == 1:
            idx = idx[None, :]
        i = jnp.take_along_axis(idx, i, axis=-1)
    if squeeze:
        v, i = v[0], i[0]
    if resources is not None:
        resources.track(v, i)
    return v, i


# ---------------------------------------------------------------------------
# operand-level dispatch: scan + select in one decision
# ---------------------------------------------------------------------------

#: strategies scan_select_k accepts (None/"auto" resolves via the tuned
#: `select_k_strategy` key, like ivf_flat's `flat_auto_engine`)
SCAN_STRATEGIES = ("fused", "two_phase")


def _fused_metric_kind(metric):
    """("l2"|"ip", want_sqrt) when the fused kernel covers `metric`,
    else None — the one gate both the auto-resolution and the explicit
    validation consult."""
    from raft_tpu.distance.distance_types import DistanceType as D

    if metric == D.InnerProduct:
        return "ip", False
    if metric in (D.L2Expanded, D.L2Unexpanded):
        return "l2", False
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        return "l2", True
    return None


def resolve_scan_strategy(n_rows: int, dim: int, k: int,
                          strategy=None, fused_ok: bool = True) -> str:
    """Resolve a scan_select_k strategy: explicit wins; else the tuned
    `select_k_strategy` winner promotes "fused" when the kernel fits
    (TPU backend, supported metric, k/VMEM envelope); else the
    materializing two-phase reference path."""
    if strategy in SCAN_STRATEGIES:
        return strategy
    if strategy not in (None, "auto"):
        raise ValueError(f"unknown scan_select_k strategy {strategy!r}")
    if fused_ok and _tuned_strategy() == "fused":
        from raft_tpu.core.config import is_tpu_backend
        from raft_tpu.ops.fused_scan import fits_fused

        if is_tpu_backend() and fits_fused(1, n_rows, dim, k):
            return "fused"
    return "two_phase"


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "interpret", "fault_key")
)
def _scan_fused_impl(queries, dataset, k: int, metric, valid=None,
                     interpret: bool = False, fault_key=None):
    """Fused scan+select: distances and selection in one Pallas kernel,
    score matrix never in HBM (ops/fused_scan.py)."""
    from raft_tpu.ops.fused_scan import fused_topk

    kind, want_sqrt = _fused_metric_kind(metric)
    ip = kind == "ip"
    vc, ids = fused_topk(
        jnp.asarray(queries, jnp.float32), jnp.asarray(dataset, jnp.float32),
        k, inner_product=ip, valid=valid, interpret=interpret,
        fault_key=fault_key,
    )
    vc, ids = vc[:, :k], ids[:, :k]
    ids = jnp.where(jnp.isfinite(vc), ids, -1)
    if ip:
        return -vc, ids  # exhausted slots: -inf, the IP worst
    # the kernel scores the bf16-rounded geometry; |q|^2 must be the
    # SAME rounded rows or near-tie ranks and values drift apart
    qb = jnp.asarray(queries, jnp.float32).astype(jnp.bfloat16).astype(
        jnp.float32
    )
    qn = jnp.sum(qb * qb, axis=1, keepdims=True)
    v = jnp.maximum(vc + qn, 0.0)
    return (jnp.sqrt(v) if want_sqrt else v), ids


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _scan_two_phase_impl(queries, dataset, k: int, metric, valid=None):
    """The materializing reference: full pairwise distances + the
    matrix-input select (exactly the path the fused kernel must agree
    with — and the fallback wherever fused doesn't fit)."""
    from raft_tpu.distance.distance_types import SIMILARITY_METRICS
    from raft_tpu.distance.pairwise import _pairwise_impl

    select_min = metric not in SIMILARITY_METRICS
    worst = jnp.inf if select_min else -jnp.inf
    d = _pairwise_impl(queries, dataset, metric)
    if valid is not None:
        d = jnp.where(valid[None, :], d, worst)
    # forced: THIS strategy is the named reference — a tuned counting/
    # topk promotion must not silently swap the kernel under the
    # "two_phase" label (the bench race and the agreement tests both
    # compare against what this path actually runs)
    v, i = _select_k_impl(d, k, select_min, forced="two_phase")
    # one public contract across strategies: a slot holding the worst
    # value (sub-k survivors under a valid mask) reports id -1, exactly
    # like the fused path — not the masked row's id top_k happens to
    # surface
    i = jnp.where(jnp.isfinite(v), i, -1)
    return v, i.astype(jnp.int32)


def scan_select_k(
    queries,
    dataset,
    k: int,
    metric="sqeuclidean",
    strategy: Optional[str] = None,
    valid=None,
    resources=None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k nearest dataset rows per query, dispatched over OPERANDS:
    the caller never materializes (or even sees) the score matrix.

    Returns (values, indices), each (n_queries, k), best-first.
    `strategy`: None/"auto" resolves via the tuned `select_k_strategy`
    key; "fused" = the fused Pallas distance+select-k kernel
    (L2/inner-product, k <= ops.fused_scan.FUSED_MAX_K; exact, with
    ties broken to the smaller row id, over the bf16-rounded operands);
    "two_phase" = materialize pairwise distances and run the matrix
    select (any metric, f32). `valid`: optional (n_rows,) bool mask —
    False rows are excluded before selection; when fewer than k rows
    survive, the tail holds the worst value with index -1 on BOTH
    strategies (the prefilter contract).
    """
    from raft_tpu.core.validation import check_matrix, check_same_cols
    from raft_tpu.distance.distance_types import resolve_metric

    q = check_matrix(queries, name="queries")
    ds = check_matrix(dataset, name="dataset")
    check_same_cols(ds, q, "dataset", "queries")
    if not (0 < k <= ds.shape[0]):
        raise ValueError(f"k={k} out of range for dataset with {ds.shape[0]} rows")
    m = resolve_metric(metric)
    fused_ok = _fused_metric_kind(m) is not None
    strat = resolve_scan_strategy(
        ds.shape[0], ds.shape[1], int(k), strategy, fused_ok=fused_ok
    )
    if strat == "fused":
        from raft_tpu.ops.fused_scan import FUSED_MAX_K, fits_fused

        if not fused_ok:
            raise ValueError(
                f"strategy='fused' supports L2/inner_product metrics, got {m}"
            )
        if not fits_fused(q.shape[0], ds.shape[0], ds.shape[1], int(k)):
            raise ValueError(
                f"strategy='fused' caps k at {FUSED_MAX_K} and the tile at "
                "the kernel's VMEM envelope; use strategy='two_phase'"
            )
        from raft_tpu.core import faults

        v, i = _scan_fused_impl(
            q, ds, int(k), m,
            valid=None if valid is None else jnp.asarray(valid, bool),
            interpret=jax.default_backend() == "cpu",  # Mosaic needs TPU
            fault_key=faults.trace_key(),
        )
    else:
        v, i = _scan_two_phase_impl(
            q, ds, int(k), m,
            valid=None if valid is None else jnp.asarray(valid, bool),
        )
    if resources is not None:
        resources.track(v, i)
    return v, i


# ---------------------------------------------------------------------------
# list-scan dispatch: the IVF engines' fused kernels, one chooser
# ---------------------------------------------------------------------------
#
# The IVF list-major engines (ivf_flat / ivf_pq / ivf_rabitq and their
# MNMG drivers) never import a kernel from ops directly — they ask THIS
# layer for a per-list fused scan+select. Strategy names extend the
# scan_select_k family onto the integer datapath (ISSUE 11):
#
#   "fused"          bf16 MXU scoring (the PR-10 family)
#   "fused_int8"     int8 x int8 -> int32 MXU scoring, per-row dequant
#                    (v5e: 394 int8 TOPS vs 197 bf16 TFLOP/s)
#   "fused_bitplane" uint32 AND+popcount RaBitQ bit-plane scoring with
#                    the unbiased estimator correction in-kernel
#
# Tuned promotion mirrors `select_k_strategy`: each integer family has
# its own measured key (flipped by bench_select_k_strategies --apply on
# chip data only), consulted ONLY by auto resolution — explicit
# strategies always win, and an explicit request past the kernel's
# envelope raises instead of silently falling back.

#: strategies the list-scan dispatch accepts
LIST_SCAN_STRATEGIES = ("fused", "fused_int8")

#: tuned keys promoting the integer fused scans — re-exported from the
#: ONE registry spelling (core.tuned.TUNED_KEYS; raftlint's
#: `tuned-key-registry` pins every `*_KEY` constant to it)
from raft_tpu.core.tuned import (  # noqa: E402
    BITPLANE_SCAN_KEY,
    INT8_SCAN_KEY,
)


def resolve_int8_trim_strategy(L: int, rot: int, k: int,
                               kbuf: Optional[int] = None,
                               strategy: Optional[str] = None):
    """Resolve the IVF-PQ int8 recon trim: explicit "fused_int8" wins
    (envelope-checked at the call site, which raises); None/"auto"
    promotes the fused int8 kernel only when the measured tuned key
    names it, the backend is TPU, and the geometry fits — else None
    (the caller keeps its reference trim)."""
    if strategy == "fused_int8":
        return strategy
    if strategy not in (None, "auto"):
        raise ValueError(f"unknown int8 trim strategy {strategy!r}")
    from raft_tpu.core import tuned

    if tuned.get(INT8_SCAN_KEY) != "fused_int8":
        return None
    from raft_tpu.core.config import is_tpu_backend
    from raft_tpu.ops.fused_scan import fits_fused_list

    if is_tpu_backend() and fits_fused_list(128, L, rot, int(k),
                                            store_itemsize=1, kbuf=kbuf):
        return "fused_int8"
    return None


def resolve_bitplane_strategy(L: int, words: int, bits: int, k: int,
                              kbuf: Optional[int] = None,
                              strategy: Optional[str] = None) -> str:
    """Resolve the RaBitQ scan engine: "xla" is the materializing
    bit-plane reference (`_search_impl_rabitq`); "fused_bitplane" the
    in-kernel scan. Explicit wins (the call site validates the envelope
    and raises past it); None/"auto" promotes fused only on a tuned
    chip-measured winner where the kernel fits."""
    if strategy in ("xla", "fused_bitplane"):
        return strategy
    if strategy not in (None, "auto"):
        raise ValueError(f"unknown bitplane scan strategy {strategy!r}")
    from raft_tpu.core import tuned

    if tuned.get(BITPLANE_SCAN_KEY) != "fused_bitplane":
        return "xla"
    from raft_tpu.core.config import is_tpu_backend
    from raft_tpu.ops.fused_scan import fits_fused_bitplane

    if is_tpu_backend() and fits_fused_bitplane(128, L, words, bits,
                                                int(k), kbuf=kbuf):
        return "fused_bitplane"
    return "xla"


def check_fused_list_request(label: str, L: int, rot: int, k: int,
                             store_itemsize: int, kbuf: Optional[int],
                             fallback: str) -> int:
    """Validate an EXPLICIT fused list-scan request against the kernel
    caps/envelope — the ONE copy of the 'explicit requests raise past
    the envelope' rule every engine call site (single-chip and MNMG)
    shares. Returns the candidate-buffer width the kernel must run
    with (>= the caller's recorded monotone `kbuf`)."""
    from raft_tpu.ops.fused_scan import (
        FUSED_MAX_K, fits_fused_list, fused_kbuf,
    )

    if int(k) > FUSED_MAX_K:
        raise ValueError(
            f"{label} caps per-list candidates at {FUSED_MAX_K}; k={k}"
        )
    kb = max(fused_kbuf(int(k)), kbuf or 0)
    if not fits_fused_list(128, L, rot, int(k),
                           store_itemsize=store_itemsize, kbuf=kb):
        raise ValueError(
            f"{label}: list length {L} exceeds the kernel's VMEM "
            f"envelope; use {fallback}"
        )
    return kb


def check_bitplane_request(label: str, L: int, words: int, bits: int,
                           k: int, kbuf: Optional[int],
                           fallback: str) -> int:
    """`check_fused_list_request` for the bit-plane geometry (the
    RaBitQ scan engines, single-chip and MNMG)."""
    from raft_tpu.ops.fused_scan import (
        FUSED_MAX_K, fits_fused_bitplane, fused_kbuf,
    )

    if int(k) > FUSED_MAX_K:
        raise ValueError(
            f"{label} caps scan candidates at {FUSED_MAX_K}; "
            f"rerank depth {k}"
        )
    kb = max(fused_kbuf(int(k)), kbuf or 0)
    if not fits_fused_bitplane(128, L, words, int(bits), int(k), kbuf=kb):
        raise ValueError(
            f"{label}: list length {L} exceeds the kernel's VMEM "
            f"envelope; use {fallback}"
        )
    return kb


def list_scan_select_k(
    lof, qres, store, base, k: int,
    strategy: str = "fused",
    q_scale=None,
    kbuf: Optional[int] = None,
    inner_product: bool = False,
    interpret: bool = False,
    fault_key=None,
    chunk_valid=None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-list fused scan+select over a slot-table store — the list
    geometry's `scan_select_k`. Returns ((ncb, chunk, kbuf) minimizing
    scores, in-list slots), best-first, exactly the `ops.fused_scan`
    list contract. `strategy`: "fused" casts the store to bf16 for the
    MXU matmul; "fused_int8" requires int8 `qres` + `store` and the
    (ncb, chunk, 1) `q_scale` per-row dequant operand, and scores on
    the int8 MXU path. Engines pass their recorded monotonic `kbuf`.
    `chunk_valid` ((ncb,) int32, probe_invert.chunk_validity): empty
    chunks — trailing fragmentation, or chunks adaptive probe budgets
    emptied — skip their MXU work in-kernel."""
    if strategy not in LIST_SCAN_STRATEGIES:
        raise ValueError(f"unknown list-scan strategy {strategy!r}")
    if strategy == "fused_int8":
        if q_scale is None:
            raise ValueError("strategy='fused_int8' requires q_scale")
        from raft_tpu.ops.fused_scan import fused_list_topk_int8

        return fused_list_topk_int8(
            lof, qres, store, base, q_scale, int(k), kbuf=kbuf,
            inner_product=inner_product, interpret=interpret,
            fault_key=fault_key, chunk_valid=chunk_valid,
        )
    if q_scale is not None:
        raise ValueError("q_scale requires strategy='fused_int8'")
    from raft_tpu.ops.fused_scan import fused_list_topk

    return fused_list_topk(
        lof, qres, store, base, int(k), kbuf=kbuf,
        inner_product=inner_product, interpret=interpret,
        fault_key=fault_key, chunk_valid=chunk_valid,
    )


def bitplane_scan_select_k(
    lof, planes, codes_t, meta, base, qmeta, k: int,
    rot_dim: int,
    bits: int,
    kbuf: Optional[int] = None,
    inner_product: bool = False,
    interpret: bool = False,
    fault_key=None,
    chunk_valid=None,
) -> Tuple[jax.Array, jax.Array]:
    """The RaBitQ bit-plane fused scan+select (strategy
    "fused_bitplane") — operand contract of
    `ops.fused_scan.fused_bitplane_topk`, reached through this layer so
    the kernel has exactly one consumer-facing door. `chunk_valid`:
    the empty-chunk skip path (see `list_scan_select_k`)."""
    from raft_tpu.ops.fused_scan import fused_bitplane_topk

    return fused_bitplane_topk(
        lof, planes, codes_t, meta, base, qmeta, int(k),
        rot_dim=int(rot_dim), bits=int(bits), kbuf=kbuf,
        inner_product=inner_product, interpret=interpret,
        fault_key=fault_key, chunk_valid=chunk_valid,
    )
