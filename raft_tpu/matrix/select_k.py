"""select_k: batched top-k selection — the ANN performance spine.

Reference parity: `raft::matrix::select_k` (matrix/select_k.cuh:78) selects
the k smallest (or largest) elements per row with their indices. The CUDA
implementation dispatches between warp-level bitonic queues
(detail/select_warpsort.cuh) and multi-pass radix select
(detail/select_radix.cuh) based on k/len/batch (detail/select_k.cuh:67-88).

TPU design: `jax.lax.top_k` lowers to an XLA sort-based TopK that is already
heavily tuned for TPU for moderate len. For very large rows we use a
two-phase selection mirroring the reference's strategy split: partition each
row into chunks, take a per-chunk top-k on-chip (phase 1, bandwidth-bound
streaming pass), then merge the per-chunk candidates with a final top-k
(phase 2) — the same shape as warpsort's per-warp queues + block merge.
Selecting the smallest is implemented by negation (top_k selects largest).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Rows longer than this go through the two-phase chunked path.
_CHUNK_THRESHOLD = 1 << 16
_CHUNK = 1 << 14

# dtypes whose values embed exactly in f32 — the one list both the
# explicit strategy="counting" validation and the tuned auto-promotion
# gate consult (int32+ and f64 would silently lose precision)
_COUNTING_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16,
                    jnp.int8, jnp.int16, jnp.uint8, jnp.uint16)


def _two_phase_largest(vals: jax.Array, k: int,
                       chunk: int = _CHUNK) -> Tuple[jax.Array, jax.Array]:
    """Two-phase chunked top-k (warpsort-queues + block-merge shape):
    per-chunk top-k (streaming pass), then a merge top-k over candidates.
    Exposed separately so the strategy bench can race it against plain
    lax.top_k / approx_max_k at any shape."""
    batch = vals.shape[:-1]
    n = vals.shape[-1]
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    if pad:
        vals = jnp.pad(vals, [(0, 0)] * len(batch) + [(0, pad)], constant_values=-jnp.inf)
    chunked = vals.reshape(*batch, nchunks, chunk)
    cvals, cidx = lax.top_k(chunked, min(k, chunk))  # (..., nchunks, kc)
    base = (jnp.arange(nchunks, dtype=cidx.dtype) * chunk)[:, None]
    cidx = cidx + base  # chunk-local -> row-global indices
    # phase 2: merge candidates
    cand_vals = cvals.reshape(*batch, -1)
    cand_idx = cidx.reshape(*batch, -1)
    mvals, midx = lax.top_k(cand_vals, k)
    out_idx = jnp.take_along_axis(cand_idx, midx, axis=-1)
    return mvals, out_idx


def _tuned_chunk_threshold():
    """Validated on-chip-measured chunk threshold, or None. A hand-merged
    or corrupt tuned value must degrade to the built-in heuristic, not
    crash the ANN spine (ivf_pq/ivf_flat guard their tuned keys the same
    way)."""
    from raft_tpu.core import tuned

    t = tuned.get("select_k_chunk_threshold")
    if not isinstance(t, (int, float)) or isinstance(t, bool) or t <= 0:
        return None
    return int(t)


def _top_k_largest(vals: jax.Array, k: int,
                   chunk_threshold: int = None) -> Tuple[jax.Array, jax.Array]:
    """top-k largest per row; two-phase for long rows. The length
    threshold is measured on-chip (bench_select_k_strategies --apply
    writes it into the tuned defaults). Public select_k reads it OUTSIDE
    jit and threads it through as a static argument (reload-aware); the
    internal ANN-spine callers reach here inside their own traces with
    chunk_threshold=None, so the tuned value is read at trace time — a
    later tuned.reload() only affects newly-traced shapes, which is fine:
    the --apply writers run in fresh processes per on-chip queue step."""
    n = vals.shape[-1]
    if chunk_threshold is None:
        chunk_threshold = _tuned_chunk_threshold()
    thresh = _CHUNK_THRESHOLD if chunk_threshold is None else int(chunk_threshold)
    if n <= thresh or n <= 2 * _CHUNK or k > _CHUNK // 4:
        return lax.top_k(vals, k)
    return _two_phase_largest(vals, k)


def _counting_promoted(vals, k: int) -> bool:
    """Trace-time gate for the measured counting-engine promotion,
    shared by the public API and `_select_k_impl` so internal hot paths
    (the brute-force per-tile select, IVF merges) also benefit from an
    on-chip strategy win. Exact engine — the flip is purely perf."""
    from raft_tpu.core import tuned
    from raft_tpu.core.config import is_tpu_backend

    if (
        tuned.get("select_k_auto_strategy") != "counting"
        or not is_tpu_backend()  # Mosaic kernel, chip-measured: CPU would
        # interpret (orders slower), GPU would fail to lower
        or vals.ndim != 2
        or vals.dtype not in _COUNTING_DTYPES
    ):
        return False
    from raft_tpu.ops.select_counting import fits_counting

    padded = vals.shape[-1] + (-vals.shape[-1]) % 128
    return bool(fits_counting(vals.shape[0], padded, int(k)))


@functools.partial(
    jax.jit, static_argnames=("k", "select_min", "chunk_threshold")
)
def _select_k_impl(vals: jax.Array, k: int, select_min: bool,
                   chunk_threshold: int = None):
    if _counting_promoted(vals, k):
        return _select_k_counting(vals, k, select_min)
    if select_min:
        # negate; NaNs/infs: -inf stays worst under negation of +inf
        v, i = _top_k_largest(-vals, k, chunk_threshold)
        return -v, i
    return _top_k_largest(vals, k, chunk_threshold)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "interpret"))
def _select_k_counting(vals: jax.Array, k: int, select_min: bool,
                       interpret: bool = False):
    """Pallas counting-select engine (ops/select_counting.py): exact
    threshold via in-VMEM bit-fixing, then a tiny (B, k) sort for the
    best-first output contract. Opt-in (strategy="counting") until the
    on-chip strategy bench decides where it wins."""
    from raft_tpu.ops.select_counting import counting_select_min

    n = vals.shape[-1]
    pad = (-n) % 128
    # cast BEFORE negating: integer negation wraps (int8 -128 -> -128,
    # unsigned mod 2^n), f32 negation is exact for every admitted dtype
    v = vals.astype(jnp.float32)
    if not select_min:
        v = -v
    if pad:
        v = jnp.pad(v, ((0, 0), (0, pad)), constant_values=jnp.inf)
    cv, ci = counting_select_min(v, k, interpret=interpret)
    # finish: best-first order over the k survivors (tiny)
    sv, order = lax.top_k(-cv, k)
    iv = jnp.take_along_axis(ci, order, axis=-1)
    out = -sv if select_min else sv
    # match every other strategy's contract: values keep the input dtype
    # (exact: all admitted dtypes embed in f32)
    return out.astype(vals.dtype), iv


def select_k(
    values,
    k: int,
    select_min: bool = True,
    indices: Optional[jax.Array] = None,
    resources=None,
    strategy: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (default) or largest values per row.

    Returns (values, indices), both shaped (batch, k), sorted best-first —
    matching matrix/select_k.cuh semantics. `indices`, when given, maps
    row-local positions to caller ids (the reference's `in_idx` optional
    input used by tile merging).

    `strategy`: None/"auto" picks the measured default (lax.top_k or the
    two-phase chunked path by shape); "topk" forces that path;
    "counting" opts into the Pallas counting-select engine
    (ops/select_counting.py), the radix-select analogue aimed at large
    rows — exact, raced by bench/bench_select_k_strategies.py.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.matrix import select_k
    >>> v, i = select_k(np.array([[3.0, 1.0, 2.0], [0.5, 4.0, 0.25]]), 2)
    >>> np.asarray(i).tolist()
    [[1, 2], [2, 0]]
    >>> np.asarray(v).tolist()
    [[1.0, 2.0], [0.25, 0.5]]
    """
    from raft_tpu.core.validation import as_array

    vals = as_array(values)
    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[None, :]
    if not (0 < k <= vals.shape[-1]):
        raise ValueError(f"k={k} out of range for row length {vals.shape[-1]}")
    if strategy not in (None, "auto", "topk", "counting"):
        raise ValueError(f"unknown select_k strategy {strategy!r}")
    if strategy in (None, "auto"):
        # a measured on-chip winner can promote the counting engine for
        # the shapes it fits (shared gate with _select_k_impl, so
        # internal hot paths get the same flip). The kernel is strictly
        # 2-D; higher-rank batches keep the ndim-agnostic default path.
        if _counting_promoted(vals, k):
            strategy = "counting"
    if strategy == "counting":
        # the engine works on the f32 order image; only dtypes that embed
        # exactly in f32 keep the documented exact-selection contract
        if vals.dtype not in _COUNTING_DTYPES:
            raise ValueError(
                f"strategy='counting' requires an f32-embeddable dtype, got {vals.dtype}"
            )
        interp = jax.default_backend() == "cpu"  # Mosaic needs TPU
        v, i = _select_k_counting(vals, int(k), bool(select_min), interp)
    else:
        v, i = _select_k_impl(
            vals, int(k), bool(select_min), _tuned_chunk_threshold()
        )
    if indices is not None:
        idx = as_array(indices)
        if idx.ndim == 1:
            idx = idx[None, :]
        i = jnp.take_along_axis(idx, i, axis=-1)
    if squeeze:
        v, i = v[0], i[0]
    if resources is not None:
        resources.track(v, i)
    return v, i
