"""Matrix operations: select_k plus gather/argmax/slice/sort utilities.

TPU-native equivalent of `cpp/include/raft/matrix/` (survey §2.4). Most ops
are thin jnp compositions (XLA fuses them); select_k is the hot one and
lives in its own module.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.matrix.select_k import scan_select_k, select_k

__all__ = [
    "select_k",
    "scan_select_k",
    "gather",
    "gather_if",
    "scatter",
    "argmax",
    "argmin",
    "slice",
    "reverse",
    "linewise_op",
    "col_wise_sort",
    "norm_rows",
    "eye",
    "fill",
    "diagonal",
    "set_diagonal",
    "upper_triangular",
    "lower_triangular",
    "power",
    "sqrt",
    "reciprocal",
    "ratio",
    "sign_flip",
    "threshold",
    "copy",
]


def gather(matrix, indices, axis: int = 0) -> jax.Array:
    """Gather rows (matrix/gather.cuh)."""
    return jnp.take(jnp.asarray(matrix), jnp.asarray(indices), axis=axis)


def gather_if(matrix, indices, mask, fill_value=0.0) -> jax.Array:
    g = gather(matrix, indices)
    m = jnp.asarray(mask)
    return jnp.where(m[:, None] if g.ndim == 2 else m, g, fill_value)


def scatter(matrix, indices, updates) -> jax.Array:
    return jnp.asarray(matrix).at[jnp.asarray(indices)].set(jnp.asarray(updates))


def argmax(matrix, axis: int = 1) -> jax.Array:
    """Per-row argmax (matrix/argmax.cuh)."""
    return jnp.argmax(jnp.asarray(matrix), axis=axis).astype(jnp.int32)


def argmin(matrix, axis: int = 1) -> jax.Array:
    return jnp.argmin(jnp.asarray(matrix), axis=axis).astype(jnp.int32)


def slice(matrix, row_start: int, row_end: int, col_start: int = 0, col_end=None) -> jax.Array:
    """Submatrix copy (matrix/slice.cuh)."""
    m = jnp.asarray(matrix)
    col_end = m.shape[1] if col_end is None else col_end
    return m[row_start:row_end, col_start:col_end]


def reverse(matrix, axis: int = 0) -> jax.Array:
    return jnp.flip(jnp.asarray(matrix), axis=axis)


def linewise_op(matrix, vec, op, along_rows: bool = True) -> jax.Array:
    """Broadcast a vector op along rows/cols (matrix/linewise_op.cuh)."""
    m = jnp.asarray(matrix)
    v = jnp.asarray(vec)
    return op(m, v[None, :] if along_rows else v[:, None])


def col_wise_sort(matrix, ascending: bool = True):
    """Sort each column; returns (sorted, indices) (matrix/col_wise_sort.cuh)."""
    m = jnp.asarray(matrix)
    idx = jnp.argsort(m, axis=0)
    if not ascending:
        idx = jnp.flip(idx, axis=0)
    return jnp.take_along_axis(m, idx, axis=0), idx.astype(jnp.int32)


def norm_rows(matrix, ord: int = 2) -> jax.Array:
    """Row norms (matrix/norm.cuh)."""
    return jnp.linalg.norm(jnp.asarray(matrix).astype(jnp.float32), ord=ord, axis=1)


def eye(n: int, m=None, dtype=jnp.float32) -> jax.Array:
    return jnp.eye(n, m, dtype=dtype)


def fill(shape, value, dtype=jnp.float32) -> jax.Array:
    return jnp.full(shape, value, dtype=dtype)


def diagonal(matrix) -> jax.Array:
    return jnp.diagonal(jnp.asarray(matrix))


def set_diagonal(matrix, vec) -> jax.Array:
    m = jnp.asarray(matrix)
    n = min(m.shape)
    idx = jnp.arange(n)
    return m.at[idx, idx].set(jnp.asarray(vec)[:n])


def upper_triangular(matrix) -> jax.Array:
    return jnp.triu(jnp.asarray(matrix))


def lower_triangular(matrix) -> jax.Array:
    return jnp.tril(jnp.asarray(matrix))


def power(matrix, exponent) -> jax.Array:
    """Elementwise power (matrix/power.cuh)."""
    return jnp.power(jnp.asarray(matrix), exponent)


def sqrt(matrix) -> jax.Array:
    """Elementwise sqrt (matrix/sqrt.cuh)."""
    return jnp.sqrt(jnp.asarray(matrix))


def reciprocal(matrix, scalar=1.0, thres: float = 0.0) -> jax.Array:
    """Guarded elementwise reciprocal: scalar/x where |x| > thres, else 0
    (matrix/reciprocal.cuh)."""
    m = jnp.asarray(matrix)
    return jnp.where(jnp.abs(m) > thres, scalar / m, jnp.zeros((), m.dtype))


def ratio(matrix) -> jax.Array:
    """Each element divided by the sum of all elements (matrix/ratio.cuh)."""
    m = jnp.asarray(matrix)
    return m / jnp.sum(m)


def sign_flip(matrix) -> jax.Array:
    """Flip the sign of each column so its max-|value| entry is positive
    (matrix/sign_flip.cuh — used to canonicalize eigenvectors)."""
    m = jnp.asarray(matrix)
    pivot = jnp.take_along_axis(m, jnp.argmax(jnp.abs(m), axis=0)[None, :], axis=0)
    return m * jnp.where(pivot < 0, -1.0, 1.0).astype(m.dtype)


def threshold(matrix, thres, fill_value=0.0) -> jax.Array:
    """Zero out entries below `thres` (matrix/threshold.cuh)."""
    m = jnp.asarray(matrix)
    return jnp.where(m < thres, jnp.asarray(fill_value, m.dtype), m)


def copy(matrix) -> jax.Array:
    """Out-of-place copy (matrix/copy.cuh)."""
    return jnp.array(jnp.asarray(matrix), copy=True)
