"""raft_tpu — TPU-native reusable ML/data-science primitives.

A ground-up JAX/XLA/Pallas re-design of the capabilities of RAPIDS RAFT
(reference: /root/reference, ~v23.02): dense & sparse linear algebra,
pairwise distances, k-selection, brute-force / IVF-Flat / IVF-PQ nearest
neighbors, k-means (plain + balanced), single-linkage & spectral
clustering, statistics, random generators, solvers — plus a distributed
comms layer lowered to XLA collectives over a `jax.sharding.Mesh`
(the TPU equivalent of raft::comms_t / raft-dask).

Design stance (not a port):
  - `jax.Array` replaces mdarray/mdspan; XLA owns streams & allocation,
    so `Resources` is a light context (mesh, rng key, logger) rather than
    a handle full of vendor library handles.
  - Compute is jit-compiled XLA with Pallas kernels on the hot paths
    (pairwise distance, select_k, IVF scan/score).
  - Distribution is SPMD via shard_map/pjit over a Mesh; collectives are
    jax.lax.{psum,all_gather,ppermute,reduce_scatter} riding ICI/DCN,
    replacing NCCL/UCX.
"""

__version__ = "0.1.0"

# forward-compat aliases (jax.shard_map, pallas CompilerParams) must be
# in place before any SPMD module runs — see core/compat.py
from raft_tpu.core.compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

from raft_tpu.core.resources import Resources
from raft_tpu.core.device_ndarray import device_ndarray

# Subpackages resolve lazily (PEP 562) so `import raft_tpu` stays light but
# `raft_tpu.neighbors.ivf_pq`-style navigation works without explicit
# submodule imports — the way pylibraft exposes its packages.
_SUBPACKAGES = (
    "cluster",
    "comms",
    "core",
    "distance",
    "integrity",
    "io",
    "jobs",
    "label",
    "linalg",
    "matrix",
    "native",
    "neighbors",
    "obs",
    "ops",
    "random",
    "serve",
    "solver",
    "sparse",
    "spatial",
    "spectral",
    "stats",
    "util",
)

# Stable (lazy) aliases for the resilience + headline-index surface:
# serving code types against these without deep-importing internals.
# Values are either the defining module (attribute resolved under the
# same name) or a (module, attribute) pair for renamed aliases;
# resolution goes through the same PEP 562 hook as the subpackages, so
# `import raft_tpu` stays light.
_LAZY_ATTRS = {
    "DegradedSearchResult": "raft_tpu.comms.resilience",
    "RankHealth": "raft_tpu.comms.resilience",
    # IVF-RaBitQ headline entry points (docs/vector_search.md quickstart)
    "ivf_rabitq_build": ("raft_tpu.neighbors.ivf_rabitq", "build"),
    "ivf_rabitq_search": ("raft_tpu.neighbors.ivf_rabitq", "search"),
}

__all__ = [
    "Resources",
    "device_ndarray",
    "__version__",
    *_LAZY_ATTRS,
    *_SUBPACKAGES,
]


def __getattr__(name):
    if name in _SUBPACKAGES:
        import importlib

        return importlib.import_module(f"raft_tpu.{name}")
    if name in _LAZY_ATTRS:
        import importlib

        spec = _LAZY_ATTRS[name]
        mod, attr = spec if isinstance(spec, tuple) else (spec, name)
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(__all__)))
