"""Statistics: descriptive stats + evaluation metrics.

TPU-native equivalent of `cpp/include/raft/stats/` (survey §2.6).
"""

from raft_tpu.stats.descriptive import (
    mean,
    sum_stat,
    stddev,
    vars_stat,
    meanvar,
    mean_center,
    mean_add,
    cov,
    minmax,
    weighted_mean,
    row_weighted_mean,
    histogram,
    dispersion,
)
from raft_tpu.stats.metrics import (
    accuracy,
    r2_score,
    regression_metrics,
    contingency_matrix,
    rand_index,
    adjusted_rand_index,
    entropy,
    mutual_info_score,
    homogeneity_score,
    completeness_score,
    v_measure,
    kl_divergence,
    silhouette_score,
    trustworthiness_score,
    information_criterion_batched,
)

__all__ = [
    "mean", "sum_stat", "stddev", "vars_stat", "meanvar", "mean_center",
    "mean_add", "cov", "minmax", "weighted_mean", "row_weighted_mean",
    "histogram", "dispersion",
    "accuracy", "r2_score", "regression_metrics", "contingency_matrix",
    "rand_index", "adjusted_rand_index", "entropy", "mutual_info_score",
    "homogeneity_score", "completeness_score", "v_measure", "kl_divergence",
    "silhouette_score", "trustworthiness_score", "information_criterion_batched",
]
