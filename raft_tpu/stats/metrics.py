"""Evaluation metrics (stats/accuracy.cuh, r2_score.cuh,
regression_metrics.cuh, contingency_matrix.cuh, adjusted_rand_index.cuh,
rand_index.cuh, mutual_info_score.cuh, homogeneity_score.cuh,
completeness_score.cuh, v_measure.cuh, entropy.cuh, kl_divergence.cuh,
silhouette_score.cuh, trustworthiness_score.cuh,
information_criterion.cuh)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# -- classification / regression -------------------------------------------


def accuracy(predictions, labels) -> jax.Array:
    p = jnp.asarray(predictions)
    l = jnp.asarray(labels)
    return jnp.mean((p == l).astype(jnp.float32))


def r2_score(y, y_hat) -> jax.Array:
    yt = jnp.asarray(y).astype(jnp.float32)
    yp = jnp.asarray(y_hat).astype(jnp.float32)
    ss_res = jnp.sum((yt - yp) ** 2)
    ss_tot = jnp.sum((yt - jnp.mean(yt)) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30)


def regression_metrics(predictions, ref) -> dict:
    """mean_abs_error, mean_squared_error, median_abs_error
    (regression_metrics.cuh)."""
    p = jnp.asarray(predictions).astype(jnp.float32)
    r = jnp.asarray(ref).astype(jnp.float32)
    err = p - r
    return {
        "mean_abs_error": jnp.mean(jnp.abs(err)),
        "mean_squared_error": jnp.mean(err**2),
        "median_abs_error": jnp.median(jnp.abs(err)),
    }


# -- clustering comparison metrics ------------------------------------------


def contingency_matrix(y_true, y_pred, n_classes: Optional[int] = None) -> jax.Array:
    a = jnp.asarray(y_true).astype(jnp.int32)
    b = jnp.asarray(y_pred).astype(jnp.int32)
    if n_classes is None:
        n_classes = int(max(int(jnp.max(a)), int(jnp.max(b)))) + 1
    idx = a * n_classes + b
    flat = jax.ops.segment_sum(
        jnp.ones_like(idx, jnp.int32), idx, num_segments=n_classes * n_classes
    )
    return flat.reshape(n_classes, n_classes)


def _comb2(x):
    x = x.astype(jnp.float32)
    return x * (x - 1.0) / 2.0


def rand_index(y_true, y_pred) -> jax.Array:
    """Unadjusted Rand index (rand_index.cuh)."""
    c = contingency_matrix(y_true, y_pred).astype(jnp.float32)
    n = jnp.sum(c)
    sum_sq = jnp.sum(c**2)
    a_sq = jnp.sum(jnp.sum(c, axis=1) ** 2)
    b_sq = jnp.sum(jnp.sum(c, axis=0) ** 2)
    tp = (sum_sq - n) / 2.0
    fp = (a_sq - sum_sq) / 2.0
    fn = (b_sq - sum_sq) / 2.0
    tn = _comb2(n) - tp - fp - fn
    return (tp + tn) / _comb2(n)


def adjusted_rand_index(y_true, y_pred) -> jax.Array:
    c = contingency_matrix(y_true, y_pred)
    n = jnp.sum(c).astype(jnp.float32)
    sum_comb = jnp.sum(_comb2(c))
    sum_a = jnp.sum(_comb2(jnp.sum(c, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(c, axis=0)))
    expected = sum_a * sum_b / jnp.maximum(_comb2(n), 1e-30)
    max_idx = 0.5 * (sum_a + sum_b)
    return (sum_comb - expected) / jnp.maximum(max_idx - expected, 1e-30)


def entropy(labels, n_classes: Optional[int] = None) -> jax.Array:
    l = jnp.asarray(labels).astype(jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.max(l)) + 1
    counts = jax.ops.segment_sum(jnp.ones_like(l, jnp.float32), l, num_segments=n_classes)
    p = counts / jnp.maximum(jnp.sum(counts), 1.0)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)), 0.0))


def mutual_info_score(y_true, y_pred) -> jax.Array:
    c = contingency_matrix(y_true, y_pred).astype(jnp.float32)
    n = jnp.sum(c)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = pij / jnp.maximum(pi * pj, 1e-30)
    return jnp.sum(jnp.where(pij > 0, pij * jnp.log(jnp.maximum(ratio, 1e-30)), 0.0))


def homogeneity_score(y_true, y_pred) -> jax.Array:
    mi = mutual_info_score(y_true, y_pred)
    h = entropy(y_true)
    return jnp.where(h > 0, mi / jnp.maximum(h, 1e-30), 1.0)


def completeness_score(y_true, y_pred) -> jax.Array:
    return homogeneity_score(y_pred, y_true)


def v_measure(y_true, y_pred, beta: float = 1.0) -> jax.Array:
    h = homogeneity_score(y_true, y_pred)
    c = completeness_score(y_true, y_pred)
    denom = beta * h + c
    return jnp.where(denom > 0, (1 + beta) * h * c / jnp.maximum(denom, 1e-30), 0.0)


def kl_divergence(p, q) -> jax.Array:
    pp = jnp.asarray(p).astype(jnp.float32)
    qq = jnp.asarray(q).astype(jnp.float32)
    safe = (pp > 0) & (qq > 0)
    return jnp.sum(jnp.where(safe, pp * jnp.log(jnp.maximum(pp, 1e-30) / jnp.maximum(qq, 1e-30)), 0.0))


# -- geometric metrics ------------------------------------------------------


def silhouette_score(X, labels, n_classes: Optional[int] = None, batch: int = 4096) -> jax.Array:
    """Mean silhouette coefficient (silhouette_score.cuh, incl. the batched
    variant): a(i) = mean intra-cluster distance, b(i) = min mean distance to
    another cluster; computed from per-cluster distance sums (one streamed
    pairwise pass, no n² materialization)."""
    from jax import lax

    x = jnp.asarray(X).astype(jnp.float32)
    l = jnp.asarray(labels).astype(jnp.int32)
    n = x.shape[0]
    if n_classes is None:
        n_classes = int(jnp.max(l)) + 1
    counts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), l, num_segments=n_classes)

    # per-point sums of L2 distances to each cluster: stream row blocks
    onehot = jax.nn.one_hot(l, n_classes, dtype=jnp.float32)  # (n, k)
    bm = min(n, max(8, batch))
    nblocks = -(-n // bm)
    pad = nblocks * bm - n
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x

    def row_fn(xb):
        d = jnp.sqrt(
            jnp.maximum(
                jnp.sum(xb**2, 1)[:, None] + jnp.sum(x**2, 1)[None, :] - 2.0 * xb @ x.T,
                0.0,
            )
        )
        return d @ onehot  # (bm, k) distance-sums per cluster

    sums = lax.map(row_fn, xp.reshape(nblocks, bm, -1)).reshape(-1, n_classes)[:n]
    own = counts[l]
    a = jnp.where(own > 1, jnp.take_along_axis(sums, l[:, None], 1)[:, 0] / jnp.maximum(own - 1, 1), 0.0)
    mean_other = sums / jnp.maximum(counts[None, :], 1.0)
    mean_other = jnp.where(
        jax.nn.one_hot(l, n_classes, dtype=bool), jnp.inf, mean_other
    )
    b = jnp.min(mean_other, axis=1)
    s = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
    return jnp.mean(s)


def trustworthiness_score(X, X_embedded, n_neighbors: int = 5) -> jax.Array:
    """Trustworthiness of an embedding (trustworthiness_score.cuh)."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl
    from raft_tpu.distance.distance_types import DistanceType

    x = jnp.asarray(X).astype(jnp.float32)
    e = jnp.asarray(X_embedded).astype(jnp.float32)
    n = x.shape[0]
    # ranks in original space
    _, ind_x = _bf_knn_impl(x, x, n, DistanceType.L2Expanded)
    _, ind_e = _bf_knn_impl(e, e, n_neighbors + 1, DistanceType.L2Expanded)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = ranks.at[jnp.arange(n)[:, None], ind_x].set(
        jnp.broadcast_to(jnp.arange(n)[None, :], (n, n)).astype(jnp.int32)
    )
    nbrs = ind_e[:, 1 : n_neighbors + 1]
    r = ranks[jnp.arange(n)[:, None], nbrs] - n_neighbors
    penalty = jnp.sum(jnp.maximum(r, 0).astype(jnp.float32))
    norm = 2.0 / (n * n_neighbors * (2.0 * n - 3.0 * n_neighbors - 1.0))
    return 1.0 - norm * penalty


def information_criterion_batched(log_likelihood, n_params: int, n_samples: int,
                                  criterion: str = "AIC") -> jax.Array:
    """AIC/AICc/BIC (information_criterion.cuh)."""
    ll = jnp.asarray(log_likelihood).astype(jnp.float32)
    if criterion == "AIC":
        return -2.0 * ll + 2.0 * n_params
    if criterion == "AICc":
        corr = 2.0 * n_params * (n_params + 1.0) / jnp.maximum(n_samples - n_params - 1.0, 1.0)
        return -2.0 * ll + 2.0 * n_params + corr
    if criterion == "BIC":
        return -2.0 * ll + n_params * jnp.log(float(n_samples))
    raise ValueError(criterion)
