"""Descriptive statistics (stats/mean.cuh, stddev.cuh, meanvar.cuh, cov.cuh,
sum.cuh, minmax.cuh, mean_center.cuh, weighted_mean.cuh, histogram.cuh,
dispersion.cuh)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def mean(data, axis: int = 0, sample: bool = False) -> jax.Array:
    """Column means (stats/mean.cuh; `sample` divides by N-1)."""
    x = jnp.asarray(data).astype(jnp.float32)
    n = x.shape[axis]
    s = jnp.sum(x, axis=axis)
    return s / (n - 1 if sample else n)


def sum_stat(data, axis: int = 0) -> jax.Array:
    return jnp.sum(jnp.asarray(data).astype(jnp.float32), axis=axis)


def stddev(data, mu=None, axis: int = 0, sample: bool = True) -> jax.Array:
    x = jnp.asarray(data).astype(jnp.float32)
    m = mean(x, axis=axis) if mu is None else jnp.asarray(mu)
    n = x.shape[axis]
    var = jnp.sum((x - jnp.expand_dims(m, axis)) ** 2, axis=axis) / (n - 1 if sample else n)
    return jnp.sqrt(var)


def vars_stat(data, mu=None, axis: int = 0, sample: bool = True) -> jax.Array:
    x = jnp.asarray(data).astype(jnp.float32)
    m = mean(x, axis=axis) if mu is None else jnp.asarray(mu)
    n = x.shape[axis]
    return jnp.sum((x - jnp.expand_dims(m, axis)) ** 2, axis=axis) / (n - 1 if sample else n)


def meanvar(data, axis: int = 0, sample: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Fused mean+variance (stats/meanvar.cuh) — XLA fuses the two passes."""
    m = mean(data, axis=axis)
    return m, vars_stat(data, mu=m, axis=axis, sample=sample)


def mean_center(data, mu=None, axis: int = 0) -> jax.Array:
    x = jnp.asarray(data).astype(jnp.float32)
    m = mean(x, axis=axis) if mu is None else jnp.asarray(mu)
    return x - jnp.expand_dims(m, axis)


def mean_add(data, mu, axis: int = 0) -> jax.Array:
    return jnp.asarray(data) + jnp.expand_dims(jnp.asarray(mu), axis)


def cov(data, mu=None, sample: bool = True, stable: bool = True) -> jax.Array:
    """Covariance matrix of rows-as-samples (stats/cov.cuh)."""
    x = mean_center(data, mu)
    n = x.shape[0]
    denom = n - 1 if sample else n
    from jax import lax

    return lax.dot(x.T, x, preferred_element_type=jnp.float32) / denom


def minmax(data, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    x = jnp.asarray(data)
    return jnp.min(x, axis=axis), jnp.max(x, axis=axis)


def weighted_mean(data, weights, axis: int = 0) -> jax.Array:
    x = jnp.asarray(data).astype(jnp.float32)
    w = jnp.asarray(weights).astype(jnp.float32)
    wsum = jnp.sum(w)
    return jnp.tensordot(w, x, axes=([0], [axis])) / jnp.maximum(wsum, 1e-30)


def row_weighted_mean(data, weights) -> jax.Array:
    """Per-row weighted mean over columns (stats/weighted_mean.cuh)."""
    x = jnp.asarray(data).astype(jnp.float32)
    w = jnp.asarray(weights).astype(jnp.float32)
    return (x * w[None, :]).sum(axis=1) / jnp.maximum(jnp.sum(w), 1e-30)


def histogram(data, n_bins: int, lower: float, upper: float) -> jax.Array:
    """Fixed-range histogram (stats/histogram.cuh) via one-hot segment sum
    (deterministic, no atomics)."""
    x = jnp.asarray(data).reshape(-1).astype(jnp.float32)
    scaled = (x - lower) / (upper - lower) * n_bins
    idx = jnp.clip(scaled.astype(jnp.int32), 0, n_bins - 1)
    valid = (x >= lower) & (x < upper)
    return jax.ops.segment_sum(valid.astype(jnp.int32), idx, num_segments=n_bins)


def dispersion(centroids, cluster_sizes, global_centroid=None, n_points: Optional[int] = None):
    """Between-cluster dispersion (stats/dispersion.cuh): sqrt of weighted
    squared distances of centroids to the global centroid."""
    c = jnp.asarray(centroids).astype(jnp.float32)
    sz = jnp.asarray(cluster_sizes).astype(jnp.float32)
    n = jnp.sum(sz) if n_points is None else n_points
    g = (
        jnp.asarray(global_centroid)
        if global_centroid is not None
        else (sz[:, None] * c).sum(0) / jnp.maximum(n, 1.0)
    )
    d = jnp.sum((c - g[None, :]) ** 2, axis=1)
    return jnp.sqrt(jnp.sum(sz * d))
