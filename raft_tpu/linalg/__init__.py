"""Dense linear algebra primitives.

TPU-native equivalent of `cpp/include/raft/linalg/` (survey §2.3). The
reference wraps cuBLAS/cuSolver and hand-rolls tiled reduction kernels; on
TPU these are jnp/lax compositions that XLA fuses and tiles onto the
MXU/VPU — the value here is API parity (names, semantics, custom main/
reduce/final ops) so reference users find every primitive.
"""

from raft_tpu.linalg.blas import gemm, gemv, axpy, dot, transpose
from raft_tpu.linalg.solvers import (
    eig_dc,
    eigh,
    svd,
    rsvd,
    qr,
    lstsq,
    cholesky,
    cholesky_r1_update,
)
from raft_tpu.linalg.elementwise import (
    unary_op,
    binary_op,
    ternary_op,
    map_op,
    eltwise_add,
    eltwise_sub,
    eltwise_multiply,
    eltwise_divide,
    eltwise_power,
    eltwise_sqrt,
    scalar_add,
    scalar_multiply,
)
from raft_tpu.linalg.reductions import (
    reduce,
    coalesced_reduction,
    strided_reduction,
    map_reduce,
    norm,
    row_norm,
    col_norm,
    normalize,
    mean_squared_error,
    reduce_rows_by_key,
    reduce_cols_by_key,
    matrix_vector_op,
)

__all__ = [
    "gemm", "gemv", "axpy", "dot", "transpose",
    "eig_dc", "eigh", "svd", "rsvd", "qr", "lstsq", "cholesky",
    "cholesky_r1_update", "lanczos",
    "unary_op", "binary_op", "ternary_op", "map_op",
    "eltwise_add", "eltwise_sub", "eltwise_multiply", "eltwise_divide",
    "eltwise_power", "eltwise_sqrt", "scalar_add", "scalar_multiply",
    "reduce", "coalesced_reduction", "strided_reduction", "map_reduce",
    "norm", "row_norm", "col_norm", "normalize", "mean_squared_error",
    "reduce_rows_by_key", "reduce_cols_by_key", "matrix_vector_op",
]


def __getattr__(name):
    # linalg/lanczos.cuh is a shim over sparse/solver/lanczos.cuh in the
    # reference; resolve it lazily (PEP 562) so `import raft_tpu.linalg`
    # doesn't initialize the whole sparse package as a side effect.
    if name == "lanczos":
        from raft_tpu.sparse.solver import lanczos

        return lanczos
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + __all__))
