"""BLAS-level ops (linalg/gemm.cuh, gemv.cuh, axpy.cuh, dot.cuh —
mdspan-typed shims over cuBLAS in the reference; MXU matmuls here)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def gemm(A, B, alpha: float = 1.0, beta: float = 0.0, C=None,
         trans_a: bool = False, trans_b: bool = False) -> jax.Array:
    """alpha * op(A) @ op(B) + beta * C with f32 accumulation."""
    a = jnp.asarray(A)
    b = jnp.asarray(B)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = alpha * lax.dot(a, b, preferred_element_type=jnp.float32)
    if C is not None and beta != 0.0:
        out = out + beta * jnp.asarray(C)
    return out.astype(a.dtype)


def gemv(A, x, alpha: float = 1.0, beta: float = 0.0, y=None,
         trans: bool = False) -> jax.Array:
    a = jnp.asarray(A)
    if trans:
        a = a.T
    out = alpha * (a @ jnp.asarray(x))
    if y is not None and beta != 0.0:
        out = out + beta * jnp.asarray(y)
    return out


def axpy(alpha: float, x, y) -> jax.Array:
    return alpha * jnp.asarray(x) + jnp.asarray(y)


def dot(x, y) -> jax.Array:
    return jnp.dot(jnp.asarray(x), jnp.asarray(y), preferred_element_type=jnp.float32)


def transpose(A) -> jax.Array:
    return jnp.asarray(A).T
