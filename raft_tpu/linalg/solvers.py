"""Dense solvers (linalg/eig.cuh, svd.cuh, rsvd.cuh, qr.cuh, lstsq.cuh,
cholesky_r1_update.cuh — cuSolver-backed in the reference).

TPU note: jnp.linalg decompositions run on device; rsvd is the
randomized-projection algorithm (Halko et al.) the reference implements,
valuable on TPU because its cost is two tall matmuls + a tiny dense SVD.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def eigh(A) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition, ascending (linalg/eig.cuh eigDC).
    Returns (eigenvalues, eigenvectors[:, i])."""
    w, v = jnp.linalg.eigh(jnp.asarray(A))
    return w, v


eig_dc = eigh  # reference name


def svd(A, full_matrices: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (U, S, V) with A = U @ diag(S) @ V.T (svd.cuh svdQR
    convention: V not V^T)."""
    u, s, vt = jnp.linalg.svd(jnp.asarray(A), full_matrices=full_matrices)
    return u, s, vt.T


def qr(A) -> Tuple[jax.Array, jax.Array]:
    return jnp.linalg.qr(jnp.asarray(A))


def rsvd(
    A,
    k: int,
    p: int = 10,
    n_iter: int = 2,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized SVD (rsvd.cuh): range finding via gaussian sketch with
    power iterations, then exact SVD of the small projection.
    Returns rank-k (U, S, V)."""
    a = jnp.asarray(A, jnp.float32)
    m, n = a.shape
    l = min(k + p, min(m, n))
    key = jax.random.PRNGKey(seed)
    omega = jax.random.normal(key, (n, l), dtype=a.dtype)
    y = a @ omega
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iter):
        z = a.T @ q
        q, _ = jnp.linalg.qr(a @ z)
    b = q.T @ a  # (l, n)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :k], s[:k], vt[:k].T


def lstsq(A, b, method: str = "svd") -> jax.Array:
    """Least squares solve (lstsq.cuh lstsqSvdQR/lstsqEig): min ||Ax - b||."""
    a = jnp.asarray(A)
    bb = jnp.asarray(b)
    if method == "eig":
        # normal equations via eigendecomposition (lstsqEig)
        g = a.T @ a
        w, v = jnp.linalg.eigh(g)
        winv = jnp.where(w > 1e-10 * jnp.max(w), 1.0 / jnp.maximum(w, 1e-30), 0.0)
        return v @ (winv * (v.T @ (a.T @ bb)))
    return jnp.linalg.lstsq(a, bb)[0]


def cholesky(A, lower: bool = True) -> jax.Array:
    c = jnp.linalg.cholesky(jnp.asarray(A))
    return c if lower else c.T


def cholesky_r1_update(L, x, lower: bool = True) -> jax.Array:
    """Rank-1 Cholesky update (cholesky_r1_update.cuh): given L with
    L@L.T = A, return L' with L'@L'.T = A + x x^T.

    Classic hyperbolic-rotation update expressed as a lax.scan over columns
    (sequential by nature; n is small in its uses — e.g. incremental
    kernels)."""
    import jax.lax as lax

    L = jnp.asarray(L, jnp.float32)
    x = jnp.asarray(x, jnp.float32).reshape(-1)
    n = L.shape[0]
    Lw = L if lower else L.T

    def body(carry, k):
        Lc, xc = carry
        lkk = Lc[k, k]
        xk = xc[k]
        r = jnp.sqrt(lkk * lkk + xk * xk)
        c = r / lkk
        s = xk / lkk
        col = Lc[:, k]
        newcol = (col + s * xc) / c
        mask = jnp.arange(n) > k
        Lc = Lc.at[:, k].set(jnp.where(jnp.arange(n) >= k, newcol, col).at[k].set(r))
        xc = jnp.where(mask, c * xc - s * Lc[:, k], xc)
        return (Lc, xc), None

    (Lout, _), _ = lax.scan(body, (Lw, x), jnp.arange(n))
    Lout = jnp.tril(Lout)
    return Lout if lower else Lout.T
