"""Elementwise/map ops (linalg/unary_op.cuh, binary_op.cuh, ternary_op.cuh,
map.cuh, eltwise.cuh, add/subtract/multiply/divide/power/sqrt.cuh).

These exist for API parity; in JAX they are trivial jnp compositions that
XLA fuses into neighboring ops."""

from __future__ import annotations

import jax.numpy as jnp


def unary_op(x, op):
    return op(jnp.asarray(x))


def binary_op(x, y, op):
    return op(jnp.asarray(x), jnp.asarray(y))


def ternary_op(x, y, z, op):
    return op(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z))


def map_op(op, *arrays):
    """linalg::map — n-ary elementwise map."""
    return op(*[jnp.asarray(a) for a in arrays])


def eltwise_add(x, y):
    return jnp.asarray(x) + jnp.asarray(y)


def eltwise_sub(x, y):
    return jnp.asarray(x) - jnp.asarray(y)


def eltwise_multiply(x, y):
    return jnp.asarray(x) * jnp.asarray(y)


def eltwise_divide(x, y):
    return jnp.asarray(x) / jnp.asarray(y)


def eltwise_power(x, y):
    return jnp.power(jnp.asarray(x), jnp.asarray(y))


def eltwise_sqrt(x):
    return jnp.sqrt(jnp.asarray(x))


def scalar_add(x, s):
    return jnp.asarray(x) + s


def scalar_multiply(x, s):
    return jnp.asarray(x) * s
