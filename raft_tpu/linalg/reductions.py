"""Reductions (linalg/reduce.cuh, coalesced_reduction.cuh,
strided_reduction.cuh, map_reduce.cuh, norm.cuh, normalize.cuh,
mean_squared_error.cuh, reduce_rows_by_key.cuh, reduce_cols_by_key.cuh,
matrix_vector_op.cuh).

The reference's reductions are parameterized by main-op (per element),
reduce-op (binary combine) and final-op (epilogue) — preserved here as
callables with the same defaults (identity, add, identity)."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _identity(x):
    return x


def reduce(
    data,
    axis: int = 1,
    main_op: Callable = _identity,
    reduce_op: str = "add",
    final_op: Callable = _identity,
    init: float = 0.0,
):
    """Generalized row/col reduction (linalg/reduce.cuh). `axis=1` reduces
    along rows (per-row outputs), matching 'along rows == coalesced' for
    row-major data in the reference."""
    x = main_op(jnp.asarray(data))
    if reduce_op == "add":
        out = jnp.sum(x, axis=axis) + init
    elif reduce_op == "min":
        out = jnp.minimum(jnp.min(x, axis=axis), init) if init else jnp.min(x, axis=axis)
    elif reduce_op == "max":
        out = jnp.maximum(jnp.max(x, axis=axis), init) if init else jnp.max(x, axis=axis)
    else:
        raise ValueError(f"unknown reduce_op {reduce_op}")
    return final_op(out)


def coalesced_reduction(data, main_op=_identity, final_op=_identity):
    """Reduce along the contiguous (last) dimension (coalesced_reduction.cuh)."""
    return reduce(data, axis=-1, main_op=main_op, final_op=final_op)


def strided_reduction(data, main_op=_identity, final_op=_identity):
    """Reduce along the strided (first) dimension (strided_reduction.cuh)."""
    return reduce(data, axis=0, main_op=main_op, final_op=final_op)


def map_reduce(op: Callable, *arrays, reduce_op: str = "add"):
    """map then full reduce (map_reduce.cuh)."""
    x = op(*[jnp.asarray(a) for a in arrays])
    return {"add": jnp.sum, "min": jnp.min, "max": jnp.max}[reduce_op](x)


def norm(data, norm_type: str = "l2", axis: int = 1, sqrt: bool = False):
    """Row/col norms (linalg/norm.cuh L1Norm/L2Norm semantics: L2 is the
    SQUARED norm unless sqrt=True — matching the reference's rowNorm)."""
    x = jnp.asarray(data).astype(jnp.float32)
    if norm_type in ("l2", 2):
        out = jnp.sum(x * x, axis=axis)
        return jnp.sqrt(out) if sqrt else out
    if norm_type in ("l1", 1):
        return jnp.sum(jnp.abs(x), axis=axis)
    if norm_type in ("linf",):
        return jnp.max(jnp.abs(x), axis=axis)
    raise ValueError(norm_type)


def row_norm(data, norm_type="l2", sqrt: bool = False):
    return norm(data, norm_type, axis=1, sqrt=sqrt)


def col_norm(data, norm_type="l2", sqrt: bool = False):
    return norm(data, norm_type, axis=0, sqrt=sqrt)


def normalize(data, norm_type: str = "l2", axis: int = 1, eps: float = 1e-12):
    """Row normalization (linalg/normalize.cuh)."""
    x = jnp.asarray(data).astype(jnp.float32)
    n = norm(x, norm_type, axis=axis, sqrt=(norm_type in ("l2", 2)))
    n = jnp.expand_dims(jnp.maximum(n, eps), axis)
    return x / n


def mean_squared_error(a, b, weight: float = 1.0):
    x = jnp.asarray(a).astype(jnp.float32)
    y = jnp.asarray(b).astype(jnp.float32)
    return weight * jnp.mean((x - y) ** 2)


def reduce_rows_by_key(data, keys, n_keys: Optional[int] = None, weights=None):
    """Segment-sum rows by key (reduce_rows_by_key.cuh) — the k-means
    centroid accumulator. Deterministic segment_sum (no atomics)."""
    x = jnp.asarray(data).astype(jnp.float32)
    k = jnp.asarray(keys)
    if n_keys is None:
        n_keys = int(jnp.max(k)) + 1
    if weights is not None:
        x = x * jnp.asarray(weights).astype(jnp.float32)[:, None]
    return jax.ops.segment_sum(x, k, num_segments=n_keys)


def reduce_cols_by_key(data, keys, n_keys: Optional[int] = None):
    """Sum columns sharing a key (reduce_cols_by_key.cuh)."""
    x = jnp.asarray(data).astype(jnp.float32)
    k = jnp.asarray(keys)
    if n_keys is None:
        n_keys = int(jnp.max(k)) + 1
    return jax.ops.segment_sum(x.T, k, num_segments=n_keys).T


def matrix_vector_op(matrix, vec, op=jnp.add, along_rows: bool = True):
    """Broadcast a vector over a matrix (matrix_vector_op.cuh).
    along_rows=True: vec has one entry per column."""
    m = jnp.asarray(matrix)
    v = jnp.asarray(vec)
    return op(m, v[None, :] if along_rows else v[:, None])
