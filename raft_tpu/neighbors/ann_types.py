"""Common ANN parameter types.

Reference parity: `raft::neighbors::ann::index_params` / `search_params`
(neighbors/ann_types.hpp:29-49). Configuration is typed dataclasses, not a
runtime flag system (survey §5.6 — keep the reference's stance).
"""

from __future__ import annotations

import dataclasses

from raft_tpu.distance.distance_types import DistanceType, resolve_metric


@dataclasses.dataclass
class IndexParamsBase:
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)


@dataclasses.dataclass
class SearchParamsBase:
    pass
