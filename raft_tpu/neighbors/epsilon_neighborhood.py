"""Epsilon neighborhood: all pairs within a radius.

Reference parity: `raft::neighbors::epsilon_neighborhood`
(epsilon_neighborhood.cuh `epsUnexpL2SqNeighborhood` — boolean adjacency +
per-row degree over squared-L2 within eps), impl
spatial/knn/detail/epsilon_neighborhood.cuh.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.distance.distance_types import resolve_metric
from raft_tpu.distance.pairwise import _pairwise_impl


def eps_neighbors(X, Y, eps: float, metric="sqeuclidean") -> Tuple[jax.Array, jax.Array]:
    """Returns (adj (m, n) bool, vertex_degrees (m,) int32): adj[i,j] iff
    dist(x_i, y_j) <= eps. eps is in the metric's units (squared L2 for the
    default, matching epsUnexpL2SqNeighborhood)."""
    x = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(Y, jnp.float32)
    m = resolve_metric(metric)
    d = _pairwise_impl(x, y, m)
    adj = d <= eps
    return adj, jnp.sum(adj, axis=1).astype(jnp.int32)
