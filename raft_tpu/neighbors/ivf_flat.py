"""IVF-Flat: inverted-file index over raw vectors.

Reference parity: `raft::neighbors::ivf_flat` — index type & params
(ivf_flat_types.hpp:41-161), build (detail/ivf_flat_build.cuh: balanced
k-means on a trainset fraction + assign + per-list interleaved storage),
search (detail/ivf_flat_search.cuh:1086: coarse GEMM+select over centers,
then fused interleaved scan+top-k per probed list), `adaptive_centers`
(ivf_flat_types.hpp:63); pylibraft `neighbors.ivf_flat`.

TPU design (not a port): XLA needs static shapes, so the CUDA growable
interleaved lists become a **padded dense list-major store**:

  - `list_data` (n_lists, max_list, dim) — each vector stored inside its
    list's slots, the direct analogue of the reference's interleaved list
    chunks (data lives IN the lists, not behind an indirection). A probed
    list is one contiguous (max_list, dim) block, so search gathers whole
    lists with large DMAs instead of per-row random access.
  - `slot_rows` (n_lists, max_list) int32 — slot -> position in
    `source_ids`, -1 for padding (kIndexGroupSize-style group-of-32
    padding, ivf_list_types.hpp:42); balanced k-means keeps max/mean small.

Search = coarse top-n_probes over centers (one MXU matmul + select_k), then
per query block: gather probed lists, one batched matmul for the fine
distances, mask padding, select_k. Both stages ride the MXU; the list
gather is the HBM-bandwidth term the reference pays in its interleaved scan.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.core.config import auto_convert_output
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.cluster import kmeans_balanced


@dataclasses.dataclass
class IndexParams:
    """Mirrors ivf_flat::index_params (ivf_flat_types.hpp:44-70)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)


@dataclasses.dataclass
class SearchParams:
    """Mirrors ivf_flat::search_params (ivf_flat_types.hpp:125).

    engine (TPU design choice, no reference analogue):
      "query" — query-major: gather each query's probed lists and score
                with one batched matmul per query block.
      "list"  — list-major: probe pairs inverted into per-list chunks so
                each list's vectors stream from HBM once per batch
                (~nq*n_probes/n_lists x less gather traffic; best for
                large query batches). Per-chunk candidate trimming uses
                the TPU approximate top-k at recall_target=0.99; the
                final per-query merge is exact.
      "auto"  — "list" when the batch re-reads each list >= 4x, else
                "query".

    The default stays "query": IVF-Flat's contract is exact-within-probed-
    lists (recall loss comes only from probing), and the list engine's
    0.99-target chunk trim would bend that silently. Opt into "list"/"auto"
    for batch-throughput workloads.

    Adaptive probing (neighbors/probe_budget, ROADMAP item 2): with
    `adaptive=True` (or a `recall_target` / explicit `budget_tau`) each
    query gets its own probe budget from the normalized gap profile of
    its sorted coarse scores, clamped to [`min_probes`, n_probes], and
    — when the index carries build-time list radii and the metric is
    L2 — `early_term` additionally skips probed lists whose distance
    lower bound provably cannot reach the query's top-k. All engines
    honor the resulting keep mask; `recall_target=1.0` (the saturated
    plan) is bit-identical to the fixed-n_probes reference, which also
    remains the fallback whenever radii are absent (old checkpoints)
    or centers move under `adaptive_centers`.

    "pallas" (alias "fused"; experimental until validated on-chip) runs
    the list-major scheme with the fused distance+select-k Pallas
    kernel (ops/fused_scan.fused_list_topk, the analogue of the
    reference's fused interleaved scan, ivf_flat_search.cuh:670):
    scoring + an EXACT in-kernel partial top-k stay fused, so the
    (chunk, L) score tile never touches HBM and — unlike the older
    bin-trim kernel — the engine is exact-within-probed-lists, the same
    contract as "query"/"list" modulo bf16 rounding of the residual
    store. It pads the index's list store to lane multiples IN PLACE on
    first use (monotone; other engines then recompile once for the
    wider shape and scan the masked pad slots), records the compiled
    candidate-buffer width (`Index.fused_kb`, grown monotonically when
    a later search asks for a larger k — a k past the recorded width
    must rebuild, never silently truncate candidates), and caps k at
    256. Scoring streams a derived bf16 RESIDUAL store (v - center,
    built lazily like IVF-PQ's recon8; +0.5x dataset HBM): residual
    magnitudes keep the bf16 matmul precise and halve the scan's
    dominant HBM stream.
    """

    n_probes: int = 20
    engine: str = "query"  # "query" | "list" | "auto" | "pallas"
    # -- adaptive probing (neighbors/probe_budget) --
    adaptive: bool = False
    recall_target: Optional[float] = None  # implies adaptive; >=1 saturates
    budget_tau: Optional[float] = None     # explicit profile cutoff
    min_probes: int = 1
    early_term: bool = True                # bound-based list skipping


class Index:
    """IVF-Flat index (ivf_flat_types.hpp:126 `struct index`).

    Attributes (all jax.Arrays):
      centers    (n_lists, dim) f32 coarse centroids
      list_data  (n_lists, max_list, dim) vectors in list-major slots
      slot_rows  (n_lists, max_list) int32 slot -> source_ids position (-1 pad)
      list_sizes (n_lists,) int32
      source_ids (n_rows,) int32 caller row ids
    """

    def __init__(self, params: IndexParams, centers, list_data, slot_rows, list_sizes, source_ids):
        self.params = params
        self.centers = centers
        self.list_data = list_data
        self.slot_rows = slot_rows
        self.list_sizes = list_sizes
        self.source_ids = source_ids
        # derived store for the fused Pallas engine (built lazily, like
        # IVF-PQ's recon8): bf16 per-slot residuals v - center and their
        # f32 norms |v - center|^2, plus the candidate-buffer width the
        # fused kernel was compiled for (k past it triggers a monotone
        # rebuild in _pad_store_to_lanes — never a silent truncation)
        self.resid_bf16 = None
        self.resid_norm = None
        self.fused_kb = None
        # per-list radii (max member distance to its centroid), the
        # early-termination bounds of adaptive probing: computed in one
        # pass at build, max-folded by extend, serialized alongside the
        # store. None = bounds absent (old checkpoints, or centers moved
        # under adaptive_centers) -> budgets-only fallback.
        self.list_radii = None
        # live-mutation state (neighbors/mutation): `tombstones` is an
        # optional (n_lists, max_list) dead-row mask (nonzero = dead;
        # None = all live, the zero-cost fast path — searches on an
        # unmutated index trace the identical program). `mut_cursor`
        # counts applied mutation-log entries at the last checkpoint
        # commit; `append_slack` records the per-list tail-slot reserve
        # the mutator maintains so upserts land without re-padding.
        self.tombstones = None
        self.mut_cursor = 0
        self.append_slack = 0
        # integrity sidecar (raft_tpu/integrity): per-list / per-table
        # CRC-32C digests; None = no sidecar (legacy), the scrubber
        # attaches one on first contact
        self.list_digests = None
        self.table_digests = None
        self._id_bound = None

    @property
    def n_tombstones(self) -> int:
        """Dead-slot count (0 when all-live) — the truthful-accounting
        input: cost-model charges bill live rows only."""
        if self.tombstones is None:
            return 0
        return int(jnp.sum(jnp.asarray(self.tombstones).astype(jnp.int32)))

    @property
    def id_bound(self) -> int:
        """One past the largest source id — the id space a search
        `prefilter` must cover. Equals `size` for default arange ids;
        larger when extend() was given custom new_indices (a size-bound
        filter would silently exclude those rows). Cached per Index
        instance (extend returns a new Index, so mutation invalidates)."""
        if self._id_bound is None:
            self._id_bound = (
                int(jnp.max(self.source_ids)) + 1 if self.size else 0
            )
        return self._id_bound

    @property
    def metric(self) -> DistanceType:
        return self.params.metric

    @property
    def n_lists(self) -> int:
        return int(self.centers.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centers.shape[1])

    @property
    def size(self) -> int:
        return int(self.source_ids.shape[0])

    @property
    def adaptive_centers(self) -> bool:
        return self.params.adaptive_centers

    @property
    def dataset(self) -> jax.Array:
        """Flat (n, dim) view of the stored vectors in insertion order
        (decoded from the list-major store; build-time helper, not a hot
        path)."""
        return _unpack_flat(self.list_data, self.slot_rows, self.size)

    def __repr__(self):
        return (
            f"ivf_flat.Index(n_lists={self.n_lists}, dim={self.dim}, size={self.size}, "
            f"metric={self.metric.name})"
        )


# ---------------------------------------------------------------------------
# build / extend
# ---------------------------------------------------------------------------


def _pack_lists(labels: np.ndarray, n_lists: int, group: int = 32):
    """Build the padded slot table from assignment labels.

    Rounds max list size up to a multiple of `group`, mirroring the
    reference's kIndexGroupSize=32 interleaving (ivf_list_types.hpp:42) —
    keeps gathered tiles lane-aligned on the VPU. Uses the native C++
    packer (raft_tpu.native) when available; numpy fallback below.
    """
    from raft_tpu import native

    packed = native.pack_lists(np.asarray(labels), n_lists, group)
    if packed is not None:
        return packed
    sizes = np.bincount(labels, minlength=n_lists)
    max_sz = max(int(sizes.max()) if len(labels) else 0, 1)
    max_sz = -(-max_sz // group) * group
    row_ids = np.full((n_lists, max_sz), -1, np.int32)
    order = np.argsort(labels, kind="stable")
    starts = np.zeros(n_lists + 1, np.int64)
    np.cumsum(sizes, out=starts[1:])
    for l in range(n_lists):
        members = order[starts[l] : starts[l + 1]]
        row_ids[l, : len(members)] = members
    return row_ids, sizes.astype(np.int32)


def _unpack_flat(list_data: jax.Array, slot_rows: jax.Array, n: int) -> jax.Array:
    """Recover the flat (n, d) row store from the list-major slots."""
    d = list_data.shape[-1]
    valid = slot_rows >= 0
    rows = jnp.where(valid, slot_rows, n)  # dump padding into a scratch row
    flat = jnp.zeros((n + 1, d), list_data.dtype).at[rows.reshape(-1)].set(
        list_data.reshape(-1, d)
    )
    return flat[:n]


@obs.spanned("neighbors.ivf_flat.build")
def build(params: IndexParams, dataset, resources=None, seed: int = 0) -> Index:
    """Train coarse centers (balanced k-means on a trainset fraction) and
    populate lists (detail/ivf_flat_build.cuh `build`)."""
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(dataset, name="dataset")
    n = x.shape[0]
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
    n_train = max(params.n_lists, int(n * frac)) if frac < 1.0 else n
    n_train = min(n_train, n)
    # random trainset subsample (parity with ivf_flat_build.cuh's build
    # path, which subsamples its trainset; IVF-PQ here already does):
    # a first-n slice is biased on sorted/clustered datasets
    if n_train < n:
        from raft_tpu.random.rng import sample_without_replacement

        sel = sample_without_replacement(jax.random.PRNGKey(seed), n, n_train)
        x_train = x[sel]
    else:
        x_train = x
    metric_name = "inner_product" if params.metric == DistanceType.InnerProduct else "sqeuclidean"
    if params.n_lists > 1024:
        centers = kmeans_balanced.fit_hierarchical(
            x_train, params.n_lists, n_iters=params.kmeans_n_iters, metric=metric_name,
            seed=seed,
        )
    else:
        centers = kmeans_balanced.fit(
            x_train, params.n_lists, n_iters=params.kmeans_n_iters, metric=metric_name,
            seed=seed,
        )
    index = Index(
        params,
        centers,
        jnp.zeros((params.n_lists, 1, x.shape[1]), x.dtype),
        jnp.full((params.n_lists, 1), -1, jnp.int32),
        jnp.zeros((params.n_lists,), jnp.int32),
        jnp.zeros((0,), jnp.int32),
    )
    # empty index: every list radius is 0 — extend max-folds each batch
    # in, so streamed builds carry exact bounds at no extra pass
    index.list_radii = jnp.zeros((params.n_lists,), jnp.float32)
    if params.add_data_on_build:
        index = extend(index, x, jnp.arange(n, dtype=jnp.int32))
    # build-time integrity sidecar: one full digest pass here, then
    # every mutation keeps it incrementally fresh (integrity/digest)
    from raft_tpu.integrity.digest import attach as _attach_digests

    _attach_digests(index, "ivf_flat")
    return index


def _append_slots(labels_new: np.ndarray, old_sizes: np.ndarray, n_lists: int,
                  group: int = 32):
    """Compute per-new-row (list, slot) placements appended after the
    existing list contents, and the grown table geometry.

    Returns (slot_abs (n_new,), new_sizes (n_lists,), new_max_list int) —
    O(n_new) host work, independent of the rows already stored (this is what
    makes batched `extend` linear overall)."""
    counts_new = np.bincount(labels_new, minlength=n_lists)
    new_sizes = old_sizes + counts_new
    new_max = max(int(new_sizes.max()) if n_lists else 1, 1)
    new_max = -(-new_max // group) * group
    # stable within-list order of the new rows
    order = np.argsort(labels_new, kind="stable")
    rank = np.empty_like(order)
    starts = np.zeros(n_lists, np.int64)
    starts[1:] = np.cumsum(counts_new)[:-1]
    rank[order] = np.arange(len(labels_new)) - starts[labels_new[order]]
    slot_abs = old_sizes[labels_new] + rank
    return slot_abs.astype(np.int32), new_sizes.astype(np.int32), new_max


@functools.partial(jax.jit, static_argnames=("new_max",))
def _grow_and_scatter_multi(tables, slot_rows, new_rows, labels, slots,
                            positions, new_max: int):
    """Grow N parallel list tables to new_max slots and place the new
    batch into its (label, slot) cells with ONE shared placement. The
    placement is a sort + searchsorted + gather — NOT an XLA scatter,
    which TPU lowers to a serialized per-index loop (a 1M-row extend
    would crawl): sort the new rows by destination cell, then every
    table cell binary-searches whether a new row landed on it and
    selects between the old value and that row. Multi-payload indexes
    (IVF-RaBitQ's codes + corrections) pay the sort once."""
    old_max = tables[0].shape[1]
    if new_max > old_max:
        tables = tuple(
            jnp.pad(t, ((0, 0), (0, new_max - old_max), (0, 0)))
            for t in tables
        )
        slot_rows = jnp.pad(
            slot_rows, ((0, 0), (0, new_max - old_max)), constant_values=-1
        )
    n_lists = tables[0].shape[0]
    n_new = new_rows[0].shape[0]
    if n_new == 0:
        return tables, slot_rows
    fl = labels.astype(jnp.int32) * new_max + slots.astype(jnp.int32)  # unique cells
    order = jnp.argsort(fl)
    sorted_fl = fl[order]
    cells = jnp.arange(n_lists * new_max, dtype=jnp.int32)
    pos = jnp.minimum(
        jnp.searchsorted(sorted_fl, cells).astype(jnp.int32), n_new - 1
    )
    hit = sorted_fl[pos] == cells
    row = order[pos]
    out = []
    for t, nv in zip(tables, new_rows):
        d = t.shape[-1]
        flat = t.reshape(n_lists * new_max, d)
        flat = jnp.where(hit[:, None], nv[row].astype(flat.dtype), flat)
        out.append(flat.reshape(n_lists, new_max, d))
    flat_rows = slot_rows.reshape(n_lists * new_max)
    flat_rows = jnp.where(hit, positions[row], flat_rows)
    return tuple(out), flat_rows.reshape(n_lists, new_max)


def _grow_and_scatter(list_data, slot_rows, nv, labels, slots, positions,
                      new_max: int):
    """Single-payload wrapper over `_grow_and_scatter_multi` (IVF-Flat's
    vectors, IVF-PQ's codes)."""
    (out,), rows = _grow_and_scatter_multi(
        (list_data,), slot_rows, (nv,), labels, slots, positions, new_max
    )
    return out, rows


@obs.spanned("neighbors.ivf_flat.extend")
def extend(index: Index, new_vectors, new_indices=None) -> Index:
    """Append vectors to the index (ivf_flat build.cuh `extend`): label ONLY
    the new rows, grow the list tables, scatter the batch into its slots.
    Cost is O(n_new + table copy) — no re-clustering or re-packing of the
    rows already stored, so streamed builds stay linear."""
    from raft_tpu.core.validation import check_matrix

    nv = check_matrix(new_vectors, name="new_vectors")
    old_n = index.size
    if new_indices is None:
        new_indices = jnp.arange(old_n, old_n + nv.shape[0], dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    metric_name = (
        "inner_product" if index.metric == DistanceType.InnerProduct else "sqeuclidean"
    )
    labels = np.asarray(kmeans_balanced.predict(nv, index.centers, metric=metric_name))
    old_sizes = np.asarray(index.list_sizes, np.int64)
    slot_abs, new_sizes, new_max = _append_slots(labels, old_sizes, index.n_lists)
    # a store padded for the Pallas engine may be wider than the sizes
    # imply — never shrink it (slots stay where they are)
    new_max = max(new_max, int(index.list_data.shape[1]))
    positions = jnp.arange(old_n, old_n + nv.shape[0], dtype=jnp.int32)
    list_data, slot_rows = _grow_and_scatter(
        index.list_data,
        index.slot_rows,
        jnp.asarray(nv).astype(index.list_data.dtype),
        jnp.asarray(labels),
        jnp.asarray(slot_abs),
        positions,
        new_max,
    )
    all_ids = jnp.concatenate([index.source_ids, new_indices]) if old_n else new_indices

    centers = index.centers
    if index.adaptive_centers:
        # running-mean center update from the new batch only
        # (ivf_flat_types.hpp:63 semantics, applied incrementally)
        from raft_tpu.cluster.kmeans_common import assign_and_reduce

        _, sums, counts, _ = assign_and_reduce(jnp.asarray(nv), centers)
        old_w = jnp.asarray(old_sizes, jnp.float32)[:, None]
        total = old_w + counts[:, None]
        upd = (centers * old_w + sums) / jnp.maximum(total, 1.0)
        centers = jnp.where(counts[:, None] > 0, upd, centers)

    out = Index(
        index.params, centers, list_data, slot_rows, jnp.asarray(new_sizes), all_ids
    )
    if index.adaptive_centers:
        # moved centers invalidate the stored bounds (radii were taken
        # against the OLD centers); adaptive probing falls back to
        # budgets-only, the documented bounds-absent semantics
        out.list_radii = None
    else:
        from raft_tpu.neighbors.probe_budget import updated_radii

        dists = np.asarray(jnp.sqrt(jnp.maximum(jnp.sum(
            (jnp.asarray(nv, jnp.float32) - index.centers[jnp.asarray(labels)]
             ) ** 2, axis=1), 0.0)))
        out.list_radii = updated_radii(
            index.list_radii, labels, dists, index.n_lists)
    # mutation state survives extend: the mask pads with live columns
    # when the store grew (new tail slots are live appends by
    # construction), cursor/slack carry verbatim
    from raft_tpu.core.bitset import carry_tombstones

    out.tombstones = carry_tombstones(index.tombstones, new_max)
    out.mut_cursor = index.mut_cursor
    out.append_slack = index.append_slack
    # integrity sidecar: only the lists this batch touched re-digest
    from raft_tpu.integrity.digest import refresh as _refresh_digests

    _refresh_digests(out, index, "ivf_flat")
    return out


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _coarse_scores(queries: jax.Array, centers: jax.Array, metric: DistanceType):
    from raft_tpu.distance.pairwise import _dot

    if metric == DistanceType.InnerProduct:
        return _dot(queries, centers), False  # larger better
    d = _dot(queries, centers)
    qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1)[:, None]
    cn = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)[None, :]
    return jnp.maximum(qn + cn - 2.0 * d, 0.0), True  # smaller better


def resolve_auto_engine(nq: int, n_probes: int, n_lists: int,
                        pallas_ok=None) -> str:
    """The ONE "auto" engine policy, shared by the single-chip and
    distributed searches: a tuned winner (`flat_auto_engine`) first,
    else the duplication heuristic (list-major streams each probed list
    once, paying off when nq*n_probes/n_lists >= 4). A tuned "fused"
    winner names the fused scan+select kernel — the same engine the
    "pallas" spelling always named, so both resolve identically.
    `pallas_ok` (callable or None) gates that winner: None means the
    caller has no pallas engine (distributed) and the winner maps to
    "list", its closest list-major analogue."""
    from raft_tpu.core import tuned

    t = tuned.get("flat_auto_engine")
    if t == "fused":
        t = "pallas"  # one fused engine, two spellings
    if t == "pallas":
        if pallas_ok is None:
            t = "list"
        elif not pallas_ok():
            t = None  # tuned winner doesn't fit this index/k; fall through
    if t in ("query", "list", "pallas"):
        return t
    dup = nq * n_probes / max(1, n_lists)
    return "list" if dup >= 4.0 else "query"


@functools.partial(
    jax.jit, static_argnames=("k", "n_probes", "metric", "query_block")
)
def _search_impl(
    queries: jax.Array,
    centers: jax.Array,
    list_data: jax.Array,
    slot_rows: jax.Array,
    k: int,
    n_probes: int,
    metric: DistanceType,
    query_block: int = 8,
    pvalid: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (distances, slot-table values): the second output carries
    whatever `slot_rows` holds per slot (source positions locally; global
    row ids in the distributed path). `pvalid` ((nq, n_probes) bool,
    optional): the adaptive probe keep mask — masked probes' slots read
    as -1, exactly like padding, before any selection."""
    nq = queries.shape[0]
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    cs, coarse_min = _coarse_scores(queries, centers, metric)
    _, probes = _select_k_impl(cs, n_probes, coarse_min)  # (nq, n_probes)

    qb = min(query_block, nq)
    nblocks = -(-nq // qb)
    pad = nblocks * qb - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0))) if pad else queries
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qblocks = qp.reshape(nblocks, qb, -1)
    pblocks = pp.reshape(nblocks, qb, n_probes)
    if pvalid is not None:
        pvp = jnp.pad(pvalid, ((0, pad), (0, 0))) if pad else pvalid
        pvblocks = pvp.reshape(nblocks, qb, n_probes)

    from raft_tpu.distance.pairwise import _MATMUL_PRECISION

    def block(inp):
        if pvalid is not None:
            qs, pr, pvb = inp  # (qb, dim), (qb, n_probes), (qb, n_probes)
        else:
            qs, pr = inp  # (qb, dim), (qb, n_probes)
        cand = slot_rows[pr]  # (qb, n_probes, max_list), -1 pad
        if pvalid is not None:
            cand = jnp.where(pvb[:, :, None], cand, -1)
        cand = cand.reshape(qb, -1)  # (qb, C) table values, -1 pad
        cdata = list_data[pr].reshape(qb, cand.shape[1], -1)  # (qb, C, dim)
        dots = jnp.einsum(
            "qd,qcd->qc", qs, cdata.astype(jnp.float32), precision=_MATMUL_PRECISION
        )
        if metric == DistanceType.InnerProduct:
            score = dots
        else:
            qn = jnp.sum(qs.astype(jnp.float32) ** 2, axis=1)[:, None]
            cn = jnp.sum(cdata.astype(jnp.float32) ** 2, axis=2)
            score = jnp.maximum(qn + cn - 2.0 * dots, 0.0)
        score = jnp.where(cand >= 0, score, worst)
        v, pos = _select_k_impl(score, k, select_min)
        ids = jnp.take_along_axis(cand, pos, axis=1)
        return v, ids

    vals, ids = lax.map(
        block,
        (qblocks, pblocks, pvblocks) if pvalid is not None
        else (qblocks, pblocks))
    vals = vals.reshape(-1, k)[:nq]
    ids = ids.reshape(-1, k)[:nq]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(vals)
    return vals, ids


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "chunk", "chunk_block", "setup_impls",
    ),
)
def _search_impl_listmajor(
    queries: jax.Array,
    centers: jax.Array,
    list_data: jax.Array,
    slot_rows: jax.Array,
    k: int,
    n_probes: int,
    metric: DistanceType,
    chunk: int = 128,
    chunk_block: int = 0,
    setup_impls: tuple = ("sort", "gather"),
    pvalid: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """List-major search: each list's vectors stream from HBM once per
    ~chunk probing queries and score with one MXU matmul — vs the
    query-major engine re-reading every probed list per query block
    (~nq*n_probes/n_lists x more gather traffic). Same candidate math; the
    per-chunk trim uses the TPU approximate top-k (recall_target=0.99, like
    the reference's filtered warp queues) and the final per-query merge is
    exact. See neighbors/probe_invert.py for the pair-inversion scheme.
    `pvalid` (adaptive probe budgets): masked pairs are dropped before
    inversion and masked again at the regroup."""
    from raft_tpu.distance.pairwise import _MATMUL_PRECISION
    from raft_tpu.neighbors.probe_invert import (
        gather_query_rows,
        invert_probes_count,
        invert_probes_sort,
        score_and_select,
    )

    nq, dim = queries.shape
    n_lists, max_list, _ = list_data.shape
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    cs, coarse_min = _coarse_scores(queries, centers, metric)
    _, probes = _select_k_impl(cs, n_probes, coarse_min)
    # impls resolved by the caller OUTSIDE this jit (static args)
    invert_impl, qs_impl = setup_impls
    invert = invert_probes_count if invert_impl == "count" else invert_probes_sort
    tables = invert(probes, n_lists, chunk, pvalid)

    qf = queries.astype(jnp.float32)
    q_pad = jnp.concatenate([qf, jnp.zeros((1, dim), jnp.float32)])

    def block(inp):
        lofb, qids = inp  # (CB,), (CB, chunk)
        v = list_data[lofb].astype(jnp.float32)  # only read of these vectors
        srows = slot_rows[lofb]
        qs = gather_query_rows(q_pad, qids, qs_impl)  # (CB, chunk, dim)
        dots = jnp.einsum("lqd,lsd->lqs", qs, v, precision=_MATMUL_PRECISION)
        if metric == DistanceType.InnerProduct:
            score = dots
        else:
            qn = jnp.sum(qs**2, axis=2)[:, :, None]
            vn = jnp.sum(v**2, axis=2)[:, None, :]
            score = jnp.maximum(qn + vn - 2.0 * dots, 0.0)
        return jnp.where(srows[:, None, :] >= 0, score, worst)

    v, ids = score_and_select(
        tables, block, slot_rows, _select_k_impl, nq, n_probes, k, select_min,
        chunk, chunk_block, max_list,
    )
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(v)
    return v, ids


def _pad_store_to_lanes(index: Index, k: int) -> None:
    """Monotone in-place pad of the list store to the fused Pallas scan's
    lane contract (ops/pq_list_scan.lane_padded). Pad slots carry
    slot_rows=-1 and zero vectors, which every engine already masks; once
    padded the store stays padded (other engines recompile once for the
    wider shape and scan the masked pad slots).

    Also (re)builds the fused engine's derived store (the IVF-Flat
    analogue of IVF-PQ's build_reconstruction): per-slot RESIDUALS
    v - center_l in bf16 plus their f32 norms. Residuals are small, so
    the kernel's bf16 matmul keeps relative precision (scoring raw
    vectors loses ~1e-2 on near-ties from the large common component),
    and bf16 halves the dominant HBM stream of the scan. Costs 0.5x the
    dataset in extra HBM, rebuilt lazily after extend.

    `k` sizes the compiled candidate-buffer width (`Index.fused_kb`,
    ops/fused_scan.fused_kbuf): searches with k <= fused_kb reuse the
    store geometry as compiled; a LARGER k must grow the width here
    (monotone, like the lane pad) — before this check existed, only a
    store-shape change triggered the rebuild and a k past the compiled
    width silently truncated the per-list candidates."""
    from raft_tpu.ops.fused_scan import fused_kbuf
    from raft_tpu.ops.pq_list_scan import lane_padded

    max_list = index.list_data.shape[1]
    extra = lane_padded(max_list) - max_list
    if extra:
        index.list_data = jnp.pad(index.list_data, ((0, 0), (0, extra), (0, 0)))
        index.slot_rows = jnp.pad(
            index.slot_rows, ((0, 0), (0, extra)), constant_values=-1
        )
    if (
        getattr(index, "resid_bf16", None) is None
        or index.resid_bf16.shape != index.list_data.shape
    ):
        resid = index.list_data.astype(jnp.float32) - index.centers[:, None, :]
        valid = (index.slot_rows >= 0)[:, :, None]
        resid = jnp.where(valid, resid, 0.0)  # pad slots: exact zeros
        index.resid_bf16 = resid.astype(jnp.bfloat16)
        index.resid_norm = jnp.sum(resid**2, axis=2)
    kb = fused_kbuf(int(k))
    if getattr(index, "fused_kb", None) is None or kb > index.fused_kb:
        index.fused_kb = kb


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "kb", "n_probes", "metric", "chunk", "interpret",
        "setup_impls", "fault_key",
    ),
)
def _search_impl_listmajor_pallas(
    queries: jax.Array,
    centers: jax.Array,
    resid_bf16: jax.Array,
    resid_norm: jax.Array,
    slot_rows: jax.Array,
    k: int,
    n_probes: int,
    metric: DistanceType,
    chunk: int = 128,
    kb: int = None,
    interpret: bool = False,
    setup_impls: tuple = ("sort", "gather"),
    fault_key=None,
    pvalid: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """List-major IVF-Flat search with the fused distance+select-k scan
    (ops/fused_scan.fused_list_topk — the kernel is store-dtype
    generic: here it streams bf16 per-slot RESIDUALS v - center;
    |q - v|^2 = |q'|^2 - 2 q'.res + |res|^2 with q' = q - center, so
    the bf16 matmul sees only small residual magnitudes and the store
    stream is half the bytes of raw f32). Scoring + an EXACT in-kernel
    partial top-k happen fused, so the (chunk, L) score tile never
    round-trips HBM — the TPU analogue of the reference's fused
    interleaved scan (detail/ivf_flat_search.cuh:670), now without the
    bin-trim recall tax the pq_list_scan engine paid. Probe inversion
    and the exact final merge are shared with the XLA trim engine.
    `kb` is the index's recorded candidate-buffer width (fused_kb);
    `fault_key` = faults.trace_key() so chaos plans retrace."""
    from raft_tpu.matrix.select_k import list_scan_select_k
    from raft_tpu.neighbors.probe_invert import (
        chunk_validity,
        gather_query_rows,
        invert_probes_count,
        invert_probes_sort,
        regroup_merge,
    )

    nq, dim = queries.shape
    n_lists, lpad, _ = resid_bf16.shape
    select_min = metric != DistanceType.InnerProduct
    ip = metric == DistanceType.InnerProduct

    cs, coarse_min = _coarse_scores(queries, centers, metric)
    _, probes = _select_k_impl(cs, n_probes, coarse_min)
    invert_impl, qs_impl = setup_impls
    invert = invert_probes_count if invert_impl == "count" else invert_probes_sort
    tables = invert(probes, n_lists, chunk, pvalid)
    lof, qid_tbl = tables.lof, tables.qid_tbl
    ncb = lof.shape[0]
    # empty chunks (trailing fragmentation + everything adaptive budgets
    # emptied) skip their MXU work inside the kernel
    cvalid = chunk_validity(qid_tbl, nq)

    qf = queries.astype(jnp.float32)
    q_pad = jnp.concatenate([qf, jnp.zeros((1, dim), jnp.float32)])
    qs = gather_query_rows(q_pad, qid_tbl, qs_impl)  # (ncb, chunk, dim)
    cent = centers[lof]  # (ncb, dim)
    qres = qs if ip else qs - cent[:, None, :]

    valid = slot_rows >= 0
    if ip:
        base = jnp.where(valid, 0.0, jnp.inf)[:, None, :]
    else:
        base = jnp.where(valid, resid_norm, jnp.inf)[:, None, :]

    vals, slot_idx = list_scan_select_k(
        lof, qres, resid_bf16, base, k, strategy="fused", kbuf=kb,
        inner_product=ip, interpret=interpret, fault_key=fault_key,
        chunk_valid=cvalid,
    )  # (ncb, chunk, kb) exact best-first, minimizing
    # the buffer is sorted: the first k slots ARE the per-(query, list)
    # top-k, so the old post-kernel trim select is gone entirely
    vals = vals[:, :, :k]
    slot_idx = slot_idx[:, :, :k]

    invalid = ~jnp.isfinite(vals)
    slot_idx = jnp.where(invalid, 0, slot_idx)  # sentinel -> safe gather
    rows = jnp.take_along_axis(slot_rows[lof][:, None, :], slot_idx, axis=2)
    rows = jnp.where(invalid, -1, rows)

    if ip:
        # IP score = q.res + q.center; kernel returned -(q.res) on valid
        qdotc = jnp.einsum("cqd,cd->cq", qs, cent)
        vals = jnp.where(invalid, -jnp.inf, -vals + qdotc[:, :, None])
    else:
        qn = jnp.sum(qres**2, axis=2)  # |q - center|^2 per (chunk row)
        vals = jnp.maximum(vals + qn[:, :, None], 0.0)

    v, rows_out = regroup_merge(
        tables, vals, rows, _select_k_impl, nq, n_probes, int(k), select_min
    )
    v = v.astype(jnp.float32)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, rows_out


def _pallas_fits(index, k: int) -> bool:
    """engine='pallas' envelope: the fused kernel's k cap and the VMEM
    budget for one grid step (the scanned store is the bf16 residual
    copy, itemsize 2) — ONE definition shared by the auto-dispatch gate
    and the explicit-engine validation. Checked at the buffer width the
    kernel will RUN with: the recorded fused_kb when it is already
    wider than this k needs (a k=10 search on a store grown to kb=256
    compiles the 256-wide buffer). raftlint's `dispatch-envelope-guard`
    machine-checks that every route into the fused kernel stays under
    this validation (docs/linting.md, kernelcheck catalog)."""
    from raft_tpu.ops.fused_scan import (
        FUSED_MAX_K, fits_fused_list, fused_kbuf,
    )
    from raft_tpu.ops.pq_list_scan import lane_padded

    if not 0 < k <= FUSED_MAX_K:
        return False
    kb = max(fused_kbuf(int(k)), getattr(index, "fused_kb", None) or 0)
    return fits_fused_list(
        128, lane_padded(int(index.list_data.shape[1])), index.dim, int(k),
        store_itemsize=2, kbuf=kb,
    )


@obs.spanned("neighbors.ivf_flat.search")
@auto_convert_output
def search(
    params: SearchParams,
    index: Index,
    queries,
    k: int,
    resources=None,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (distances, neighbor source ids), (nq, k), best-first
    (pylibraft ivf_flat.search signature).

    `prefilter`: optional `core.bitset.Bitset` (or 1-D boolean mask) over
    the index's id space (`index.id_bound` ids — == size unless extend() used custom new_indices) — samples whose bit is clear
    are excluded before any trim/selection in EVERY engine, including the
    fused Pallas scan (sample-filtering parity with later RAFT's
    `search_with_filtering`). When fewer than k samples pass, the tail
    holds the worst distance with id -1."""
    from raft_tpu.core.validation import check_matrix

    q = check_matrix(queries, name="queries")
    if q.shape[1] != index.dim:
        raise ValueError(f"query dim {q.shape[1]} != index dim {index.dim}")
    if index.size == 0:
        raise ValueError("index is empty")
    k = int(k)
    if not (0 < k):
        raise ValueError("k must be positive")
    n_probes = int(min(max(1, params.n_probes), index.n_lists))
    # every engine masks scores to the worst value wherever the slot
    # table reads -1 (before trim/selection), so a filtered view is the
    # entire filtering mechanism; applied per branch because the pallas
    # branch pads the table first
    from raft_tpu.core.bitset import make_slot_filter

    maybe_filter = make_slot_filter(prefilter, index.id_bound,
                                    index.source_ids,
                                    tombstones=index.tombstones)
    engine = params.engine
    if engine == "fused":
        engine = "pallas"  # one fused engine, two spellings
    if engine == "auto":
        engine = resolve_auto_engine(
            q.shape[0], n_probes, index.n_lists,
            pallas_ok=lambda: _pallas_fits(index, k),
        )
    # adaptive probing: one (nq, n_probes) keep mask from the coarse
    # geometry (budgets + optional radius bounds), applied by every
    # engine; None = the fixed-n_probes reference path, verbatim
    from raft_tpu.neighbors import probe_budget

    ap = probe_budget.resolve_params(params, n_probes)
    pvalid = None
    scanned_mean = None
    if ap is not None:
        # bounds stay OFF under a prefilter: list_sizes counts
        # filtered-out members, so a bound's k-covering prefix could be
        # entirely filtered and a list holding true ELIGIBLE neighbors
        # would be skipped — budgets-only is the sound fallback. Same
        # soundness argument for tombstones (sizes count dead rows).
        radii = (index.list_radii
                 if ap.early_term and prefilter is None
                 and index.tombstones is None else None)
        pvalid, scanned = probe_budget.probe_plan(
            jnp.asarray(q, jnp.float32), index.centers,
            n_probes=n_probes, min_probes=ap.min_probes, k=k,
            metric=index.metric, tau=ap.tau,
            radii=radii, sizes=index.list_sizes)
        scanned_mean = probe_budget.account(
            "ivf_flat", scanned, int(q.shape[0]), n_probes)
    if obs.enabled():
        # list-major streams every padded list; query-major touches the
        # probed ones — the model must charge what the engine scans
        # (the ACTUAL adaptive mean, not worst-case n_probes, on the
        # engines that skip masked work), and the fused engine never
        # materializes the score tile
        # truthful accounting under mutation: dead (tombstoned) slots
        # contribute no candidates, so the model bills live rows only
        obs.span_cost(**obs.perf.cost_for(
            "neighbors.ivf_flat.search", nq=int(q.shape[0]),
            n_probes=n_probes, n_lists=int(index.n_lists),
            n_rows=int(index.list_data.shape[0] * index.list_data.shape[1])
            - index.n_tombstones,
            dim=int(index.dim), k=k,
            scanned_lists=(int(index.n_lists) if engine == "list"
                           else (scanned_mean if scanned_mean is not None
                                 else n_probes)),
            fused=engine == "pallas"))
    if engine == "pallas":
        from raft_tpu.neighbors.probe_invert import macro_batched
        from raft_tpu.ops.fused_scan import FUSED_MAX_K

        if k > FUSED_MAX_K:
            raise ValueError(
                f"engine='pallas' caps per-list candidates at "
                f"{FUSED_MAX_K}; k={k}"
            )
        # check the VMEM envelope BEFORE padding the store: a rejected
        # request must not leave the index mutated
        if not _pallas_fits(index, k):
            raise ValueError(
                f"engine='pallas': padded list length x dim {index.dim} "
                "exceeds the kernel's VMEM envelope; use engine='list'"
            )
        _pad_store_to_lanes(index, k)
        srows = maybe_filter(index.slot_rows)
        from raft_tpu.core import faults
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        setup = resolve_setup_impls(index.n_lists, engine="flat")
        vals, rows = macro_batched(
            lambda sl, pv=None: _search_impl_listmajor_pallas(
                sl, index.centers, index.resid_bf16, index.resid_norm,
                srows, k, n_probes, index.metric, kb=index.fused_kb,
                interpret=jax.default_backend() == "cpu",
                setup_impls=setup, fault_key=faults.trace_key(),
                pvalid=pv,
            ),
            jnp.asarray(q),
            int(k),
            extra=pvalid,
        )
    elif engine == "list":
        from raft_tpu.core import tuned
        from raft_tpu.neighbors.probe_invert import CHUNK_BLOCKS, macro_batched

        srows = maybe_filter(index.slot_rows)
        cb = int(tuned.get_choice("listmajor_chunk_block", CHUNK_BLOCKS, 0))
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        setup = resolve_setup_impls(index.n_lists, engine="flat")
        vals, rows = macro_batched(
            lambda sl, pv=None: _search_impl_listmajor(
                sl, index.centers, index.list_data, srows, k, n_probes,
                index.metric, chunk_block=cb, setup_impls=setup,
                pvalid=pv,
            ),
            jnp.asarray(q),
            int(k),
            extra=pvalid,
        )
    elif engine == "query":
        vals, rows = _search_impl(
            q, index.centers, index.list_data, maybe_filter(index.slot_rows),
            k, n_probes, index.metric, pvalid=pvalid
        )
    else:
        raise ValueError(f"unknown engine {engine!r}")
    ids = jnp.where(rows >= 0, index.source_ids[jnp.maximum(rows, 0)], -1)
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


# ---------------------------------------------------------------------------
# serialization (detail/ivf_flat_serialize.cuh parity)
# ---------------------------------------------------------------------------

_SERIAL_VERSION = 4  # v2: list-major; v3: mutation; v4: digest sidecar


def save(filename: str, index: Index) -> None:
    from raft_tpu.core.serialize import serialize_arrays

    arrays = {
        "centers": index.centers,
        "list_data": index.list_data,
        "slot_rows": index.slot_rows,
        "list_sizes": index.list_sizes,
        "source_ids": index.source_ids,
    }
    if index.list_radii is not None:
        # early-termination bounds ride the checkpoint; old files
        # simply lack the key and load with bounds absent (fallback)
        arrays["list_radii"] = index.list_radii
    if index.tombstones is not None:
        # dead-row mask (u8: serialized compactly); absent = all-live,
        # the pre-mutation era's implicit contract
        arrays["tombstones"] = jnp.asarray(index.tombstones).astype(jnp.uint8)
    meta = {
        "kind": "ivf_flat",
        "version": _SERIAL_VERSION,
        "metric": int(index.metric),
        "metric_arg": index.params.metric_arg,
        "n_lists": index.n_lists,
        "adaptive_centers": index.params.adaptive_centers,
        # mutation protocol state: applied-log-entry count at this
        # commit + the mutator's reserved per-list tail slack
        "mut_cursor": int(index.mut_cursor),
        "append_slack": int(index.append_slack),
    }
    from raft_tpu.integrity.digest import pack_lists

    packed = pack_lists(index, "ivf_flat")
    if packed is not None:
        # per-list CRC-32C sidecar (v4) rides first-class so the
        # scrubber resumes with build-time coverage after a load
        arrays["list_digests"] = packed
        meta["table_digests"] = {
            k: int(v) for k, v in (index.table_digests or {}).items()}
    serialize_arrays(filename, arrays, meta)


def load(filename: str) -> Index:
    # schema-checked read (core.serialize.CKPT_SCHEMA): kind + version
    # gates, required-field presence, and corrupt registered-optional
    # fields (list_radii) dropped so the load degrades to budgets-only
    # instead of crashing
    from raft_tpu.core.serialize import read_ckpt

    arrays, meta = read_ckpt(filename, "ivf_flat")
    if meta.get("version", 1) < 2:
        raise ValueError("ivf_flat index file version too old (pre-list-major)")
    params = IndexParams(
        n_lists=meta["n_lists"],
        metric=DistanceType(meta["metric"]),
        metric_arg=meta.get("metric_arg", 2.0),
        adaptive_centers=meta.get("adaptive_centers", False),
    )
    index = Index(
        params,
        arrays["centers"],
        arrays["list_data"],
        arrays["slot_rows"],
        arrays["list_sizes"],
        arrays["source_ids"],
    )
    index.list_radii = arrays.get("list_radii")
    # mutation-era fields (v3): absent in old checkpoints -> all-live,
    # cursor 0, no reserved slack — exactly the pre-mutation semantics
    index.tombstones = arrays.get("tombstones")
    index.mut_cursor = int(meta.get("mut_cursor", 0))
    index.append_slack = int(meta.get("append_slack", 0))
    # integrity sidecar (v4): absent/corrupt -> no sidecar, the
    # scrubber attaches a fresh one on first contact
    from raft_tpu.integrity.digest import unpack_lists

    unpack_lists(index, "ivf_flat", arrays.get("list_digests"),
                 meta.get("table_digests"))
    return index
