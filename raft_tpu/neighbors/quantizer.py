"""Generalized vector-quantizer layer shared by the IVF indexes.

The quantizer is the piece of an IVF index that turns per-list residuals
(vector - coarse center) into compact codes and scores queries against
those codes without decompressing the lists. Before this module the only
implementation lived inline in `ivf_pq.py`; IVF-RaBitQ (arXiv
2602.23999) needs the same five verbs with a totally different code
format, so the verbs are a contract now:

    train(key, residuals, labels)    fit quantizer state (codebooks, ...)
    encode(residuals, labels)        residual rows -> {name: code array}
    decode(payload)                  codes -> approximate residuals
    score_table(query_residuals)     query-side scoring precomputation
    estimate_distances(table, ...)   scores from table + codes (reference
                                     semantics; the indexes own the
                                     blocked/jitted hot engines)
    rerank_candidates(...)           exact re-rank via neighbors/refine
    state_arrays()/state_meta()/from_state   serialize hooks

Two implementations:

  `PqQuantizer`     product quantization — the codebook-EM trainer and
                    the per-subspace encode MOVED here verbatim from
                    ivf_pq.py (same jitted functions, so the refactored
                    ivf_pq build/extend stay bit-identical to the
                    pre-refactor goldens in tests/goldens/).
  `RabitqQuantizer` RaBitQ — sign-binarized residuals packed into uint32
                    words plus two per-row correction scalars
                    (residual norm and <o, x_bar>), scanned with
                    AND+popcount integer ops and an UNBIASED distance
                    estimator (the paper's <q, x_bar>/<o, x_bar> form),
                    then cheaply reranked. Training is O(1): no
                    codebooks — the fast-build half of the paper.

Layering: this module is the shared foundation both `ivf_pq` and
`ivf_rabitq` import, so it must never import an index module at module
scope (tools/raftlint pins this — the quantizer-cycle layer rule).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.cluster.kmeans_balanced import _balanced_em

PER_SUBSPACE = "per_subspace"
PER_CLUSTER = "per_cluster"

#: query-side quantization bits of the RaBitQ scan (tuned override key:
#: "rabitq_query_bits"); 8 keeps the scalar-quantization error an order
#: of magnitude under the 1-bit code error at bench dims
DEFAULT_QUERY_BITS = 8


# ---------------------------------------------------------------------------
# the contract
# ---------------------------------------------------------------------------


class Quantizer:
    """Abstract quantizer: the five verbs every IVF code format provides.

    Implementations are lightweight state holders (jax arrays +
    geometry ints); the heavy math lives in jitted module functions so
    index engines can call the same traced programs the quantizer's
    reference methods use.
    """

    kind: str = "?"

    def train(self, key, residuals, labels=None) -> "Quantizer":
        """Fit quantizer state from a residual sample; returns self."""
        raise NotImplementedError

    def encode(self, residuals, labels=None) -> Dict[str, jax.Array]:
        """Encode residual rows -> named per-row code arrays."""
        raise NotImplementedError

    def decode(self, payload: Dict[str, jax.Array]) -> jax.Array:
        """Best-effort residual reconstruction from codes."""
        raise NotImplementedError

    def score_table(self, query_residuals, **kw) -> Dict[str, jax.Array]:
        """Query-side scoring precomputation (LUT / bit planes / ...)."""
        raise NotImplementedError

    def estimate_distances(self, table, payload, **kw) -> jax.Array:
        """(nq, m) estimated squared-L2 distances between the table's
        queries and the payload's codes — the reference scoring
        semantics the index engines must agree with."""
        raise NotImplementedError

    def rerank_candidates(self, dataset, queries, candidates, k,
                          metric="sqeuclidean", resources=None):
        """Exact re-rank of candidate rows through the shared refine
        stage (neighbors/refine.py) — identical for every quantizer, so
        the lossy format can never leak into the exact stage."""
        from raft_tpu.neighbors.refine import refine

        return refine(dataset, queries, candidates, k, metric=metric,
                      resources=resources)

    # -- serialize hooks ----------------------------------------------
    def state_arrays(self) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def state_meta(self) -> dict:
        raise NotImplementedError

    @classmethod
    def from_state(cls, arrays: Dict[str, jax.Array], meta: dict) -> "Quantizer":
        raise NotImplementedError


# ---------------------------------------------------------------------------
# PQ codebook training + encode (moved verbatim from ivf_pq.py — the
# jitted functions are THE implementation; ivf_pq re-exports them)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("pq_dim", "n_codebook", "n_iters"))
def _train_codebooks_per_subspace(key, residuals, pq_dim, n_codebook, n_iters):
    """vmapped balanced-EM over subspaces: residuals (n, rot_dim) ->
    (pq_dim, n_codebook, pq_len) codebooks. One compiled program trains all
    subspaces (train_per_subset, ivf_pq_build.cuh:393)."""
    n, rot_dim = residuals.shape
    pq_len = rot_dim // pq_dim
    sub = residuals.reshape(n, pq_dim, pq_len).transpose(1, 0, 2)  # (pq_dim, n, pq_len)
    keys = jax.random.split(key, pq_dim)
    # small trainsets (< 2^pq_bits residuals) fall back to sampling with
    # replacement; duplicate seeds separate during EM
    replace = n < n_codebook
    init_idx = jax.vmap(
        lambda k: jax.random.choice(k, n, (n_codebook,), replace=replace)
    )(keys)
    inits = jnp.take_along_axis(sub, init_idx[:, :, None], axis=1)

    em = functools.partial(_balanced_em, n_iters=n_iters, metric="sqeuclidean")
    return jax.vmap(em)(keys, sub, inits)


def _train_codebooks_per_cluster(
    key, residuals, labels, n_lists, pq_len, n_codebook, n_iters, samples_per_cluster=2048
):
    """Per-cluster codebooks (train_per_cluster, ivf_pq_build.cuh:473):
    every cluster trains ONE codebook over its residual subvectors (all
    subspaces pooled as samples). Host pads per-cluster sample sets to a
    fixed size, then one vmapped EM trains all clusters at once."""
    n, rot_dim = residuals.shape
    pq_dim = rot_dim // pq_len
    labels_np = np.asarray(labels)
    res_np = np.asarray(residuals).reshape(n * pq_dim, pq_len)
    rng = np.random.default_rng(0)
    batch = np.zeros((n_lists, samples_per_cluster, pq_len), np.float32)
    for l in range(n_lists):
        members = np.nonzero(labels_np == l)[0]
        if len(members) == 0:
            batch[l] = rng.normal(size=(samples_per_cluster, pq_len)).astype(np.float32)
            continue
        rows = (members[:, None] * pq_dim + np.arange(pq_dim)[None, :]).reshape(-1)
        take = rng.choice(rows, samples_per_cluster, replace=len(rows) < samples_per_cluster)
        batch[l] = res_np[take]
    batch = jnp.asarray(batch)
    keys = jax.random.split(key, n_lists)
    init_idx = jax.vmap(
        lambda k: jax.random.choice(k, samples_per_cluster, (n_codebook,), replace=False)
    )(keys)
    inits = jnp.take_along_axis(batch, init_idx[:, :, None], axis=1)
    em = functools.partial(_balanced_em, n_iters=n_iters, metric="sqeuclidean")
    return jax.vmap(em)(keys, batch, inits)


def _block_rows_for_encode(n: int, pq_dim: int, nb: int) -> int:
    # ~2^24 f32 elements (64MB) for the (bm, pq_dim, nb) distance block:
    # large enough that a 1M-row encode is a few hundred map iterations
    # (tiny blocks serialize the build), small enough to stay resident
    bm = max(1, (1 << 24) // max(1, pq_dim * nb))
    bm = min(bm, n)
    return max(8, bm // 8 * 8) if bm >= 8 else bm


@functools.partial(jax.jit, static_argnames=("per_cluster",))
def _encode(residuals, labels, pq_centers, per_cluster: bool) -> jax.Array:
    """Residuals (n, rot_dim) -> codes (n, pq_dim) uint8: per-subspace
    nearest codebook entry (compute_pq_code, ivf_pq_build.cuh:578)."""
    n, rot_dim = residuals.shape
    if per_cluster:
        n_books, nb, pq_len = pq_centers.shape
    else:
        pq_dim_, nb, pq_len = pq_centers.shape
    pq_dim = rot_dim // pq_len
    bm = _block_rows_for_encode(n, pq_dim, nb)
    nblocks = -(-n // bm)
    pad = nblocks * bm - n
    rp = jnp.pad(residuals, ((0, pad), (0, 0))) if pad else residuals
    lp = jnp.pad(labels, (0, pad)) if pad else labels
    rblocks = rp.reshape(nblocks, bm, pq_dim, pq_len)
    lblocks = lp.reshape(nblocks, bm)

    def enc(inp):
        rb, lb = inp  # (bm, pq_dim, pq_len), (bm,)
        if per_cluster:
            books = pq_centers[lb]  # (bm, nb, pq_len)
            d = (
                jnp.sum(rb**2, axis=2)[:, :, None]
                - 2.0 * jnp.einsum("mpl,mbl->mpb", rb, books)
                + jnp.sum(books**2, axis=2)[:, None, :]
            )
        else:
            d = (
                jnp.sum(rb**2, axis=2)[:, :, None]
                - 2.0 * jnp.einsum("mpl,pbl->mpb", rb, pq_centers)
                + jnp.sum(pq_centers**2, axis=2)[None, :, :]
            )
        return jnp.argmin(d, axis=2).astype(jnp.uint8)

    codes = lax.map(enc, (rblocks, lblocks))
    return codes.reshape(-1, pq_dim)[:n]


class PqQuantizer(Quantizer):
    """Product quantization state: per-subspace or per-cluster codebooks.

    `train` and `encode` call the exact jitted functions the pre-refactor
    ivf_pq.py inlined (same XLA cache keys), so routing the index through
    this class changes nothing about its numerics — the contract pinned
    by tests/goldens/ivf_pq_prerefactor.json."""

    kind = "pq"

    def __init__(self, codebook_kind: str = PER_SUBSPACE, pq_bits: int = 8,
                 pq_dim: int = 0, pq_len: int = 0, n_lists: int = 0,
                 pq_centers: Optional[jax.Array] = None,
                 n_iters: int = 25):
        if codebook_kind not in (PER_SUBSPACE, PER_CLUSTER):
            raise ValueError(f"bad codebook_kind {codebook_kind}")
        self.codebook_kind = codebook_kind
        self.pq_bits = int(pq_bits)
        self.pq_dim = int(pq_dim)
        self.pq_len = int(pq_len)
        self.n_lists = int(n_lists)
        self.n_iters = int(n_iters)
        self.pq_centers = pq_centers

    @property
    def per_cluster(self) -> bool:
        return self.codebook_kind == PER_CLUSTER

    @classmethod
    def from_centers(cls, pq_centers, per_cluster: bool) -> "PqQuantizer":
        """Wrap already-trained codebooks (the encode-only path build,
        extend and the distributed builds share)."""
        q = cls(PER_CLUSTER if per_cluster else PER_SUBSPACE)
        q.pq_centers = pq_centers
        q.pq_len = int(pq_centers.shape[-1])
        return q

    def train(self, key, residuals, labels=None) -> "PqQuantizer":
        nb = 1 << self.pq_bits
        if self.per_cluster:
            self.pq_centers = _train_codebooks_per_cluster(
                key, residuals, labels, self.n_lists, self.pq_len, nb,
                self.n_iters,
            )
        else:
            self.pq_centers = _train_codebooks_per_subspace(
                key, residuals, self.pq_dim, nb, self.n_iters,
            )
        return self

    def encode(self, residuals, labels=None) -> Dict[str, jax.Array]:
        if labels is None:
            labels = jnp.zeros((residuals.shape[0],), jnp.int32)
        return {"codes": _encode(residuals, labels, self.pq_centers,
                                 self.per_cluster)}

    def decode(self, payload: Dict[str, jax.Array]) -> jax.Array:
        """Codebook lookup reconstruction (per_subspace reference path;
        per_cluster needs labels — pass them in the payload)."""
        codes = jnp.asarray(payload["codes"], jnp.int32)  # (n, pq_dim)
        n, pq_dim = codes.shape
        if self.per_cluster:
            books = self.pq_centers[jnp.asarray(payload["labels"], jnp.int32)]
            rec = jnp.take_along_axis(
                books, codes[:, :, None], axis=1)  # (n, pq_dim, pq_len)
        else:
            flat = self.pq_centers.reshape(-1, self.pq_centers.shape[-1])
            nb = self.pq_centers.shape[1]
            rows = codes + jnp.arange(pq_dim, dtype=jnp.int32)[None, :] * nb
            rec = flat[rows]
        return rec.reshape(n, -1)

    def score_table(self, query_residuals, **kw) -> Dict[str, jax.Array]:
        """The classic PQ LUT: (nq, pq_dim, nb) squared sub-distances
        (per_subspace reference form)."""
        if self.per_cluster:
            raise NotImplementedError(
                "per_cluster LUTs are per-probe (the index engines build "
                "them inline); the reference table covers per_subspace")
        nq = query_residuals.shape[0]
        qsub = query_residuals.reshape(nq, -1, self.pq_centers.shape[-1])
        dots = jnp.einsum("qpl,pbl->qpb", qsub, self.pq_centers)
        bn = jnp.sum(self.pq_centers**2, axis=2)[None, :, :]
        qn = jnp.sum(qsub**2, axis=2)[:, :, None]
        return {"lut": qn + bn - 2.0 * dots}

    def estimate_distances(self, table, payload, **kw) -> jax.Array:
        lut = table["lut"]  # (nq, pq_dim, nb)
        codes = jnp.asarray(payload["codes"], jnp.int32)  # (m, pq_dim)
        nq, pq_dim, nb = lut.shape
        lut2 = lut.reshape(nq, pq_dim * nb)
        idx = (codes + jnp.arange(pq_dim, dtype=jnp.int32)[None, :] * nb)
        return jnp.sum(lut2[:, idx], axis=2)  # (nq, m)

    def state_arrays(self) -> Dict[str, jax.Array]:
        return {"pq_centers": self.pq_centers}

    def state_meta(self) -> dict:
        return {"quantizer": self.kind, "codebook_kind": self.codebook_kind,
                "pq_bits": self.pq_bits}

    @classmethod
    def from_state(cls, arrays, meta) -> "PqQuantizer":
        q = cls(codebook_kind=meta["codebook_kind"],
                pq_bits=int(meta.get("pq_bits", 8)))
        q.pq_centers = arrays["pq_centers"]
        q.pq_len = int(q.pq_centers.shape[-1])
        return q


# ---------------------------------------------------------------------------
# RaBitQ bit-code helpers (pure jnp, traceable — the index engines call
# the SAME functions inside their jits, so reference and hot path agree)
# ---------------------------------------------------------------------------

WORD_BITS = 32


def packed_words(rot_dim: int) -> int:
    """uint32 words per packed code row (rot_dim must be 32-aligned)."""
    if rot_dim % WORD_BITS:
        raise ValueError(f"rot_dim {rot_dim} must be a multiple of {WORD_BITS}")
    return rot_dim // WORD_BITS


def pack_bits(bits) -> jax.Array:
    """(..., rot_dim) {0,1} -> (..., W) uint32 little-endian words
    (bit i of word w = dimension w*32 + i)."""
    b = jnp.asarray(bits).astype(jnp.uint32)
    w = b.reshape(b.shape[:-1] + (-1, WORD_BITS))
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    return jnp.sum(w << shifts, axis=-1).astype(jnp.uint32)


def unpack_bits(words, rot_dim: int) -> jax.Array:
    """(..., W) uint32 -> (..., rot_dim) {0,1} int32 — pack's inverse."""
    w = jnp.asarray(words, jnp.uint32)[..., None]
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    bits = (w >> shifts) & jnp.uint32(1)
    return bits.reshape(words.shape[:-1] + (rot_dim,)).astype(jnp.int32)


def quantize_queries(qres, query_bits: int):
    """Per-row scalar quantization of query residuals for the bit-plane
    scan: qres_i ~= lo + delta * u_i with u in [0, 2^bits). Returns
    (planes (..., bits, W) uint32, lo (..., 1), delta (..., 1))."""
    lo = jnp.min(qres, axis=-1, keepdims=True)
    hi = jnp.max(qres, axis=-1, keepdims=True)
    levels = (1 << query_bits) - 1
    delta = jnp.maximum((hi - lo) / levels, 1e-12)
    u = jnp.clip(jnp.round((qres - lo) / delta), 0, levels).astype(jnp.int32)
    planes = jnp.stack(
        [pack_bits((u >> j) & 1) for j in range(query_bits)], axis=-2
    )  # (..., bits, W)
    return planes, lo, delta


def binary_dot(codes, planes) -> jax.Array:
    """sum_{i: code bit i set} u_i via AND+popcount over the query's bit
    planes — the RaBitQ fast scan's integer core. `codes` (..., W)
    uint32 broadcast against `planes` (..., bits, W); returns f32 of the
    broadcast shape minus the (bits, W) axes."""
    inter = lax.population_count(codes[..., None, :] & planes)
    per_plane = jnp.sum(inter.astype(jnp.int32), axis=-1)  # (..., bits)
    weights = (1 << jnp.arange(per_plane.shape[-1], dtype=jnp.int32))
    return jnp.sum(per_plane * weights, axis=-1).astype(jnp.float32)


def estimate_dot(s_set, pop, qsum, o_dot, rot_dim: int) -> jax.Array:
    """The unbiased RaBitQ estimator of <q_res, o> (o = residual
    direction): <q_res, x_bar> / <o, x_bar> with
    <q_res, x_bar> = (2*S - sum(q_res)) / sqrt(D), S = sum of q_res over
    set bits. `pop` is unused here (S already folds it) — kept in the
    signature so engines computing S = lo*pop + delta*S_u pass both."""
    del pop
    qxb = (2.0 * s_set - qsum) / np.sqrt(float(rot_dim))
    return qxb / jnp.maximum(o_dot, 1e-12)


class RabitqQuantizer(Quantizer):
    """RaBitQ: 1-bit sign codes over rotated residuals + two correction
    scalars per row.

    encode(residuals) returns
        codes (n, W) uint32   packed sign bits of the rotated residual
        aux   (n, 2) f32      [|r|, <o, x_bar>] with o = r/|r| and
                              x_bar = sign(r)/sqrt(D)

    The estimator (paper eq. form): <q, o> ~= <q, x_bar>/<o, x_bar>,
    unbiased over the random rotation, giving
        |q - v|^2 ~= |q_res|^2 + |r|^2 - 2|r| * <q,o>-estimate.
    Training is a no-op — there is nothing to fit, which is exactly the
    build-speed advantage over codebook EM."""

    kind = "rabitq"

    def __init__(self, rot_dim: int, query_bits: int = DEFAULT_QUERY_BITS):
        self.rot_dim = int(rot_dim)
        self.words = packed_words(self.rot_dim)
        if not (1 <= int(query_bits) <= 8):
            raise ValueError(f"query_bits must be in [1, 8], got {query_bits}")
        self.query_bits = int(query_bits)

    def train(self, key, residuals, labels=None) -> "RabitqQuantizer":
        return self  # nothing to fit: the whole point

    def encode(self, residuals, labels=None) -> Dict[str, jax.Array]:
        r = jnp.asarray(residuals, jnp.float32)
        bits = (r >= 0).astype(jnp.uint32)
        rnorm = jnp.sqrt(jnp.sum(r * r, axis=-1))
        # <o, x_bar> = sum|r_i| / (|r| * sqrt(D)); zero residuals (row ==
        # its center) get o_dot 1 so the correction divide stays finite —
        # rnorm 0 already zeroes their estimator term
        o_dot = jnp.where(
            rnorm > 0,
            jnp.sum(jnp.abs(r), axis=-1)
            / (jnp.maximum(rnorm, 1e-30) * np.sqrt(float(self.rot_dim))),
            1.0,
        )
        return {"codes": pack_bits(bits),
                "aux": jnp.stack([rnorm, o_dot], axis=-1)}

    def decode(self, payload: Dict[str, jax.Array]) -> jax.Array:
        """|r| * <o, x_bar> * x_bar — the L2-optimal reconstruction of
        the residual from its sign code (the projection of r onto the
        x_bar direction)."""
        signs = unpack_bits(payload["codes"], self.rot_dim) * 2 - 1
        aux = jnp.asarray(payload["aux"], jnp.float32)
        scale = aux[..., 0] * aux[..., 1] / np.sqrt(float(self.rot_dim))
        return signs.astype(jnp.float32) * scale[..., None]

    def score_table(self, query_residuals, **kw) -> Dict[str, jax.Array]:
        qres = jnp.asarray(query_residuals, jnp.float32)
        planes, lo, delta = quantize_queries(qres, self.query_bits)
        return {
            "planes": planes, "lo": lo, "delta": delta,
            "qsum": jnp.sum(qres, axis=-1, keepdims=True),
            "qnorm2": jnp.sum(qres * qres, axis=-1, keepdims=True),
        }

    def estimate_distances(self, table, payload, exact_queries=None) -> jax.Array:
        """(nq, m) estimated squared L2 distances. With `exact_queries`
        (the raw (nq, rot_dim) residuals) the set-bit sums use exact f32
        instead of the quantized planes — the estimator the unbiasedness
        property test isolates (no scalar-quantization noise)."""
        codes = jnp.asarray(payload["codes"], jnp.uint32)  # (m, W)
        aux = jnp.asarray(payload["aux"], jnp.float32)
        rnorm, o_dot = aux[..., 0], aux[..., 1]
        pop = jnp.sum(
            lax.population_count(codes).astype(jnp.int32), axis=-1
        ).astype(jnp.float32)  # (m,)
        if exact_queries is not None:
            q = jnp.asarray(exact_queries, jnp.float32)
            bits = unpack_bits(codes, self.rot_dim).astype(jnp.float32)
            s = q @ bits.T  # (nq, m): exact sum over set bits
            qsum = jnp.sum(q, axis=-1, keepdims=True)
            qnorm2 = jnp.sum(q * q, axis=-1, keepdims=True)
        else:
            s_u = binary_dot(codes[None, :, :], table["planes"][:, None])
            s = table["lo"] * pop[None, :] + table["delta"] * s_u
            qsum, qnorm2 = table["qsum"], table["qnorm2"]
        est = estimate_dot(s, pop, qsum, o_dot[None, :], self.rot_dim)
        return qnorm2 + rnorm[None, :] ** 2 - 2.0 * rnorm[None, :] * est

    def state_arrays(self) -> Dict[str, jax.Array]:
        return {}

    def state_meta(self) -> dict:
        return {"quantizer": self.kind, "rot_dim": self.rot_dim,
                "query_bits": self.query_bits}

    @classmethod
    def from_state(cls, arrays, meta) -> "RabitqQuantizer":
        return cls(int(meta["rot_dim"]),
                   int(meta.get("query_bits", DEFAULT_QUERY_BITS)))
