"""IVF-RaBitQ: inverted-file ANN index over 1-bit RaBitQ codes.

Reference: the IVF-RaBitQ paper (arXiv 2602.23999) — binary codes
scanned with popcount-style integer ops, an exact-ish UNBIASED distance
estimator, and a cheap exact rerank; and the TPU-KNN paper (arXiv
2206.14286) for the scan shape: never materialize full fp32 score
matrices — the candidate stream here is 1 bit/dim plus two f32
correction scalars per row.

Why it exists next to IVF-PQ (ROADMAP open item 2): *build speed*.
IVF-PQ's build is dominated by codebook EM + codebook-assignment encode;
RaBitQ has NO codebooks — encode is a sign() and two reductions — so
the index builds in roughly the coarse-kmeans time alone, which is what
extrapolates to 100M-row production indexes. Search trades that for
1-bit codes: the estimator ranks candidates well enough that a
`rerank_mult * k` exact re-rank through the shared refine stage
(neighbors/refine.py) recovers recall >= 0.95 at bench geometry.

Layout (all per-IVF-list, the ivf_flat/ivf_pq slot-table scheme):

    rotation  (rot_dim, dim) f32   random orthogonal (always random —
                                   sign binarization needs isotropy);
                                   rot_dim = dim rounded up to 32
    centers   (n_lists, rot_dim)   coarse centroids in rotated space
    codes     (n_lists, max_list, rot_dim/32) uint32 packed sign bits
    aux       (n_lists, max_list, 2) f32  [|r|, <o, x_bar>] corrections
    slot_rows / list_sizes / source_ids   as in ivf_flat

Search: coarse top-n_probes (shared `_coarse_select`), then per query
block the packed codes of the probed lists are scanned with AND+popcount
over the query's quantized bit planes (quantizer.binary_dot), the
unbiased estimator (quantizer.estimate_dot) turns bit overlaps into
distance estimates, and the top rerank_mult*k candidates re-rank exactly
against the original rows (stored on the index by default, or passed as
`refine_dataset`).

Quantizer math lives in neighbors/quantizer.py (`RabitqQuantizer`); the
engine here is the blocked/jitted application of the same traceable
helpers, so the property-tested reference and the hot path cannot
disagree.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.config import auto_convert_output
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.neighbors.ivf_pq import _coarse_select, _make_rotation
from raft_tpu.neighbors.ivf_flat import _append_slots, _grow_and_scatter_multi
from raft_tpu.neighbors.quantizer import (
    DEFAULT_QUERY_BITS,
    RabitqQuantizer,
    binary_dot,
    estimate_dot,
    packed_words,
    quantize_queries,
)

#: host-side chaos site: the encode stage of build/extend (the stage
#: whose cheapness IS the fast-build claim — drills prove a slow or
#: flaky encode pass degrades latency, never results)
ENCODE_SITE = "ivf_rabitq.build.encode"

#: exact-rerank gather cap, matching the distributed refine's 256-row
#: shortlist cap (mnmg_ivf_search) so serve/MNMG depths agree
_MAX_RERANK = 256


@dataclasses.dataclass
class IndexParams:
    """Build parameters (coarse stage mirrors ivf_pq.IndexParams; there
    is deliberately no codebook knob — RaBitQ has none to tune)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    add_data_on_build: bool = True
    # keep the raw rows on the index for the exact rerank stage (the
    # single-chip convenience; costs dataset-sized HBM like IVF-Flat).
    # False = quantized-only index; pass refine_dataset to search, or
    # accept estimator-ranked results.
    store_dataset: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)


@dataclasses.dataclass
class SearchParams:
    """Search parameters.

    query_bits   scalar-quantization bits of the query bit planes
                 (1..8); 0 = auto — the measured tuned key
                 ("rabitq_query_bits") when a chip profile wrote one,
                 else 8.
    rerank_mult  exact-rerank depth multiplier: the scan keeps
                 rerank_mult * k candidates (capped at 256) for the
                 refine stage; 0 = auto — tuned key
                 ("rabitq_rerank_mult"), else 4. Rerank engages whenever
                 original rows are available (index.dataset or
                 refine_dataset); without them the estimator ranking is
                 returned directly.
    scan_engine  bit-plane scan implementation:
                 "xla"   — the materializing reference scan
                           (`_search_impl_rabitq`: gather + AND+popcount
                           in XLA).
                 "fused" — the fused bit-plane list scan (ISSUE 11):
                           matrix/select_k.bitplane_scan_select_k runs
                           AND+popcount scoring AND the exact partial
                           top-k inside one kernel, with the unbiased
                           estimator correction applied in-kernel — the
                           candidate bit planes never materialize in
                           HBM, only (queries, rerank_mult*k) survivors
                           flow to the exact rerank. Same integer
                           scores, explicit requests past the kernel's
                           envelope raise.
                 "auto"  — "xla" unless the measured tuned key
                           (matrix/select_k.BITPLANE_SCAN_KEY, flipped
                           by bench_select_k_strategies --apply on chip
                           data) promotes the fused scan where the
                           geometry fits.
    """

    n_probes: int = 20
    query_bits: int = 0
    rerank_mult: int = 0
    scan_engine: str = "auto"
    # -- adaptive probing (neighbors/probe_budget, ROADMAP item 2) --
    # per-query probe budgets from the rotated coarse gap profile;
    # early-termination bounds come FREE here — the aux table already
    # stores every member's residual norm |r|, so list radii derive
    # lazily with no build-time pass or serialization change. Bounds
    # are exact-space; the estimator ranking's recall is covered by
    # the banked frontier (the PQ caveat). recall_target >= 1.0
    # saturates, bit-identical to the fixed-n_probes reference.
    adaptive: bool = False
    recall_target: Optional[float] = None
    budget_tau: Optional[float] = None
    min_probes: int = 1
    early_term: bool = True


def resolve_query_bits(query_bits: int) -> int:
    """The ONE auto-resolution of the query quantization depth (tuned
    key "rabitq_query_bits"), shared by the single-chip and distributed
    searches."""
    if query_bits:
        if not (1 <= int(query_bits) <= 8):
            raise ValueError(f"query_bits must be in [1, 8], got {query_bits}")
        return int(query_bits)
    from raft_tpu.core import tuned

    t = tuned.get("rabitq_query_bits")
    return int(t) if t in (1, 2, 3, 4, 5, 6, 7, 8) else DEFAULT_QUERY_BITS


def resolve_rerank_mult(rerank_mult: int) -> int:
    """Auto-resolution of the rerank depth multiplier (tuned key
    "rabitq_rerank_mult")."""
    if rerank_mult:
        if rerank_mult < 1:
            raise ValueError(f"rerank_mult must be >= 1, got {rerank_mult}")
        return int(rerank_mult)
    from raft_tpu.core import tuned

    t = tuned.get("rabitq_rerank_mult")
    return int(t) if isinstance(t, int) and 1 <= t <= 64 else 4


class Index:
    """IVF-RaBitQ index (see module docstring for the table layout)."""

    def __init__(self, params: IndexParams, rotation, centers, codes, aux,
                 slot_rows, list_sizes, source_ids, dataset=None):
        self.params = params
        self.rotation = rotation
        self.centers = centers
        self.codes = codes
        self.aux = aux
        self.slot_rows = slot_rows
        self.list_sizes = list_sizes
        self.source_ids = source_ids
        # raw rows in insertion order (store_dataset=True) — the rerank
        # stage's gather source; None on loaded / quantized-only indexes
        self.dataset = dataset
        # fused bit-plane scan's derived store (build_bitplane_store):
        # codes_t (n_lists, W, L) word-transposed lane-padded uint32,
        # bp_meta (n_lists, 3, L) f32 [popcount, |r|, <o,x_bar>],
        # slot_rows_pad (n_lists, L) int32 (-1 pads), fused_kb the
        # monotonically-grown candidate-buffer width (ivf_flat contract)
        self.codes_t = None
        self.bp_meta = None
        self.slot_rows_pad = None
        self.fused_kb = None
        # adaptive probing's per-list radii, derived lazily from the
        # aux table's stored |r| column (extend returns a new Index,
        # so the cache can never go stale)
        self._list_radii = None
        # live-mutation state (neighbors/mutation): optional dead-row
        # mask (n_lists, max_list; nonzero = dead, None = all-live),
        # applied-log cursor at the last checkpoint commit, reserved
        # per-list append slack. Masked into slot_rows/slot_rows_pad by
        # `core.bitset.make_slot_filter` (pad-aware).
        self.tombstones = None
        self.mut_cursor = 0
        self.append_slack = 0
        # integrity sidecar (raft_tpu/integrity): per-list / per-table
        # CRC-32C digests; None = no sidecar (legacy)
        self.list_digests = None
        self.table_digests = None
        self._id_bound = None

    @property
    def n_tombstones(self) -> int:
        """Dead-slot count (0 when all-live) — truthful accounting:
        cost-model charges bill live rows only."""
        if self.tombstones is None:
            return 0
        return int(jnp.sum(jnp.asarray(self.tombstones).astype(jnp.int32)))

    @property
    def list_radii(self):
        """(n_lists,) f32 max member residual norm per list — the
        early-termination bounds of adaptive probing, a free per-list
        max over the aux table's |r| column."""
        if self._list_radii is None and self.size:
            from raft_tpu.neighbors.probe_budget import list_radii_from_aux

            self._list_radii = list_radii_from_aux(self.aux, self.slot_rows)
        return self._list_radii

    @property
    def id_bound(self) -> int:
        """One past the largest source id — the id space a search
        `prefilter` must cover (== size unless extend() used custom
        new_indices). Cached per instance (extend returns a new Index)."""
        if self._id_bound is None:
            self._id_bound = (
                int(jnp.max(self.source_ids)) + 1 if self.size else 0
            )
        return self._id_bound

    @property
    def metric(self) -> DistanceType:
        return self.params.metric

    @property
    def n_lists(self) -> int:
        return int(self.centers.shape[0])

    @property
    def dim(self) -> int:
        return int(self.rotation.shape[1])

    @property
    def rot_dim(self) -> int:
        return int(self.rotation.shape[0])

    @property
    def words(self) -> int:
        return int(self.codes.shape[2])

    @property
    def size(self) -> int:
        return int(self.source_ids.shape[0])

    def __repr__(self):
        return (
            f"ivf_rabitq.Index(n_lists={self.n_lists}, dim={self.dim}, "
            f"rot_dim={self.rot_dim}, size={self.size}, "
            f"metric={self.metric.name})"
        )


# ---------------------------------------------------------------------------
# build / extend
# ---------------------------------------------------------------------------


def rabitq_rot_dim(dim: int) -> int:
    """Packing geometry: dim rounded up to whole 32-bit words."""
    return -(-dim // 32) * 32


@jax.jit
def _encode_rotated(v_rot, labels, centers):
    """Rotated rows -> (codes (n, W) uint32, aux (n, 2) f32) RaBitQ
    payload — the quantizer's encode applied to per-list residuals, as
    one jitted program (shared by extend and the distributed build,
    which calls it inside shard_map)."""
    residuals = v_rot - centers[labels]
    quant = RabitqQuantizer(int(v_rot.shape[-1]))
    payload = quant.encode(residuals)
    return payload["codes"], payload["aux"]


def label_and_encode(vectors, rotation, centers, metric: DistanceType):
    """Rotate, assign to coarse lists, and RaBitQ-encode the residuals —
    the shared encode sequence of `extend` and the distributed build
    (which traces this under shard_map — keep it host-effect-free; the
    "ivf_rabitq.build.encode" chaos hook fires in the HOST callers,
    `extend` and `mnmg.ivf_rabitq_build`, so injection is per-call on
    both paths, never swallowed by a trace cache).
    Returns (labels (n,), codes (n, W) uint32, aux (n, 2) f32)."""
    metric_name = (
        "inner_product" if metric == DistanceType.InnerProduct else "sqeuclidean"
    )
    v_rot = jnp.asarray(vectors, jnp.float32) @ rotation.T
    labels = kmeans_balanced.predict(v_rot, centers, metric=metric_name)
    codes, aux = _encode_rotated(v_rot, labels, centers)
    return labels, codes, aux


@obs.spanned("neighbors.ivf_rabitq.build")
def build(params: IndexParams, dataset, resources=None, seed: int = 0) -> Index:
    """Train rotation + coarse centers, then encode + pack lists. No
    codebook stage — the build is coarse-kmeans-bound, the fast-build
    half of the RaBitQ paper (measured vs IVF-PQ in
    bench/bench_ivf_rabitq.py)."""
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(dataset, name="dataset").astype(jnp.float32)
    n, dim = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    rot_dim = rabitq_rot_dim(dim)
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    # always a random rotation: sign binarization is only unbiased under
    # an isotropic basis (identity would bias toward axis-aligned data)
    rotation = _make_rotation(rk, rot_dim, dim, True)

    # the ONE single-chip coarse-fit scaffolding shared with ivf_pq.build
    # — and the whole training: no codebook stage follows
    from raft_tpu.neighbors.ivf_pq import _coarse_fit

    centers, _, key = _coarse_fit(params, x, rotation, key, seed)

    W = packed_words(rot_dim)
    index = Index(
        params,
        rotation,
        centers,
        jnp.zeros((params.n_lists, 1, W), jnp.uint32),
        jnp.zeros((params.n_lists, 1, 2), jnp.float32),
        jnp.full((params.n_lists, 1), -1, jnp.int32),
        jnp.zeros((params.n_lists,), jnp.int32),
        jnp.zeros((0,), jnp.int32),
    )
    if params.add_data_on_build:
        index = extend(index, x, jnp.arange(n, dtype=jnp.int32))
    # build-time integrity sidecar (kept fresh incrementally after)
    from raft_tpu.integrity.digest import attach as _attach_digests

    _attach_digests(index, "ivf_rabitq")
    if resources is not None:
        resources.track(index.codes)
    return index


@obs.spanned("neighbors.ivf_rabitq.extend")
def extend(index: Index, new_vectors, new_indices=None) -> Index:
    """Label, encode and append new vectors — O(n_new + table copy),
    sharing ivf_flat's slot placement + gather-scatter so streamed
    builds stay linear."""
    from raft_tpu.core.validation import check_matrix

    nv = check_matrix(new_vectors, name="new_vectors").astype(jnp.float32)
    old_n = index.size
    if new_indices is None:
        new_indices = jnp.arange(old_n, old_n + nv.shape[0], dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    # chaos site (host-side, per call): slow_rank models a slow encode
    # pass — latency only, results untouched; flaky_bootstrap a
    # transient dispatch failure retried by callers
    faults.fault_point(ENCODE_SITE)
    labels, new_codes, new_aux = label_and_encode(
        nv, index.rotation, index.centers, index.metric
    )

    labels_np = np.asarray(labels, np.int64)
    old_sizes = np.asarray(index.list_sizes, np.int64)
    slot_abs, new_sizes, new_max = _append_slots(labels_np, old_sizes,
                                                 index.n_lists)
    # a store padded wider than the sizes imply (fused-engine lanes,
    # mutation append slack) must never shrink — slots stay where they are
    new_max = max(new_max, int(index.slot_rows.shape[1]))
    positions = jnp.arange(old_n, old_n + nv.shape[0], dtype=jnp.int32)
    # one shared placement sort grows BOTH payload tables
    (codes_tbl, aux_tbl), slot_rows = _grow_and_scatter_multi(
        (index.codes, index.aux), index.slot_rows, (new_codes, new_aux),
        jnp.asarray(labels_np), jnp.asarray(slot_abs), positions, new_max,
    )
    all_ids = (jnp.concatenate([index.source_ids, new_indices])
               if old_n else new_indices)

    ds = None
    if index.params.store_dataset:
        ds = nv if index.dataset is None else jnp.concatenate(
            [index.dataset, nv])

    out = Index(
        index.params,
        index.rotation,
        index.centers,
        codes_tbl,
        aux_tbl,
        slot_rows,
        jnp.asarray(new_sizes),
        all_ids,
        dataset=ds,
    )
    # mutation state survives extend (new tail slots are live appends)
    from raft_tpu.core.bitset import carry_tombstones

    out.tombstones = carry_tombstones(index.tombstones,
                                      int(slot_rows.shape[1]))
    out.mut_cursor = index.mut_cursor
    out.append_slack = index.append_slack
    # integrity sidecar: only the lists this batch touched re-digest
    from raft_tpu.integrity.digest import refresh as _refresh_digests

    _refresh_digests(out, index, "ivf_rabitq")
    return out


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _rabitq_query_block(n_probes: int, max_list: int, query_bits: int,
                        words: int) -> int:
    # keep the (qb, np, max_list, bits, W) popcount intersection tensor
    # ~<= 2^22 int32 elements (16MB) — the scan's dominant intermediate
    qb = max(1, (1 << 22) // max(1, n_probes * max_list * query_bits * words))
    return int(min(qb, 16))


@functools.partial(
    jax.jit, static_argnames=("k", "n_probes", "metric", "query_bits")
)
def _search_impl_rabitq(
    queries,
    rotation,
    centers,
    codes,
    aux,
    slot_rows,
    k: int,
    n_probes: int,
    metric: DistanceType,
    query_bits: int = DEFAULT_QUERY_BITS,
    pvalid: jax.Array = None,
):
    """Binary-code scan: per (query, probe) the packed sign codes stream
    once and score via AND+popcount against the query's quantized bit
    planes (quantizer.binary_dot), then the unbiased RaBitQ estimator
    (quantizer.estimate_dot) maps bit overlaps to distances. Integer ops
    end to end on the candidate side — no fp32 score matrix of the
    probed rows ever materializes (TPU-KNN's memory-shape argument).
    Returns (estimated distances, slot-table values) of shape (nq, k);
    the second output carries whatever `slot_rows` holds (positions
    locally, global ids distributed)."""
    nq = queries.shape[0]
    n_lists, max_list, W = codes.shape
    rot_dim = rotation.shape[0]
    select_min = metric != DistanceType.InnerProduct
    ip = metric == DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes, metric)
    rnorm = aux[..., 0]
    o_dot = aux[..., 1]
    # a candidate depth beyond the probed width selects everything there
    # is; the tail pads to k below (worst score, row -1) so the output
    # width contract holds for ANY k
    k_sel = int(min(k, n_probes * max_list))

    qb = _rabitq_query_block(n_probes, max_list, query_bits, W)
    nblocks = -(-nq // qb)
    pad = nblocks * qb - nq
    qp = jnp.pad(q_rot, ((0, pad), (0, 0))) if pad else q_rot
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qblocks = qp.reshape(nblocks, qb, rot_dim)
    pblocks = pp.reshape(nblocks, qb, n_probes)
    if pvalid is not None:
        pvp = jnp.pad(pvalid, ((0, pad), (0, 0))) if pad else pvalid
        pvblocks = pvp.reshape(nblocks, qb, n_probes)

    def block(inp):
        if pvalid is not None:
            qs, pr, pvb = inp  # + (qb, n_probes) adaptive keep mask
        else:
            qs, pr = inp  # (qb, rot_dim), (qb, n_probes)
        pc = centers[pr]  # (qb, np, rot)
        if ip:
            qres = jnp.broadcast_to(qs[:, None, :], pc.shape)
        else:
            qres = qs[:, None, :] - pc
        planes, lo, delta = quantize_queries(qres, query_bits)
        qsum = jnp.sum(qres, axis=-1)  # (qb, np)

        cand = codes[pr]  # (qb, np, max_list, W) uint32
        # per-slot set-bit counts of the PROBED lists only (popcounting
        # the whole table would make every query O(index size))
        pop = jnp.sum(
            lax.population_count(cand).astype(jnp.int32), axis=-1
        ).astype(jnp.float32)  # (qb, np, max_list)
        # S_u[q,n,s] = sum of quantized query levels over the code's set
        # bits — AND+popcount over the bit planes (the fast-scan core)
        s_u = binary_dot(cand, planes[:, :, None, :, :])  # (qb,np,S)
        s = lo * pop + delta * s_u  # (qb, np, S); lo/delta (qb,np,1)
        est = estimate_dot(s, pop, qsum[:, :, None], o_dot[pr], rot_dim)
        rn = rnorm[pr]
        if ip:
            qdotc = jnp.sum(qs[:, None, :] * pc, axis=2)
            scores = qdotc[:, :, None] + rn * est
        else:
            qcn = jnp.sum(qres**2, axis=2)
            scores = qcn[:, :, None] + rn**2 - 2.0 * rn * est
        rows = slot_rows[pr]  # (qb, np, max_list)
        if pvalid is not None:
            rows = jnp.where(pvb[:, :, None], rows, -1)
        rows = rows.reshape(qb, -1)
        scores = scores.reshape(qb, -1)
        scores = jnp.where(rows >= 0, scores, worst)
        v, pos = _select_k_impl(scores, k_sel, select_min)
        r = jnp.take_along_axis(rows, pos, axis=1)
        if k_sel < k:  # pad the tail: worst score, row -1 (static shapes)
            v = jnp.pad(v, ((0, 0), (0, k - k_sel)), constant_values=worst)
            r = jnp.pad(r, ((0, 0), (0, k - k_sel)), constant_values=-1)
        return v, r

    vals, rows = lax.map(
        block,
        (qblocks, pblocks, pvblocks) if pvalid is not None
        else (qblocks, pblocks))
    vals = vals.reshape(-1, k)[:nq]
    rows = rows.reshape(-1, k)[:nq]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, rows


def rerank_depth(k: int, rerank_mult: int) -> int:
    """Candidate depth the scan keeps for the exact rerank: never below
    k, capped at the shared 256-row gather bound (the distributed
    refine's shortlist cap)."""
    return max(int(k), min(int(rerank_mult) * int(k), _MAX_RERANK))


def derive_bitplane_tables(codes, aux, slot_table, lpad: int):
    """The fused bit-plane store derivation — ONE recipe shared by the
    single-chip builder and the distributed per-rank builder
    (`mnmg_rabitq._build_distributed_bitplane`), over arbitrary leading
    axes: lane-pad the slot axis to `lpad`, word-TRANSPOSE the packed
    codes (L onto the 128-lane register axis), and stack the per-slot
    estimator meta rows [popcount(code), |r|, <o, x_bar>] the kernel's
    operand contract depends on. Pad slots carry zero codes/meta and
    slot value -1. The two stores cannot drift because they both call
    here.

    codes (..., S, W) uint32, aux (..., S, 2) f32, slot_table (..., S)
    -> (codes_t (..., W, L), meta (..., 3, L), slots_pad (..., L))."""
    extra = lpad - int(codes.shape[-2])
    pad3 = [(0, 0)] * (codes.ndim - 2) + [(0, extra), (0, 0)]
    codes_p = jnp.pad(codes, pad3)
    aux_p = jnp.pad(aux, pad3)
    codes_t = jnp.swapaxes(codes_p, -1, -2)
    # per-slot set-bit counts: the SAME popcount-and-sum the XLA
    # reference computes per probed row, hoisted to build time (it is
    # query-independent) — pad slots popcount 0
    pop = jnp.sum(
        lax.population_count(codes_p).astype(jnp.int32), axis=-1
    ).astype(jnp.float32)
    meta = jnp.stack([pop, aux_p[..., 0], aux_p[..., 1]], axis=-2)
    slots_pad = jnp.pad(
        slot_table, [(0, 0)] * (slot_table.ndim - 1) + [(0, extra)],
        constant_values=-1,
    )
    return codes_t, meta, slots_pad


def build_bitplane_store(index: Index, k: int) -> None:
    """Populate the fused bit-plane scan's derived store: the packed
    sign codes word-TRANSPOSED to (n_lists, W, L) with the slot axis
    lane-padded (L on the 128-lane register axis — the kernel
    broadcasts each code word row against the query's plane column),
    plus the (n_lists, 3, L) per-slot estimator meta rows
    [popcount(code), |r|, <o, x_bar>] the in-kernel correction reads.
    Pad slots carry zero codes / zero meta and slot_rows_pad -1, so the
    per-call +inf base masks them before selection.

    `k` sizes the compiled candidate-buffer width (`Index.fused_kb`,
    ops/fused_scan.fused_kbuf): monotone growth, exactly the ivf_flat
    lazy-store invalidation contract — a narrower compiled buffer on a
    later larger-k search would silently truncate per-list candidates."""
    from raft_tpu.ops.fused_scan import fused_kbuf
    from raft_tpu.ops.pq_list_scan import lane_padded

    lpad = lane_padded(int(index.codes.shape[1]))
    if index.codes_t is None or int(index.codes_t.shape[2]) != lpad:
        index.codes_t, index.bp_meta, index.slot_rows_pad = (
            derive_bitplane_tables(index.codes, index.aux,
                                   index.slot_rows, lpad)
        )
    kb = fused_kbuf(int(k))
    if index.fused_kb is None or kb > index.fused_kb:
        index.fused_kb = kb


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "query_bits", "chunk",
                     "kb", "interpret", "setup_impls", "fault_key"),
)
def _search_impl_rabitq_fused(
    queries,
    rotation,
    centers,
    codes_t,
    bp_meta,
    slot_rows_pad,
    k: int,
    n_probes: int,
    metric: DistanceType,
    query_bits: int = DEFAULT_QUERY_BITS,
    chunk: int = 128,
    kb: int = None,
    interpret: bool = False,
    setup_impls: tuple = ("sort", "gather"),
    fault_key=None,
    pvalid: jax.Array = None,
):
    """List-major bit-plane search with the fused scan+select kernel
    (matrix/select_k.bitplane_scan_select_k): probe pairs invert to
    per-list chunks (the shared `probe_invert` machinery), each chunk's
    query residuals quantize to bit planes through the SAME
    `quantizer.quantize_queries` the XLA reference uses, and one kernel
    per chunk runs AND+popcount scoring, the unbiased estimator
    correction, AND the exact partial top-k — per-(query, slot)
    estimator scores are computed with the reference's exact op order
    (integer bit-plane sums are associative; the f32 correction applies
    the same expression), so the two engines' scores agree. Returns
    (estimator distances, slot-table values), the `_search_impl_rabitq`
    contract."""
    from raft_tpu.matrix.select_k import bitplane_scan_select_k
    from raft_tpu.neighbors.probe_invert import (
        chunk_validity,
        gather_query_rows,
        invert_probes_count,
        invert_probes_sort,
        regroup_merge,
    )

    nq = queries.shape[0]
    n_lists, W, L = codes_t.shape
    rot_dim = rotation.shape[0]
    select_min = metric != DistanceType.InnerProduct
    ip = metric == DistanceType.InnerProduct

    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes,
                                   metric)
    invert_impl, qs_impl = setup_impls
    invert = (invert_probes_count if invert_impl == "count"
              else invert_probes_sort)
    tables = invert(probes, n_lists, chunk, pvalid)
    lof, qid_tbl = tables.lof, tables.qid_tbl
    cvalid = chunk_validity(qid_tbl, nq)  # empty chunks skip in-kernel

    q_pad = jnp.concatenate([q_rot, jnp.zeros((1, rot_dim), q_rot.dtype)])
    qs = gather_query_rows(q_pad, qid_tbl, qs_impl)  # (ncb, chunk, rot)
    cent = centers[lof]
    qres = qs if ip else qs - cent[:, None, :]
    planes, lo, delta = quantize_queries(qres, query_bits)
    planes = planes.reshape(planes.shape[0], planes.shape[1], -1)
    qsum = jnp.sum(qres, axis=-1)  # (ncb, chunk)
    if ip:
        qconst = jnp.einsum("cqd,cd->cq", qs, cent)  # q . center
    else:
        qconst = jnp.sum(qres**2, axis=2)  # |q - center|^2
    qmeta = jnp.stack(
        [lo[..., 0], delta[..., 0], qsum, qconst], axis=1
    )  # (ncb, 4, chunk)

    base = jnp.where(slot_rows_pad >= 0, 0.0, jnp.inf)[:, None, :]

    vals, slot_idx = bitplane_scan_select_k(
        lof, planes, codes_t, bp_meta, base, qmeta, k,
        rot_dim=rot_dim, bits=query_bits, kbuf=kb, inner_product=ip,
        interpret=interpret, fault_key=fault_key, chunk_valid=cvalid,
    )  # (ncb, chunk, kb) exact best-first, canonical-minimizing
    vals = vals[:, :, :k]
    slot_idx = slot_idx[:, :, :k]

    invalid = ~jnp.isfinite(vals)
    slot_idx = jnp.where(invalid, 0, slot_idx)  # sentinel -> safe gather
    rows = jnp.take_along_axis(
        slot_rows_pad[lof][:, None, :], slot_idx, axis=2
    )
    rows = jnp.where(invalid, -1, rows)
    if ip:
        # kernel returned the negated estimator similarity
        vals = jnp.where(invalid, -jnp.inf, -vals)

    v, rows_out = regroup_merge(
        tables, vals, rows, _select_k_impl, nq, n_probes, int(k),
        select_min,
    )
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, rows_out


@obs.spanned("neighbors.ivf_rabitq.search")
@auto_convert_output
def search(
    params: SearchParams, index: Index, queries, k: int, resources=None,
    prefilter=None, refine_dataset=None,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search; returns (distances, neighbor source ids) (nq, k).

    The scan ranks candidates by the unbiased RaBitQ estimator; when
    original rows are available (the index stored them, or
    `refine_dataset` — rows in insertion order — is passed) the top
    `rerank_mult * k` candidates re-rank EXACTLY through the shared
    refine stage and the returned distances are exact. Without rows the
    estimator ranking (and its estimated distances) is returned.

    `prefilter`: optional `core.bitset.Bitset` (or 1-D boolean mask)
    over the index's id space (`index.id_bound` ids) — filtered samples
    are excluded before trim/selection, same contract as ivf_flat/
    ivf_pq. When fewer than k samples pass, the tail holds the worst
    distance with id -1."""
    from raft_tpu.core.validation import check_matrix

    q = check_matrix(queries, name="queries")
    if q.shape[1] != index.dim:
        raise ValueError(f"query dim {q.shape[1]} != index dim {index.dim}")
    if index.size == 0:
        raise ValueError("index is empty")
    k = int(k)
    if k <= 0:
        raise ValueError("k must be positive")
    from raft_tpu.core.bitset import make_slot_filter

    maybe_filter = make_slot_filter(prefilter, index.id_bound,
                                    index.source_ids,
                                    tombstones=index.tombstones)
    n_probes = int(min(max(1, params.n_probes), index.n_lists))
    query_bits = resolve_query_bits(params.query_bits)
    rerank_mult = resolve_rerank_mult(params.rerank_mult)
    ds = refine_dataset if refine_dataset is not None else index.dataset
    kk = rerank_depth(k, rerank_mult) if ds is not None else k

    # scan-engine resolution through the dispatch layer (the single
    # chooser): explicit "fused" validates the envelope and RAISES past
    # it; "auto" promotes fused only on a chip-measured tuned winner
    if params.scan_engine not in ("auto", "xla", "fused"):
        raise ValueError(f"unknown scan_engine {params.scan_engine!r}")
    from raft_tpu.matrix.select_k import (
        check_bitplane_request, resolve_bitplane_strategy,
    )
    from raft_tpu.ops.fused_scan import FUSED_MAX_K, fused_kbuf
    from raft_tpu.ops.pq_list_scan import lane_padded

    lpad = lane_padded(int(index.codes.shape[1]))
    if params.scan_engine == "fused":
        check_bitplane_request(
            "scan_engine='fused'", lpad, index.words, int(query_bits),
            kk, index.fused_kb, "scan_engine='xla'",
        )
        strat = "fused_bitplane"
    elif params.scan_engine == "auto" and 0 < kk <= FUSED_MAX_K:
        strat = resolve_bitplane_strategy(
            lpad, index.words, int(query_bits), kk,
            kbuf=max(fused_kbuf(kk), index.fused_kb or 0),
        )
    else:
        strat = "xla"

    # adaptive probing: one (nq, n_probes) keep mask from the rotated
    # coarse geometry; radii come free from the aux |r| column. Plan
    # depth = kk (the rerank shortlist must survive early termination)
    from raft_tpu.neighbors import probe_budget

    ap = probe_budget.resolve_params(params, n_probes)
    pvalid = None
    scanned_mean = None
    if ap is not None:
        # bounds OFF under a prefilter (see ivf_flat.search: the
        # k-covering prefix counts filtered members) — budgets only;
        # same soundness argument under tombstones (sizes count dead)
        radii = (index.list_radii
                 if ap.early_term and prefilter is None
                 and index.tombstones is None else None)
        pvalid, scanned = probe_budget.probe_plan(
            jnp.asarray(q, jnp.float32), index.centers,
            n_probes=n_probes, min_probes=ap.min_probes, k=int(kk),
            metric=index.metric, tau=ap.tau, rotation=index.rotation,
            radii=radii, sizes=index.list_sizes)
        scanned_mean = probe_budget.account(
            "ivf_rabitq", scanned, int(q.shape[0]), n_probes)
    if obs.enabled():
        # n_rows = padded slot count (n_lists * max_list) — the scan
        # streams pad slots of each probed list too. The fused engine
        # charges the fused geometry: popcount ops against the integer
        # peak, no score-matrix bytes. Adaptive budgets charge the
        # ACTUAL per-query scanned mean, not worst-case n_probes.
        obs.span_cost(**obs.perf.cost_for(
            "neighbors.ivf_rabitq.search", nq=int(q.shape[0]),
            n_probes=(scanned_mean if scanned_mean is not None
                      else n_probes),
            n_lists=int(index.n_lists),
            n_rows=int(index.codes.shape[0] * index.codes.shape[1])
            - index.n_tombstones,
            dim=int(index.dim), k=k,
            query_bits=int(query_bits),
            rerank_mult=int(rerank_mult) if ds is not None else 0,
            fused=strat == "fused_bitplane"))

    if strat == "fused_bitplane":
        from raft_tpu.neighbors.probe_invert import (
            macro_batched, resolve_setup_impls,
        )

        build_bitplane_store(index, kk)  # fused_kb grows monotonically
        srows_pad = maybe_filter(index.slot_rows_pad)
        # qs impl resolved like the flat engines (f32-exact gate): the
        # plane quantization must see the reference's exact query rows
        setup = resolve_setup_impls(index.n_lists, engine="flat")
        kb = index.fused_kb
        vals, rows = macro_batched(
            lambda sl, pv=None: _search_impl_rabitq_fused(
                sl, index.rotation, index.centers, index.codes_t,
                index.bp_meta, srows_pad, kk, n_probes, index.metric,
                query_bits=query_bits, kb=kb,
                interpret=jax.default_backend() == "cpu",
                setup_impls=setup, fault_key=faults.trace_key(),
                pvalid=pv,
            ),
            jnp.asarray(q),
            kk,
            extra=pvalid,
        )
    else:
        vals, rows = _search_impl_rabitq(
            jnp.asarray(q), index.rotation, index.centers, index.codes,
            index.aux, maybe_filter(index.slot_rows), kk, n_probes,
            index.metric, query_bits=query_bits, pvalid=pvalid,
        )
    if ds is not None:
        # exact rerank through the shared refine stage: candidates are
        # dataset POSITIONS (insertion order; -1 pads skipped), the id
        # map applies after
        quant = RabitqQuantizer(index.rot_dim, query_bits)
        vals, rows = quant.rerank_candidates(
            ds, q, rows, k, metric=index.metric)
    ids = jnp.where(rows >= 0, index.source_ids[jnp.maximum(rows, 0)], -1)
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


# ---------------------------------------------------------------------------
# serialization (quantizer serialize hooks + the shared CRC container)
# ---------------------------------------------------------------------------

_SERIAL_VERSION = 3  # v2: mutation fields; v3: digest sidecar


def save(filename: str, index: Index) -> None:
    """Serialize the quantized index (checksummed container,
    core/serialize.py). The raw-row store is NOT serialized — a loaded
    index reranks via `refine_dataset`, or serves estimator-ranked."""
    from raft_tpu.core.serialize import serialize_arrays

    quant = RabitqQuantizer(index.rot_dim)
    arrays = {
        "rotation": index.rotation,
        "centers": index.centers,
        "codes": index.codes,
        "aux": index.aux,
        "slot_rows": index.slot_rows,
        "list_sizes": index.list_sizes,
        "source_ids": index.source_ids,
        **quant.state_arrays(),
    }
    if index.tombstones is not None:
        # dead-row mask (u8); absent = all-live (pre-mutation files)
        arrays["tombstones"] = jnp.asarray(index.tombstones).astype(jnp.uint8)
    meta = {
        "kind": "ivf_rabitq",
        "version": _SERIAL_VERSION,
        "metric": int(index.metric),
        "n_lists": index.n_lists,
        "mut_cursor": int(index.mut_cursor),
        "append_slack": int(index.append_slack),
        **quant.state_meta(),
    }
    from raft_tpu.integrity.digest import pack_lists

    packed = pack_lists(index, "ivf_rabitq")
    if packed is not None:
        # per-list CRC-32C sidecar (v3, raft_tpu/integrity)
        arrays["list_digests"] = packed
        meta["table_digests"] = {
            k: int(v) for k, v in (index.table_digests or {}).items()}
    serialize_arrays(filename, arrays, meta)


def load(filename: str) -> Index:
    # schema-checked read (core.serialize.CKPT_SCHEMA): kind + version
    # gates, required-field presence checked before construction
    from raft_tpu.core.serialize import read_ckpt

    arrays, meta = read_ckpt(filename, "ivf_rabitq")
    params = IndexParams(
        n_lists=meta["n_lists"],
        metric=DistanceType(meta["metric"]),
        store_dataset=False,
    )
    index = Index(
        params,
        arrays["rotation"],
        arrays["centers"],
        arrays["codes"],
        arrays["aux"],
        arrays["slot_rows"],
        arrays["list_sizes"],
        arrays["source_ids"],
    )
    # mutation-era fields (v2): absent in old checkpoints -> all-live
    index.tombstones = arrays.get("tombstones")
    index.mut_cursor = int(meta.get("mut_cursor", 0))
    index.append_slack = int(meta.get("append_slack", 0))
    # integrity sidecar (v3): absent/corrupt -> no sidecar
    from raft_tpu.integrity.digest import unpack_lists

    unpack_lists(index, "ivf_rabitq", arrays.get("list_digests"),
                 meta.get("table_digests"))
    return index
