"""Candidate refinement: exact re-ranking of ANN results.

Reference parity: `raft::neighbors::refine` (neighbors/refine.cuh:71,93,
detail/refine.cuh) — given candidate neighbor ids from a lossy index
(typically IVF-PQ), recompute exact distances against the original dataset
and keep the best k. pylibraft `neighbors.refine`.

TPU design: a gather of candidate rows + one batched matmul per query block
+ select_k — the same streamed pattern as IVF-Flat's fine stage.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.core.config import auto_convert_output


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k: int, metric: DistanceType):
    nq, nc = candidates.shape
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    qb = max(1, (1 << 22) // max(1, nc * dataset.shape[1]))
    qb = min(qb, nq)
    nblocks = -(-nq // qb)
    pad = nblocks * qb - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0))) if pad else queries
    cp = jnp.pad(candidates, ((0, pad), (0, 0)), constant_values=-1) if pad else candidates

    from raft_tpu.distance.pairwise import _MATMUL_PRECISION

    def block(inp):
        qs, cand = inp
        cdata = dataset[jnp.maximum(cand, 0)].astype(jnp.float32)  # (qb, nc, dim)
        dots = jnp.einsum("qd,qcd->qc", qs.astype(jnp.float32), cdata,
                          precision=_MATMUL_PRECISION)
        if metric == DistanceType.InnerProduct:
            score = dots
        else:
            qn = jnp.sum(qs.astype(jnp.float32) ** 2, axis=1)[:, None]
            cn = jnp.sum(cdata**2, axis=2)
            score = jnp.maximum(qn + cn - 2.0 * dots, 0.0)
        score = jnp.where(cand >= 0, score, worst)
        v, pos = _select_k_impl(score, k, select_min)
        return v, jnp.take_along_axis(cand, pos, axis=1)

    vals, ids = lax.map(
        block, (qp.reshape(nblocks, qb, -1), cp.reshape(nblocks, qb, nc))
    )
    vals = vals.reshape(-1, k)[:nq]
    ids = ids.reshape(-1, k)[:nq]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(vals)
    return vals, ids

@auto_convert_output
def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
    resources=None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank `candidates` (nq, n_cand) with exact distances; return the
    best (distances, indices) of shape (nq, k). Ids of -1 are skipped."""
    from raft_tpu.core.validation import check_matrix

    ds = check_matrix(dataset, name="dataset")
    q = check_matrix(queries, name="queries")
    cand = jnp.asarray(candidates)
    if cand.ndim != 2 or cand.shape[0] != q.shape[0]:
        raise ValueError("candidates must be (n_queries, n_candidates)")
    m = resolve_metric(metric)
    if k > cand.shape[1]:
        raise ValueError(f"k={k} > n_candidates={cand.shape[1]}")
    vals, ids = _refine_impl(ds, q, cand.astype(jnp.int32), int(k), m)
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


@auto_convert_output
def refine_host(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
    resources=None,
) -> Tuple[jax.Array, jax.Array]:
    """Host-dataset refine (the reference's host-side overload,
    detail/refine.cuh host impl; neighbors/refine.cuh:93): the full
    dataset stays in host RAM (numpy/memmap) — only the candidate rows
    (nq x n_cand x dim, a few MB) are gathered on host and shipped to the
    device for the exact re-rank. This is the 10M+/100M-row pipeline where
    uploading the whole dataset to HBM is not an option."""
    import numpy as np

    from raft_tpu.core.validation import check_matrix

    q = check_matrix(queries, name="queries")
    cand = np.asarray(candidates)
    if cand.ndim != 2 or cand.shape[0] != q.shape[0]:
        raise ValueError("candidates must be (n_queries, n_candidates)")
    m = resolve_metric(metric)
    if k > cand.shape[1]:
        raise ValueError(f"k={k} > n_candidates={cand.shape[1]}")
    host = np.asarray(dataset)
    cdata = host[np.clip(cand, 0, host.shape[0] - 1)].astype(np.float32)
    vals, ids = _refine_gathered_impl(
        jnp.asarray(cdata), q, jnp.asarray(cand.astype(np.int32)), int(k), m
    )
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine_gathered_impl(cdata, queries, candidates, k: int, metric: DistanceType):
    """Exact re-rank when candidate rows are already gathered:
    cdata (nq, nc, dim) aligned with candidates (nq, nc)."""
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    qs = queries.astype(jnp.float32)

    from raft_tpu.distance.pairwise import _MATMUL_PRECISION

    dots = jnp.einsum("qd,qcd->qc", qs, cdata.astype(jnp.float32),
                      precision=_MATMUL_PRECISION)
    if metric == DistanceType.InnerProduct:
        score = dots
    else:
        qn = jnp.sum(qs**2, axis=1)[:, None]
        cn = jnp.sum(cdata.astype(jnp.float32) ** 2, axis=2)
        score = jnp.maximum(qn + cn - 2.0 * dots, 0.0)
    score = jnp.where(candidates >= 0, score, worst)
    v, pos = _select_k_impl(score, k, select_min)
    ids = jnp.take_along_axis(candidates, pos, axis=1)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(v)
    return v, ids
