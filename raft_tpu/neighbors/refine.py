"""Candidate refinement: exact re-ranking of ANN results.

Reference parity: `raft::neighbors::refine` (neighbors/refine.cuh:71,93,
detail/refine.cuh) — given candidate neighbor ids from a lossy index
(typically IVF-PQ), recompute exact distances against the original dataset
and keep the best k. pylibraft `neighbors.refine`.

TPU design: a gather of candidate rows + one batched matmul per query block
+ select_k — the same streamed pattern as IVF-Flat's fine stage. The
select is dispatched through `matrix.select_k`: the "fused" strategy
(tuned `select_k_strategy`, or explicit `strategy="fused"`) re-ranks each
query's gathered candidate block with the fused distance+select-k kernel
(ops/fused_scan.fused_list_topk, one "list" of candidates per query), so
the (nq, n_cand) score matrix never materializes — the fused
exact-distance rerank that backs IVF-PQ/IVF-RaBitQ recall recovery.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.core.config import auto_convert_output

_LANES = 128


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k: int, metric: DistanceType):
    nq, nc = candidates.shape
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    qb = max(1, (1 << 22) // max(1, nc * dataset.shape[1]))
    qb = min(qb, nq)
    nblocks = -(-nq // qb)
    pad = nblocks * qb - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0))) if pad else queries
    cp = jnp.pad(candidates, ((0, pad), (0, 0)), constant_values=-1) if pad else candidates

    from raft_tpu.distance.pairwise import _MATMUL_PRECISION

    def block(inp):
        qs, cand = inp
        cdata = dataset[jnp.maximum(cand, 0)].astype(jnp.float32)  # (qb, nc, dim)
        dots = jnp.einsum("qd,qcd->qc", qs.astype(jnp.float32), cdata,
                          precision=_MATMUL_PRECISION)
        if metric == DistanceType.InnerProduct:
            score = dots
        else:
            qn = jnp.sum(qs.astype(jnp.float32) ** 2, axis=1)[:, None]
            cn = jnp.sum(cdata**2, axis=2)
            score = jnp.maximum(qn + cn - 2.0 * dots, 0.0)
        score = jnp.where(cand >= 0, score, worst)
        v, pos = _select_k_impl(score, k, select_min)
        return v, jnp.take_along_axis(cand, pos, axis=1)

    vals, ids = lax.map(
        block, (qp.reshape(nblocks, qb, -1), cp.reshape(nblocks, qb, nc))
    )
    vals = vals.reshape(-1, k)[:nq]
    ids = ids.reshape(-1, k)[:nq]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(vals)
    return vals, ids


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "interpret", "fault_key")
)
def _refine_fused_impl(dataset, queries, candidates, k: int,
                       metric: DistanceType, interpret: bool = False,
                       fault_key=None):
    """Fused exact rerank: gather each query's candidate rows and hand
    the block to the fused scan+select kernel as one "list" per query
    (chunk=1), so scoring and selection stay in VMEM and only the
    (nq, k) result reaches HBM. Exact over the bf16-rounded candidate
    rows, ties to the smaller candidate slot (== the smaller position
    in the candidate list — the lax.top_k stable order)."""
    cdata = dataset[jnp.maximum(candidates, 0)]
    return _fused_rerank_gathered(
        cdata, queries, candidates, k, metric, interpret, fault_key
    )


def _fused_rerank_gathered(cdata, queries, candidates, k: int,
                           metric: DistanceType, interpret: bool,
                           fault_key):
    """Shared fused rerank over already-gathered candidate rows
    (cdata (nq, nc, dim) aligned with candidates (nq, nc)); traced
    inside the callers' jits."""
    from raft_tpu.ops.fused_scan import fused_list_topk

    ip = metric == DistanceType.InnerProduct
    nq, nc = candidates.shape
    ncp = -(-nc // _LANES) * _LANES
    # the kernel dots bf16 operands: ship the store AS bf16 (halving the
    # dominant candidate stream, like every other fused caller) and —
    # critically — derive |v|^2 and |q|^2 from the SAME rounded rows.
    # Mixing unrounded f32 norms with bf16 dots cancels wrong on data
    # with a large common offset (|v|^2 - 2<q,v> is a difference of two
    # huge near-equal terms; the flat _scan_fused_impl pins the same
    # invariant).
    cb = cdata.astype(jnp.bfloat16)
    if ncp > nc:
        cb = jnp.pad(cb, ((0, 0), (0, ncp - nc), (0, 0)))
        candidates = jnp.pad(
            candidates, ((0, 0), (0, ncp - nc)), constant_values=-1
        )
    cf = cb.astype(jnp.float32)
    valid = candidates >= 0
    if ip:
        base = jnp.where(valid, 0.0, jnp.inf)[:, None, :]
    else:
        base = jnp.where(valid, jnp.sum(cf * cf, axis=2), jnp.inf)[:, None, :]
    qf = queries.astype(jnp.float32)
    vals, slots = fused_list_topk(
        jnp.arange(nq, dtype=jnp.int32), qf[:, None, :], cb, base, k,
        inner_product=ip, interpret=interpret, fault_key=fault_key,
    )  # (nq, 1, kbuf) exact best-first, minimizing
    vals = vals[:, 0, :k]
    slots = slots[:, 0, :k]
    invalid = ~jnp.isfinite(vals)
    slots = jnp.where(invalid, 0, slots)  # sentinel -> safe gather
    ids = jnp.take_along_axis(candidates, slots, axis=1)
    ids = jnp.where(invalid, -1, ids)
    if ip:
        return jnp.where(invalid, -jnp.inf, -vals), ids
    qb = qf.astype(jnp.bfloat16).astype(jnp.float32)
    qn = jnp.sum(qb * qb, axis=1, keepdims=True)
    v = jnp.maximum(vals + qn, 0.0)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(v)
    return v, ids


def _resolve_refine_strategy(strategy, metric: DistanceType, nc: int,
                             dim: int, k: int) -> str:
    """Refine's select dispatch: the one tuned `select_k_strategy`
    policy (matrix.select_k), gated on the fused LIST kernel covering
    this metric/geometry — refine's fused path is one lane-padded
    candidate "list" per query, so the fit check is fits_fused_list
    (bf16 store), not the flat-scan envelope."""
    from raft_tpu.matrix.select_k import (
        _fused_metric_kind, resolve_scan_strategy,
    )
    from raft_tpu.ops.fused_scan import fits_fused_list

    ncp = -(-nc // _LANES) * _LANES
    fits = 0 < k <= ncp and fits_fused_list(1, ncp, dim, k,
                                            store_itemsize=2)
    if strategy == "fused":
        if _fused_metric_kind(metric) is None:
            raise ValueError(
                f"strategy='fused' supports L2/inner_product metrics, "
                f"got {metric}"
            )
        if not fits:
            raise ValueError(
                f"strategy='fused': candidate block ({ncp} x dim {dim}, "
                f"k={k}) exceeds the fused kernel's envelope; use "
                "strategy='two_phase'"
            )
        return "fused"
    return resolve_scan_strategy(
        nc, dim, k, strategy,
        fused_ok=_fused_metric_kind(metric) is not None and fits,
    )


def _charge_refine_cost(nq: int, nc: int, dim: int, k: int, fused: bool):
    if obs.enabled():
        obs.span_cost(**obs.perf.cost_for(
            "neighbors.refine", nq=nq, n_cand=nc, dim=dim, k=k,
            dtype="bf16" if fused else "f32", fused=fused))


@obs.spanned("neighbors.refine")
@auto_convert_output
def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
    resources=None,
    strategy: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank `candidates` (nq, n_cand) with exact distances; return the
    best (distances, indices) of shape (nq, k). Ids of -1 are skipped.

    `strategy`: None/"auto" resolves the select through the tuned
    `select_k_strategy` dispatch (matrix.select_k); "fused" forces the
    fused rerank kernel (exact over bf16-rounded rows, score matrix
    never in HBM); "two_phase" forces the materializing reference path.
    """
    from raft_tpu.core.validation import check_matrix

    ds = check_matrix(dataset, name="dataset")
    q = check_matrix(queries, name="queries")
    cand = jnp.asarray(candidates)
    if cand.ndim != 2 or cand.shape[0] != q.shape[0]:
        raise ValueError("candidates must be (n_queries, n_candidates)")
    m = resolve_metric(metric)
    if k > cand.shape[1]:
        raise ValueError(f"k={k} > n_candidates={cand.shape[1]}")
    strat = _resolve_refine_strategy(
        strategy, m, int(cand.shape[1]), int(ds.shape[1]), int(k)
    )
    _charge_refine_cost(int(q.shape[0]), int(cand.shape[1]),
                        int(ds.shape[1]), int(k), strat == "fused")
    if strat == "fused":
        from raft_tpu.core import faults

        vals, ids = _refine_fused_impl(
            ds, q, cand.astype(jnp.int32), int(k), m,
            interpret=jax.default_backend() == "cpu",  # Mosaic needs TPU
            fault_key=faults.trace_key(),
        )
    else:
        vals, ids = _refine_impl(ds, q, cand.astype(jnp.int32), int(k), m)
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


@obs.spanned("neighbors.refine")
@auto_convert_output
def refine_host(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
    resources=None,
    strategy: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Host-dataset refine (the reference's host-side overload,
    detail/refine.cuh host impl; neighbors/refine.cuh:93): the full
    dataset stays in host RAM (numpy/memmap) — only the candidate rows
    (nq x n_cand x dim, a few MB) are gathered on host and shipped to the
    device for the exact re-rank. This is the 10M+/100M-row pipeline where
    uploading the whole dataset to HBM is not an option. `strategy`
    dispatches the device-side select like `refine`."""
    import numpy as np

    from raft_tpu.core.validation import check_matrix

    q = check_matrix(queries, name="queries")
    cand = np.asarray(candidates)
    if cand.ndim != 2 or cand.shape[0] != q.shape[0]:
        raise ValueError("candidates must be (n_queries, n_candidates)")
    m = resolve_metric(metric)
    if k > cand.shape[1]:
        raise ValueError(f"k={k} > n_candidates={cand.shape[1]}")
    host = np.asarray(dataset)
    strat = _resolve_refine_strategy(
        strategy, m, int(cand.shape[1]), int(host.shape[1]), int(k)
    )
    _charge_refine_cost(int(q.shape[0]), int(cand.shape[1]),
                        int(host.shape[1]), int(k), strat == "fused")
    cdata = host[np.clip(cand, 0, host.shape[0] - 1)].astype(np.float32)
    if strat == "fused":
        from raft_tpu.core import faults

        vals, ids = _refine_fused_gathered_impl(
            jnp.asarray(cdata), q, jnp.asarray(cand.astype(np.int32)),
            int(k), m, interpret=jax.default_backend() == "cpu",
            fault_key=faults.trace_key(),
        )
    else:
        vals, ids = _refine_gathered_impl(
            jnp.asarray(cdata), q, jnp.asarray(cand.astype(np.int32)),
            int(k), m
        )
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "interpret", "fault_key")
)
def _refine_fused_gathered_impl(cdata, queries, candidates, k: int,
                                metric: DistanceType,
                                interpret: bool = False, fault_key=None):
    """Fused twin of `_refine_gathered_impl` (candidate rows already
    gathered on host)."""
    return _fused_rerank_gathered(
        cdata, queries, candidates, k, metric, interpret, fault_key
    )


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine_gathered_impl(cdata, queries, candidates, k: int, metric: DistanceType):
    """Exact re-rank when candidate rows are already gathered:
    cdata (nq, nc, dim) aligned with candidates (nq, nc)."""
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    qs = queries.astype(jnp.float32)

    from raft_tpu.distance.pairwise import _MATMUL_PRECISION

    dots = jnp.einsum("qd,qcd->qc", qs, cdata.astype(jnp.float32),
                      precision=_MATMUL_PRECISION)
    if metric == DistanceType.InnerProduct:
        score = dots
    else:
        qn = jnp.sum(qs**2, axis=1)[:, None]
        cn = jnp.sum(cdata.astype(jnp.float32) ** 2, axis=2)
        score = jnp.maximum(qn + cn - 2.0 * dots, 0.0)
    score = jnp.where(candidates >= 0, score, worst)
    v, pos = _select_k_impl(score, k, select_min)
    ids = jnp.take_along_axis(candidates, pos, axis=1)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(v)
    return v, ids
