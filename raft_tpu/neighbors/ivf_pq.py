"""IVF-PQ: product-quantized inverted-file ANN index (the north star).

Reference parity: `raft::neighbors::ivf_pq` — params & index
(ivf_pq_types.hpp:43-110, list layout :153-215), build
(detail/ivf_pq_build.cuh:1074: subsample → random-orthogonal rotation via QR
:177 → balanced k-means :1189 → per-subspace :393 / per-cluster :473
codebook training → encode :578,:629), search (detail/ivf_pq_search.cuh:1550:
batch → rotate → select_clusters :133 → LUT scoring kernel :611 →
postprocess :373,:401); pylibraft `neighbors.ivf_pq` (ivf_pq.pyx:91-271).

TPU design (not a port):
  - Codebook training is ONE jit: `vmap` of the balanced-EM trainer over
    subspaces — pq_dim independent k-means problems become a single batched
    XLA program (vs the reference's sequential per-subspace kernel launches).
  - Codes are stored one-byte-per-code in a padded (n_lists, max_list,
    pq_dim) uint8 slot table (4..8 bit codes all fit; bit-packing on TPU
    costs more in unpack VPU ops than it saves in HBM for pq_bits=8, and
    pq_bits<8 simply uses a smaller codebook).
  - Search scoring: per (query, probe) the LUT (pq_dim, 2^bits) is built by
    one batched MXU matmul; scores are pq_dim embedding-style gathers from
    the LUT summed on the VPU — the XLA-native equivalent of the
    reference's shared-memory LUT kernel (compute_similarity_kernel).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.neighbors.ivf_flat import _pack_lists
# codebook training + encode live in the shared quantizer layer now
# (neighbors/quantizer.py, PR 6); the underscore names stay importable
# from here because comms/ and bench/ call them by these paths — and the
# jitted functions are the very same objects, so the refactor is
# bit-identical (pinned by tests/goldens/ivf_pq_prerefactor.json)
from raft_tpu.neighbors.quantizer import (
    PER_CLUSTER,
    PER_SUBSPACE,
    PqQuantizer,
    _block_rows_for_encode,  # noqa: F401  (re-export: bench/profilers)
    _encode,
    _train_codebooks_per_cluster,  # noqa: F401  (re-export: comms)
    _train_codebooks_per_subspace,  # noqa: F401  (re-export: comms)
)
from raft_tpu import obs
from raft_tpu.core.config import auto_convert_output


@dataclasses.dataclass
class IndexParams:
    """Mirrors ivf_pq::index_params (ivf_pq_types.hpp:43-110)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8
    pq_dim: int = 0  # 0 = auto (dim/4 rounded to multiple of 8, ref heuristic)
    codebook_kind: str = PER_SUBSPACE
    force_random_rotation: bool = False
    add_data_on_build: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if not (4 <= self.pq_bits <= 8):
            raise ValueError("pq_bits must be in [4, 8]")
        if self.pq_dim < 0:
            raise ValueError(f"pq_dim must be >= 0 (0 = auto), got {self.pq_dim}")
        if self.codebook_kind not in (PER_SUBSPACE, PER_CLUSTER):
            raise ValueError(f"bad codebook_kind {self.codebook_kind}")


# duplication (nq * n_probes / n_lists) at or below which the tuned
# listmajor_chunk key applies: the profiler races chunk widths at the
# refined np8 shape (dup = 32 at bench geometry); the np32 ladder
# (dup = 128) is measured at the 128 default and must stay there
_LOW_DUP_CHUNK_BOUND = 48


@dataclasses.dataclass
class SearchParams:
    """Mirrors ivf_pq::search_params (ivf_pq_types.hpp:112-150).

    `internal_distance_dtype`/`lut_dtype` map to the score dtype used in
    scoring (fp32 default; bf16 reduces HBM traffic like the reference's
    half/fp8 LUTs).
    """

    n_probes: int = 20
    lut_dtype: str = "float32"  # "float32" | "bfloat16"
    # API parity with ivf_pq_types.hpp:112-150: the reference lets scores
    # accumulate in half precision. On TPU the MXU accumulates f32 natively
    # (bf16 inputs, f32 accumulation), so "float16"/"bfloat16" instead
    # control the stored score dtype in the list-major engine: bf16 trim
    # scores, halving that engine's dominant HBM stream (~1e-3 relative
    # ranking noise). Other engines keep f32 scores (the lut engine's LUT
    # dtype is `lut_dtype`). "float32" = exact f32 everywhere. "auto"
    # (default) resolves from the measured tuned hint on TPU (bf16 trim
    # won the 2026-08-01 chip ladder by 11% at equal recall) and to
    # "float32" on every other backend, so CPU test numerics are stable.
    internal_distance_dtype: str = "auto"
    # Scoring engine (TPU design choice, no reference analogue):
    #   "lut"    — classic PQ LUT scoring (embedding-style gathers from the
    #              per-probe LUT; minimal HBM traffic: pq_dim bytes/vector).
    #   "recon8" — ScaNN-style int8 reconstruction scoring: codes are
    #              decoded once (build side) into per-dim-quantized int8
    #              vectors and scored with one MXU matmul per query block
    #              (rot_dim bytes/vector of traffic, zero gathers). Fastest
    #              on TPU, where the MXU beats per-element gathers.
    #   "recon8_list" — list-major recon8: probe pairs inverted to per-list
    #              query buckets so each list's codes are streamed from HBM
    #              exactly once per batch (vs ~nq*n_probes/n_lists times in
    #              the query-major engines). Best for large query batches.
    #   "auto"   — the measured tuned engine when a chip profile wrote
    #              one; else recon8_list when the batch re-reads lists
    #              >=4x, recon8 on TPU below that (lut's big flattened
    #              gather kernel-faults TPU devices — docs/perf.md
    #              device-fault section), lut on other backends.
    # Default "auto" (VERDICT r4 #5): a default-constructed SearchParams
    # must land on the measured winner, never the faulting lut engine.
    score_mode: str = "auto"  # "lut" | "recon8" | "recon8_list" | "auto"
    # recon8_list matmul operand dtype (TPU design choice): "bf16" upcasts
    # the int8 codes to bfloat16; "int8" additionally quantizes each
    # query's residual row to int8 (ScaNN-style symmetric scoring) so the
    # chunk matmul runs int8 x int8 -> int32 at the MXU's double int8
    # rate with half the query-side operand bytes. Adds one more
    # quantization to the query side only; candidate ordering shifts are
    # absorbed by refine/probe margins.
    score_dtype: str = "bf16"  # "bf16" | "int8"
    # recon8_list per-chunk trim implementation:
    #   "approx" — XLA scoring matmul + lax.approx_min_k (default).
    #   "exact"  — XLA scoring matmul + exact lax.top_k per superblock:
    #              zero candidate loss (the approx bin-trim's recall tax
    #              becomes a measured choice; VERDICT r4 #6) at the cost
    #              of the full sort network.
    #   "pallas" — fused Pallas list-scan (ops/pq_list_scan.py): scoring
    #              and the best+second-best bin reduction stay in VMEM;
    #              codes are read by scalar-prefetch indexing with no
    #              gather copy. Experimental on-chip; incompatible with
    #              score_dtype="int8", ignores internal_distance_dtype,
    #              and caps per-list candidates at 256 (k <= 256).
    #   "fused"  — fused distance + EXACT partial select-k
    #              (matrix/select_k.list_scan_select_k, the select_k
    #              dispatch layer's fused list kernel): same fused
    #              geometry as "pallas" (score tile never in HBM,
    #              scalar-prefetch code reads) but the in-kernel top-k
    #              is exact, so the only loss left is the PQ
    #              quantization itself. Caps per-list candidates at 256
    #              (k <= 256). With score_dtype="int8" the scoring
    #              matmul runs on the MXU's int8 datapath
    #              (ISSUE 11: dispatch strategy "fused_int8" — int8
    #              dot, int32 accumulate, per-row dequant on the VPU)
    #              and bit-agrees with the "pallas" int8 trim's scores.
    #   "auto"   — "approx" unless the measured integer tuned key
    #              (matrix/select_k.INT8_SCAN_KEY, written by
    #              bench_select_k_strategies --apply on chip data)
    #              promotes the fused int8 trim for an int8-scored
    #              list-major search whose geometry fits the kernel.
    trim_engine: str = "auto"  # "auto"|"approx"|"exact"|"pallas"|"fused"
    # -- adaptive probing (neighbors/probe_budget, ROADMAP item 2) --
    # per-query probe budgets from the coarse gap profile (+ radius
    # bounds for L2 when the index carries them); recall_target >= 1.0
    # saturates, bit-identical to the fixed-n_probes reference. Note
    # the PQ caveat: bounds are exact-space (rotation is orthonormal),
    # while PQ scores are quantized estimates — early termination's
    # no-dropped-neighbor guarantee is exact-geometry, the quantized
    # ranking's recall is covered by the banked frontier instead.
    adaptive: bool = False
    recall_target: Optional[float] = None
    budget_tau: Optional[float] = None
    min_probes: int = 1
    early_term: bool = True


class Index:
    """IVF-PQ index.

    rotation  (rot_dim, dim) f32 — orthogonal input transform
    centers   (n_lists, rot_dim) f32 — coarse centroids (rotated space)
    pq_centers:
        per_subspace: (pq_dim, 2^bits, pq_len)
        per_cluster:  (n_lists, 2^bits, pq_len)
    codes     (n_lists, max_list, pq_dim) uint8 slot table
    slot_valid(n_lists, max_list) bool
    source_ids(n_rows,) int32; slot_rows (n_lists, max_list) int32 -> row id
    """

    def __init__(self, params, rotation, centers, pq_centers, codes, slot_rows,
                 list_sizes, source_ids):
        self.params = params
        self.rotation = rotation
        self.centers = centers
        self.pq_centers = pq_centers
        self.codes = codes
        self.slot_rows = slot_rows
        self.list_sizes = list_sizes
        self.source_ids = source_ids
        # int8 reconstruction store, built lazily for score_mode="recon8":
        # recon8 (n_lists, lpad, rot_dim) int8, recon_scale (rot_dim,) f32,
        # recon_norm (n_lists, lpad) f32, slot_rows_pad (n_lists, lpad)
        # int32 — lpad = max_list rounded up to 128 (see build_reconstruction)
        self.recon8 = None
        self.recon_scale = None
        self.recon_norm = None
        self.slot_rows_pad = None
        # fused-trim candidate-buffer width (ops/fused_scan.fused_kbuf),
        # grown monotonically when a later search's k outruns it — the
        # ivf_flat lazy-store invalidation contract, applied to the
        # fused/fused_int8 trims (a narrower compiled buffer would
        # silently truncate the per-list candidates)
        self.fused_kb = None
        # per-list radii in ROTATED space (max member residual norm) —
        # the early-termination bounds of adaptive probing, computed
        # incrementally by extend from the exact pre-quantization rows
        # and serialized with the index. None = bounds absent (old
        # checkpoints) -> budgets-only fallback.
        self.list_radii = None
        # live-mutation state (neighbors/mutation): optional dead-row
        # mask (n_lists, max_list; nonzero = dead, None = all-live),
        # the applied-log cursor at the last checkpoint commit, and the
        # mutator's reserved per-list append slack. The mask is masked
        # into the slot tables (slot_rows AND the lane-padded
        # slot_rows_pad — pad-aware) by `core.bitset.make_slot_filter`.
        self.tombstones = None
        self.mut_cursor = 0
        self.append_slack = 0
        # integrity sidecar (raft_tpu/integrity): per-list / per-table
        # CRC-32C digests; None = no sidecar (legacy)
        self.list_digests = None
        self.table_digests = None
        self._id_bound = None

    @property
    def n_tombstones(self) -> int:
        """Dead-slot count (0 when all-live) — truthful accounting:
        cost-model charges bill live rows only."""
        if self.tombstones is None:
            return 0
        return int(jnp.sum(jnp.asarray(self.tombstones).astype(jnp.int32)))

    @property
    def id_bound(self) -> int:
        """One past the largest source id — the id space a search
        `prefilter` must cover. Equals `size` for default arange ids;
        larger when extend() was given custom new_indices (a size-bound
        filter would silently exclude those rows). Cached per Index
        instance (extend returns a new Index, so mutation invalidates)."""
        if self._id_bound is None:
            self._id_bound = (
                int(jnp.max(self.source_ids)) + 1 if self.size else 0
            )
        return self._id_bound

    @property
    def metric(self):
        return self.params.metric

    @property
    def n_lists(self):
        return int(self.centers.shape[0])

    @property
    def dim(self):
        return int(self.rotation.shape[1])

    @property
    def rot_dim(self):
        return int(self.rotation.shape[0])

    @property
    def pq_dim(self):
        return int(self.codes.shape[2])

    @property
    def pq_len(self):
        return self.rot_dim // self.pq_dim

    @property
    def pq_bits(self):
        return int(self.params.pq_bits)

    @property
    def size(self):
        return int(self.source_ids.shape[0])

    def __repr__(self):
        return (
            f"ivf_pq.Index(n_lists={self.n_lists}, dim={self.dim}, pq_dim={self.pq_dim}, "
            f"pq_bits={self.pq_bits}, size={self.size}, metric={self.metric.name})"
        )


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def _auto_pq_dim(dim: int) -> int:
    # ivf_pq_types.hpp pq_dim==0 heuristic: dim/4 rounded down to mult of 8
    d = max(1, dim // 4)
    if d > 8:
        d = d // 8 * 8
    return d


def _make_rotation(key, rot_dim: int, dim: int, force_random: bool) -> jax.Array:
    """Random orthogonal rotation via QR of a gaussian
    (ivf_pq_build.cuh:177 make_rotation_matrix)."""
    if not force_random and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    g = jax.random.normal(key, (max(rot_dim, dim), max(rot_dim, dim)), dtype=jnp.float32)
    q, r = jnp.linalg.qr(g)
    # sign-fix for a uniform (Haar) rotation
    q = q * jnp.sign(jnp.diagonal(r))[None, :]
    return q[:rot_dim, :dim]


def _coarse_fit(params, x, rotation, key, seed: int):
    """Single-chip coarse stage shared by the PQ and RaBitQ builds:
    trainset-fraction subsample (key-top-k sampler — no n-length
    permutation at 10M+ scale, rng.py:128), rotate, balanced k-means
    (hierarchical past 1024 lists). ONE implementation so trainset
    sizing/seeding/EM choices cannot diverge per quantizer (the
    single-chip mirror of mnmg_ivf_build._coarse_fit_rotated). Splits
    the caller's `key` exactly once, so downstream draws (PQ's codebook
    key) see the same stream as before the extraction. Returns
    (centers, rotated trainset, key)."""
    n = x.shape[0]
    frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
    n_train = min(n, max(params.n_lists * 4, int(n * frac)))
    key, sk = jax.random.split(key)
    if n_train < n:
        from raft_tpu.random.rng import sample_without_replacement

        train_sel = sample_without_replacement(sk, n, n_train)
        x_train_rot = x[train_sel] @ rotation.T
    else:
        x_train_rot = x @ rotation.T

    metric_name = (
        "inner_product" if params.metric == DistanceType.InnerProduct
        else "sqeuclidean"
    )
    fit = (kmeans_balanced.fit_hierarchical if params.n_lists > 1024
           else kmeans_balanced.fit)
    centers = fit(x_train_rot, params.n_lists, n_iters=params.kmeans_n_iters,
                  metric=metric_name, seed=seed)
    return centers, x_train_rot, key


@obs.spanned("neighbors.ivf_pq.build")
def build(params: IndexParams, dataset, resources=None, seed: int = 0) -> Index:
    """Train rotation, coarse centers, codebooks; encode + pack lists
    (detail/ivf_pq_build.cuh:1074)."""
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(dataset, name="dataset").astype(jnp.float32)
    n, dim = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    pq_dim = params.pq_dim or _auto_pq_dim(dim)
    pq_len = -(-dim // pq_dim)
    rot_dim = pq_dim * pq_len
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    rotation = _make_rotation(rk, rot_dim, dim, params.force_random_rotation or rot_dim != dim)

    centers, x_train_rot, key = _coarse_fit(params, x, rotation, key, seed)
    n_train = int(x_train_rot.shape[0])
    metric_name = "inner_product" if params.metric == DistanceType.InnerProduct else "sqeuclidean"

    # codebooks from trainset residuals. Codebook EM only needs enough
    # samples to fit 2^pq_bits centroids per subspace (the reference trains
    # codebooks on the same subsampled trainset, ivf_pq_build.cuh:393);
    # capping the residual set keeps the vmapped-EM stage O(1) in dataset
    # size without measurable recall cost. PER_CLUSTER partitions the
    # sampled rows across n_lists before training, so its cap must scale
    # with n_lists to keep every cluster's sample set populated.
    nb = 1 << params.pq_bits
    max_cb_rows = max(65536, 64 * nb)
    if params.codebook_kind == PER_CLUSTER:
        max_cb_rows = max(max_cb_rows, 256 * params.n_lists)
    if n_train > max_cb_rows:
        key, rk2 = jax.random.split(key)
        cb_sel = jax.random.choice(rk2, n_train, (max_cb_rows,), replace=False)
        x_cb = x_train_rot[cb_sel]
    else:
        x_cb = x_train_rot
    train_labels = kmeans_balanced.predict(x_cb, centers, metric=metric_name)
    residuals = x_cb - centers[train_labels]
    key, ck = jax.random.split(key)
    # codebook training through the shared quantizer layer (the jitted
    # trainers are the pre-refactor functions — bit-identical)
    quant = PqQuantizer(
        codebook_kind=params.codebook_kind, pq_bits=params.pq_bits,
        pq_dim=pq_dim, pq_len=pq_len, n_lists=params.n_lists,
    )
    pq_centers = quant.train(ck, residuals, train_labels).pq_centers

    index = Index(
        params,
        rotation,
        centers,
        pq_centers,
        jnp.zeros((params.n_lists, 1, pq_dim), jnp.uint8),
        jnp.full((params.n_lists, 1), -1, jnp.int32),
        jnp.zeros((params.n_lists,), jnp.int32),
        jnp.zeros((0,), jnp.int32),
    )
    # empty index: zero radii — extend max-folds each batch's exact
    # (rotated-space) residual norms in, one pass over assignments
    index.list_radii = jnp.zeros((params.n_lists,), jnp.float32)
    if params.add_data_on_build:
        index = extend(index, x, jnp.arange(n, dtype=jnp.int32))
    # build-time integrity sidecar (kept fresh incrementally after)
    from raft_tpu.integrity.digest import attach as _attach_digests

    _attach_digests(index, "ivf_pq")
    if resources is not None:
        resources.track(index.codes)
    return index


def label_and_encode(
    vectors, rotation, centers, pq_centers, metric: DistanceType,
    per_cluster: bool, with_dists: bool = False,
):
    """Rotate, assign to coarse lists, and PQ-encode the residuals — the
    shared encode sequence used by `extend` and the distributed build
    (comms.mnmg.ivf_pq_build). Returns (labels (n,), codes (n, pq_dim));
    with `with_dists` additionally the exact rotated-space residual
    norms (adaptive probing's list-radius update rides the residuals
    this pass already computed — no second rotation matmul)."""
    metric_name = (
        "inner_product" if metric == DistanceType.InnerProduct else "sqeuclidean"
    )
    v_rot = jnp.asarray(vectors, jnp.float32) @ rotation.T
    labels = kmeans_balanced.predict(v_rot, centers, metric=metric_name)
    residuals = v_rot - centers[labels]
    quant = PqQuantizer.from_centers(pq_centers, per_cluster)
    codes = quant.encode(residuals, labels)["codes"]
    if with_dists:
        dists = jnp.sqrt(jnp.maximum(
            jnp.sum(residuals ** 2, axis=1), 0.0))
        return labels, codes, dists
    return labels, codes


@obs.spanned("neighbors.ivf_pq.extend")
def extend(index: Index, new_vectors, new_indices=None) -> Index:
    """Label, encode and append new vectors (ivf_pq_build.cuh:1061 extend +
    process_and_fill_codes :724). Incremental: only the new batch is
    labeled/encoded and scattered into grown code tables — O(n_new + table
    copy), so streamed 100M-row builds stay linear."""
    from raft_tpu.core.validation import check_matrix
    from raft_tpu.neighbors.ivf_flat import _append_slots, _grow_and_scatter

    nv = check_matrix(new_vectors, name="new_vectors").astype(jnp.float32)
    old_n = index.size
    if new_indices is None:
        new_indices = jnp.arange(old_n, old_n + nv.shape[0], dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    per_cluster = index.params.codebook_kind == PER_CLUSTER
    labels, new_codes, resid_dists = label_and_encode(
        nv, index.rotation, index.centers, index.pq_centers, index.metric,
        per_cluster, with_dists=True,
    )

    labels_np = np.asarray(labels, np.int64)
    old_sizes = np.asarray(index.list_sizes, np.int64)
    slot_abs, new_sizes, new_max = _append_slots(labels_np, old_sizes, index.n_lists)
    # a store padded wider than the sizes imply (fused-engine lanes,
    # mutation append slack) must never shrink — slots stay where they are
    new_max = max(new_max, int(index.codes.shape[1]))
    positions = jnp.arange(old_n, old_n + nv.shape[0], dtype=jnp.int32)
    codes_tbl, slot_rows = _grow_and_scatter(
        index.codes,
        index.slot_rows,
        new_codes,
        jnp.asarray(labels_np),
        jnp.asarray(slot_abs),
        positions,
        new_max,
    )
    all_ids = jnp.concatenate([index.source_ids, new_indices]) if old_n else new_indices

    out = Index(
        index.params,
        index.rotation,
        index.centers,
        index.pq_centers,
        codes_tbl,
        slot_rows,
        jnp.asarray(new_sizes),
        all_ids,
    )
    from raft_tpu.neighbors.probe_budget import updated_radii

    # exact rotated-space residual norms of the new batch (the bounds
    # must hold for the TRUE geometry, not the quantized codes) — the
    # encode pass above already computed the residuals
    out.list_radii = updated_radii(
        index.list_radii, labels_np, np.asarray(resid_dists), index.n_lists)
    # mutation state survives extend (new tail slots are live appends)
    from raft_tpu.core.bitset import carry_tombstones

    out.tombstones = carry_tombstones(index.tombstones,
                                      int(codes_tbl.shape[1]))
    out.mut_cursor = index.mut_cursor
    out.append_slack = index.append_slack
    # integrity sidecar: only the lists this batch touched re-digest
    from raft_tpu.integrity.digest import refresh as _refresh_digests

    _refresh_digests(out, index, "ivf_pq")
    return out


# ---------------------------------------------------------------------------
# int8 reconstruction store (TPU scoring engine for score_mode="recon8")
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("per_cluster", "list_block"))
def _decode_quantize(codes, pq_centers, per_cluster: bool, list_block: int = 64):
    """Decode PQ codes to per-dim symmetric int8 + the decoded norms.

    Returns (recon8 (L, S, rot) int8, scale (rot,) f32, rnorm (L, S) f32).
    Decoding is the inverse of `_encode` (per-subspace codebook lookup);
    scale is a per-dimension max-abs over the codebooks themselves, so it
    needs no pass over the decoded data."""
    n_lists, max_list, pq_dim = codes.shape
    pq_len = pq_centers.shape[-1]
    rot_dim = pq_dim * pq_len
    # per-dim scale from codebook entries (bounds every reconstruction)
    if per_cluster:
        # entries shared by all subspaces of a list -> same per-pq_len scale
        amax = jnp.max(jnp.abs(pq_centers), axis=(0, 1))  # (pq_len,)
        scale = jnp.tile(amax, pq_dim) / 127.0
    else:
        amax = jnp.max(jnp.abs(pq_centers), axis=1)  # (pq_dim, pq_len)
        scale = amax.reshape(rot_dim) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    inv = (1.0 / scale).reshape(pq_dim, pq_len)

    nblocks = -(-n_lists // list_block)
    pad = nblocks * list_block - n_lists
    cp = jnp.pad(codes, ((0, pad), (0, 0), (0, 0))) if pad else codes
    cblocks = cp.reshape(nblocks, list_block, max_list, pq_dim)
    lids = jnp.arange(nblocks * list_block).reshape(nblocks, list_block)

    def dec(inp):
        cb, lid = inp  # (lb, S, P) uint8, (lb,)
        idx = cb.astype(jnp.int32)
        # codebook lookups as flat axis-0 gathers (the broadcasted 5-D
        # take_along_axis form kernel-faults on TPU at large index counts,
        # same class as the search-path gather fixed alongside)
        nb = pq_centers.shape[-2]
        if per_cluster:
            books = pq_centers[jnp.minimum(lid, pq_centers.shape[0] - 1)]  # (lb,B,pl)
            flat = books.reshape(-1, pq_len)
            lb = idx.shape[0]
            rows = jnp.arange(lb, dtype=jnp.int32)[:, None, None] * nb + idx
            rec = flat[rows]  # (lb, S, P, pl)
        else:
            flat = pq_centers.reshape(-1, pq_len)  # (P*B, pl)
            rows = jnp.arange(pq_dim, dtype=jnp.int32)[None, None, :] * nb + idx
            rec = flat[rows]  # (lb, S, P, pl)
        q = jnp.clip(jnp.round(rec * inv[None, None, :, :]), -127, 127).astype(jnp.int8)
        deq = q.astype(jnp.float32) * scale.reshape(pq_dim, pq_len)[None, None]
        rnorm = jnp.sum(deq.reshape(*q.shape[:2], -1) ** 2, axis=-1)
        return q.reshape(*q.shape[:2], rot_dim), rnorm

    recon8, rnorm = lax.map(dec, (cblocks, lids))
    recon8 = recon8.reshape(-1, max_list, rot_dim)[:n_lists]
    rnorm = rnorm.reshape(-1, max_list)[:n_lists]
    return recon8, scale, rnorm


def build_reconstruction(index: Index, pad_to_lanes: bool = False) -> Index:
    """Populate the int8 reconstruction store used by score_mode="recon8"
    (idempotent; called lazily from `search`).

    With `pad_to_lanes` the store's slot axis is padded to a multiple of
    128 lanes (>= 256) — the shape contract of the fused Pallas list-scan
    (ops/pq_list_scan.py) — with `slot_rows_pad` marking pad slots
    invalid and `recon_norm` +inf there, so every recon8 engine masks
    them exactly like in-list padding. Only the pallas trim asks for the
    padding (the default engines keep the tight store); once padded, the
    store stays padded (monotone, still idempotent)."""
    if index.recon8 is None:
        r8, scale, rnorm = _decode_quantize(
            index.codes, index.pq_centers, index.params.codebook_kind == PER_CLUSTER
        )
        index.recon8, index.recon_scale, index.recon_norm = r8, scale, rnorm
        index.slot_rows_pad = index.slot_rows
    if pad_to_lanes:
        from raft_tpu.ops.pq_list_scan import lane_padded

        max_list = index.recon8.shape[1]
        extra = lane_padded(max_list) - max_list
        if extra:
            index.recon8 = jnp.pad(index.recon8, ((0, 0), (0, extra), (0, 0)))
            index.recon_norm = jnp.pad(
                index.recon_norm, ((0, 0), (0, extra)), constant_values=jnp.inf
            )
            index.slot_rows_pad = jnp.pad(
                index.slot_rows_pad, ((0, 0), (0, extra)), constant_values=-1
            )
    return index


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _resolve_score_mode(params: SearchParams, nq: int, n_probes: int, n_lists: int) -> str:
    """Resolve score_mode="auto" to a concrete engine.

    Order: an explicit int8/pallas request pins recon8_list (the only
    engine honoring it); else a measured tuned key (`pq_auto_engine`,
    written by bench/apply_profile_hints.py from chip data) wins; else
    the duplication heuristic. On TPU the resolution NEVER lands on lut
    (even from a tuned key): its flattened gather kernel-faulted the
    device and a fault poisons the process backend — small batches get
    the gather-free recon8 engine instead."""
    mode = params.score_mode
    if mode != "auto":
        return mode
    if params.score_dtype == "int8" or params.trim_engine in (
        "pallas", "exact", "fused"
    ):
        return "recon8_list"
    from raft_tpu.core import tuned

    on_tpu = jax.default_backend() == "tpu"
    t = tuned.get("pq_auto_engine")
    if t in ("lut", "recon8", "recon8_list") and not (t == "lut" and on_tpu):
        return t
    dup = nq * n_probes / max(1, n_lists)
    if dup >= 4.0:
        return "recon8_list"
    return "recon8" if on_tpu else "lut"


_LUT_TPU_OVERRIDE = "RAFT_TPU_ALLOW_LUT_TPU"


def _check_lut_allowed() -> None:
    """Permanent fence (VERDICT r4 #5): explicit score_mode='lut' on TPU
    raises with the fault context instead of risking a device fault; the
    env override exists for fault-repro/profiling sessions only."""
    import os

    if jax.default_backend() == "tpu" and os.environ.get(_LUT_TPU_OVERRIDE) != "1":
        raise ValueError(
            "score_mode='lut' is fenced on TPU: its flattened LUT gather "
            "kernel-faulted the device at bench index counts (2026-08-01, "
            "docs/perf.md device-fault section) and a fault poisons the "
            "process's backend. Use score_mode='auto' (the measured "
            "engine), 'recon8', or 'recon8_list'; set "
            f"{_LUT_TPU_OVERRIDE}=1 only to reproduce/profile the fault."
        )


def _quantize_query_rows(u):
    """Symmetric per-row int8 quantization for ScaNN-style scoring:
    returns (q8, row_scale) with u ~= q8 * row_scale. Shared by the XLA
    and Pallas list-major engines — their parity depends on identical
    quantization."""
    ua = jnp.max(jnp.abs(u), axis=-1, keepdims=True) + 1e-12
    q8 = jnp.clip(jnp.round(u / ua * 127.0), -127, 127).astype(jnp.int8)
    return q8, ua / 127.0


def _query_block_size(n_probes: int, max_list: int, pq_dim: int) -> int:
    # keep the gathered codes block (qb, n_probes*max_list, pq_dim) ~<= 2^24 elems
    qb = max(1, (1 << 24) // max(1, n_probes * max_list * pq_dim))
    return int(min(qb, 16))


def _coarse_select(queries, rotation, centers, n_probes: int, metric: DistanceType):
    """Coarse stage shared by all engines (traced inside each engine's jit):
    rotate queries and pick the n_probes closest coarse centers
    (select_clusters, ivf_pq_search.cuh:133). Returns (q_rot, probes)."""
    from raft_tpu.distance.pairwise import _dot

    select_min = metric != DistanceType.InnerProduct
    q_rot = queries.astype(jnp.float32) @ rotation.T
    cd = _dot(q_rot, centers)
    if metric == DistanceType.InnerProduct:
        coarse = cd
    else:
        # query norm is constant per row; the argmin is unaffected
        coarse = jnp.sum(centers**2, axis=1)[None, :] - 2.0 * cd
    _, probes = _select_k_impl(coarse, n_probes, select_min)
    return q_rot, probes


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "per_cluster", "lut_bf16"),
)
def _search_impl(
    queries,
    rotation,
    centers,
    pq_centers,
    codes,
    slot_rows,
    k: int,
    n_probes: int,
    metric: DistanceType,
    per_cluster: bool,
    lut_bf16: bool = False,
    pvalid: jax.Array = None,
):
    nq, _ = queries.shape
    n_lists, max_list, pq_dim = codes.shape
    nb = pq_centers.shape[-2] if per_cluster else pq_centers.shape[1]
    pq_len = pq_centers.shape[-1]
    rot_dim = pq_dim * pq_len
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes, metric)

    qb = _query_block_size(n_probes, max_list, pq_dim)
    nblocks = -(-nq // qb)
    pad = nblocks * qb - nq
    qp = jnp.pad(q_rot, ((0, pad), (0, 0))) if pad else q_rot
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qblocks = qp.reshape(nblocks, qb, rot_dim)
    pblocks = pp.reshape(nblocks, qb, n_probes)
    if pvalid is not None:
        pvp = jnp.pad(pvalid, ((0, pad), (0, 0))) if pad else pvalid
        pvblocks = pvp.reshape(nblocks, qb, n_probes)

    sub_dim = (pq_dim, pq_len)

    def block(inp):
        if pvalid is not None:
            qs, pr, pvb = inp  # + (qb, n_probes) adaptive keep mask
        else:
            qs, pr = inp  # (qb, rot_dim), (qb, n_probes)
        # residual of query vs each probed center: (qb, n_probes, rot_dim)
        pc = centers[pr]
        if metric == DistanceType.InnerProduct:
            qres = jnp.broadcast_to(qs[:, None, :], (qb, n_probes, rot_dim))
        else:
            qres = qs[:, None, :] - pc
        qsub = qres.reshape(qb, n_probes, *sub_dim)  # (qb,np,pq_dim,pq_len)

        # ---- LUT build: one batched matmul (compute_similarity LUT :726) ----
        if per_cluster:
            books = pq_centers[pr]  # (qb, np, nb, pq_len)
            dots = jnp.einsum("qnpl,qnbl->qnpb", qsub, books)
            bn = jnp.sum(books**2, axis=3)[:, :, None, :]
        else:
            dots = jnp.einsum("qnpl,pbl->qnpb", qsub, pq_centers)
            bn = jnp.sum(pq_centers**2, axis=2)[None, None, :, :]
        if metric == DistanceType.InnerProduct:
            lut = dots  # score contribution q·c_b (plus q·center handled below)
        else:
            lut = bn - 2.0 * dots  # ||q_sub - c_b||² minus const ||q_sub||²
        if lut_bf16:
            lut = lut.astype(jnp.bfloat16)

        # ---- gather codes & score (compute_similarity_kernel :611) ----
        cand_codes = codes[pr]  # (qb, np, max_list, pq_dim) uint8
        idx = cand_codes.astype(jnp.int32)
        # embedding-style gather: scores[q,n,s] = sum_p lut[q,n,p, idx[q,n,s,p]],
        # flattened to one 2-D take_along_axis (per-subspace offsets fold the
        # pq_dim axis into the LUT row) — the broadcasted 5-D gather form
        # kernel-faulted on TPU at 1M-index shapes
        lut2 = lut.reshape(qb * n_probes, pq_dim * nb)
        idx2 = (idx + jnp.arange(pq_dim, dtype=jnp.int32) * nb).reshape(
            qb * n_probes, max_list * pq_dim
        )
        gathered = jnp.take_along_axis(lut2, idx2, axis=1).reshape(
            qb, n_probes, max_list, pq_dim
        )
        scores = jnp.sum(gathered.astype(jnp.float32), axis=3)  # (qb,np,max_list)
        if metric == DistanceType.InnerProduct:
            # add query·center term per probe
            qdotc = jnp.einsum("qnd,qnd->qn", jnp.broadcast_to(qs[:, None, :], pc.shape), pc)
            scores = scores + qdotc[:, :, None]
        else:
            # add residual-norm const: ||q - center||² per probe
            qcn = jnp.sum(qres**2, axis=2)
            scores = scores + qcn[:, :, None]

        rows = slot_rows[pr]  # (qb, np, max_list)
        if pvalid is not None:
            rows = jnp.where(pvb[:, :, None], rows, -1)
        rows = rows.reshape(qb, -1)  # (qb, np*max_list)
        scores = scores.reshape(qb, -1)
        scores = jnp.where(rows >= 0, scores, worst)
        v, pos = _select_k_impl(scores, k, select_min)
        return v, jnp.take_along_axis(rows, pos, axis=1)

    vals, rows = lax.map(
        block,
        (qblocks, pblocks, pvblocks) if pvalid is not None
        else (qblocks, pblocks))
    vals = vals.reshape(-1, k)[:nq]
    rows = rows.reshape(-1, k)[:nq]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, rows


@functools.partial(
    jax.jit, static_argnames=("k", "n_probes", "metric")
)
def _search_impl_recon8(
    queries,
    rotation,
    centers,
    recon8,
    recon_scale,
    recon_norm,
    slot_rows,
    k: int,
    n_probes: int,
    metric: DistanceType,
    pvalid: jax.Array = None,
):
    """int8 reconstruction scoring: one bf16 MXU matmul per query block
    against dequantized decoded vectors — the TPU-native replacement for
    the reference's shared-memory LUT kernel (compute_similarity_kernel,
    ivf_pq_search.cuh:611). Residual math matches the LUT path:
    score = ||q - center||^2 shifted by the reconstruction terms."""
    nq, _ = queries.shape
    n_lists, max_list, rot_dim = recon8.shape
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf

    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes, metric)

    qb = _query_block_size(n_probes, max_list, rot_dim)
    nblocks = -(-nq // qb)
    pad = nblocks * qb - nq
    qp = jnp.pad(q_rot, ((0, pad), (0, 0))) if pad else q_rot
    pp = jnp.pad(probes, ((0, pad), (0, 0))) if pad else probes
    qblocks = qp.reshape(nblocks, qb, rot_dim)
    pblocks = pp.reshape(nblocks, qb, n_probes)
    if pvalid is not None:
        pvp = jnp.pad(pvalid, ((0, pad), (0, 0))) if pad else pvalid
        pvblocks = pvp.reshape(nblocks, qb, n_probes)
    scale_bf = recon_scale.astype(jnp.bfloat16)

    def block(inp):
        if pvalid is not None:
            qs, pr, pvb = inp  # + (qb, n_probes) adaptive keep mask
        else:
            qs, pr = inp  # (qb, rot_dim), (qb, n_probes)
        pc = centers[pr]  # (qb, np, rot)
        if metric == DistanceType.InnerProduct:
            qres = jnp.broadcast_to(qs[:, None, :], pc.shape)
        else:
            qres = qs[:, None, :] - pc
        r8 = recon8[pr]  # (qb, np, max_list, rot) int8
        deq = r8.astype(jnp.bfloat16) * scale_bf[None, None, None, :]
        dots = jnp.einsum(
            "qnd,qnsd->qns",
            qres.astype(jnp.bfloat16),
            deq,
            preferred_element_type=jnp.float32,
        )
        if metric == DistanceType.InnerProduct:
            qdotc = jnp.sum(qs[:, None, :] * pc, axis=2)
            scores = dots + qdotc[:, :, None]
        else:
            qcn = jnp.sum(qres**2, axis=2)
            scores = qcn[:, :, None] - 2.0 * dots + recon_norm[pr]
        rows = slot_rows[pr]  # (qb, np, max_list)
        if pvalid is not None:
            rows = jnp.where(pvb[:, :, None], rows, -1)
        rows = rows.reshape(qb, -1)
        scores = scores.reshape(qb, -1)
        scores = jnp.where(rows >= 0, scores, worst)
        v, pos = _select_k_impl(scores, k, select_min)
        return v, jnp.take_along_axis(rows, pos, axis=1)

    vals, rows = lax.map(
        block,
        (qblocks, pblocks, pvblocks) if pvalid is not None
        else (qblocks, pblocks))
    vals = vals.reshape(-1, k)[:nq]
    rows = rows.reshape(-1, k)[:nq]
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, rows


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "chunk", "chunk_block", "int8_queries",
        "trim_bf16", "exact_trim", "setup_impls",
    ),
)
def _search_impl_recon8_listmajor(
    queries,
    rotation,
    centers,
    recon8,
    recon_scale,
    recon_norm,
    slot_rows,
    k: int,
    n_probes: int,
    metric: DistanceType,
    chunk: int = 128,
    chunk_block: int = 0,
    int8_queries: bool = False,
    trim_bf16: bool = False,
    exact_trim: bool = False,
    setup_impls: tuple = ("sort", "gather"),
    pvalid: jax.Array = None,
):
    """List-major scoring: each list's codes are streamed from HBM once per
    ~chunk queries probing it and scored with one bf16 MXU matmul.

    The query-major engines gather `codes[probes]` per query, so each list
    is re-read ~nq*n_probes/n_lists times; at bench shape (nq=4096,
    n_probes=32, n_lists=1024) that is a 128x duplication of the dominant
    HBM stream. Here the (query, list) probe pairs are sorted by list and
    split into fixed-size chunks of `chunk` pairs ("virtual lists" — hot
    lists get several chunks, so query skew costs padding only inside one
    chunk, never globally). Each chunk does one (chunk, rot) x (rot,
    max_list) matmul plus a per-row top-k, and the per-pair candidates are
    regrouped to query-major by an inverse-permutation *gather* for the
    final select_k.

    TPU notes: the whole pipeline is sorts + searchsorted + gathers — no
    XLA scatters (TPU lowers scatters to a serialized per-index loop, which
    measured ~100x slower here). The chunk-table bound P//chunk + n_lists
    is static, so batches of the same shape never recompile. The reference
    has no analogue of this engine: its SM-resident LUT makes query-major
    cheap on GPU (compute_similarity_kernel, ivf_pq_search.cuh:611), while
    on TPU the MXU/HBM economics invert the loop instead.

    The coarse probe selection runs inside this same jit (single dispatch:
    the tunnel between host and chip adds ~70ms per call, so one program =
    one round trip)."""
    from raft_tpu.neighbors.probe_invert import (
        gather_query_rows,
        invert_probes_count,
        invert_probes_sort,
        score_and_select,
    )

    nq = queries.shape[0]
    n_lists, max_list, rot_dim = recon8.shape
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes, metric)
    # impls resolved by the caller OUTSIDE this jit (static args), so a
    # tuned flip retraces instead of serving the stale program
    invert_impl, qs_impl = setup_impls
    invert = invert_probes_count if invert_impl == "count" else invert_probes_sort
    tables = invert(probes, n_lists, chunk, pvalid)

    q_pad = jnp.concatenate([q_rot, jnp.zeros((1, rot_dim), q_rot.dtype)])
    scale_bf = recon_scale.astype(jnp.bfloat16)

    def block(inp):
        lofb, qids = inp  # (CB,), (CB, chunk)
        r8 = recon8[lofb]  # (CB, max_list, rot) — the only read of these codes
        rn = recon_norm[lofb]
        srows = slot_rows[lofb]
        cent = centers[lofb]
        qs = gather_query_rows(q_pad, qids, qs_impl)  # (CB, chunk, rot)
        if metric == DistanceType.InnerProduct:
            qres = qs
        else:
            qres = qs - cent[:, None, :]
        if int8_queries:
            # symmetric int8 scoring: fold the per-dim code scale into the
            # query residual, quantize each residual row to int8, and run
            # the chunk matmul as int8 x int8 -> int32 on the MXU
            u = qres * recon_scale[None, None, :]
            u8, row_scale = _quantize_query_rows(u)
            idots = jnp.einsum(
                "lqd,lsd->lqs", u8, r8, preferred_element_type=jnp.int32
            )
            dots = idots.astype(jnp.float32) * row_scale
        else:
            deq = r8.astype(jnp.bfloat16) * scale_bf[None, None, :]
            dots = jnp.einsum(
                "lqd,lsd->lqs",
                qres.astype(jnp.bfloat16),
                deq,
                preferred_element_type=jnp.float32,
            )
        if metric == DistanceType.InnerProduct:
            qdotc = jnp.einsum("lqd,ld->lq", qs, cent)
            scores = dots + qdotc[:, :, None]
        else:
            qcn = jnp.sum(qres**2, axis=2)
            scores = qcn[:, :, None] - 2.0 * dots + rn[:, None, :]
        scores = jnp.where(srows[:, None, :] >= 0, scores, worst)
        if trim_bf16:
            # bf16 trim (internal_distance_dtype parity with the
            # reference's half-precision internal distances,
            # ivf_pq_types.hpp:112-150): the score tensor is the dominant
            # HBM stream of this engine (~chunk*max_list*4B per chunk vs
            # max_list*rot_dim*1B of codes); storing it bf16 halves that
            # round-trip into the approximate trim. The final merge then
            # ranks on bf16 scores (~1e-3 relative noise on near-ties).
            scores = scores.astype(jnp.bfloat16)
        return scores

    v, rows_out = score_and_select(
        tables, block, slot_rows, _select_k_impl, nq, n_probes, k, select_min,
        chunk, chunk_block, max_list, exact_trim=exact_trim,
    )
    v = v.astype(jnp.float32)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, rows_out


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "chunk", "interpret", "int8_queries", "fold",
        "setup_impls",
    ),
)
def _search_impl_recon8_listmajor_pallas(
    queries,
    rotation,
    centers,
    recon8,
    recon_scale,
    recon_norm,
    slot_rows_pad,
    k: int,
    n_probes: int,
    metric: DistanceType,
    chunk: int = 128,
    interpret: bool = False,
    int8_queries: bool = False,
    fold: str = "exact",
    setup_impls: tuple = ("sort", "gather"),
    pvalid: jax.Array = None,
):
    """List-major search with the fused Pallas list-scan trim
    (ops/pq_list_scan.py): per chunk, scoring and the best+second-best
    bin reduction happen inside one kernel, so the (chunk, L) score tile
    never round-trips HBM and the codes are read straight from the index
    by scalar-prefetch indexing (no gather copy). Everything around the
    kernel — probe inversion, exact final merge — is shared with the XLA
    trim engine."""
    from raft_tpu.neighbors.probe_invert import (
        gather_query_rows,
        invert_probes_count,
        invert_probes_sort,
        regroup_merge,
    )
    from raft_tpu.ops.pq_list_scan import pq_list_scan, _BINS

    nq = queries.shape[0]
    n_lists, lpad, rot_dim = recon8.shape
    select_min = metric != DistanceType.InnerProduct
    ip = metric == DistanceType.InnerProduct

    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes, metric)
    invert_impl, qs_impl = setup_impls
    invert = invert_probes_count if invert_impl == "count" else invert_probes_sort
    tables = invert(probes, n_lists, chunk, pvalid)
    lof, qid_tbl = tables.lof, tables.qid_tbl
    ncb = lof.shape[0]

    # per-chunk query residuals with the int8 store's scale folded in
    # (the kernel then consumes raw int8 codes with no dequant multiply)
    q_pad = jnp.concatenate([q_rot, jnp.zeros((1, rot_dim), q_rot.dtype)])
    qs = gather_query_rows(q_pad, qid_tbl, qs_impl)  # (ncb, chunk, rot)
    cent = centers[lof]  # (ncb, rot)
    qres = qs if ip else qs - cent[:, None, :]
    qres_s = qres * recon_scale[None, None, :]

    # additive per-slot base: L2 -> recon norm; IP -> 0; invalid -> +inf
    valid = slot_rows_pad >= 0
    if ip:
        # kernel minimizes base - dots = -dots on valid slots
        base = jnp.where(valid, 0.0, jnp.inf)[:, None, :]
    else:
        base = jnp.where(valid, recon_norm, jnp.inf)[:, None, :]

    if int8_queries:
        # symmetric int8 scoring in-kernel (the XLA engine's int8 path,
        # moved inside the fused scan): quantize each scale-folded query
        # residual row to int8 and let the kernel dequant by the per-row
        # scale after its int8 x int8 -> int32 matmul
        q8, row_scale = _quantize_query_rows(qres_s)
        vals, slot_idx = pq_list_scan(
            lof, q8, recon8, base, inner_product=ip, interpret=interpret,
            q_scale=row_scale, fold=fold,
        )
    else:
        vals, slot_idx = pq_list_scan(
            lof, qres_s, recon8, base, inner_product=ip, interpret=interpret,
            fold=fold,
        )  # (ncb, chunk, 512) minimizing

    invalid = ~jnp.isfinite(vals)
    rows = jnp.take_along_axis(slot_rows_pad[lof][:, None, :], slot_idx, axis=2)
    rows = jnp.where(invalid, -1, rows)

    # undo the kernel's minimization frame and add per-query constants
    if ip:
        # IP score = dots + q.center; kernel returned -dots on valid slots
        qdotc = jnp.einsum("cqd,cd->cq", qs, cent)
        vals = jnp.where(invalid, -jnp.inf, -vals + qdotc[:, :, None])
    else:
        qcn = jnp.sum(qres**2, axis=2)  # (ncb, chunk)
        vals = vals + qcn[:, :, None]

    # trim the bin candidates to the merge width kk (tiny exact top-k)
    cands = vals.shape[-1]
    kk = min(k, _BINS)
    tv, tpos = _select_k_impl(
        vals.reshape(ncb * vals.shape[1], cands), kk, select_min
    )
    tr = jnp.take_along_axis(rows.reshape(ncb * rows.shape[1], cands), tpos, axis=1)
    tv = tv.reshape(ncb, -1, kk)
    tr = tr.reshape(ncb, -1, kk)

    v, rows_out = regroup_merge(
        tables, tv, tr, _select_k_impl, nq, n_probes, int(k), select_min
    )
    v = v.astype(jnp.float32)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, rows_out


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "n_probes", "metric", "chunk", "interpret", "int8_queries",
        "kb", "setup_impls", "fault_key",
    ),
)
def _search_impl_recon8_listmajor_fused(
    queries,
    rotation,
    centers,
    recon8,
    recon_scale,
    recon_norm,
    slot_rows_pad,
    k: int,
    n_probes: int,
    metric: DistanceType,
    chunk: int = 128,
    interpret: bool = False,
    int8_queries: bool = False,
    kb: int = None,
    setup_impls: tuple = ("sort", "gather"),
    fault_key=None,
    pvalid: jax.Array = None,
):
    """List-major search with the fused distance + EXACT select-k trim
    (matrix/select_k.list_scan_select_k — the select_k dispatch layer's
    fused list kernel): same fused geometry as the `pallas` trim (one
    kernel per chunk scores the whole list straight out of the int8
    store and the (chunk, L) score tile never round-trips HBM), but the
    in-kernel partial top-k is exact with ties to the smaller slot, so
    there is no bin-trim recall term — the per-(query, list) candidates
    are exactly what trim_engine='exact' computes, without
    materializing the scores. With `int8_queries` the scoring matmul
    runs int8 x int8 -> int32 on the MXU's doubled int8 rate (dispatch
    strategy "fused_int8"): rows quantize through the SAME
    `_quantize_query_rows` as the pallas int8 trim, so the two engines'
    scores are bit-identical f32 values. `kb` is the index's recorded
    monotonic candidate-buffer width (`fused_kb`); `fault_key` =
    faults.trace_key() so chaos plans retrace."""
    from raft_tpu.matrix.select_k import list_scan_select_k
    from raft_tpu.neighbors.probe_invert import (
        chunk_validity,
        gather_query_rows,
        invert_probes_count,
        invert_probes_sort,
        regroup_merge,
    )

    nq = queries.shape[0]
    n_lists, lpad, rot_dim = recon8.shape
    select_min = metric != DistanceType.InnerProduct
    ip = metric == DistanceType.InnerProduct

    q_rot, probes = _coarse_select(queries, rotation, centers, n_probes, metric)
    invert_impl, qs_impl = setup_impls
    invert = invert_probes_count if invert_impl == "count" else invert_probes_sort
    tables = invert(probes, n_lists, chunk, pvalid)
    lof, qid_tbl = tables.lof, tables.qid_tbl
    cvalid = chunk_validity(qid_tbl, nq)  # empty chunks skip in-kernel

    q_pad = jnp.concatenate([q_rot, jnp.zeros((1, rot_dim), q_rot.dtype)])
    qs = gather_query_rows(q_pad, qid_tbl, qs_impl)  # (ncb, chunk, rot)
    cent = centers[lof]
    qres = qs if ip else qs - cent[:, None, :]
    qres_s = qres * recon_scale[None, None, :]

    valid = slot_rows_pad >= 0
    if ip:
        base = jnp.where(valid, 0.0, jnp.inf)[:, None, :]
    else:
        base = jnp.where(valid, recon_norm, jnp.inf)[:, None, :]

    if int8_queries:
        # symmetric int8 scoring fused end to end: quantize the
        # scale-folded residual rows exactly like the pallas trim and
        # hand the int8 operands to the dispatch layer's int8 kernel
        q8, row_scale = _quantize_query_rows(qres_s)
        vals, slot_idx = list_scan_select_k(
            lof, q8, recon8, base, k, strategy="fused_int8",
            q_scale=row_scale, kbuf=kb, inner_product=ip,
            interpret=interpret, fault_key=fault_key, chunk_valid=cvalid,
        )
    else:
        vals, slot_idx = list_scan_select_k(
            lof, qres_s, recon8, base, k, strategy="fused", kbuf=kb,
            inner_product=ip, interpret=interpret, fault_key=fault_key,
            chunk_valid=cvalid,
        )  # (ncb, chunk, kbuf) exact best-first, minimizing
    vals = vals[:, :, :k]
    slot_idx = slot_idx[:, :, :k]

    invalid = ~jnp.isfinite(vals)
    slot_idx = jnp.where(invalid, 0, slot_idx)  # sentinel -> safe gather
    rows = jnp.take_along_axis(slot_rows_pad[lof][:, None, :], slot_idx, axis=2)
    rows = jnp.where(invalid, -1, rows)

    if ip:
        qdotc = jnp.einsum("cqd,cd->cq", qs, cent)
        vals = jnp.where(invalid, -jnp.inf, -vals + qdotc[:, :, None])
    else:
        qcn = jnp.sum(qres**2, axis=2)  # (ncb, chunk)
        vals = vals + qcn[:, :, None]

    v, rows_out = regroup_merge(
        tables, vals, rows, _select_k_impl, nq, n_probes, int(k), select_min
    )
    v = v.astype(jnp.float32)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, rows_out


@obs.spanned("neighbors.ivf_pq.search")
@auto_convert_output
def search(
    params: SearchParams, index: Index, queries, k: int, resources=None,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search; returns (distances, neighbor source ids) (nq, k).

    `prefilter`: optional `core.bitset.Bitset` (or 1-D boolean mask) over
    the index's id space (`index.id_bound` ids — == size unless extend() used custom new_indices) — samples whose bit is clear
    are excluded before any trim/selection in EVERY engine, including the
    fused Pallas scan (sample-filtering parity with later RAFT's
    `search_with_filtering`). When fewer than k samples pass, the tail
    holds the worst distance with id -1.

    Note: trim_engine='pallas' (experimental until validated on-chip) pads
    the index's reconstruction store to lane multiples IN PLACE on first
    use; later searches on the same index with other engines recompile for
    the padded shape and scan the (masked) pad slots."""
    from raft_tpu.core.validation import check_matrix

    q = check_matrix(queries, name="queries")
    if q.shape[1] != index.dim:
        raise ValueError(f"query dim {q.shape[1]} != index dim {index.dim}")
    if index.size == 0:
        raise ValueError("index is empty")
    # every engine masks candidate scores to the worst value wherever its
    # slot table reads -1 (before trim/selection), so a filtered view is
    # the entire filtering mechanism; applied per branch because the
    # recon8/pallas engines use the padded table from build_reconstruction
    from raft_tpu.core.bitset import make_slot_filter

    maybe_filter = make_slot_filter(prefilter, index.id_bound,
                                    index.source_ids,
                                    tombstones=index.tombstones)
    n_probes = int(min(max(1, params.n_probes), index.n_lists))
    mode = params.score_mode
    if params.score_dtype not in ("bf16", "int8"):
        raise ValueError(f"unknown score_dtype {params.score_dtype!r}")
    idd = params.internal_distance_dtype
    if idd == "auto":
        # resolve from the measured tuned hint, TPU only (the hint was
        # measured on chip; CPU tests keep exact f32 trim numerics)
        idd = "float32"
        if jax.default_backend() == "tpu":
            from raft_tpu.core import tuned

            hinted = tuned.hints().get("internal_distance_dtype")
            if hinted in ("float32", "float16", "bfloat16"):
                idd = hinted
    if idd not in ("float32", "float16", "bfloat16"):
        raise ValueError(
            f"unknown internal_distance_dtype {params.internal_distance_dtype!r}"
        )
    if mode == "auto":
        mode = _resolve_score_mode(params, q.shape[0], n_probes, index.n_lists)
    elif params.score_dtype == "int8" and mode != "recon8_list":
        raise ValueError(
            f"score_dtype='int8' requires score_mode 'recon8_list' or 'auto', got {mode!r}"
        )
    # trim resolution: explicit values pin; "auto" = "approx" unless the
    # dispatch layer's measured integer key promotes the fused int8 trim
    # for this geometry (chip-measured, envelope-gated — the single
    # chooser of ISSUE 11)
    trim = params.trim_engine
    if trim not in ("auto", "approx", "exact", "pallas", "fused"):
        raise ValueError(f"unknown trim_engine {params.trim_engine!r}")
    if trim == "auto":
        trim = "approx"
        if mode == "recon8_list" and params.score_dtype == "int8":
            from raft_tpu.matrix.select_k import resolve_int8_trim_strategy
            from raft_tpu.ops.fused_scan import FUSED_MAX_K, fused_kbuf
            from raft_tpu.ops.pq_list_scan import lane_padded

            if 0 < int(k) <= FUSED_MAX_K:
                kb_probe = max(fused_kbuf(int(k)), index.fused_kb or 0)
                promoted = resolve_int8_trim_strategy(
                    lane_padded(int(index.codes.shape[1])), index.rot_dim,
                    int(k), kbuf=kb_probe,
                )
                if promoted == "fused_int8":
                    trim = "fused"
    # adaptive probing: one (nq, n_probes) keep mask from the rotated
    # coarse geometry (budgets + optional radius bounds), shared by
    # every score mode; None = the fixed-n_probes reference, verbatim
    from raft_tpu.neighbors import probe_budget

    ap = probe_budget.resolve_params(params, n_probes)
    pvalid = None
    scanned_mean = None
    if ap is not None:
        # bounds OFF under a prefilter (see ivf_flat.search: the
        # k-covering prefix counts filtered members) — budgets only;
        # same soundness argument under tombstones (sizes count dead)
        radii = (index.list_radii
                 if ap.early_term and prefilter is None
                 and index.tombstones is None else None)
        pvalid, scanned = probe_budget.probe_plan(
            jnp.asarray(q, jnp.float32), index.centers,
            n_probes=n_probes, min_probes=ap.min_probes, k=int(k),
            metric=index.metric, tau=ap.tau, rotation=index.rotation,
            radii=radii, sizes=index.list_sizes)
        scanned_mean = probe_budget.account(
            "ivf_pq", scanned, int(q.shape[0]), n_probes)
    if obs.enabled():
        # list-major modes stream every padded list per query batch;
        # query-major modes touch the probed lists only (the ACTUAL
        # adaptive mean when budgets are on); the fused/pallas trims
        # never materialize the score tile
        obs.span_cost(**obs.perf.cost_for(
            "neighbors.ivf_pq.search", nq=int(q.shape[0]),
            n_probes=n_probes, n_lists=int(index.n_lists),
            n_rows=int(index.codes.shape[0] * index.codes.shape[1])
            - index.n_tombstones,
            dim=int(index.dim), pq_dim=int(index.pq_dim), k=int(k),
            dtype=params.score_dtype,
            scanned_lists=(int(index.n_lists)
                           if (mode.endswith("_list") and trim != "fused")
                           else (scanned_mean if scanned_mean is not None
                                 else n_probes)),
            fused=(mode == "recon8_list"
                   and trim in ("pallas", "fused"))))
    for eng in ("pallas", "exact", "fused"):
        if trim == eng and mode != "recon8_list":
            raise ValueError(
                f"trim_engine='{eng}' requires score_mode 'recon8_list'"
            )
    if mode == "recon8_list" and trim == "fused":
        from raft_tpu.matrix.select_k import check_fused_list_request
        from raft_tpu.neighbors.probe_invert import macro_batched
        from raft_tpu.ops.pq_list_scan import lane_padded

        # caps/envelope checked BEFORE padding the index's store (a
        # rejected request must not leave the index mutated), at the
        # buffer width the kernel will RUN with: the recorded fused_kb
        # when it is already wider than this k needs
        kb = check_fused_list_request(
            "trim_engine='fused'", lane_padded(int(index.codes.shape[1])),
            index.rot_dim, int(k), 1, index.fused_kb,
            "the default trim_engine='approx'",
        )
        build_reconstruction(index, pad_to_lanes=True)
        index.fused_kb = kb  # monotonic: kb >= the recorded width
        srows_pad = maybe_filter(index.slot_rows_pad)
        from raft_tpu.core import faults
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        setup = resolve_setup_impls(index.n_lists)
        vals, rows = macro_batched(
            lambda sl, pv=None: _search_impl_recon8_listmajor_fused(
                sl,
                index.rotation,
                index.centers,
                index.recon8,
                index.recon_scale,
                index.recon_norm,
                srows_pad,
                int(k),
                n_probes,
                index.metric,
                interpret=jax.default_backend() == "cpu",
                int8_queries=params.score_dtype == "int8",
                kb=kb,
                setup_impls=setup,
                fault_key=faults.trace_key(),
                pvalid=pv,
            ),
            jnp.asarray(q),
            int(k),
            extra=pvalid,
        )
    elif mode == "recon8_list" and trim == "pallas":
        from raft_tpu.neighbors.probe_invert import macro_batched
        from raft_tpu.ops.pq_list_scan import _BINS, fits_pallas, lane_padded

        if int(k) > _BINS:
            raise ValueError(
                f"trim_engine='pallas' caps per-list candidates at {_BINS}; k={k}"
            )
        # check the VMEM envelope BEFORE padding the index's store: a
        # rejected request must not leave the index mutated
        lpad = lane_padded(int(index.codes.shape[1]))
        if not fits_pallas(128, lpad, index.rot_dim):
            raise ValueError(
                f"trim_engine='pallas': list length {lpad} exceeds the kernel's "
                "VMEM envelope; use the default trim_engine='approx'"
            )
        build_reconstruction(index, pad_to_lanes=True)
        srows_pad = maybe_filter(index.slot_rows_pad)
        from raft_tpu.ops.pq_list_scan import fold_variant
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        fold = fold_variant()
        setup = resolve_setup_impls(index.n_lists)
        vals, rows = macro_batched(
            lambda sl, pv=None: _search_impl_recon8_listmajor_pallas(
                sl,
                index.rotation,
                index.centers,
                index.recon8,
                index.recon_scale,
                index.recon_norm,
                srows_pad,
                int(k),
                n_probes,
                index.metric,
                interpret=jax.default_backend() == "cpu",
                int8_queries=params.score_dtype == "int8",
                fold=fold,
                setup_impls=setup,
                pvalid=pv,
            ),
            jnp.asarray(q),
            int(k),
            extra=pvalid,
        )
    elif mode == "recon8_list":
        from raft_tpu.core import tuned
        from raft_tpu.neighbors.probe_invert import macro_batched

        build_reconstruction(index)
        srows_pad = maybe_filter(index.slot_rows_pad)
        # chunk rows per virtual list: the measured tuned key when valid,
        # applied ONLY at low-duplication shapes (where the race that
        # produced it ran: the P//chunk + n_lists fragmentation bound
        # leaves 128-row chunks mostly empty). High-dup batches keep the
        # 128 default the np32 engine ladder was measured under — a key
        # tuned at np8 must not regress the np32 path.
        chunk = 128
        dup = q.shape[0] * n_probes / max(1, index.n_lists)
        if dup <= _LOW_DUP_CHUNK_BOUND:
            t_chunk = tuned.get("listmajor_chunk", 128)
            if t_chunk in (32, 64, 128):
                chunk = int(t_chunk)
        # scoring granularity: 0 = one einsum per superblock (~nsuper
        # scan iterations/batch); a positive tuned value restores the
        # round-1..4 inner lax.map structure (see probe_invert)
        from raft_tpu.neighbors.probe_invert import CHUNK_BLOCKS

        cb = int(tuned.get_choice("listmajor_chunk_block", CHUNK_BLOCKS, 0))
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        setup = resolve_setup_impls(index.n_lists)
        vals, rows = macro_batched(
            lambda sl, pv=None: _search_impl_recon8_listmajor(
                sl,
                index.rotation,
                index.centers,
                index.recon8,
                index.recon_scale,
                index.recon_norm,
                srows_pad,
                int(k),
                n_probes,
                index.metric,
                chunk=chunk,
                chunk_block=cb,
                int8_queries=params.score_dtype == "int8",
                trim_bf16=idd in ("bfloat16", "float16"),
                exact_trim=trim == "exact",
                setup_impls=setup,
                pvalid=pv,
            ),
            jnp.asarray(q),
            int(k),
            extra=pvalid,
        )
    elif mode == "recon8":
        build_reconstruction(index)
        vals, rows = _search_impl_recon8(
            q,
            index.rotation,
            index.centers,
            index.recon8,
            index.recon_scale,
            index.recon_norm,
            maybe_filter(index.slot_rows_pad),
            int(k),
            n_probes,
            index.metric,
            pvalid=pvalid,
        )
    elif mode == "lut":
        _check_lut_allowed()
        vals, rows = _search_impl(
            q,
            index.rotation,
            index.centers,
            index.pq_centers,
            index.codes,
            maybe_filter(index.slot_rows),
            int(k),
            n_probes,
            index.metric,
            index.params.codebook_kind == PER_CLUSTER,
            params.lut_dtype == "bfloat16",
            pvalid=pvalid,
        )
    else:
        raise ValueError(f"unknown score_mode {mode!r}")
    ids = jnp.where(rows >= 0, index.source_ids[jnp.maximum(rows, 0)], -1)
    if resources is not None:
        resources.track(vals, ids)
    return vals, ids


# ---------------------------------------------------------------------------
# serialization (detail/ivf_pq_serialize.cuh:36, version-tagged container)
# ---------------------------------------------------------------------------

_SERIAL_VERSION = 3  # v2: mutation fields; v3: digest sidecar


def save(filename: str, index: Index) -> None:
    from raft_tpu.core.serialize import serialize_arrays

    arrays = {
        "rotation": index.rotation,
        "centers": index.centers,
        "pq_centers": index.pq_centers,
        "codes": index.codes,
        "slot_rows": index.slot_rows,
        "list_sizes": index.list_sizes,
        "source_ids": index.source_ids,
    }
    if index.list_radii is not None:
        # adaptive probing's early-termination bounds; absent in old
        # files, which load with bounds off (budgets-only fallback)
        arrays["list_radii"] = index.list_radii
    if index.tombstones is not None:
        # dead-row mask (u8); absent = all-live (pre-mutation files)
        arrays["tombstones"] = jnp.asarray(index.tombstones).astype(jnp.uint8)
    meta = {
        "kind": "ivf_pq",
        "version": _SERIAL_VERSION,
        "metric": int(index.metric),
        "n_lists": index.n_lists,
        "pq_bits": index.pq_bits,
        "codebook_kind": index.params.codebook_kind,
        "mut_cursor": int(index.mut_cursor),
        "append_slack": int(index.append_slack),
    }
    from raft_tpu.integrity.digest import pack_lists

    packed = pack_lists(index, "ivf_pq")
    if packed is not None:
        # per-list CRC-32C sidecar (v3, raft_tpu/integrity)
        arrays["list_digests"] = packed
        meta["table_digests"] = {
            k: int(v) for k, v in (index.table_digests or {}).items()}
    serialize_arrays(filename, arrays, meta)


def load(filename: str) -> Index:
    # schema-checked read (core.serialize.CKPT_SCHEMA): kind + version
    # gates, required-field presence, corrupt optional fields dropped
    from raft_tpu.core.serialize import read_ckpt

    arrays, meta = read_ckpt(filename, "ivf_pq")
    params = IndexParams(
        n_lists=meta["n_lists"],
        metric=DistanceType(meta["metric"]),
        pq_bits=meta["pq_bits"],
        codebook_kind=meta["codebook_kind"],
    )
    index = Index(
        params,
        arrays["rotation"],
        arrays["centers"],
        arrays["pq_centers"],
        arrays["codes"],
        arrays["slot_rows"],
        arrays["list_sizes"],
        arrays["source_ids"],
    )
    index.list_radii = arrays.get("list_radii")
    # mutation-era fields (v2): absent in old checkpoints -> all-live
    index.tombstones = arrays.get("tombstones")
    index.mut_cursor = int(meta.get("mut_cursor", 0))
    index.append_slack = int(meta.get("append_slack", 0))
    # integrity sidecar (v3): absent/corrupt -> no sidecar
    from raft_tpu.integrity.digest import unpack_lists

    unpack_lists(index, "ivf_pq", arrays.get("list_digests"),
                 meta.get("table_digests"))
    return index
