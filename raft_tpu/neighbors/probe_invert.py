"""Probe-pair inversion for list-major IVF search engines.

Query-major IVF search (the reference's layout: one CUDA block per (query,
probe) — ivf_pq_search.cuh:611, ivf_flat_search.cuh:670) gathers each
probed list's storage once per query, so a batch re-reads every list
~nq*n_probes/n_lists times from HBM. The list-major engines instead invert
the (query, list) probe pairs into per-list buckets and stream each list
once. This module holds the shared inversion: sort pairs by list, split
each list's bucket into fixed-size chunks of `chunk` pairs ("virtual
lists", so hot-list skew costs padding only inside one chunk), and emit
  - per-chunk tables (which list, which queries) for the scoring loop, and
  - a per-pair (chunk, slot) address for regrouping candidates back to
    query-major order with a pure gather.

Everything is sorts + searchsorted + gathers — no XLA scatters (TPU lowers
scatter to a serialized per-index loop) — and every shape is static: the
chunk budget uses the bound sum(ceil(c_i/chunk)) <= P//chunk + n_lists, so
equal-shaped batches never recompile and no host sync is needed.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


# listmajor_chunk_block tuned values every list-major engine honors
# (0 = single-einsum superblocks; positive = inner lax.map granularity)
CHUNK_BLOCKS = (0, 8, 16, 32, 64)


class ChunkTables(NamedTuple):
    """Static-shape chunk tables for one query batch.

    lof      (ncb,)        list id scored by each chunk
    qid_tbl  (ncb, chunk)  query ids in each chunk; `nq` marks padding
                           (callers append a zero sentinel query row)
    g0       (nq*n_probes,) chunk id holding each original probe pair
    s0       (nq*n_probes,) slot of that pair within its chunk
    pair_valid (nq*n_probes,) bool, or None — adaptive probe budgets
                           (neighbors/probe_budget): False pairs were
                           dropped before inversion (they occupy no
                           chunk slot; their g0/s0 are clamped to 0 and
                           `regroup_merge` masks their candidates to
                           the worst value / row -1). None = every
                           pair live (the fixed-n_probes reference).
    """

    lof: jax.Array
    qid_tbl: jax.Array
    g0: jax.Array
    s0: jax.Array
    pair_valid: Optional[jax.Array] = None


def chunk_count(nq: int, n_probes: int, n_lists: int, chunk: int) -> int:
    """Static upper bound on the number of chunks for a batch."""
    return (nq * n_probes) // chunk + n_lists


def invert_probes(probes: jax.Array, n_lists: int, chunk: int,
                  pvalid: Optional[jax.Array] = None) -> ChunkTables:
    """Build chunk tables from a (nq, n_probes) probe matrix (traced).

    Dispatches between the sort-based (`invert_probes_sort`) and
    counting-based (`invert_probes_count`) constructions via the
    `invert_impl` tuned key; both produce bit-identical tables (raced and
    equality-checked by `bench/bench_invert_race.py`). Engines should
    prefer resolving the impl OUTSIDE their jit via
    `resolve_setup_impls` and calling the chosen construction directly,
    so a tuned flip retraces instead of serving the stale program.

    `pvalid` (nq, n_probes) bool, optional: adaptive probe budgets —
    False pairs are dropped from the tables entirely (they enter the
    sentinel bucket `n_lists`, which owns no chunks), so shrunken
    budgets shrink the populated chunk count and the fused kernels'
    `chunk_valid` path can skip the empties."""
    if resolve_invert_impl(n_lists) == "count":
        return invert_probes_count(probes, n_lists, chunk, pvalid)
    return invert_probes_sort(probes, n_lists, chunk, pvalid)


INVERT_IMPLS = ("sort", "count")

# the counting construction's blocked one-hot planes cost O(P * n_lists)
# compare/cumsum work and its block floor stops bounding memory past this
# many lists — above it the sort-based construction wins regardless of
# what the (1024-list) chip race measured, so the tuned choice is gated
_COUNT_MAX_LISTS = 8192


def resolve_invert_impl(n_lists: int = 0) -> str:
    """The tuned chunk-table construction for list-major engines."""
    from raft_tpu.core import tuned

    impl = tuned.get_choice("invert_impl", INVERT_IMPLS, "sort")
    if impl == "count" and n_lists > _COUNT_MAX_LISTS:
        return "sort"
    return impl


def resolve_setup_impls(n_lists: int, engine: str = "pq") -> tuple:
    """(invert_impl, qs_impl) for a list-major search, resolved at the
    call site OUTSIDE the engine's jit so they participate in the jit
    cache key — a tuned flip mid-process (bench --apply + reload) must
    retrace the engine, not keep serving the stale wrapper (the same
    hazard the distributed wrapper cache keys guard against). `engine`
    ("pq" | "flat") keys the qs-impl resolution: see `resolve_qs_impl`
    for the flat-engine bf16 gate."""
    return resolve_invert_impl(n_lists), resolve_qs_impl(engine)


def _chunk_geometry(counts, nq: int, n_probes: int, n_lists: int, chunk: int):
    """Chunk-table geometry shared by both constructions: per-list chunk
    spans and the per-chunk (list, in-list position, validity) tables,
    derived purely from per-list pair counts. Returns
    (base, lof, cl, pos, valid) — both impls MUST share this (the
    `invert_impl` tuned key's bit-identity contract rides on it)."""
    cpl = (counts + chunk - 1) // chunk  # chunks per list
    cb = jnp.cumsum(cpl)  # inclusive
    base = (cb - cpl).astype(jnp.int32)  # first chunk id of each list

    ncb = chunk_count(nq, n_probes, n_lists, chunk)
    g = jnp.arange(ncb, dtype=jnp.int32)
    lof = jnp.minimum(jnp.searchsorted(cb, g, side="right"), n_lists - 1).astype(
        jnp.int32
    )
    cl = g - base[lof]  # chunk index within its list
    pos = cl[:, None] * chunk + jnp.arange(chunk, dtype=jnp.int32)[None, :]
    valid = pos < counts[lof][:, None]
    return base, lof, cl, pos, valid


def invert_probes_sort(probes: jax.Array, n_lists: int, chunk: int,
                       pvalid: Optional[jax.Array] = None) -> ChunkTables:
    """Sort-based construction: two stable argsorts over the P=nq*n_probes
    pair array (the second computes the inverse permutation for the
    regroup addresses). Budget-masked pairs (`pvalid` False) move to the
    sentinel bucket `n_lists` — they sort past every real list, count
    toward no chunk, and their regroup addresses clamp to (0, 0) behind
    the tables' `pair_valid` mask."""
    nq, n_probes = probes.shape
    p_total = nq * n_probes
    flat = probes.reshape(-1).astype(jnp.int32)
    pv = None
    if pvalid is not None:
        pv = pvalid.reshape(-1)
        flat = jnp.where(pv, flat, jnp.int32(n_lists))
    order = jnp.argsort(flat, stable=True)
    sorted_lists = flat[order]
    sorted_q = (order // n_probes).astype(jnp.int32)
    lids = jnp.arange(n_lists, dtype=jnp.int32)
    starts = jnp.searchsorted(sorted_lists, lids, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(sorted_lists, lids, side="right").astype(jnp.int32)
    counts = ends - starts
    base, lof, _, pos, valid = _chunk_geometry(counts, nq, n_probes, n_lists, chunk)
    pair = jnp.clip(starts[lof][:, None] + pos, 0, p_total - 1)
    qid_tbl = jnp.where(valid, sorted_q[pair], nq)

    inv = jnp.argsort(order).astype(jnp.int32)  # original pair -> sorted position
    pos0 = inv - starts[jnp.minimum(flat, n_lists - 1)]
    g0 = base[jnp.minimum(flat, n_lists - 1)] + pos0 // chunk
    s0 = pos0 % chunk
    if pv is not None:
        g0 = jnp.where(pv, g0, 0)
        s0 = jnp.where(pv, s0, 0)
    return ChunkTables(lof, qid_tbl, g0, s0, pv)


def _blocked_bucket_ranks(flat: jax.Array, n_lists: int) -> tuple:
    """Stable per-pair rank within its list bucket + per-list counts,
    without sorting: a lax.scan over fixed-size blocks builds each
    block's one-hot list membership, cumsums it down the block for
    in-block stable ranks, and carries per-list totals across blocks.
    All work is compares/cumsums/reduces on (block, n_lists+1) planes —
    VPU-shaped, no XLA sort or scatter. Returns (rank[P], counts)."""
    (p_total,) = flat.shape
    # bound the per-iteration plane to ~64MB of int32
    block = min(8192, max(256, (1 << 24) // (n_lists + 1)))
    nb = -(-p_total // block)
    pad = nb * block - p_total
    fpad = jnp.pad(flat, (0, pad), constant_values=n_lists) if pad else flat
    cols = jnp.arange(n_lists + 1, dtype=jnp.int32)

    def step(carry, l):
        oh = l[:, None] == cols[None, :]
        cs = jnp.cumsum(oh.astype(jnp.int32), axis=0)
        rank = jnp.sum(jnp.where(oh, cs - 1 + carry[None, :], 0), axis=1)
        return carry + cs[-1], rank

    carry0 = jnp.zeros(n_lists + 1, jnp.int32)
    totals, ranks = jax.lax.scan(step, carry0, fpad.reshape(nb, block))
    return ranks.reshape(-1)[:p_total], totals[:n_lists]


def invert_probes_count(probes: jax.Array, n_lists: int, chunk: int,
                        pvalid: Optional[jax.Array] = None) -> ChunkTables:
    """Counting-based construction (TPU-native): ONE variadic stable sort
    replaces the sort-heavy parts of `invert_probes_sort` (which pays two
    chained argsorts plus two searchsorted passes over the P-sized array),
    and the inverse-permutation addresses come from a blocked one-hot
    cumsum instead of a second sort.

      - per-pair in-bucket ranks + per-list counts: `_blocked_bucket_ranks`
        (no sort) — this alone replaces argsort(order) and both
        P-sized searchsorted calls (starts = exclusive-cumsum of counts);
      - g0/s0: base[flat] + rank arithmetic (pure elementwise + one
        small-table gather);
      - qid_tbl: one stable `lax.sort((flat, qid))` for the list-grouped
        query ids, then per-chunk CONTIGUOUS rows via vmapped
        dynamic_slice (each chunk's pairs are adjacent in sorted order —
        a windowed load, not a 262k-element random gather).

    Bit-identical to `invert_probes_sort` (stability makes ranks equal to
    inv - starts[flat]); raced + equality-gated on chip by
    `bench/bench_invert_race.py --apply`, which flips the `invert_impl`
    tuned key. Budget-masked pairs (`pvalid` False) land in the sentinel
    bucket `n_lists` — the blocked rank pass already treats it as its
    padding column, so counts/chunks shrink exactly like the sort
    construction's."""
    nq, n_probes = probes.shape
    p_total = nq * n_probes
    flat = probes.reshape(-1).astype(jnp.int32)
    pv = None
    if pvalid is not None:
        pv = pvalid.reshape(-1)
        flat = jnp.where(pv, flat, jnp.int32(n_lists))

    rank, counts = _blocked_bucket_ranks(flat, n_lists)
    starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
    base, lof, cl, _, valid = _chunk_geometry(counts, nq, n_probes, n_lists, chunk)

    # list-grouped query ids: one stable variadic sort (same permutation
    # as invert_probes_sort's stable argsort, so payload order matches)
    qid = (jnp.arange(p_total, dtype=jnp.int32) // n_probes).astype(jnp.int32)
    _, sorted_q = jax.lax.sort((flat, qid), num_keys=1)
    # each chunk reads a contiguous window [starts[lof]+cl*chunk, +chunk);
    # pad by one chunk of sentinels so trailing empty chunks stay in range
    sq_pad = jnp.concatenate(
        [sorted_q, jnp.full((chunk,), nq, jnp.int32)]
    )
    off = jnp.clip(starts[lof] + cl * chunk, 0, p_total)
    rows = jax.vmap(
        lambda o: jax.lax.dynamic_slice(sq_pad, (o,), (chunk,))
    )(off)
    qid_tbl = jnp.where(valid, rows, nq)

    g0 = base[jnp.minimum(flat, n_lists - 1)] + rank // chunk
    s0 = rank % chunk
    if pv is not None:
        g0 = jnp.where(pv, g0, 0)
        s0 = jnp.where(pv, s0, 0)
    return ChunkTables(lof, qid_tbl, g0, s0, pv)


# listmajor_qs_impl tuned values (query-row materialization inside the
# scoring blocks): "gather" = XLA fancy-index; "onehot_bf16" = one-hot
# matmul in bf16 (MXU-shaped; rows bf16-rounded — acceptable for the PQ
# engines, whose int8-reconstruction scoring already quantizes harder
# than bf16 rounding, but NOT precision-neutral for the IVF-Flat
# list-major engine, which scores qs at f32 Precision.HIGHEST —
# distance/pairwise.py — so a shared bf16 winner is gated off the flat
# engines in `resolve_qs_impl`); "onehot_f32h" = one-hot matmul at
# precision=highest (bit-exact vs the gather, ~6x the MXU passes). The
# first on-chip diag measured the isolated gather at ~1 GB/s (106.7 ms
# for a ~100 MB stream at bench shape) — the one-hot forms trade that
# for ~0.2 TFLOP of MXU work. Raced by bench/bench_invert_race.py.
QS_IMPLS = ("gather", "onehot_bf16", "onehot_f32h")


def gather_query_rows(q_pad: jax.Array, qids: jax.Array, impl: str) -> jax.Array:
    """Materialize (..., chunk, dim) query rows from a (..., chunk) id
    table over the sentinel-padded (nq+1, dim) query matrix.

    The one-hot impls bound their materialized (rows, nq+1) plane to
    ~32 MB by looping sub-blocks of leading rows through `lax.map` —
    the SAME formulation at any caller granularity, so a chip race of
    this function measures exactly what the engines execute."""
    if impl == "gather":
        return q_pad[qids]
    if impl == "onehot_bf16":
        dt, prec = jnp.bfloat16, jax.lax.Precision.DEFAULT
    elif impl == "onehot_f32h":
        dt, prec = jnp.float32, jax.lax.Precision.HIGHEST
    else:
        raise ValueError(f"unknown query-row impl {impl!r}")
    nq1 = q_pad.shape[0]
    qp = q_pad.astype(dt)

    def onehot_rows(ids):
        oh = (ids[..., None] == jnp.arange(nq1, dtype=jnp.int32)).astype(dt)
        return jnp.einsum(
            "...cn,nd->...cd", oh, qp, precision=prec,
            preferred_element_type=jnp.float32,
        )

    lead = qids.shape[:-1]
    chunk = qids.shape[-1]
    rows_total = 1
    for s in lead:
        rows_total *= s
    # sub-block size bounding the one-hot plane to ~32 MB
    qb = max(1, (1 << 25) // max(1, chunk * nq1 * jnp.dtype(dt).itemsize))
    if not lead or rows_total <= qb:
        return onehot_rows(qids).astype(q_pad.dtype)
    flat_ids = qids.reshape(rows_total, chunk)
    bpad = (-rows_total) % qb
    if bpad:
        flat_ids = jnp.pad(flat_ids, ((0, bpad), (0, 0)))
    out = jax.lax.map(onehot_rows, flat_ids.reshape(-1, qb, chunk))
    out = out.reshape(-1, chunk, q_pad.shape[1])[:rows_total]
    return out.reshape(*lead, chunk, q_pad.shape[1]).astype(q_pad.dtype)


def resolve_qs_impl(engine: str = "pq") -> str:
    """The tuned query-row materialization for list-major engines.

    The shared `listmajor_qs_impl` key was raced on the PQ engine, where
    bf16-rounded query rows are lossless relative to the int8 scoring
    that follows. The IVF-Flat list-major engine scores at f32
    Precision.HIGHEST, so a shared "onehot_bf16" winner would silently
    degrade flat-engine precision — for engine="flat" it is gated back
    to "gather" unless the flat-specific key `listmajor_qs_impl_flat`
    (written by a flat-engine race) explicitly opts in."""
    from raft_tpu.core import tuned

    if engine == "flat":
        own = tuned.get_choice("listmajor_qs_impl_flat", QS_IMPLS, None)
        if own is not None:
            return own
        shared = tuned.get_choice("listmajor_qs_impl", QS_IMPLS, "gather")
        return "gather" if shared == "onehot_bf16" else shared
    return tuned.get_choice("listmajor_qs_impl", QS_IMPLS, "gather")


def score_and_select(
    tables: ChunkTables,
    block_fn,
    slot_rows: jax.Array,
    select_k_fn,
    nq: int,
    n_probes: int,
    k: int,
    select_min: bool,
    chunk: int,
    chunk_block: int,
    max_list: int,
    exact_trim: bool = False,
):
    """Shared back half of a list-major search (traced inside the engine's
    jit): two-level blocked scoring, per-superblock approximate trim,
    gather-based regroup to query-major, exact final merge.

    `block_fn(lof_block, qid_block) -> (CB, chunk, max_list)` computes the
    engine-specific candidate scores (IVF-Flat: raw-vector distances;
    IVF-PQ: int8-reconstruction distances) with invalid slots already
    masked to the worst value. `select_k_fn(scores, k, select_min)` is the
    exact top-k used for the final merge.

    Superblocks of `sb` chunks bound the materialized score buffer to
    ~2^27 elements regardless of max_list skew; each superblock is trimmed
    with the TPU-native approximate top-k (PartialReduce,
    jax.lax.approx_min_k) at recall_target=0.99 — the tradeoff the
    reference makes with its warp-level filtered queues
    (select_warpsort.cuh `warp_sort_filtered`).

    `chunk_block` controls the scoring granularity WITHIN a superblock:
    0 (the default dispatch) scores the whole superblock with one
    batched `block_fn` call — one large einsum, ~nsuper scan iterations
    per batch. A positive value runs an inner `lax.map` over blocks of
    that many chunks; at bench shape (ncb≈2048, chunk_block=8) that is
    ~256 serialized scan iterations whose per-iteration overhead, not
    FLOPs or bytes, dominated the round-4 measured 570 ms/batch (~60×
    off the HBM roofline, docs/perf.md). Kept raceable via the
    `listmajor_chunk_block` tuned key so the chip profiler can flip it
    with data.
    """
    from jax import lax

    lof, qid_tbl = tables.lof, tables.qid_tbl
    ncb = lof.shape[0]
    kk = min(k, max_list)

    budget = 1 << 27
    step = chunk_block if chunk_block else 1
    sb = max(step, budget // max(1, chunk * max_list))
    sb = min(-(-sb // step) * step, -(-ncb // step) * step)
    nsuper = -(-ncb // sb)
    bpad = nsuper * sb - ncb
    lof_b = (jnp.pad(lof, (0, bpad)) if bpad else lof).reshape(nsuper, sb)
    qid_b = (
        jnp.pad(qid_tbl, ((0, bpad), (0, 0)), constant_values=nq) if bpad else qid_tbl
    ).reshape(nsuper, sb, chunk)

    def super_block(inp):
        lofs, qids = inp  # (sb,), (sb, chunk)
        if chunk_block:
            nb_in = sb // chunk_block
            scores = lax.map(
                block_fn,
                (
                    lofs.reshape(nb_in, chunk_block),
                    qids.reshape(nb_in, chunk_block, chunk),
                ),
            )
            scores = scores.reshape(sb, chunk, max_list)
        else:
            scores = block_fn((lofs, qids))
        if exact_trim:
            # exact per-superblock trim (lax.top_k): pays the full sort
            # network but loses zero candidates — the option VERDICT r4
            # #6 asks for, so the approx bin-trim's recall tax is a
            # measured choice, not a forced one
            if select_min:
                v, si = lax.top_k(-scores, kk)
                v = -v
            else:
                v, si = lax.top_k(scores, kk)
        elif select_min:
            v, si = lax.approx_min_k(scores, kk, recall_target=0.99)
        else:
            v, si = lax.approx_max_k(scores, kk, recall_target=0.99)
        rows_sb = jnp.take_along_axis(slot_rows[lofs][:, None, :], si, axis=2)
        return v, rows_sb

    vals, rows = lax.map(super_block, (lof_b, qid_b))  # (nsuper, sb, chunk, kk)
    vals = vals.reshape(-1, chunk, kk)[:ncb]
    rows = rows.reshape(-1, chunk, kk)[:ncb]
    return regroup_merge(tables, vals, rows, select_k_fn, nq, n_probes, k, select_min)


def regroup_merge(
    tables: ChunkTables,
    vals: jax.Array,   # (ncb, chunk, kk) per-chunk trimmed candidate scores
    rows: jax.Array,   # (ncb, chunk, kk) their source-row ids (-1 invalid)
    select_k_fn,
    nq: int,
    n_probes: int,
    k: int,
    select_min: bool,
):
    """Regroup per-chunk candidates to query-major (pure gather through
    the (g0, s0) pair addresses — no scatter) and merge exactly.
    Budget-masked pairs (tables.pair_valid False) contribute the worst
    value / row -1, exactly like a sub-k prefilter tail."""
    g0, s0 = tables.g0, tables.s0
    kk = vals.shape[-1]
    cand_v = vals[g0, s0]
    cand_r = rows[g0, s0]
    if tables.pair_valid is not None:
        worst = jnp.asarray(
            jnp.inf if select_min else -jnp.inf, cand_v.dtype)
        m = tables.pair_valid[:, None]
        cand_v = jnp.where(m, cand_v, worst)
        cand_r = jnp.where(m, cand_r, -1)
    cand_v = cand_v.reshape(nq, n_probes * kk)
    cand_r = cand_r.reshape(nq, n_probes * kk)
    v, pos2 = select_k_fn(cand_v, k, select_min)
    ids = jnp.take_along_axis(cand_r, pos2, axis=1)
    return v, ids


def chunk_validity(qid_tbl: jax.Array, nq: int) -> jax.Array:
    """(ncb,) int32 flag per chunk: 1 when the chunk holds at least one
    live pair, 0 when every slot is padding (`nq`). The fused list
    kernels take it as a scalar-prefetch operand and skip the MXU/VPU
    work of empty chunks — the trailing fragmentation chunks of any
    batch, and every chunk adaptive budgets empty out."""
    return jnp.any(qid_tbl != nq, axis=1).astype(jnp.int32)


def macro_batched(search_slice_fn, queries: jax.Array, k: int, mb: int = 4096,
                  extra: Optional[jax.Array] = None):
    """Run a list-major search over macro-batches of queries, bounding the
    chunk tables and score buffers per call.

    Every slice is padded up a power-of-two ladder (256, 512, ..., mb), so
    arbitrary batch sizes compile at most ~5 shapes per index (a varying-
    batch serving workload never retraces), and a 4097-query batch pays one
    4096-batch plus one 256-batch of work — not two full batches.
    `search_slice_fn(padded_slice)` must return (vals, rows) for the padded
    slice.

    `extra`: optional (nq, ...) per-query side array (the adaptive probe
    keep mask) sliced and padded in LOCKSTEP with the queries — pad rows
    get all-False, so padding scans nothing — and passed as the slice
    fn's second argument."""
    nq_total = queries.shape[0]
    if nq_total == 0:
        return (
            jnp.zeros((0, k), jnp.float32),
            jnp.full((0, k), -1, jnp.int32),
        )
    outs = []
    for s in range(0, nq_total, mb):
        sl = queries[s : s + mb]
        ex = extra[s : s + mb] if extra is not None else None
        target = _ladder(sl.shape[0], mb)
        pad = target - sl.shape[0]
        if pad:
            sl = jnp.pad(sl, ((0, pad), (0, 0)))
            if ex is not None:
                ex = jnp.pad(ex, ((0, pad), (0, 0)),
                             constant_values=False)
        v, r = (search_slice_fn(sl) if extra is None
                else search_slice_fn(sl, ex))
        outs.append((v[: target - pad], r[: target - pad]))
    if len(outs) == 1:
        return outs[0]
    return (
        jnp.concatenate([v for v, _ in outs]),
        jnp.concatenate([r for _, r in outs]),
    )


def _ladder(n: int, cap: int) -> int:
    t = 256
    while t < n and t < cap:
        t *= 2
    return min(t, cap)
