"""Probe-pair inversion for list-major IVF search engines.

Query-major IVF search (the reference's layout: one CUDA block per (query,
probe) — ivf_pq_search.cuh:611, ivf_flat_search.cuh:670) gathers each
probed list's storage once per query, so a batch re-reads every list
~nq*n_probes/n_lists times from HBM. The list-major engines instead invert
the (query, list) probe pairs into per-list buckets and stream each list
once. This module holds the shared inversion: sort pairs by list, split
each list's bucket into fixed-size chunks of `chunk` pairs ("virtual
lists", so hot-list skew costs padding only inside one chunk), and emit
  - per-chunk tables (which list, which queries) for the scoring loop, and
  - a per-pair (chunk, slot) address for regrouping candidates back to
    query-major order with a pure gather.

Everything is sorts + searchsorted + gathers — no XLA scatters (TPU lowers
scatter to a serialized per-index loop) — and every shape is static: the
chunk budget uses the bound sum(ceil(c_i/chunk)) <= P//chunk + n_lists, so
equal-shaped batches never recompile and no host sync is needed.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


# listmajor_chunk_block tuned values every list-major engine honors
# (0 = single-einsum superblocks; positive = inner lax.map granularity)
CHUNK_BLOCKS = (0, 8, 16, 32, 64)


class ChunkTables(NamedTuple):
    """Static-shape chunk tables for one query batch.

    lof      (ncb,)        list id scored by each chunk
    qid_tbl  (ncb, chunk)  query ids in each chunk; `nq` marks padding
                           (callers append a zero sentinel query row)
    g0       (nq*n_probes,) chunk id holding each original probe pair
    s0       (nq*n_probes,) slot of that pair within its chunk
    """

    lof: jax.Array
    qid_tbl: jax.Array
    g0: jax.Array
    s0: jax.Array


def chunk_count(nq: int, n_probes: int, n_lists: int, chunk: int) -> int:
    """Static upper bound on the number of chunks for a batch."""
    return (nq * n_probes) // chunk + n_lists


def invert_probes(probes: jax.Array, n_lists: int, chunk: int) -> ChunkTables:
    """Build chunk tables from a (nq, n_probes) probe matrix (traced)."""
    nq, n_probes = probes.shape
    p_total = nq * n_probes
    flat = probes.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(flat, stable=True)
    sorted_lists = flat[order]
    sorted_q = (order // n_probes).astype(jnp.int32)
    lids = jnp.arange(n_lists, dtype=jnp.int32)
    starts = jnp.searchsorted(sorted_lists, lids, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(sorted_lists, lids, side="right").astype(jnp.int32)
    counts = ends - starts
    cpl = (counts + chunk - 1) // chunk  # chunks per list
    cb = jnp.cumsum(cpl)  # inclusive
    base = (cb - cpl).astype(jnp.int32)  # first chunk id of each list

    ncb = chunk_count(nq, n_probes, n_lists, chunk)
    g = jnp.arange(ncb, dtype=jnp.int32)
    lof = jnp.minimum(jnp.searchsorted(cb, g, side="right"), n_lists - 1).astype(
        jnp.int32
    )
    cl = g - base[lof]  # chunk index within its list
    pos = cl[:, None] * chunk + jnp.arange(chunk, dtype=jnp.int32)[None, :]
    valid = pos < counts[lof][:, None]
    pair = jnp.clip(starts[lof][:, None] + pos, 0, p_total - 1)
    qid_tbl = jnp.where(valid, sorted_q[pair], nq)

    inv = jnp.argsort(order).astype(jnp.int32)  # original pair -> sorted position
    pos0 = inv - starts[flat]  # position within its list bucket
    g0 = base[flat] + pos0 // chunk
    s0 = pos0 % chunk
    return ChunkTables(lof, qid_tbl, g0, s0)


def score_and_select(
    tables: ChunkTables,
    block_fn,
    slot_rows: jax.Array,
    select_k_fn,
    nq: int,
    n_probes: int,
    k: int,
    select_min: bool,
    chunk: int,
    chunk_block: int,
    max_list: int,
    exact_trim: bool = False,
):
    """Shared back half of a list-major search (traced inside the engine's
    jit): two-level blocked scoring, per-superblock approximate trim,
    gather-based regroup to query-major, exact final merge.

    `block_fn(lof_block, qid_block) -> (CB, chunk, max_list)` computes the
    engine-specific candidate scores (IVF-Flat: raw-vector distances;
    IVF-PQ: int8-reconstruction distances) with invalid slots already
    masked to the worst value. `select_k_fn(scores, k, select_min)` is the
    exact top-k used for the final merge.

    Superblocks of `sb` chunks bound the materialized score buffer to
    ~2^27 elements regardless of max_list skew; each superblock is trimmed
    with the TPU-native approximate top-k (PartialReduce,
    jax.lax.approx_min_k) at recall_target=0.99 — the tradeoff the
    reference makes with its warp-level filtered queues
    (select_warpsort.cuh `warp_sort_filtered`).

    `chunk_block` controls the scoring granularity WITHIN a superblock:
    0 (the default dispatch) scores the whole superblock with one
    batched `block_fn` call — one large einsum, ~nsuper scan iterations
    per batch. A positive value runs an inner `lax.map` over blocks of
    that many chunks; at bench shape (ncb≈2048, chunk_block=8) that is
    ~256 serialized scan iterations whose per-iteration overhead, not
    FLOPs or bytes, dominated the round-4 measured 570 ms/batch (~60×
    off the HBM roofline, docs/perf.md). Kept raceable via the
    `listmajor_chunk_block` tuned key so the chip profiler can flip it
    with data.
    """
    from jax import lax

    lof, qid_tbl, g0, s0 = tables
    ncb = lof.shape[0]
    kk = min(k, max_list)

    budget = 1 << 27
    step = chunk_block if chunk_block else 1
    sb = max(step, budget // max(1, chunk * max_list))
    sb = min(-(-sb // step) * step, -(-ncb // step) * step)
    nsuper = -(-ncb // sb)
    bpad = nsuper * sb - ncb
    lof_b = (jnp.pad(lof, (0, bpad)) if bpad else lof).reshape(nsuper, sb)
    qid_b = (
        jnp.pad(qid_tbl, ((0, bpad), (0, 0)), constant_values=nq) if bpad else qid_tbl
    ).reshape(nsuper, sb, chunk)

    def super_block(inp):
        lofs, qids = inp  # (sb,), (sb, chunk)
        if chunk_block:
            nb_in = sb // chunk_block
            scores = lax.map(
                block_fn,
                (
                    lofs.reshape(nb_in, chunk_block),
                    qids.reshape(nb_in, chunk_block, chunk),
                ),
            )
            scores = scores.reshape(sb, chunk, max_list)
        else:
            scores = block_fn((lofs, qids))
        if exact_trim:
            # exact per-superblock trim (lax.top_k): pays the full sort
            # network but loses zero candidates — the option VERDICT r4
            # #6 asks for, so the approx bin-trim's recall tax is a
            # measured choice, not a forced one
            if select_min:
                v, si = lax.top_k(-scores, kk)
                v = -v
            else:
                v, si = lax.top_k(scores, kk)
        elif select_min:
            v, si = lax.approx_min_k(scores, kk, recall_target=0.99)
        else:
            v, si = lax.approx_max_k(scores, kk, recall_target=0.99)
        rows_sb = jnp.take_along_axis(slot_rows[lofs][:, None, :], si, axis=2)
        return v, rows_sb

    vals, rows = lax.map(super_block, (lof_b, qid_b))  # (nsuper, sb, chunk, kk)
    vals = vals.reshape(-1, chunk, kk)[:ncb]
    rows = rows.reshape(-1, chunk, kk)[:ncb]
    return regroup_merge(tables, vals, rows, select_k_fn, nq, n_probes, k, select_min)


def regroup_merge(
    tables: ChunkTables,
    vals: jax.Array,   # (ncb, chunk, kk) per-chunk trimmed candidate scores
    rows: jax.Array,   # (ncb, chunk, kk) their source-row ids (-1 invalid)
    select_k_fn,
    nq: int,
    n_probes: int,
    k: int,
    select_min: bool,
):
    """Regroup per-chunk candidates to query-major (pure gather through
    the (g0, s0) pair addresses — no scatter) and merge exactly."""
    _, _, g0, s0 = tables
    kk = vals.shape[-1]
    cand_v = vals[g0, s0].reshape(nq, n_probes * kk)
    cand_r = rows[g0, s0].reshape(nq, n_probes * kk)
    v, pos2 = select_k_fn(cand_v, k, select_min)
    ids = jnp.take_along_axis(cand_r, pos2, axis=1)
    return v, ids


def macro_batched(search_slice_fn, queries: jax.Array, k: int, mb: int = 4096):
    """Run a list-major search over macro-batches of queries, bounding the
    chunk tables and score buffers per call.

    Every slice is padded up a power-of-two ladder (256, 512, ..., mb), so
    arbitrary batch sizes compile at most ~5 shapes per index (a varying-
    batch serving workload never retraces), and a 4097-query batch pays one
    4096-batch plus one 256-batch of work — not two full batches.
    `search_slice_fn(padded_slice)` must return (vals, rows) for the padded
    slice."""
    nq_total = queries.shape[0]
    if nq_total == 0:
        return (
            jnp.zeros((0, k), jnp.float32),
            jnp.full((0, k), -1, jnp.int32),
        )
    outs = []
    for s in range(0, nq_total, mb):
        sl = queries[s : s + mb]
        target = _ladder(sl.shape[0], mb)
        pad = target - sl.shape[0]
        if pad:
            sl = jnp.pad(sl, ((0, pad), (0, 0)))
        v, r = search_slice_fn(sl)
        outs.append((v[: target - pad], r[: target - pad]))
    if len(outs) == 1:
        return outs[0]
    return (
        jnp.concatenate([v for v, _ in outs]),
        jnp.concatenate([r for _, r in outs]),
    )


def _ladder(n: int, cap: int) -> int:
    t = 256
    while t < n and t < cap:
        t *= 2
    return min(t, cap)
