"""Random ball cover: metric-pruned exact k-NN for low-dim / haversine data.

Reference parity: `raft::neighbors::ball_cover` (ball_cover.cuh:63,112 —
`build_index`, `all_knn_query`, `knn_query`; `BallCoverIndex` in
ball_cover_types.hpp; impl spatial/knn/detail/ball_cover{,/registers}.cuh).
The reference picks sqrt(n) random landmarks, groups points by nearest
landmark, and prunes with the triangle inequality (the registers.cuh
kernels carry a per-thread kth-distance bound and skip whole balls whose
center distance minus radius cannot beat it).

TPU design: landmark grouping is the same padded slot table as IVF-Flat;
the exact query is TWO static-shape passes instead of the reference's
per-thread dynamic early-exit (data-dependent loop bounds don't compile):

  pass 1  probe the `p1` balls with the smallest LOWER BOUND
          lb(q, l) = d(q, landmark_l) - radius_l (the triangle-inequality
          floor on any distance into ball l), score exactly, and take the
          per-query kth best as bound B;
  prune   a ball can hold a true top-k member only if lb <= B — count how
          many balls survive per query;
  pass 2  only when some query needs more than p1 balls: re-probe with
          p2 = max surviving count (rounded up to a power of two, so at
          most log(L) program shapes exist), again by smallest lb.

Exactness: every excluded ball has lb > B >= true kth distance, so no
true neighbor can live there. Squared metrics (sqeuclidean) are compared
in the root domain — the triangle inequality holds for the metric, not
its square. The p2 resolution is one host sync per batch (documented
cost; the win is skipping the gather+matmul for distant balls, which at
sqrt(n) landmarks is most of them on clustered data).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import _pairwise_impl
from raft_tpu.matrix.select_k import _select_k_impl


@dataclasses.dataclass
class BallCoverIndex:
    """ball_cover_types.hpp BallCoverIndex parity."""

    dataset: jax.Array        # (n, dim)
    landmarks: jax.Array      # (n_landmarks, dim)
    row_ids: jax.Array        # (n_landmarks, max_ball) int32, -1 pad
    radii: jax.Array          # (n_landmarks,) ball radius (metric units)
    metric: DistanceType

    @property
    def n(self) -> int:
        return int(self.dataset.shape[0])

    @property
    def n_landmarks(self) -> int:
        return int(self.landmarks.shape[0])


def build_index(dataset, metric="haversine", n_landmarks: int = 0, seed: int = 0) -> BallCoverIndex:
    """Sample sqrt(n) landmarks, group points by nearest landmark
    (ball_cover.cuh build_index)."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    x = jnp.asarray(dataset, jnp.float32)
    n = x.shape[0]
    m = resolve_metric(metric)
    k = n_landmarks or max(1, int(np.sqrt(n)))
    rng = np.random.default_rng(seed)
    sel = rng.choice(n, k, replace=False)
    landmarks = x[jnp.asarray(sel)]
    d = _pairwise_impl(x, landmarks, m)
    labels = np.asarray(jnp.argmin(d, axis=1))
    radii = np.zeros(k, np.float32)
    dmin = np.asarray(jnp.min(d, axis=1))
    for l in range(k):
        mem = dmin[labels == l]
        radii[l] = mem.max() if len(mem) else 0.0
    row_ids, _ = _pack_lists(labels, k)
    return BallCoverIndex(x, landmarks, jnp.asarray(row_ids), jnp.asarray(radii), m)


# metrics whose (root-domain) values satisfy the triangle inequality —
# the precondition of ball pruning. Cosine/correlation/inner-product
# families do NOT; they fall back to probing every ball (still exact,
# just unpruned — the pre-round-5 behavior).
_TRIANGLE_METRICS = frozenset({
    DistanceType.Haversine,
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.L1,
    DistanceType.Linf,
})

_SQUARED_METRICS = (DistanceType.L2Expanded, DistanceType.L2Unexpanded)


def _root_domain(index: BallCoverIndex, d):
    """Map raw metric values into the domain where the triangle inequality
    holds: squared-euclidean variants compare as sqrt; true metrics
    (haversine, L2Sqrt*, L1, Linf) pass through."""
    if index.metric in _SQUARED_METRICS:
        return jnp.sqrt(jnp.maximum(d, 0.0))
    return d


def _landmark_lower_bounds(index: BallCoverIndex, q):
    """Root-domain lower bound lb(q, l) = d(q, landmark_l) - radius_l.

    For the expanded-L2 metrics the landmark distances are recomputed via
    the UNEXPANDED form (direct sum of squared differences): the expanded
    engine's norm-cancellation error (~1e-3 relative at f32; see
    pairwise.py set_matmul_precision notes) is the dominant term of the
    pruning-bound error budget, and landmarks are only ~sqrt(n) rows so
    the exact form costs nothing. Radii came from the expanded build pass
    and keep their error — the caller's slack covers it."""
    m = index.metric
    if m in _SQUARED_METRICS:
        ld = _pairwise_impl(q, index.landmarks, DistanceType.L2Unexpanded)
    else:
        ld = _pairwise_impl(q, index.landmarks, m)
    return _root_domain(index, ld) - _root_domain(index, index.radii)[None, :]


def _probe_exact(index: BallCoverIndex, q, lb, p: int, k: int):
    """Score the p balls with the smallest lower bound per query, exactly.
    Returns (vals, ids) of the per-query top-k over those candidates."""
    _, probes = _select_k_impl(lb, p, True)  # (nq, p)
    cand = index.row_ids[probes].reshape(q.shape[0], -1)  # (nq, p*max_ball)

    def block(args):
        qi, ci = args
        cdata = index.dataset[jnp.maximum(ci, 0)]
        d = _pairwise_impl(qi[None, :], cdata, index.metric)[0]
        return jnp.where(ci >= 0, d, jnp.inf)

    d_all = jax.lax.map(block, (q, cand))
    kk = min(k, cand.shape[1])
    v, pos = _select_k_impl(d_all, kk, True)
    ids = jnp.take_along_axis(cand, pos, axis=1)
    if kk < k:  # fewer candidates than k: pad the tail (callers mask -1)
        v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
        ids = jnp.pad(ids, ((0, 0), (0, k - kk)), constant_values=-1)
    return v, ids


def knn_query(
    index: BallCoverIndex, queries, k: int, n_probes: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN via two-pass triangle-inequality ball pruning
    (ball_cover.cuh knn_query; registers.cuh bound semantics — see module
    docstring for the static-shape TPU formulation).

    n_probes=0 (default): exact. n_probes>0: fixed-probe approximate mode
    (probes that many closest-by-lower-bound balls, no second pass)."""
    q = jnp.asarray(queries, jnp.float32)
    L = index.n_landmarks
    if q.shape[0] == 0:
        return (jnp.zeros((0, k), jnp.float32), jnp.full((0, k), -1, jnp.int32))
    lb = _landmark_lower_bounds(index, q)

    if n_probes > 0:
        return _probe_exact(index, q, lb, min(n_probes, L), k)

    if index.metric not in _TRIANGLE_METRICS:
        # no valid lower bound without the triangle inequality: stay
        # exact by probing every ball (the pruning win is metric-gated)
        return _probe_exact(index, q, lb, L, k)

    # pass 1: a cheap probe wave sized for clustered data
    p1 = min(L, max(32, k))
    v1, ids1 = _probe_exact(index, q, lb, p1, k)

    # prune: balls that could still hold a true top-k member. Slack is
    # sized to the EXPANDED distance engine's f32 error class (~1e-3
    # relative; the bound B and the build-time radii both come from it),
    # not mere rounding — an under-sized slack silently breaks the
    # exactness contract.
    bound = _root_domain(index, v1[:, k - 1])  # (nq,)
    survives = lb <= (bound * (1.0 + 4e-3) + 1e-6)[:, None]  # (nq, L)
    needed = int(jnp.max(jnp.sum(survives, axis=1)))  # host sync (1 scalar)
    if needed <= p1:
        return v1, ids1

    # pass 2: enough balls for every query, pow2-rounded so at most
    # log(L) distinct program shapes ever compile
    p2 = p1
    while p2 < needed:
        p2 *= 2
    p2 = min(p2, L)
    return _probe_exact(index, q, lb, p2, k)


def all_knn_query(index: BallCoverIndex, k: int, n_probes: int = 0):
    """k-NN of every indexed point (ball_cover.cuh all_knn_query)."""
    return knn_query(index, index.dataset, k, n_probes)


def eps_nn_query(index: BallCoverIndex, queries, eps: float):
    """Range query via the same ball structure: boolean adjacency."""
    from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors

    return eps_neighbors(queries, index.dataset, eps, metric=index.metric)
