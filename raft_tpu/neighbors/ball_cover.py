"""Random ball cover: metric-pruned exact k-NN for low-dim / haversine data.

Reference parity: `raft::neighbors::ball_cover` (ball_cover.cuh:63,112 —
`build_index`, `all_knn_query`, `knn_query`; `BallCoverIndex` in
ball_cover_types.hpp; impl spatial/knn/detail/ball_cover{,/registers}.cuh).
The reference picks sqrt(n) random landmarks, groups points by nearest
landmark, and prunes with the triangle inequality.

TPU design: landmark grouping is the same padded slot table as IVF-Flat;
search probes the closest `n_probes` landmark balls with exact distances and
guarantees exactness by choosing n_probes via the ball-radius bound
(probe balls whose center distance - radius < current kth distance —
evaluated in a fixed-probe-count form to keep shapes static, with the
option to fall back to all balls for guaranteed-exact queries).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import _pairwise_impl
from raft_tpu.matrix.select_k import _select_k_impl


@dataclasses.dataclass
class BallCoverIndex:
    """ball_cover_types.hpp BallCoverIndex parity."""

    dataset: jax.Array        # (n, dim)
    landmarks: jax.Array      # (n_landmarks, dim)
    row_ids: jax.Array        # (n_landmarks, max_ball) int32, -1 pad
    radii: jax.Array          # (n_landmarks,) ball radius
    metric: DistanceType

    @property
    def n(self) -> int:
        return int(self.dataset.shape[0])

    @property
    def n_landmarks(self) -> int:
        return int(self.landmarks.shape[0])


def build_index(dataset, metric="haversine", n_landmarks: int = 0, seed: int = 0) -> BallCoverIndex:
    """Sample sqrt(n) landmarks, group points by nearest landmark
    (ball_cover.cuh build_index)."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    x = jnp.asarray(dataset, jnp.float32)
    n = x.shape[0]
    m = resolve_metric(metric)
    k = n_landmarks or max(1, int(np.sqrt(n)))
    rng = np.random.default_rng(seed)
    sel = rng.choice(n, k, replace=False)
    landmarks = x[jnp.asarray(sel)]
    d = _pairwise_impl(x, landmarks, m)
    labels = np.asarray(jnp.argmin(d, axis=1))
    radii = np.zeros(k, np.float32)
    dmin = np.asarray(jnp.min(d, axis=1))
    for l in range(k):
        mem = dmin[labels == l]
        radii[l] = mem.max() if len(mem) else 0.0
    row_ids, _ = _pack_lists(labels, k)
    return BallCoverIndex(x, landmarks, jnp.asarray(row_ids), jnp.asarray(radii), m)


def knn_query(
    index: BallCoverIndex, queries, k: int, n_probes: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN via ball pruning (ball_cover.cuh knn_query). n_probes=0
    probes enough balls for exactness (all of them in the static-shape
    worst case — the pruning win on TPU is skipping the gather/compute for
    distant balls when the caller allows approximation)."""
    q = jnp.asarray(queries, jnp.float32)
    nprobe = index.n_landmarks if n_probes == 0 else min(n_probes, index.n_landmarks)
    ld = _pairwise_impl(q, index.landmarks, index.metric)  # (nq, L)
    _, probes = _select_k_impl(ld, nprobe, True)
    max_ball = index.row_ids.shape[1]
    cand = index.row_ids[probes].reshape(q.shape[0], -1)  # (nq, nprobe*max_ball)
    worst = jnp.inf

    def block(args):
        qi, ci = args
        cdata = index.dataset[jnp.maximum(ci, 0)]
        d = _pairwise_impl(qi[None, :], cdata, index.metric)[0]
        return jnp.where(ci >= 0, d, worst)

    d_all = jax.lax.map(block, (q, cand))
    v, pos = _select_k_impl(d_all, k, True)
    ids = jnp.take_along_axis(cand, pos, axis=1)
    return v, ids


def all_knn_query(index: BallCoverIndex, k: int, n_probes: int = 0):
    """k-NN of every indexed point (ball_cover.cuh all_knn_query)."""
    return knn_query(index, index.dataset, k, n_probes)


def eps_nn_query(index: BallCoverIndex, queries, eps: float):
    """Range query via the same ball structure: boolean adjacency."""
    from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors

    return eps_neighbors(queries, index.dataset, eps, metric=index.metric)
