"""Live mutable indexes: crash-atomic upsert/delete with tombstones.

Every index in the library is a padded list-major store whose engines
mask candidate scores to the worst value wherever the slot table reads
-1 — the same mechanism that implements pads and prefilters. Mutation
rides it end to end:

- **delete** marks the victim's (list, slot) cells in a per-index
  `tombstones` mask; `core.bitset.make_slot_filter` folds the mask into
  the slot-table view every engine scans, so dead rows vanish from the
  fused Pallas kernels (`valid`/`chunk_valid` skip them), the XLA
  references (masked to +inf/-1), and refine/regroup_merge (a dead row
  is never a candidate, so nothing can resurrect it). `tombstones is
  None` = all-live: an unmutated index traces the identical program
  bit-for-bit.
- **upsert** tombstones every live slot holding the id, then appends
  the new row through the index's own `extend` (label + encode +
  scatter) — rows land in reserved tail slots (`ensure_append_slack`)
  so steady-state churn never re-pads the store, and the
  `resid_bf16`/`recon8`/`codes_t` lazy-store + `fused_kb` invalidation
  contracts do the rest.
- **rebalance** compacts tombstone-heavy lists: live rows pack left in
  slot order (deterministic), the store re-pads to the live geometry
  plus the reserved slack, and the mask drops back to None.

Crash-atomicity is the jobs/streaming batch-boundary protocol applied
to mutation (`Mutator`): each batch's payload is a CRC'd container
(`_save_batch`, `serialize.atomic_write` — never torn) written BEFORE
its line is appended to the CRC'd `mutlog.jsonl` (torn-line-terminating
appends, the MANIFEST.jsonl pattern), and checkpoint commits save the
whole index with `mut_cursor` = applied-entry count. A SIGKILL at ANY
point resumes bit-identically: the log's valid dense prefix is the
ground truth, the checkpoint is a replay shortcut, and a re-issued
driver sequence dedupes against the log by sequence number. Chaos
sites: `mutation.log.commit` (`crash_point` fires both after a log
append and after a checkpoint commit — the two SIGKILL windows),
`mutation.tombstone`, `mutation.rebalance`.

Layer contract (tools/raftlint/rules/layers.py): this module is
orchestration ABOVE the index modules — they are resolved lazily at
call time (`MODULE_CYCLE_BAN`), module scope touches only core/obs
(`MODULE_ALLOWED`).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.serialize import crc32c

#: chaos sites (core.faults.FAULT_SITES)
LOG_COMMIT_SITE = "mutation.log.commit"
TOMBSTONE_SITE = "mutation.tombstone"
REBALANCE_SITE = "mutation.rebalance"

#: index kinds the mutation protocol understands
KINDS = ("ivf_flat", "ivf_pq", "ivf_rabitq")

LOG_NAME = "mutlog.jsonl"
CKPT_NAME = "index.ckpt"

#: slot-group width of every list store (the kIndexGroupSize=32 lane
#: contract `_pack_lists`/`_append_slots` round to)
GROUP = 32


class MutationLogError(RuntimeError):
    """The mutation log and its checkpoint disagree in a way replay
    cannot reconcile (externally truncated log, payload/line op
    mismatch, an op this build does not know) — resuming would diverge
    from the pre-crash state, so the open refuses, typed."""


def _index_module(kind: str):
    """The `neighbors` module for a mutable index kind (lazy: mutation
    orchestrates the index modules, so they resolve at call time — the
    jobs/streaming idiom, enforced by MODULE_CYCLE_BAN)."""
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as mod
    elif kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as mod
    elif kind == "ivf_rabitq":
        from raft_tpu.neighbors import ivf_rabitq as mod
    else:
        raise ValueError(f"unknown index kind {kind!r}; one of {KINDS}")
    return mod


def kind_of(index) -> str:
    """Index kind from the instance's defining module."""
    mod = type(index).__module__.rsplit(".", 1)[-1]
    if mod not in KINDS:
        raise TypeError(f"not a mutable index: {type(index)!r}")
    return mod


def _payload_attrs(kind: str) -> Tuple[str, ...]:
    """The per-kind list-major payload tables that share slot geometry
    with `slot_rows` (axis 1 = slots)."""
    if kind == "ivf_flat":
        return ("list_data",)
    if kind == "ivf_pq":
        return ("codes",)
    return ("codes", "aux")


#: derived (runtime) stores invalidated by any slot-geometry change —
#: each rebuilds lazily on first use; `fused_kb` survives (monotone
#: candidate-buffer contract, ivf_flat `_pad_store_to_lanes`)
_DERIVED_ATTRS = ("resid_bf16", "resid_norm", "recon8", "recon_scale",
                  "recon_norm", "slot_rows_pad", "codes_t", "bp_meta",
                  "_list_radii")


def _clone(index):
    """Shallow copy: mutation returns a NEW index object (the serve
    layer swaps the reference between device batches — in-flight
    searches keep scanning the old object, zero-dip)."""
    import copy

    return copy.copy(index)


def _tomb_mask(index) -> np.ndarray:
    t = index.tombstones
    if t is None:
        return np.zeros(np.asarray(index.slot_rows).shape, bool)
    return np.asarray(t).astype(bool)


def live_rows(index) -> int:
    """Occupied slots minus tombstones — the truthful row count of a
    mutated index (`index.size` counts every appended row, including
    superseded upsert versions)."""
    sr = np.asarray(index.slot_rows)
    return int(((sr >= 0) & ~_tomb_mask(index)).sum())


def tombstone(index, ids):
    """Mark every LIVE slot holding one of `ids` dead; returns
    (new_index, n_dead). Ids absent from the index (or already dead)
    are ignored — delete is idempotent. The slot table itself is
    untouched (placement survives for compaction); only the mask grows,
    so unaffected queries stay bit-identical modulo the mask operand."""
    faults.fault_point(TOMBSTONE_SITE)
    sr = np.asarray(index.slot_rows)
    sid = np.asarray(index.source_ids)
    t = _tomb_mask(index)
    ids = np.unique(np.asarray(ids, sid.dtype).ravel())
    # positions whose id is a victim -> their (list, slot) cells; an
    # upserted id holds several positions, but only live slots flip
    victim_pos = np.isin(sid, ids)
    dead_new = victim_pos[np.maximum(sr, 0)] & (sr >= 0) & ~t
    n = int(dead_new.sum())
    if n == 0:
        return index, 0
    out = _clone(index)
    out.tombstones = jnp.asarray(t | dead_new)
    from raft_tpu.integrity.digest import refresh as _refresh_digests

    _refresh_digests(out, index)  # only the flipped mask rows re-digest
    if obs.enabled():
        obs.counter("mutation.tombstones").inc(n)
        obs.event("mutation", op="delete", index_kind=kind_of(index), n=n)
    return out, n


def delete(index, ids):
    """Online delete: tombstone `ids`. Returns the new index."""
    out, _ = tombstone(index, ids)
    return out


def upsert(index, vectors, ids=None):
    """Online upsert: retire any live row holding each id, then append
    the new rows through the index's own `extend` (label + encode +
    scatter into reserved tail slots). `ids=None` assigns fresh ids
    (`index.id_bound` onward) — a pure insert. Returns the new index;
    the OLD object keeps serving unchanged (zero-dip swap contract)."""
    kind = kind_of(index)
    mod = _index_module(kind)
    vectors = np.asarray(vectors)
    if ids is None:
        base = index.id_bound
        ids = np.arange(base, base + vectors.shape[0], dtype=np.int32)
    ids = np.asarray(ids, np.int32).ravel()
    if ids.shape[0] != vectors.shape[0]:
        raise ValueError(
            f"{vectors.shape[0]} vectors but {ids.shape[0]} ids")
    out, _ = tombstone(index, ids)
    out = mod.extend(out, vectors, new_indices=jnp.asarray(ids))
    if obs.enabled():
        obs.counter("mutation.upserts").inc(int(ids.shape[0]))
        obs.event("mutation", op="upsert", index_kind=kind, n=int(ids.shape[0]))
    return out


def ensure_append_slack(index, slack: int):
    """Reserve at least `slack` free tail slots in every list (rounded
    to the 32-slot group), so upsert batches scatter into existing pad
    columns instead of re-padding the store each time. Grow-only (the
    `extend` never-shrink contract); derived fused stores invalidate
    and rebuild lazily at the wider geometry. Returns the new index
    (the input when already wide enough)."""
    slack = int(slack)
    if slack < 0:
        raise ValueError(f"slack must be >= 0, got {slack}")
    kind = kind_of(index)
    sizes = np.asarray(index.list_sizes, np.int64)
    width = int(np.asarray(index.slot_rows).shape[1])
    need = int(sizes.max() if sizes.size else 0) + slack
    need = -(-max(need, 1) // GROUP) * GROUP
    if need <= width:
        if index.append_slack != slack:
            index = _clone(index)
            index.append_slack = slack
        return index
    extra = need - width
    out = _clone(index)
    for name in _payload_attrs(kind):
        tbl = getattr(index, name)
        setattr(out, name, jnp.pad(
            tbl, ((0, 0), (0, extra)) + ((0, 0),) * (tbl.ndim - 2)))
    out.slot_rows = jnp.pad(index.slot_rows, ((0, 0), (0, extra)),
                            constant_values=-1)
    from raft_tpu.core.bitset import carry_tombstones

    out.tombstones = carry_tombstones(index.tombstones, need)
    out.append_slack = slack
    for name in _DERIVED_ATTRS:
        if hasattr(out, name):
            setattr(out, name, None)
    from raft_tpu.integrity.digest import refresh as _refresh_digests

    _refresh_digests(out, index)  # geometry grew: full re-digest
    return out


def compact(index, *, slack: Optional[int] = None):
    """Drop tombstoned rows: live slots pack left in slot order (a
    deterministic host-side repack), the store width shrinks to the
    live geometry plus the reserved `slack` (default: the index's
    recorded `append_slack`), and the mask returns to None. Superseded
    `source_ids` entries stay (they are unreferenced; positions must
    not shift — slot values index into `source_ids`). `list_radii`
    stay: a max over former members still bounds the survivors."""
    kind = kind_of(index)
    slack = index.append_slack if slack is None else int(slack)
    sr = np.asarray(index.slot_rows)
    t = _tomb_mask(index)
    live = (sr >= 0) & ~t
    live_sizes = live.sum(axis=1).astype(np.int32)
    new_max = int(live_sizes.max() if live_sizes.size else 0) + slack
    new_max = -(-max(new_max, 1) // GROUP) * GROUP
    # stable left-pack: argsort on "dead" puts live slots first in
    # original slot order (kind='stable'), one shared gather for every
    # payload table
    order = np.argsort(~live, axis=1, kind="stable")
    packed_live = np.take_along_axis(live, order, axis=1)
    new_sr = np.where(packed_live, np.take_along_axis(sr, order, axis=1), -1)
    out = _clone(index)
    if new_max <= sr.shape[1]:
        new_sr = new_sr[:, :new_max]
        cut = order[:, :new_max]
    else:
        pad = new_max - sr.shape[1]
        new_sr = np.pad(new_sr, ((0, 0), (0, pad)), constant_values=-1)
        cut = np.pad(order, ((0, 0), (0, pad)), mode="edge")
    for name in _payload_attrs(kind):
        tbl = np.asarray(getattr(index, name))
        gathered = np.take_along_axis(
            tbl, cut.reshape(cut.shape + (1,) * (tbl.ndim - 2)), axis=1)
        if new_max > sr.shape[1]:
            gathered[:, sr.shape[1]:] = 0
        setattr(out, name, jnp.asarray(gathered))
    out.slot_rows = jnp.asarray(new_sr.astype(sr.dtype))
    out.list_sizes = jnp.asarray(live_sizes)
    out.tombstones = None
    out.append_slack = slack
    for name in _DERIVED_ATTRS:
        if hasattr(out, name):
            setattr(out, name, None)
    from raft_tpu.integrity.digest import refresh as _refresh_digests

    _refresh_digests(out, index)  # repack moved slots: re-digest them
    if obs.enabled():
        obs.counter("mutation.rebalances").inc()
        obs.event("mutation", op="rebalance", index_kind=kind,
                  n=int(t.sum()), width=new_max)
    return out


def rebalance(index, *, min_dead_frac: float = 0.0,
              slack: Optional[int] = None):
    """Compact when the store is tombstone-heavy enough to pay for it:
    dead slots / occupied slots >= `min_dead_frac` (0.0 = always).
    Returns (index, compacted_bool). The background-maintenance entry
    point — `Mutator.rebalance` logs it, `jobs.resumable_mutate` runs
    it preemptibly."""
    faults.fault_point(REBALANCE_SITE)
    sr = np.asarray(index.slot_rows)
    occupied = int((sr >= 0).sum())
    dead = int((_tomb_mask(index) & (sr >= 0)).sum())
    if occupied == 0 or dead == 0 or dead < min_dead_frac * occupied:
        return index, False
    return compact(index, slack=slack), True


# ---------------------------------------------------------------------------
# crash-atomic mutation log
# ---------------------------------------------------------------------------


def _save_batch(path: str, op: str, seq: int, ids: np.ndarray,
                vectors: Optional[np.ndarray]) -> None:
    """One mutation batch's payload container (CRC'd, atomic — a kill
    mid-write leaves NO file, so a payload either exists whole or its
    log line was never appended)."""
    from raft_tpu.core.serialize import serialize_arrays

    arrays = {"ids": jnp.asarray(ids, jnp.int32)}
    if vectors is not None:
        arrays["vectors"] = jnp.asarray(vectors, jnp.float32)
    serialize_arrays(path, arrays, {
        "kind": "mutation_batch",
        "version": 1,
        "op": op,
        "seq": int(seq),
    })


def _load_batch(path: str):
    """Read one payload container back; returns (op, seq, ids, vectors
    — None for deletes/rebalances)."""
    from raft_tpu.core.serialize import read_ckpt

    arrays, meta = read_ckpt(path, "mutation_batch")
    ids = np.asarray(arrays["ids"])
    vectors = arrays.get("vectors")
    if vectors is not None:
        vectors = np.asarray(vectors)
    return meta["op"], int(meta["seq"]), ids, vectors


class MutationLog:
    """Append-only CRC'd mutation journal (`mutlog.jsonl`).

    One line per committed batch: ``{"v", "seq", "op", "payload",
    "crc"}`` where `crc` is CRC-32C over the line's canonical encoding
    without the crc field (torn or rotted lines are skipped on read).
    Appends terminate a torn final line first (the MANIFEST.jsonl /
    obs.ledger discipline), and the payload container is written BEFORE
    its line — so the set of valid lines whose seq forms a dense prefix
    is exactly the set of durable mutations."""

    def __init__(self, root: str):
        self.root = os.path.abspath(os.fspath(root))
        os.makedirs(self.root, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.root, LOG_NAME)

    def payload_path(self, seq: int) -> str:
        return os.path.join(self.root, f"mut_{int(seq):06d}.ckpt")

    @staticmethod
    def _line_crc(entry: dict) -> int:
        body = {k: v for k, v in entry.items() if k != "crc"}
        blob = json.dumps(body, sort_keys=True,
                          separators=(",", ":")).encode()
        return crc32c(blob)

    def entries(self) -> list:
        """Valid entries, as the longest dense seq prefix. Torn or
        CRC-rotted lines are SKIPPED — a kill mid-append leaves a torn
        tail, and the resumed run legitimately appends its re-issued
        copy of that seq right after it. Safety comes from the seq
        rule: a valid line whose seq is not the next expected one ends
        the log THERE (a skipped line in the MIDDLE leaves a gap, so
        externally-damaged state can never be bridged silently)."""
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path, "r", encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn line; the next line may be its redo
                if not isinstance(e, dict) or e.get("crc") != self._line_crc(e):
                    continue  # rotted line; ditto
                if int(e.get("seq", -1)) != len(out):
                    break
                out.append(e)
        return out

    def append(self, op: str, seq: int, payload: Optional[str]) -> dict:
        entry = {"v": 1, "seq": int(seq), "op": op, "payload": payload}
        entry["crc"] = self._line_crc(entry)
        line = json.dumps(entry, sort_keys=True)
        with open(self.path, "a+b") as fh:
            fh.seek(0, os.SEEK_END)
            if fh.tell() > 0:
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) != b"\n":
                    fh.write(b"\n")  # terminate a torn predecessor
            fh.write(line.encode() + b"\n")
            fh.flush()
            os.fsync(fh.fileno())
        return entry


class Mutator:
    """Crash-atomic online mutation of one index (module docstring).

    Layout under `root`: `mutlog.jsonl` + `mut_<seq>.ckpt` payloads +
    `index.ckpt` (the committed checkpoint, carrying `mut_cursor` =
    applied-entry count). Open with the cold-start index; when a
    committed checkpoint exists it REPLACES the argument (the
    jobs/streaming resume contract) and the log's tail beyond the
    cursor replays deterministically, so a SIGKILL at any point —
    payload write, log append, checkpoint commit — resumes to the
    bit-identical state.

    A re-run driver re-issues its mutation sequence from the top; calls
    whose seq is already in the log are skipped (their effect is either
    in the checkpoint or was just replayed), which is what makes the
    kill-and-rerun drill converge. `ckpt_every` batches between
    checkpoint commits bounds replay work; `slack` is the per-list
    append reserve (`ensure_append_slack`) renewed at each commit."""

    def __init__(self, root: str, index=None, *, kind: Optional[str] = None,
                 ckpt_every: int = 8, slack: int = 0, retain: int = 0):
        self.log = MutationLog(root)
        self.ckpt_every = max(1, int(ckpt_every))
        self.slack = int(slack)
        # point-in-time recovery window (raft_tpu/integrity): keep the
        # `retain` newest commit checkpoints as cursor-stamped
        # snapshots; payload GC then sweeps only below the oldest
        # retained cursor so every retained base can replay forward.
        # 0 = no window, the pre-PITR behavior verbatim.
        self.retain = max(0, int(retain))
        ckpt = os.path.join(self.log.root, CKPT_NAME)
        if os.path.exists(ckpt):
            if kind is None:
                kind = kind_of(index) if index is not None else None
            if kind is None:
                raise ValueError("resume needs kind= (or an index)")
            index = _index_module(kind).load(ckpt)
        elif index is None:
            raise ValueError("no committed checkpoint: pass the index")
        self.kind = kind or kind_of(index)
        self.index = index
        if self.slack:
            self.index = ensure_append_slack(self.index, self.slack)
        entries = self.log.entries()
        cursor = int(self.index.mut_cursor)
        if cursor > len(entries):
            raise MutationLogError(
                f"checkpoint cursor {cursor} beyond the log "
                f"({len(entries)} entries) — the log was truncated "
                "externally; refusing a divergent resume")
        for e in entries[cursor:]:
            self._apply(e)
        self.applied = len(entries)
        self._issued = 0

    # -- protocol ------------------------------------------------------
    @property
    def ckpt_path(self) -> str:
        return os.path.join(self.log.root, CKPT_NAME)

    def _apply(self, entry: dict) -> None:
        """Deterministically apply one logged entry to the in-memory
        index (the replay path and the live path share it)."""
        op = entry["op"]
        if op == "rebalance":
            self.index, _ = rebalance(self.index, slack=self.slack or None)
            return
        op2, _, ids, vectors = _load_batch(
            self.log.payload_path(entry["seq"]))
        if op2 != op:
            raise MutationLogError(
                f"payload op {op2!r} != log op {op!r} at seq "
                f"{entry['seq']}")
        if op == "upsert":
            self.index = upsert(self.index, vectors, ids)
        elif op == "delete":
            self.index = delete(self.index, ids)
        else:
            raise MutationLogError(f"unknown logged op {op!r}")

    def _submit(self, op: str, ids, vectors=None):
        seq = self._issued
        self._issued += 1
        if seq < self.applied:
            return self.index  # already durable (pre-kill run logged it)
        if vectors is not None or op in ("upsert", "delete"):
            _save_batch(self.log.payload_path(seq), op, seq,
                        np.asarray(ids, np.int32), vectors)
        self.log.append(op, seq, None if op == "rebalance"
                        else os.path.basename(self.log.payload_path(seq)))
        entry = {"op": op, "seq": seq}
        self._apply(entry)
        self.applied += 1
        # SIGKILL window 1: the log is ahead of the checkpoint — the
        # resume must replay this entry (count-th visit kills; see
        # core.faults.crash_point)
        faults.crash_point(LOG_COMMIT_SITE)
        if self.applied - int(self.index.mut_cursor) >= self.ckpt_every:
            self.commit()
        return self.index

    def upsert(self, vectors, ids):
        """Log + apply one upsert batch. Returns the current index."""
        return self._submit("upsert", ids, np.asarray(vectors, np.float32))

    def delete(self, ids):
        """Log + apply one delete batch. Returns the current index."""
        return self._submit("delete", ids)

    def rebalance(self):
        """Log + apply a compaction, then commit immediately (the
        geometry change makes checkpointing now strictly cheaper than
        replaying it later). Returns the current index."""
        out = self._submit("rebalance", np.empty((0,), np.int32))
        self.commit()
        return out

    def commit(self):
        """Checkpoint the index with `mut_cursor` = applied entries
        (one atomic file — the batch-boundary commit), then sweep the
        payload containers the checkpoint superseded."""
        if int(self.index.mut_cursor) != self.applied:
            idx = _clone(self.index)
            idx.mut_cursor = self.applied
            idx.append_slack = self.slack
            from raft_tpu.integrity.digest import attach as _attach_digests

            if getattr(idx, "list_digests", None) is None:
                # mutation-commit digest hook: an index that predates
                # the sidecar (legacy checkpoint) gains one here, so
                # every committed checkpoint is scrub-coverable
                _attach_digests(idx, self.kind)
            _index_module(self.kind).save(self.ckpt_path, idx)
            self.index = idx
            sweep_below = self.applied
            if self.retain:
                import importlib
                import shutil

                # importlib, not `from ... import restore`: the package
                # re-binds `restore` to the FUNCTION, shadowing the module
                _pitr = importlib.import_module(
                    "raft_tpu.integrity.restore")

                # a byte-for-byte copy of the commit IS the snapshot —
                # the PITR byte-identity claim needs no second writer
                shutil.copyfile(self.ckpt_path,
                                _pitr.snapshot_path(self.log.root,
                                                    self.applied))
                kept = _pitr.prune(self.log.root, keep=self.retain)
                sweep_below = min(kept) if kept else self.applied
            for seq in range(sweep_below):
                p = self.log.payload_path(seq)
                if os.path.exists(p):
                    try:
                        os.remove(p)
                    except OSError:
                        pass  # an orphan payload is ignored garbage
            if obs.enabled():
                obs.event("mutation", op="commit", index_kind=self.kind,
                          cursor=self.applied)
        # SIGKILL window 2: after the checkpoint commit — the resume
        # must NOT replay (cursor == log length)
        faults.crash_point(LOG_COMMIT_SITE)
        return self.index


# ---------------------------------------------------------------------------
# serve-layer feed (zero-dip swap-in)
# ---------------------------------------------------------------------------


class MutationFeed:
    """Thread-safe queue of committed mutation batches for the serve
    layer: a mutator (any thread) `publish`es, the serving loop drains
    BETWEEN device batches (`serve.engine` `_heal_between_batches`) and
    swaps its index reference — in-flight traffic keeps the old object,
    so coverage never dips and unaffected queries stay bit-identical.

    Batches are the `apply_batch` shapes: ``("upsert", vectors, ids)``,
    ``("delete", ids)``, ``("rebalance",)``."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._pending: list = []

    def publish(self, batch: tuple) -> None:
        if not batch or batch[0] not in ("upsert", "delete", "rebalance"):
            raise ValueError(f"unknown mutation batch {batch!r:.60}")
        with self._lock:
            self._pending.append(batch)

    def drain(self) -> list:
        with self._lock:
            out, self._pending = self._pending, []
        return out


def apply_batch(index, batch: tuple):
    """Apply one feed batch to an index, returning the new index."""
    op = batch[0]
    if op == "upsert":
        return upsert(index, batch[1], batch[2])
    if op == "delete":
        return delete(index, batch[1])
    if op == "rebalance":
        out, _ = rebalance(index)
        return out
    raise ValueError(f"unknown mutation op {op!r}")
