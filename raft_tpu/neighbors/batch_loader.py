"""Host<->device batch streaming for out-of-HBM datasets.

Reference parity: `batch_load_iterator` (spatial/knn/detail/ann_utils.cuh:388)
— RAFT streams host-resident datasets through a device-side staging buffer in
fixed-size batches so 100M-row index builds never need the full dataset on
device. TPU equivalent: an iterator yielding device-resident `jax.Array`
blocks of a uniform (padded) batch shape, so downstream jit programs compile
ONCE for the batch shape and get reused for every batch; an optional
double-buffering mode enqueues the next host->device transfer before the
caller finishes consuming the current block (XLA dispatch is async, so the
copy overlaps compute).

Used by `ivf_flat.build`/`ivf_pq.build` callers at the 100M scale: build on a
subsample, then `extend` batch-by-batch.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.core import faults


class BatchLoadIterator:
    """Iterate a host array (numpy / memmap) in device-resident batches.

    Every yielded block has the SAME shape (batch_size, ...): the final
    partial batch is zero-padded, and `valid` gives its true row count —
    static shapes keep XLA from recompiling per batch (the reference pads
    similarly to keep one kernel configuration, ann_utils.cuh:388).
    """

    def __init__(
        self,
        host_array,
        batch_size: int,
        device: Optional[jax.Device] = None,
        prefetch: bool = True,
        dtype=None,
    ):
        self.host = host_array
        self.n = int(host_array.shape[0])
        self.batch_size = int(batch_size)
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.device = device
        self.prefetch = prefetch
        self.dtype = dtype
        self.n_batches = -(-self.n // self.batch_size) if self.n else 0

    def __len__(self) -> int:
        return self.n_batches

    def _load(self, b: int) -> Tuple[jax.Array, int]:
        # chaos site: slow/flaky host reads and poisoned blocks (a torn
        # memmap page, a failing storage path) — no-op without a plan;
        # rank-scoped faults target this controller's process index
        faults.fault_point("batch_loader.load", rank=jax.process_index())
        lo = b * self.batch_size
        hi = min(lo + self.batch_size, self.n)
        block = np.asarray(self.host[lo:hi])
        block = faults.corrupt_host("batch_loader.load", block,
                                    rank=jax.process_index())
        if self.dtype is not None:
            block = block.astype(self.dtype, copy=False)
        valid = hi - lo
        if valid < self.batch_size:
            pad = np.zeros((self.batch_size - valid,) + block.shape[1:], block.dtype)
            block = np.concatenate([block, pad], axis=0)
        arr = jax.device_put(block, self.device)
        return arr, valid

    def __iter__(self) -> Iterator[Tuple[jax.Array, int]]:
        """Yields (device_block, valid_rows)."""
        if self.n_batches == 0:
            return
        if not self.prefetch:
            for b in range(self.n_batches):
                yield self._load(b)
            return
        # double buffering: device_put is async; enqueue batch b+1 before
        # handing b to the caller so transfer overlaps their compute.
        nxt = self._load(0)
        for b in range(1, self.n_batches):
            cur, nxt = nxt, None
            nxt = self._load(b)
            yield cur
        yield nxt


def extend_batched(extend_fn, index, host_array, batch_size: int, start_id: int = 0):
    """Stream `host_array` into an ANN index via repeated `extend_fn`
    (ivf_flat.extend / ivf_pq.extend) — the reference's big-build loop.

    Slices the host array directly (extend uploads each batch exactly once);
    `extend` is incremental, so total work is linear in the dataset."""
    n = int(host_array.shape[0])
    offset = start_id
    for lo in range(0, n, batch_size):
        hi = min(lo + batch_size, n)
        ids = jnp.arange(offset, offset + (hi - lo), dtype=jnp.int32)
        index = extend_fn(index, np.asarray(host_array[lo:hi]), ids)
        offset += hi - lo
    return index
