"""Query-adaptive probe budgets + early-terminating list scans.

Every IVF search used to scan a fixed ``n_probes`` lists per query, so
easy queries subsidized hard ones and recall was one global knob. This
module is the shared budget layer behind ROADMAP item 2 (JUNO, arxiv
2312.01712: sparsity-aware pruning of the candidate space beats fixed
probing):

  budgets      after the coarse top-``n_probes`` select, each query gets
               its own probe budget from the *normalized distance-gap
               profile* of its sorted coarse scores: a query whose
               nearest centroids separate sharply from the rest stops
               early; a query in a flat neighborhood keeps probing.
               ``tau`` in (0, 1] is the profile cutoff — ``tau >= 1``
               saturates every budget at ``n_probes`` (the bit-exact
               fixed-probe reference), ``tau -> 0`` collapses to
               ``min_probes``.
  early term   per-list score lower bounds from build-time list radii
               (max member distance to its centroid): a probed list
               whose bound ``max(0, |q - c_l| - r_l)`` cannot beat a
               provable upper bound on the query's k-th distance is
               skipped. Sound for L2 metrics (triangle inequality);
               inner product and indexes without stored radii fall back
               to budgets only.
  masking      both decisions land in ONE (nq, n_probes) boolean keep
               mask, applied positionally to each engine's own sorted
               probe list: query-major engines mask the slot gather,
               list-major engines drop masked pairs before probe
               inversion (fewer populated chunks), and the fused list
               kernels skip fully-empty chunks via their ``chunk_valid``
               scalar-prefetch path — ragged work padded TPU-shaped.
  accounting   the ACTUAL per-batch scanned-list totals feed the
               ``ivf.scanned_lists`` / ``ivf.budget_hist`` counters and
               the cost model's ``scanned_lists`` charge, so the saving
               is visible in ``obs.report`` and perfgate instead of
               silently charging worst-case work.

Serving resolves a per-request ``recall_target`` onto ``tau`` through
the ``adaptive_probe_policy`` tuned key (calibration banked by
``bench/bench_adaptive_probes.py --apply``); ``recall_target >= 1.0``
resolves to the saturated plan, which is bit-identical to the fixed
path by construction (and pinned by tests/test_probe_budget.py).

Layering: this module sits beside the quantizer layer — importable by
the three index engines, comms and serve; it must never import an index
module back (raftlint MODULE_CYCLE_BAN) and is sealed from ops like the
rest of neighbors (ANY_LEVEL_BAN).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.matrix.select_k import _select_k_impl

#: chaos-drill injection site: corrupt_shard here NaNs a seeded fraction
#: of the traced per-query budget vector; the plan clamps corrupted
#: entries down to ``min_probes`` (a *shrunken* budget — degraded recall
#: that is visible, never a crash), and the plan jit keys on
#: ``faults.trace_key()`` so install/clear retraces.
BUDGET_SITE = "ivf.probe_budget"

#: tuned key holding the measured recall_target -> tau calibration
#: (written by bench_adaptive_probes --apply): {"default_tau": float,
#: "targets": [[recall_target, tau], ...]} sorted by recall_target.
#: Re-exported from the ONE registry spelling (core.tuned.TUNED_KEYS).
from raft_tpu.core.tuned import POLICY_KEY  # noqa: E402

#: conservative built-in calibration used until a bench --apply banks a
#: per-index measured table. Deliberately generous taus: an uncalibrated
#: deployment must err toward scanning more, not missing recall.
DEFAULT_POLICY = {
    "default_tau": 0.6,
    "targets": [[0.85, 0.35], [0.90, 0.45], [0.95, 0.60], [0.99, 0.80]],
}

_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class AdaptiveResolved:
    """A search's resolved adaptive-probing configuration: the profile
    cutoff ``tau`` (>= 1.0 means saturated budgets), the per-query
    budget floor, and whether bound-based early termination may engage
    (still gated at the engine on radii availability + an L2 metric)."""

    tau: float
    min_probes: int
    early_term: bool


def resolve_tau(recall_target: Optional[float]) -> float:
    """recall_target -> tau through the tuned policy (POLICY_KEY, else
    DEFAULT_POLICY): the smallest banked tau whose calibrated recall
    covers the request; requests above every banked target — or >= 1.0
    — saturate (tau = 1.0, the fixed-probe reference)."""
    from raft_tpu.core import tuned

    policy = tuned.get(POLICY_KEY)
    if not (isinstance(policy, dict) and isinstance(policy.get("targets"), list)):
        policy = DEFAULT_POLICY
    if recall_target is None:
        try:
            return float(policy.get("default_tau", DEFAULT_POLICY["default_tau"]))
        except (TypeError, ValueError):
            return float(DEFAULT_POLICY["default_tau"])
    rt = float(recall_target)
    if rt >= 1.0:
        return 1.0
    # sanitize BEFORE sorting: one malformed entry in a hand-edited
    # tuned table must degrade (be skipped), not crash every adaptive
    # search through the sort key
    entries = []
    for entry in policy["targets"]:
        try:
            entries.append((float(entry[0]), float(entry[1])))
        except (TypeError, ValueError, IndexError):
            continue
    best = None
    for target, tau in sorted(entries):
        if target >= rt:
            best = tau
            break
    return 1.0 if best is None else min(max(best, 0.0), 1.0)


def resolve_params(params, n_probes: int) -> Optional[AdaptiveResolved]:
    """Resolve an engine SearchParams' adaptive fields (``adaptive``,
    ``recall_target``, ``budget_tau``, ``min_probes``, ``early_term``)
    to an `AdaptiveResolved`, or None for the fixed-``n_probes`` path.
    Setting any of ``recall_target`` / ``budget_tau`` implies adaptive;
    a saturated resolution (tau >= 1.0) from ``recall_target`` keeps
    early termination OFF so ``recall_target=1.0`` stays bit-identical
    to the fixed reference (an explicit ``budget_tau`` keeps the
    caller's ``early_term`` choice)."""
    adaptive = bool(getattr(params, "adaptive", False))
    rt = getattr(params, "recall_target", None)
    bt = getattr(params, "budget_tau", None)
    if not (adaptive or rt is not None or bt is not None):
        return None
    if bt is not None:
        tau = float(bt)
        early = bool(getattr(params, "early_term", True))
    else:
        tau = resolve_tau(rt)
        early = bool(getattr(params, "early_term", True)) and tau < 1.0
    mp = int(min(max(1, int(getattr(params, "min_probes", 1))), int(n_probes)))
    return AdaptiveResolved(tau=tau, min_probes=mp, early_term=early)


def resolve(n_probes: int, adaptive: bool = False, recall_target=None,
            budget_tau=None, min_probes: int = 1,
            early_term: bool = True) -> Optional[AdaptiveResolved]:
    """Keyword-argument spelling of `resolve_params` for callers without
    a SearchParams object (the MNMG drivers, serve adapters)."""
    import types

    return resolve_params(
        types.SimpleNamespace(
            adaptive=adaptive, recall_target=recall_target,
            budget_tau=budget_tau, min_probes=min_probes,
            early_term=early_term),
        n_probes)


def policy_token(params, n_probes: int):
    """Hashable token describing how the adaptive fields shape the
    COMPILED program — the serve compile-cache key component. ``tau``
    and ``min_probes`` are traced operands (one program serves every
    value), so only the adaptive/bounds structure of the plan
    distinguishes programs."""
    ap = resolve_params(params, n_probes)
    if ap is None:
        return None
    return ("adaptive", bool(ap.early_term))


# ---------------------------------------------------------------------------
# traced plan math (shared by the jitted single-chip wrapper and the
# MNMG drivers, which compute the plan on replicated coarse geometry)
# ---------------------------------------------------------------------------


def _coarse_dists(q_eff: jax.Array, centers: jax.Array, metric: DistanceType,
                  pq_style: bool = False):
    """Coarse scores ORDER-IDENTICAL to the engine the mask will be
    applied in (the keep mask is positional over the engine's own
    sorted probe list, so the plan must sort by the engine's exact f32
    values — a merely order-equivalent formula can flip near-ties):
    IVF-Flat's `_coarse_scores` full squared L2, or — with `pq_style`
    — ivf_pq `_coarse_select`'s unshifted, unclamped ``|c|^2 - 2<q,c>``
    (IVF-PQ and IVF-RaBitQ). Returns (scores, qn_shift, select_min);
    bound distances recover as ``max(scores + qn_shift, 0)`` when a
    shift was dropped."""
    from raft_tpu.distance.pairwise import _dot

    d = _dot(q_eff, centers)
    if metric == DistanceType.InnerProduct:
        return d, None, False
    qn = jnp.sum(q_eff.astype(jnp.float32) ** 2, axis=1)[:, None]
    cn = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)[None, :]
    if pq_style:
        return cn - 2.0 * d, qn, True
    return jnp.maximum(qn + cn - 2.0 * d, 0.0), None, True


def assign_budgets(cvals: jax.Array, select_min: bool, tau,
                   min_probes) -> jax.Array:
    """Per-query budgets from the normalized gap profile of the sorted
    coarse scores ``cvals`` (nq, P), best-first. The profile
    g_j = (v_j - v_0) / (v_last - v_0 + eps) is nondecreasing in j, so
    the budget is the prefix length with g <= tau, clamped to
    [min_probes, P]. tau >= 1 keeps every position (saturated)."""
    v0 = cvals[:, :1]
    vl = cvals[:, -1:]
    if select_min:
        g = (cvals - v0) / (vl - v0 + _EPS)
    else:
        g = (v0 - cvals) / (v0 - vl + _EPS)
    budgets = jnp.sum((g <= tau).astype(jnp.int32), axis=1)
    mp = jnp.asarray(min_probes, jnp.int32)
    return jnp.clip(budgets, mp, jnp.int32(cvals.shape[1]))


def _maybe_corrupt_budgets(budgets: jax.Array, min_probes) -> jax.Array:
    """BUDGET_SITE chaos hook: corrupt_shard NaNs a seeded fraction of
    the (float-viewed) budget vector; corrupted entries SHRINK to the
    floor — recall degrades visibly, the plan never crashes. Inert
    (same jaxpr) without an installed plan."""
    from raft_tpu.core.faults import corrupt_in_trace

    bf = corrupt_in_trace(BUDGET_SITE, budgets.astype(jnp.float32),
                          jnp.int32(0))
    return jnp.where(jnp.isnan(bf),
                     jnp.asarray(min_probes, jnp.int32), budgets)


def early_term_keep(cvals: jax.Array, pradii: jax.Array, psizes: jax.Array,
                    k: int, base_keep: jax.Array) -> jax.Array:
    """Sound bound-based keep mask over the budget-kept probed lists
    (L2 geometry). For probed list j at coarse distance d_j with radius
    r_j every member lies in [max(0, d_j - r_j), d_j + r_j]. Walk the
    budget-kept prefix until its cumulative member count covers k: the
    running max upper bound there, U, provably bounds the query's k-th
    distance, so any list with lower bound > U cannot contribute —
    skipping it can never drop a true top-k neighbor (the oracle
    property tests/test_probe_budget.py pins). Fewer than k members in
    the whole kept set -> U = +inf -> nothing skipped."""
    d = jnp.sqrt(jnp.maximum(cvals, 0.0))
    ub = d + pradii
    lb = jnp.maximum(d - pradii, 0.0)
    sizes_eff = jnp.where(base_keep, psizes.astype(jnp.int32), 0)
    ub_eff = jnp.where(base_keep, ub, -jnp.inf)
    csize = jnp.cumsum(sizes_eff, axis=1)
    run_ub = lax.cummax(ub_eff, axis=1)
    need = csize >= jnp.int32(k)
    U = jnp.min(jnp.where(need, run_ub, jnp.inf), axis=1, keepdims=True)
    return lb <= U


def plan_keep_mask(q_eff: jax.Array, centers: jax.Array, tau, min_probes,
                   n_probes: int, k: int, metric: DistanceType,
                   radii: Optional[jax.Array] = None,
                   sizes: Optional[jax.Array] = None,
                   pq_coarse: bool = False,
                   ) -> Tuple[jax.Array, jax.Array]:
    """The traced plan (callable inside any jit / shard_map body):
    coarse select -> budgets -> optional early-termination bounds.
    Returns ((nq, n_probes) bool keep mask, (nq,) int32 scanned-list
    counts). ``q_eff`` is the engine's coarse-space query matrix
    (rotated for PQ/RaBitQ, with ``pq_coarse`` selecting their exact
    coarse formula so the positional mask cannot misalign on f32
    near-ties); ``radii``/``sizes`` enable the bound pass (L2 metrics
    only — the caller gates)."""
    cs, qn_shift, select_min = _coarse_dists(q_eff, centers, metric,
                                             pq_style=pq_coarse)
    cvals, probes = _select_k_impl(cs, n_probes, select_min)
    budgets = assign_budgets(cvals, select_min, tau, min_probes)
    budgets = _maybe_corrupt_budgets(budgets, min_probes)
    pos = jnp.arange(n_probes, dtype=jnp.int32)[None, :]
    keep = pos < budgets[:, None]
    if radii is not None and sizes is not None:
        # bound distances need the FULL squared L2 — restore the
        # per-row |q|^2 the pq-style ordering formula drops
        dist2 = (jnp.maximum(cvals + qn_shift, 0.0)
                 if qn_shift is not None else cvals)
        keep = keep & early_term_keep(
            dist2, radii[probes], sizes[probes], k, keep)
        # the budget floor survives the bound pass (predictable minimum
        # work per query; position 0 is provably kept anyway)
        keep = keep | (pos < jnp.asarray(min_probes, jnp.int32))
    return keep, jnp.sum(keep.astype(jnp.int32), axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "k", "metric", "rotated", "use_bounds",
                     "fault_key"),
)
def _plan_impl(queries, rotation, centers, radii, sizes, tau, min_probes,
               n_probes: int, k: int, metric: DistanceType, rotated: bool,
               use_bounds: bool, fault_key=None):
    del fault_key  # participates in the jit cache key only (chaos retrace)
    q = queries.astype(jnp.float32)
    q_eff = q @ rotation.T if rotated else q
    return plan_keep_mask(
        q_eff, centers, tau, min_probes, n_probes, k, metric,
        radii=radii if use_bounds else None,
        sizes=sizes if use_bounds else None,
        pq_coarse=rotated,
    )


def probe_plan(queries, centers, *, n_probes: int, min_probes: int, k: int,
               metric: DistanceType, tau: float, rotation=None,
               radii=None, sizes=None) -> Tuple[jax.Array, jax.Array]:
    """Host entry: compute the (nq, n_probes) keep mask + per-query
    scanned counts for one batch. The coarse stage here duplicates the
    engine's in-jit coarse matmul (one (nq, n_lists) product — small
    against the scan it prunes); budgets are a pure per-row function of
    the query, so masks computed on the full batch slice losslessly
    into the engines' macro-batches. ``radii`` engages the bound pass
    only for L2-family metrics (IP has no triangle inequality — bounds
    absent means budgets only, the documented fallback)."""
    from raft_tpu.core import faults

    use_bounds = (radii is not None and sizes is not None
                  and metric != DistanceType.InnerProduct)
    return _plan_impl(
        jnp.asarray(queries),
        jnp.zeros((1, 1), jnp.float32) if rotation is None
        else jnp.asarray(rotation),
        jnp.asarray(centers),
        jnp.zeros((centers.shape[0],), jnp.float32) if radii is None
        else jnp.asarray(radii, jnp.float32),
        jnp.zeros((centers.shape[0],), jnp.int32) if sizes is None
        else jnp.asarray(sizes, jnp.int32),
        jnp.float32(tau), jnp.int32(min_probes),
        int(n_probes), int(k), metric, rotation is not None,
        use_bounds, fault_key=faults.trace_key(),
    )


# ---------------------------------------------------------------------------
# build-time list radii
# ---------------------------------------------------------------------------


@jax.jit
def _flat_radii_impl(list_data, slot_rows, centers):
    d2 = jnp.sum(
        (list_data.astype(jnp.float32) - centers[:, None, :]) ** 2, axis=2
    )
    d2 = jnp.where(slot_rows >= 0, d2, 0.0)
    return jnp.sqrt(jnp.max(d2, axis=1))


def list_radii_from_store(list_data, slot_rows, centers) -> jax.Array:
    """(n_lists,) f32 max member distance to its centroid, from a
    padded list-major store — the one-pass build-time derivation
    (IVF-Flat; empty lists get radius 0)."""
    return _flat_radii_impl(list_data, slot_rows, centers)


@jax.jit
def _aux_radii_impl(aux, slot_rows):
    rn = jnp.where(slot_rows >= 0, aux[..., 0], 0.0)
    return jnp.max(rn, axis=1)


def list_radii_from_aux(aux, slot_rows) -> jax.Array:
    """(n_lists,) f32 radii for IVF-RaBitQ: the aux table already
    stores each member's residual norm |r| (its distance to the
    centroid in rotated space), so radii are a free per-list max."""
    return _aux_radii_impl(aux, slot_rows)


def updated_radii(old_radii, labels: np.ndarray, dists: np.ndarray,
                  n_lists: int):
    """Incremental extend-time radius update: per-list max of the new
    batch's center distances folded into the existing radii. ``None``
    old radii on a non-empty index stay None (an old checkpoint without
    stored bounds cannot recover them from a batch — fallback persists,
    by design)."""
    if old_radii is None:
        return None
    new = np.asarray(old_radii, np.float32).copy()
    if len(labels):
        np.maximum.at(new, np.asarray(labels, np.int64),
                      np.asarray(dists, np.float32))
    return jnp.asarray(new)


# ---------------------------------------------------------------------------
# truthful accounting
# ---------------------------------------------------------------------------


def account(engine: str, scanned: jax.Array, nq: int,
            n_probes: int) -> Optional[float]:
    """Land one batch's ACTUAL scanned-list totals in the obs registry
    (`ivf.scanned_lists` counter + `ivf.budget_hist` histogram of the
    per-query counts, with the worst-case total alongside so the saving
    is readable straight off a snapshot) and return the per-query mean
    the cost model should charge instead of worst-case ``n_probes``.

    With obs disabled this is a NO-OP returning None (the mean's only
    consumer is the obs span-cost charge): materializing the counts
    would block the host on the device plan for nothing — a pure
    pipeline stall on the serving hot path."""
    from raft_tpu import obs

    if not obs.enabled():
        return None
    counts = np.asarray(scanned)
    total = int(counts.sum())
    mean = float(total) / max(1, int(nq))
    obs.counter("ivf.scanned_lists").inc(total)
    obs.counter("ivf.scanned_lists_worst_case").inc(int(nq) * int(n_probes))
    hist = obs.histogram("ivf.budget_hist")
    vals, reps = np.unique(counts, return_counts=True)
    for v, r in zip(vals, reps):
        hist.observe_n(float(v), int(r))  # one locked update per value
    obs.event("probe_budget", engine=engine, queries=int(nq),
              scanned_lists=total, worst_case=int(nq) * int(n_probes))
    return mean
