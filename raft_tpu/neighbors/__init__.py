"""Nearest-neighbor search: brute-force, IVF-Flat, IVF-PQ, refinement.

TPU-native equivalent of `cpp/include/raft/neighbors/` (survey §2.9).
Submodules mirror pylibraft.neighbors.
"""

from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors import ivf_rabitq
from raft_tpu.neighbors import quantizer
from raft_tpu.neighbors import ball_cover
from raft_tpu.neighbors.refine import refine
from raft_tpu.neighbors import batch_loader
from raft_tpu.neighbors.batch_loader import BatchLoadIterator
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors
from raft_tpu.neighbors.ann_types import IndexParamsBase, SearchParamsBase

__all__ = [
    "brute_force",
    "batch_loader",
    "BatchLoadIterator",
    "ivf_flat",
    "ivf_pq",
    "ivf_rabitq",
    "quantizer",
    "ball_cover",
    "refine",
    "eps_neighbors",
    "IndexParamsBase",
    "SearchParamsBase",
]
