"""Brute-force (exact) k-nearest neighbors.

Reference parity: `raft::neighbors::brute_force::knn` (neighbors/brute_force.cuh:148),
the tiled engine `tiled_brute_force_knn` (detail/knn_brute_force.cuh:51) and
`knn_merge_parts` (neighbors/brute_force.cuh:80, detail/knn_merge_parts.cuh);
pylibraft `neighbors.brute_force.knn`.

TPU design: stream the database through in column tiles. Each tile computes a
(q, tile) distance block (MXU matmul for expanded metrics) and immediately
reduces it to a running top-k carried through a `lax.scan` — distance
materialization is bounded by the tile size, exactly the role of the
reference's tiling + warpsort queue merging, but expressed functionally so
XLA can overlap the matmul of tile t+1 with the top-k of tile t.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_types import DistanceType, resolve_metric, SIMILARITY_METRICS
from raft_tpu.distance.pairwise import _pairwise_impl
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu import obs
from raft_tpu.core.config import auto_convert_output

# database rows per tile in the scanned path
_TILE = 1 << 15


@functools.partial(
    jax.jit, static_argnums=(2, 3), static_argnames=("k", "metric", "metric_arg", "tile")
)
def _bf_knn_impl(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    metric: DistanceType,
    *,
    metric_arg: float = 2.0,
    tile: int = _TILE,
    n_valid=None,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """`n_valid` (may be a traced scalar): rows at or past it are masked
    to the worst value BEFORE selection — masking after a top-k lets pad
    rows displace true neighbors out of the selection entirely (zero pads
    sit closer to many queries than real far-away rows). `prefilter`
    (core.bitset.Bitset over dataset row ids, a pytree arg) masks
    filtered-out rows the same way, also before selection."""
    n = dataset.shape[0]
    select_min = metric not in SIMILARITY_METRICS
    worst = jnp.inf if select_min else -jnp.inf

    if n <= max(2 * tile, 4 * k):
        d = _pairwise_impl(queries, dataset, metric, metric_arg=metric_arg)
        if n_valid is not None:
            d = jnp.where(jnp.arange(n)[None, :] < n_valid, d, worst)
        if prefilter is not None:
            d = jnp.where(prefilter.test(jnp.arange(n))[None, :], d, worst)
        vals, idx = _select_k_impl(d, k, select_min)
        return vals, idx.astype(jnp.int32)

    ntiles = -(-n // tile)
    pad = ntiles * tile - n
    if pad:
        padval = jnp.full((pad, dataset.shape[1]), 0, dataset.dtype)
        dataset = jnp.concatenate([dataset, padval], axis=0)
    tiles = dataset.reshape(ntiles, tile, dataset.shape[1])
    q = queries.shape[0]
    limit = n if n_valid is None else jnp.minimum(n_valid, n)

    def step(carry, inp):
        best_v, best_i = carry
        t, dtile = inp
        d = _pairwise_impl(queries, dtile, metric, metric_arg=metric_arg)
        base = t * tile
        if pad or n_valid is not None or prefilter is not None:
            col = jnp.arange(tile) + base
            keep = col[None, :] < limit
            if prefilter is not None:
                keep = keep & prefilter.test(col)[None, :]
            d = jnp.where(keep, d, worst)
        v, i = _select_k_impl(d, min(k, tile), select_min)
        i = i.astype(jnp.int32) + base
        # merge running queue with tile candidates (knn_merge_parts)
        cat_v = jnp.concatenate([best_v, v], axis=1)
        cat_i = jnp.concatenate([best_i, i], axis=1)
        mv, mi = _select_k_impl(cat_v, k, select_min)
        return (mv, jnp.take_along_axis(cat_i, mi, axis=1)), None

    init = (
        jnp.full((q, k), worst, jnp.float32),
        jnp.full((q, k), -1, jnp.int32),
    )
    (vals, idx), _ = lax.scan(step, init, (jnp.arange(ntiles), tiles))
    return vals, idx

@obs.spanned("neighbors.brute_force.knn")
@auto_convert_output
def knn(
    dataset,
    queries,
    k: int,
    metric="sqeuclidean",
    metric_arg: float = 2.0,
    resources=None,
    engine: str = "tiled",
    prefilter=None,
    compute_dtype=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN: returns (distances, indices), each (n_queries, k),
    sorted best-first. pylibraft-compatible (neighbors/brute_force.pyx).

    `engine`: "tiled" (default — XLA pairwise tiles + select_k),
    "pallas"/"fused" — the fused distance+select-k scan (the
    fused_l2_knn analogue, spatial/knn/detail/fused_l2_knn.cuh), a thin
    wrapper over `matrix.scan_select_k(strategy="fused")`
    (ops/fused_scan.py): one Pallas kernel scores bf16 tiles on the MXU
    and keeps the per-query candidate buffer in VMEM, so the
    (nq, n) score matrix never touches HBM. EXACT over the
    bf16-rounded operands (ties to the smaller row id) — the same
    rounding trade as compute_dtype=bfloat16;
    L2/sqeuclidean/inner_product only, k <= 256 — or "auto", which
    resolves through the tuned `select_k_strategy` dispatch policy.

    `compute_dtype`: optional dtype the operands are cast to before the
    distance computation (accumulation stays f32). `jnp.bfloat16` takes
    ONE MXU pass where f32 inputs need the six-pass HIGHEST mode —
    several times faster — at the cost of ranking the bf16-rounded
    points: neighbors whose true distance gap is below bf16 noise may
    swap (measured recall@10 ~0.99 on 1M x 96 gaussian blobs). The
    reference's half-precision instantiations make the same trade
    (detail/knn_brute_force.cuh's half specializations).

    `prefilter`: optional `core.bitset.Bitset` (or 1-D boolean mask)
    over dataset row ids — rows whose bit is clear are excluded BEFORE
    selection (sample-filtering parity with later RAFT's
    `search_with_filtering`). When fewer than k rows pass, the tail
    holds the worst distance with index -1.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import brute_force
    >>> data = np.array([[0.0], [1.0], [10.0]])
    >>> d, i = brute_force.knn(data, np.array([[0.9]]), k=2)
    >>> np.asarray(i).tolist()
    [[1, 0]]
    """
    from raft_tpu.core.validation import check_matrix, check_same_cols

    ds = check_matrix(dataset, name="dataset")
    q = check_matrix(queries, name="queries")
    check_same_cols(ds, q, "dataset", "queries")
    if engine == "fused":
        engine = "pallas"  # one fused engine, two spellings
    if compute_dtype is not None:
        if engine == "pallas":
            # the fused kernel already computes in bf16; pre-rounding
            # the operands would only degrade recall with no speed gain
            raise ValueError(
                "compute_dtype applies to engine='tiled' only "
                "(engine='pallas' already computes in bf16)"
            )
        ds = ds.astype(compute_dtype)
        q = q.astype(compute_dtype)
    if not (0 < k <= ds.shape[0]):
        raise ValueError(f"k={k} out of range for dataset with {ds.shape[0]} rows")
    m = resolve_metric(metric)
    if engine == "auto":
        # route the engine decision through the one dispatch policy
        # (matrix.select_k): the tuned `select_k_strategy` winner picks
        # the fused scan when the kernel fits this geometry
        from raft_tpu.matrix.select_k import (
            _fused_metric_kind, resolve_scan_strategy,
        )

        strat = resolve_scan_strategy(
            int(ds.shape[0]), int(ds.shape[1]), int(k), None,
            fused_ok=_fused_metric_kind(m) is not None
            and compute_dtype is None,
        )
        engine = "pallas" if strat == "fused" else "tiled"
    if engine not in ("tiled", "pallas"):
        raise ValueError(f"unknown engine {engine!r}")
    if obs.enabled():
        # the fused engine never materializes the score matrix: charge
        # the fused geometry so banked MFU reflects the fusion
        obs.span_cost(**obs.perf.cost_for(
            "neighbors.brute_force.knn", n=int(ds.shape[0]),
            nq=int(q.shape[0]), d=int(ds.shape[1]), k=int(k),
            dtype=jnp.bfloat16 if engine == "pallas" else ds.dtype,
            fused=engine == "pallas"))
    pf = None
    if prefilter is not None:
        from raft_tpu.core.bitset import as_bitset

        pf = as_bitset(prefilter, ds.shape[0])
    if engine == "pallas":
        vals, idx = _bf_fused_pallas(ds, q, int(k), m, prefilter=pf)
    else:
        vals, idx = _bf_knn_impl(
            ds, q, int(k), m, metric_arg=float(metric_arg), prefilter=pf
        )
    if pf is not None:
        # fewer than k rows may pass the filter: a worst-scored slot can
        # still carry a masked row's id out of the tie — re-test returned
        # ids against the bitset (score-based detection would also clobber
        # a surviving row whose true distance overflows to inf)
        idx = jnp.where(pf.test(idx), idx, -1)
    if resources is not None:
        resources.track(vals, idx)
    return vals, idx


def _bf_fused_pallas(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    metric: DistanceType,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Thin wrapper over the one dispatch door: the fused scan IS
    `matrix.scan_select_k(strategy="fused")` (ops/fused_scan.py). The
    old residual-chunked reuse of the IVF list-scan engine is gone —
    the flat fused kernel streams the dataset directly, is exact over
    the bf16-rounded operands, and returns (values, ids) without the
    score matrix ever touching HBM."""
    from raft_tpu.matrix.select_k import _fused_metric_kind, scan_select_k

    if _fused_metric_kind(metric) is None:
        raise ValueError(
            f"engine='pallas' supports L2/inner_product metrics, got {metric}"
        )
    valid = None
    if prefilter is not None:
        # a (n,) mask IS the whole filtering mechanism: masked rows
        # score +inf before the in-kernel selection
        valid = prefilter.test(jnp.arange(dataset.shape[0]))
    vals, idx = scan_select_k(
        queries, dataset, int(k), metric=metric, strategy="fused",
        valid=valid,
    )
    return vals, idx.astype(jnp.int32)


@obs.spanned("neighbors.brute_force.knn_merge_parts")
def knn_merge_parts(
    distances,
    indices,
    k: Optional[int] = None,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part top-k results into a global top-k.

    Parity with `knn_merge_parts` (neighbors/brute_force.cuh:80): inputs are
    (n_parts, n_queries, k_part) stacks or (n_queries, n_parts*k_part)
    concatenations of per-shard results whose indices are already global.
    """
    d = jnp.asarray(distances)
    i = jnp.asarray(indices)
    if d.ndim == 3:
        n_parts, n_q, kp = d.shape
        d = jnp.moveaxis(d, 0, 1).reshape(n_q, n_parts * kp)
        i = jnp.moveaxis(i, 0, 1).reshape(n_q, n_parts * kp)
    k = d.shape[1] if k is None else k
    v, sel = _select_k_impl(d, int(k), bool(select_min))
    return v, jnp.take_along_axis(i, sel, axis=1)
