"""Brute-force (exact) k-nearest neighbors.

Reference parity: `raft::neighbors::brute_force::knn` (neighbors/brute_force.cuh:148),
the tiled engine `tiled_brute_force_knn` (detail/knn_brute_force.cuh:51) and
`knn_merge_parts` (neighbors/brute_force.cuh:80, detail/knn_merge_parts.cuh);
pylibraft `neighbors.brute_force.knn`.

TPU design: stream the database through in column tiles. Each tile computes a
(q, tile) distance block (MXU matmul for expanded metrics) and immediately
reduces it to a running top-k carried through a `lax.scan` — distance
materialization is bounded by the tile size, exactly the role of the
reference's tiling + warpsort queue merging, but expressed functionally so
XLA can overlap the matmul of tile t+1 with the top-k of tile t.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_types import DistanceType, resolve_metric, SIMILARITY_METRICS
from raft_tpu.distance.pairwise import _pairwise_impl
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu import obs
from raft_tpu.core.config import auto_convert_output

# database rows per tile in the scanned path
_TILE = 1 << 15


@functools.partial(
    jax.jit, static_argnums=(2, 3), static_argnames=("k", "metric", "metric_arg", "tile")
)
def _bf_knn_impl(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    metric: DistanceType,
    *,
    metric_arg: float = 2.0,
    tile: int = _TILE,
    n_valid=None,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """`n_valid` (may be a traced scalar): rows at or past it are masked
    to the worst value BEFORE selection — masking after a top-k lets pad
    rows displace true neighbors out of the selection entirely (zero pads
    sit closer to many queries than real far-away rows). `prefilter`
    (core.bitset.Bitset over dataset row ids, a pytree arg) masks
    filtered-out rows the same way, also before selection."""
    n = dataset.shape[0]
    select_min = metric not in SIMILARITY_METRICS
    worst = jnp.inf if select_min else -jnp.inf

    if n <= max(2 * tile, 4 * k):
        d = _pairwise_impl(queries, dataset, metric, metric_arg=metric_arg)
        if n_valid is not None:
            d = jnp.where(jnp.arange(n)[None, :] < n_valid, d, worst)
        if prefilter is not None:
            d = jnp.where(prefilter.test(jnp.arange(n))[None, :], d, worst)
        vals, idx = _select_k_impl(d, k, select_min)
        return vals, idx.astype(jnp.int32)

    ntiles = -(-n // tile)
    pad = ntiles * tile - n
    if pad:
        padval = jnp.full((pad, dataset.shape[1]), 0, dataset.dtype)
        dataset = jnp.concatenate([dataset, padval], axis=0)
    tiles = dataset.reshape(ntiles, tile, dataset.shape[1])
    q = queries.shape[0]
    limit = n if n_valid is None else jnp.minimum(n_valid, n)

    def step(carry, inp):
        best_v, best_i = carry
        t, dtile = inp
        d = _pairwise_impl(queries, dtile, metric, metric_arg=metric_arg)
        base = t * tile
        if pad or n_valid is not None or prefilter is not None:
            col = jnp.arange(tile) + base
            keep = col[None, :] < limit
            if prefilter is not None:
                keep = keep & prefilter.test(col)[None, :]
            d = jnp.where(keep, d, worst)
        v, i = _select_k_impl(d, min(k, tile), select_min)
        i = i.astype(jnp.int32) + base
        # merge running queue with tile candidates (knn_merge_parts)
        cat_v = jnp.concatenate([best_v, v], axis=1)
        cat_i = jnp.concatenate([best_i, i], axis=1)
        mv, mi = _select_k_impl(cat_v, k, select_min)
        return (mv, jnp.take_along_axis(cat_i, mi, axis=1)), None

    init = (
        jnp.full((q, k), worst, jnp.float32),
        jnp.full((q, k), -1, jnp.int32),
    )
    (vals, idx), _ = lax.scan(step, init, (jnp.arange(ntiles), tiles))
    return vals, idx

@obs.spanned("neighbors.brute_force.knn")
@auto_convert_output
def knn(
    dataset,
    queries,
    k: int,
    metric="sqeuclidean",
    metric_arg: float = 2.0,
    resources=None,
    engine: str = "tiled",
    prefilter=None,
    compute_dtype=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN: returns (distances, indices), each (n_queries, k),
    sorted best-first. pylibraft-compatible (neighbors/brute_force.pyx).

    `engine`: "tiled" (default — XLA pairwise tiles + select_k) or
    "pallas" — the fused scan (the fused_l2_knn analogue,
    spatial/knn/detail/fused_l2_knn.cuh): the dataset streams as
    sequential bf16 residual chunks through the fused list-scan kernel,
    so score tiles never round-trip HBM. Candidate trimming makes it
    near-exact, not exact (same bin-trim loss class as the IVF pallas
    engines); L2/sqeuclidean/inner_product only, k <= 256.

    `compute_dtype`: optional dtype the operands are cast to before the
    distance computation (accumulation stays f32). `jnp.bfloat16` takes
    ONE MXU pass where f32 inputs need the six-pass HIGHEST mode —
    several times faster — at the cost of ranking the bf16-rounded
    points: neighbors whose true distance gap is below bf16 noise may
    swap (measured recall@10 ~0.99 on 1M x 96 gaussian blobs). The
    reference's half-precision instantiations make the same trade
    (detail/knn_brute_force.cuh's half specializations).

    `prefilter`: optional `core.bitset.Bitset` (or 1-D boolean mask)
    over dataset row ids — rows whose bit is clear are excluded BEFORE
    selection (sample-filtering parity with later RAFT's
    `search_with_filtering`). When fewer than k rows pass, the tail
    holds the worst distance with index -1.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import brute_force
    >>> data = np.array([[0.0], [1.0], [10.0]])
    >>> d, i = brute_force.knn(data, np.array([[0.9]]), k=2)
    >>> np.asarray(i).tolist()
    [[1, 0]]
    """
    from raft_tpu.core.validation import check_matrix, check_same_cols

    ds = check_matrix(dataset, name="dataset")
    q = check_matrix(queries, name="queries")
    check_same_cols(ds, q, "dataset", "queries")
    if compute_dtype is not None:
        if engine == "pallas":
            # the fused store is already bf16 internally; pre-rounding
            # the operands would only degrade recall with no speed gain
            raise ValueError(
                "compute_dtype applies to engine='tiled' only "
                "(engine='pallas' already streams a bf16 store)"
            )
        ds = ds.astype(compute_dtype)
        q = q.astype(compute_dtype)
    if not (0 < k <= ds.shape[0]):
        raise ValueError(f"k={k} out of range for dataset with {ds.shape[0]} rows")
    if obs.enabled():
        obs.span_cost(**obs.perf.cost_for(
            "neighbors.brute_force.knn", n=int(ds.shape[0]),
            nq=int(q.shape[0]), d=int(ds.shape[1]), k=int(k),
            dtype=ds.dtype))
    m = resolve_metric(metric)
    if engine not in ("tiled", "pallas"):
        raise ValueError(f"unknown engine {engine!r}")
    pf = None
    if prefilter is not None:
        from raft_tpu.core.bitset import as_bitset

        pf = as_bitset(prefilter, ds.shape[0])
    if engine == "pallas":
        vals, idx = _bf_fused_pallas(ds, q, int(k), m, prefilter=pf)
    else:
        vals, idx = _bf_knn_impl(
            ds, q, int(k), m, metric_arg=float(metric_arg), prefilter=pf
        )
    if pf is not None:
        # fewer than k rows may pass the filter: a worst-scored slot can
        # still carry a masked row's id out of the tie — re-test returned
        # ids against the bitset (score-based detection would also clobber
        # a surviving row whose true distance overflows to inf)
        idx = jnp.where(pf.test(idx), idx, -1)
    if resources is not None:
        resources.track(vals, idx)
    return vals, idx


def _bf_fused_pallas(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    metric: DistanceType,
    list_size: int = 8192,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused brute-force scan: the dataset is split into sequential
    chunks that play the role of IVF lists (every query "probes" every
    chunk), each chunk stored as bf16 residuals against its own mean —
    any per-list center keeps |q-v|^2 = |q'|^2 - 2 q'.res + |res|^2
    exact, and residual magnitudes keep bf16 precise. Reuses the IVF
    list-scan engine end to end (kernel, probe inversion, merge)."""
    from raft_tpu.neighbors.ivf_flat import _search_impl_listmajor_pallas
    from raft_tpu.neighbors.probe_invert import macro_batched
    from raft_tpu.ops.pq_list_scan import _BINS, fits_pallas, lane_padded

    if metric not in (
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.L2Unexpanded,
        DistanceType.L2SqrtUnexpanded,
        DistanceType.InnerProduct,
    ):
        raise ValueError(
            f"engine='pallas' supports L2/inner_product metrics, got {metric}"
        )
    if k > _BINS:
        raise ValueError(f"engine='pallas' caps k at {_BINS}; k={k}")
    n, d = dataset.shape
    # lane_padded applies the kernel's >= _BINS floor (small datasets
    # would otherwise flunk fits_pallas with a misleading VMEM error)
    list_size = lane_padded(min(list_size, n))
    if not fits_pallas(128, list_size, d, store_itemsize=2):
        raise ValueError(
            f"engine='pallas' VMEM envelope exceeded (list_size={list_size}, dim={d})"
        )
    n_lists = -(-n // list_size)
    centers, resid, resid_norm, slot_rows = _bf_fused_store(
        dataset, n_lists, list_size
    )
    if prefilter is not None:
        # the engine masks scores to +inf wherever the slot table reads
        # -1 (before the in-kernel bin trim), so a filtered view is the
        # whole filtering mechanism; slots hold dataset row ids directly
        from raft_tpu.core.bitset import filter_slot_table

        slot_rows = filter_slot_table(slot_rows, None, prefilter)
    interpret = jax.default_backend() == "cpu"  # Mosaic needs TPU
    want_sqrt = metric in (
        DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded
    )
    inner_metric = (
        DistanceType.InnerProduct
        if metric == DistanceType.InnerProduct
        else (DistanceType.L2SqrtExpanded if want_sqrt else DistanceType.L2Expanded)
    )
    return macro_batched(
        lambda sl: _search_impl_listmajor_pallas(
            sl, centers, resid, resid_norm, slot_rows, k, n_lists,
            inner_metric, interpret=interpret,
        ),
        jnp.asarray(queries, jnp.float32),
        int(k),
    )


@functools.partial(jax.jit, static_argnames=("n_lists", "list_size"))
def _bf_fused_store(dataset: jax.Array, n_lists: int, list_size: int):
    """One fused XLA program building the chunked residual store (pad,
    reshape, per-chunk mean, bf16 residuals, norms, slot ids) — repeated
    knn() calls over the same dataset shape reuse the compilation."""
    n, d = dataset.shape
    npad = n_lists * list_size - n
    ds = jnp.pad(dataset.astype(jnp.float32), ((0, npad), (0, 0)))
    store = ds.reshape(n_lists, list_size, d)
    slot_rows = jnp.arange(n_lists * list_size, dtype=jnp.int32).reshape(
        n_lists, list_size
    )
    slot_rows = jnp.where(slot_rows < n, slot_rows, -1)
    centers = jnp.mean(store, axis=1)
    resid = store - centers[:, None, :]
    resid_norm = jnp.sum(resid * resid, axis=2)
    return centers, resid.astype(jnp.bfloat16), resid_norm, slot_rows


@obs.spanned("neighbors.brute_force.knn_merge_parts")
def knn_merge_parts(
    distances,
    indices,
    k: Optional[int] = None,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part top-k results into a global top-k.

    Parity with `knn_merge_parts` (neighbors/brute_force.cuh:80): inputs are
    (n_parts, n_queries, k_part) stacks or (n_queries, n_parts*k_part)
    concatenations of per-shard results whose indices are already global.
    """
    d = jnp.asarray(distances)
    i = jnp.asarray(indices)
    if d.ndim == 3:
        n_parts, n_q, kp = d.shape
        d = jnp.moveaxis(d, 0, 1).reshape(n_q, n_parts * kp)
        i = jnp.moveaxis(i, 0, 1).reshape(n_q, n_parts * kp)
    k = d.shape[1] if k is None else k
    v, sel = _select_k_impl(d, int(k), bool(select_min))
    return v, jnp.take_along_axis(i, sel, axis=1)
