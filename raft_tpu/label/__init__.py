"""Label utilities.

TPU-native equivalent of `cpp/include/raft/label/` (survey §2.12):
`getUniquelabels`/`make_monotonic` (label/classlabels.cuh) and
`merge_labels` (label/merge_labels.cuh).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def get_unique_labels(labels) -> jax.Array:
    """Sorted unique labels (classlabels.cuh getUniquelabels)."""
    return jnp.unique(jnp.asarray(labels))


def make_monotonic(labels, ignore_value: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """Remap labels to 0..n_unique-1 preserving order (classlabels.cuh
    make_monotonic). Returns (monotonic_labels, unique_values).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.label import make_monotonic
    >>> mono, uniq = make_monotonic(np.array([30, 10, 30, 20]))
    >>> np.asarray(mono).tolist(), np.asarray(uniq).tolist()
    ([2, 0, 2, 1], [10, 20, 30])

    Host numpy integer inputs route through the native C++ path (one
    sort+dedup pass) when available; device inputs stay on device."""
    if (
        ignore_value is None
        and isinstance(labels, np.ndarray)
        and np.issubdtype(labels.dtype, np.integer)
    ):
        from raft_tpu import native

        packed = native.make_monotonic(labels)
        if packed is not None:
            mono, uniq = packed
            return jnp.asarray(mono, jnp.int32), jnp.asarray(uniq)
    l = jnp.asarray(labels)
    uniq = jnp.unique(l)
    if ignore_value is not None:
        uniq = uniq[uniq != ignore_value]
    mono = jnp.searchsorted(uniq, l)
    if ignore_value is not None:
        mono = jnp.where(l == ignore_value, ignore_value, mono)
    return mono.astype(jnp.int32), uniq


def merge_labels(labels_a, labels_b, mask=None, max_iter: Optional[int] = None) -> jax.Array:
    """Union-find-style merge of two labelings (merge_labels.cuh): connected
    labels (sharing any point) collapse to their minimum representative.

    The reference iterates a min-propagation kernel to a fixed point; here a
    lax.while_loop propagates per-point minima through both labelings until
    stable — same algorithm, deterministic, jit-compiled.
    """
    a = jnp.asarray(labels_a).astype(jnp.int32)
    b = jnp.asarray(labels_b).astype(jnp.int32)
    n = a.shape[0]
    na = int(jnp.max(a)) + 1 if n else 1
    nb = int(jnp.max(b)) + 1 if n else 1
    m = jnp.ones((n,), bool) if mask is None else jnp.asarray(mask, bool)
    # current label value per point starts as a
    cur = a.astype(jnp.float32)
    big = jnp.inf

    def seg_min(vals, keys, num):
        return jax.ops.segment_min(jnp.where(m, vals, big), keys, num_segments=num)

    def body(state):
        cur, _ = state
        ra = seg_min(cur, a, na)  # min label value per a-group
        cur1 = jnp.where(m, jnp.minimum(cur, ra[a]), cur)
        rb = seg_min(cur1, b, nb)
        cur2 = jnp.where(m, jnp.minimum(cur1, rb[b]), cur1)
        changed = jnp.any(cur2 != cur)
        return cur2, changed

    def cond(state):
        return state[1]

    cur, _ = lax.while_loop(cond, body, (cur, jnp.array(True)))
    return cur.astype(jnp.int32)
