"""Exporters: JSON snapshots, Prometheus exposition text, profiler
trace sessions.

Three consumers, three formats:
  - `snapshot()` / `save_snapshot()`: the machine-readable joined view
    (registry metrics + bus events) a test asserts on and
    `python -m raft_tpu.obs.report` renders for humans;
  - `render_prometheus()`: flat `name value` exposition text for a
    scrape endpoint — ONE formatter, shared with
    `serve.metrics.ServerMetrics.render_text` so the two surfaces can't
    drift (the pre-obs ServerMetrics carried its own copy);
  - `trace_session()`: a `jax.profiler.trace` wrapper so "give me a TPU
    timeline for this block" is one line next to the span API instead
    of profiler boilerplate.
"""

from __future__ import annotations

import contextlib
import json
import re
from typing import Optional

from raft_tpu.obs import bus as _bus_mod
from raft_tpu.obs import registry as _reg_mod

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def snapshot(registry: Optional[_reg_mod.Registry] = None,
             bus: Optional[_bus_mod.EventBus] = None,
             rank: Optional[int] = None,
             world: Optional[int] = None,
             label: Optional[str] = None) -> dict:
    """Joined point-in-time view: {"metrics": ..., "events": [...],
    "platform": ...} plus optional rank/world/label identity fields (the
    per-rank captures `obs.report --merge` aligns).

    Ordering is deterministic — metrics sort by name, events by seq —
    so two runs of the same seeded drill differ only in clock fields
    ("t", "dur_s", histogram timing aggregates), which tests strip. The
    embedded platform record (obs.perf.platform_info) pins which peak
    table any MFU derived from this snapshot was computed against.
    """
    reg = registry if registry is not None else _reg_mod.GLOBAL
    b = bus if bus is not None else _bus_mod.GLOBAL
    snap = {"metrics": reg.snapshot(), "events": b.events()}
    try:
        from raft_tpu.obs import perf as _perf

        snap["platform"] = _perf.platform_info()
    except Exception:  # pragma: no cover - defensive
        pass
    if rank is not None:
        snap["rank"] = int(rank)
    if world is not None:
        snap["world"] = int(world)
    if label is not None:
        snap["label"] = str(label)
    return snap


def save_snapshot(path: str, **kwargs) -> dict:
    """Write `snapshot()` to `path` as JSON; returns the snapshot.
    The write is atomic (tmp + rename): a reader can never observe a
    torn snapshot, and a crash mid-write leaves any previous snapshot
    intact — the contract every obs JSON writer honors (machine-checked
    by raftlint's `hygiene-obs-torn-write`)."""
    snap = snapshot(**kwargs)
    from raft_tpu.core.serialize import atomic_write

    with atomic_write(path) as tmp:
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=1, default=repr)
    return snap


def prom_name(name: str, prefix: str = "") -> str:
    """Sanitize a dotted metric name into the Prometheus charset."""
    return _NAME_OK.sub("_", prefix + name)


def _prom_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return f"{v:.6g}"
    raise TypeError(f"non-numeric metric value {v!r}")


def render_prometheus(values: dict, prefix: str = "raft_tpu_") -> str:
    """Flat dict -> Prometheus exposition text (`name value` lines,
    sorted by name; None values are skipped — exposition has no null).
    NaN renders as `nan`, which Prometheus' float parser accepts."""
    lines = []
    for key in sorted(values):
        val = values[key]
        if val is None:
            continue
        lines.append(f"{prom_name(key, prefix)} {_prom_value(val)}")
    return "\n".join(lines) + "\n"


def render_registry_prometheus(registry: Optional[_reg_mod.Registry] = None,
                               prefix: str = "raft_tpu_") -> str:
    """The whole registry as exposition text: counters and gauges as-is,
    histograms as real Prometheus histogram families — cumulative
    `<name>_bucket{le="..."}` series plus `<name>_sum`/`<name>_count` —
    with the `min`/`max`/`mean`/`last` aggregates kept as companion
    gauges, and collector sections under `<collector>_<key>`."""
    reg = registry if registry is not None else _reg_mod.GLOBAL
    snap = reg.snapshot()
    flat = {}
    flat.update(snap["counters"])
    flat.update(snap["gauges"])
    for cname, section in snap.get("collectors", {}).items():
        if not isinstance(section, dict):
            continue
        for key, v in section.items():
            if isinstance(v, (int, float, bool)):
                flat[f"{cname}.{key}"] = v
    bucket_lines = []
    # each histogram family comes from ONE locked read (export_state) so
    # its _count/_sum can never disagree with its _bucket{+Inf} under a
    # concurrent observe — Prometheus scrape-atomicity per family
    for name, hist in reg.histogram_items():
        agg, buckets = hist.export_state()
        for stat, v in agg.items():
            # Prometheus histogram convention: the observation total is
            # the `_sum` series (the aggregate dict calls it "total")
            flat[f"{name}.{'sum' if stat == 'total' else stat}"] = v
        base = prom_name(f"{name}.bucket", prefix)
        bucket_lines.extend(f'{base}{{le="{le}"}} {n}'
                            for le, n in buckets)
    lines = render_prometheus(flat, prefix).splitlines()
    return "\n".join(lines + bucket_lines) + "\n"


@contextlib.contextmanager
def trace_session(logdir: str, create_perfetto_link: bool = False):
    """Profiler trace session: everything inside the block lands in a
    `jax.profiler` trace under `logdir` (viewable with TensorBoard /
    Perfetto). Composes with spans — `trace_range` names show up inside
    the captured timeline.

        with obs.trace_session("/tmp/tb"):
            ivf_flat.search(p, index, q, k=10)
    """
    import jax

    with jax.profiler.trace(logdir, create_perfetto_link=create_perfetto_link):
        yield logdir
