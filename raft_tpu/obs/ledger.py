"""Append-only bench ledger: the perf trajectory, one JSON line per row.

BENCH_*.json files are per-run snapshots that later runs overwrite; the
repo has "flown blind on perf for 5 PRs" exactly because overwriting
leaves no history to compare against (ROADMAP open item 5a). The ledger
is the fix: `bench/common.Banker` appends every banked row here —
including honest in-process-CPU fallback rows — stamped with the git
SHA, platform, and whatever span-phase / MFU attribution the row
carries, so `tools/perfgate` can hold every future PR's fresh numbers
against a rolling baseline.

File discipline:
  - append-only JSONL (one `json.dumps` line per entry, O_APPEND
    semantics via mode "a"); a torn final line from a killed process
    must never poison the file — `read()` skips unparseable lines.
  - `RAFT_TPU_BENCH_LEDGER` overrides the path (CI's perf tier points
    it at a temp file so hermetic runs don't pollute the repo ledger).
  - entries never carry absolute paths or host identity — the ledger is
    committed, and committed artifacts stay machine-portable.

This module is obs-layer: stdlib at module scope (jax only inside the
guarded `sniff_platform`), and no bench import — the measurement layer
reads the library, never the reverse; raftlint's layer-purity rule
seals that direction.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import List, Optional

#: env override for the ledger path (CI temp ledgers, tests)
ENV_PATH = "RAFT_TPU_BENCH_LEDGER"

#: default file name, resolved against a caller-provided directory
#: (Banker passes the directory its results file lives in — the repo
#: root for every in-tree bench)
DEFAULT_NAME = "BENCH_LEDGER.jsonl"


def resolve_path(default_dir: Optional[str] = None) -> str:
    """The ledger path: `RAFT_TPU_BENCH_LEDGER` when set, else
    DEFAULT_NAME under `default_dir` (or the working directory)."""
    env = os.environ.get(ENV_PATH, "").strip()
    if env:
        return env
    return os.path.join(default_dir or os.getcwd(), DEFAULT_NAME)


def git_sha(repo_dir: Optional[str] = None) -> str:
    """Short git SHA of `repo_dir` (or cwd); "unknown" when git is
    unavailable — a ledger row beats a crashed bench."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10.0,
            cwd=repo_dir or None,
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else "unknown"
    except Exception:
        return "unknown"


def make_entry(*, bench: str, row: dict, platform: Optional[str] = None,
               sha: Optional[str] = None, repo_dir: Optional[str] = None,
               **tags) -> dict:
    """One ledger entry: identity fields first (sha / utc / platform /
    bench / honesty tags), the banked row nested under "row" so bench
    row keys can never collide with ledger bookkeeping."""
    entry = {
        "sha": sha if sha is not None else git_sha(repo_dir),
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": platform or "unknown",
        "bench": str(bench),
    }
    for key, val in sorted(tags.items()):
        if val is not None:
            entry[key] = val
    entry["row"] = dict(row)
    return entry


def append(entry: dict, path: Optional[str] = None,
           default_dir: Optional[str] = None) -> str:
    """Append one entry as a JSON line; returns the path written. The
    write is a single buffered line in append mode — concurrent bench
    processes interleave whole lines, never halves of two. A torn final
    line (a SIGKILL mid-append left no trailing newline) is terminated
    first, so the dead process's half-row corrupts only itself, never
    the next bench's entry."""
    p = path if path is not None else resolve_path(default_dir)
    line = json.dumps(entry, sort_keys=False)
    prefix = ""
    try:
        with open(p, "rb") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                prefix = "\n"
    except (OSError, ValueError):
        pass  # missing or empty file: nothing to terminate
    with open(p, "a") as f:
        f.write(prefix + line + "\n")
    return p


def sniff_platform() -> str:
    """Banker's config-string platform sniff (never initializes a
    backend that could hang against a dead relay)."""
    try:
        import jax

        return ("cpu" if str(jax.config.jax_platforms or ""
                             ).startswith("cpu") else "tpu")
    except Exception:
        return "unknown"


def bank_row(*, bench: str, row: dict, platform: Optional[str] = None,
             repo_dir: Optional[str] = None,
             ledger_dir: Optional[str] = None, **tags) -> Optional[str]:
    """The one banking entry point every producer shares (Banker rows,
    bench.py headline sessions): sniff the platform when not given,
    stamp the entry, append, and NEVER raise — a broken ledger must not
    kill the bench that just measured something. Returns the path
    written, or None on failure. Keeping producers on this helper means
    a tagging change can't silently fork the entry shape between them
    (which would split perfgate's baseline groups)."""
    try:
        entry = make_entry(
            bench=bench, row=row,
            platform=platform if platform is not None else sniff_platform(),
            repo_dir=repo_dir, **tags)
        return append(entry, default_dir=ledger_dir or repo_dir)
    except Exception:
        return None


def read(path: str) -> List[dict]:
    """Every parseable entry, file order. Torn/corrupt lines (a SIGKILL
    mid-append) are skipped, not fatal — same discipline as
    bench.py's partial-file reader."""
    rows: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(entry, dict):
                    rows.append(entry)
    except OSError:
        return []
    return rows
