"""Metric registry: thread-safe counters, gauges, histograms.

The RAFT reference ships logging/NVTX as first-class core components but
leaves metrics to the embedding application; a serving-scale TPU library
cannot (ROADMAP north star: heavy live traffic + chaos drills need
auditable numbers). This registry is the one place time, bytes, and
compiles are accounted: instruments are named with dotted paths
("comms.allreduce.bytes", "serve.compile_cache.miss"), get-or-create is
idempotent, and `snapshot()` returns a deterministically ordered dict so
tests can assert on exact values.

Design notes:
  - Every instrument carries its own lock; observation is O(1) and
    allocation-free, so hot paths (a collective per trace, a span per
    driver call) pay nanoseconds, and nothing here imports jax.
  - Histograms keep running aggregates (count/total/min/max/last), not
    reservoirs: aggregates join snapshots deterministically, which is
    what the test contract needs; latency *percentiles* stay where the
    windows live (`serve.metrics.ServerMetrics` rings).
  - `add_collector` lets component-local metric objects (one
    `ServerMetrics` per server) contribute a named section to the global
    snapshot without moving their state here.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, List, Optional, Tuple


class Counter:
    """Monotone counter. `inc(n)` with n >= 0; `.value` reads atomically."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (n={n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Point-in-time value; `set`/`add` under the instrument lock."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


#: default `le` bounds (seconds-scaled — spans and latencies are the
#: dominant observers). Cumulative counts against these bounds are what
#: the Prometheus exporter renders as real `_bucket{le=...}` series.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class Histogram:
    """Running aggregate of observations (count/total/min/max/last) plus
    fixed `le` bucket counts.

    The aggregate side stays deliberately reservoir-free: deterministic
    under identical observation sequences, O(1), and what the snapshot
    test contract pins. The bucket side (also deterministic — fixed
    bounds, integer counts) exists for Prometheus exposition: real
    cumulative `_bucket{le=...}`/`_sum`/`_count` series instead of
    aggregate-only gauges, so a scrape can compute quantiles over time.
    Latency *percentile windows* still live where the rings are
    (`serve.metrics.ServerMetrics`).
    """

    __slots__ = ("name", "_lock", "count", "total", "min", "max", "last",
                 "buckets", "_bucket_counts")

    def __init__(self, name: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.reset()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            i = bisect.bisect_left(self.buckets, v)
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1

    def observe_n(self, v: float, n: int) -> None:
        """`n` identical observations in one locked update — the bulk
        form batch instrumentation uses (the adaptive-probing budget
        histogram lands one value per QUERY; per-row observe() calls
        would put O(batch) lock round-trips on the serving hot path).
        Deterministic: equivalent to n consecutive observe(v) calls."""
        v = float(v)
        n = int(n)
        if n <= 0:
            return
        with self._lock:
            self.count += n
            self.total += v * n
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            i = bisect.bisect_left(self.buckets, v)
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += n

    def aggregate(self) -> dict:
        return self.export_state()[0]

    def bucket_counts(self) -> List[Tuple[str, int]]:
        """Cumulative (le, count) pairs, Prometheus semantics: each entry
        counts observations <= its bound; the final "+Inf" entry equals
        `count`. Labels are formatted once here so every exposition
        surface renders identical `le` strings."""
        return self.export_state()[1]

    def export_state(self) -> Tuple[dict, List[Tuple[str, int]]]:
        """(aggregate, cumulative buckets) from ONE locked read — the
        exposition renderer uses this so a scrape's `_count`/`_sum` can
        never disagree with its `_bucket{+Inf}` (an observe landing
        between two separate reads would split the family)."""
        with self._lock:
            agg = {
                "count": self.count,
                "total": self.total,
                "min": self.min,
                "max": self.max,
                "mean": (self.total / self.count) if self.count else None,
                "last": self.last,
            }
            per = list(self._bucket_counts)
            total = self.count
        out: List[Tuple[str, int]] = []
        cum = 0
        for bound, n in zip(self.buckets, per):
            cum += n
            out.append((format(bound, "g"), cum))
        out.append(("+Inf", total))
        return agg, out

    def reset(self) -> None:
        with self._lock:
            self.count = 0
            self.total = 0.0
            self.min = None
            self.max = None
            self.last = None
            self._bucket_counts = [0] * len(self.buckets)


class Registry:
    """Get-or-create instrument store with deterministic snapshots.

    One global instance backs the library (`raft_tpu.obs.registry()`);
    component-local registries (e.g. per-`ServerMetrics`) use private
    instances so two servers never collide on a name.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._collectors: Dict[str, Callable[[], dict]] = {}

    def _get(self, table: dict, name: str, cls):
        with self._lock:
            inst = table.get(name)
            if inst is None:
                for other in (self._counters, self._gauges, self._histograms):
                    if other is not table and name in other:
                        raise ValueError(
                            f"metric name {name!r} already registered as a "
                            f"different instrument kind"
                        )
                inst = table[name] = cls(name)
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, name, Histogram)

    def histogram_items(self) -> List[Tuple[str, Histogram]]:
        """Sorted (name, Histogram) pairs — the exporter's path to the
        live bucket counts, which `snapshot()` (pure aggregates, the
        pinned test shape) deliberately does not carry."""
        with self._lock:
            return sorted(self._histograms.items())

    def add_collector(self, name: str, fn: Callable[[], dict]) -> None:
        """Register a callable contributing a named dict section to
        `snapshot()["collectors"]` (e.g. one per live ServerMetrics)."""
        with self._lock:
            self._collectors[str(name)] = fn

    def remove_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(str(name), None)

    def snapshot(self) -> dict:
        """Deterministically ordered view: sorted names, plain scalars.
        Collector failures surface as an "error" entry, never an
        exception — a broken component must not take down the scrape."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._histograms.items())
            collectors = sorted(self._collectors.items())
        snap = {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.aggregate() for n, h in hists},
        }
        if collectors:
            out = {}
            for n, fn in collectors:
                try:
                    out[n] = fn()
                except Exception as e:  # pragma: no cover - defensive
                    out[n] = {"error": repr(e)}
            snap["collectors"] = out
        return snap

    def reset(self) -> None:
        """Zero every instrument and drop collectors (test hygiene)."""
        with self._lock:
            for table in (self._counters, self._gauges, self._histograms):
                for inst in table.values():
                    inst.reset()
            self._collectors.clear()

    def clear(self) -> None:
        """Drop every instrument definition (not just their values)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._collectors.clear()


# the library-wide registry; accessed via raft_tpu.obs.registry()
GLOBAL = Registry()
