"""Request-scope tracing for the serve path.

The serve engine's spans answer "where did this *batch*'s wall-clock
go"; they cannot answer the tail-latency question "where did this
*request*'s 40 ms go" — queue wait vs coalesce linger vs device time vs
scatter are different fixes, and p99 work needs the attribution per
request, not per batch. A `TraceCtx` minted at `submit()` rides the
`_Request` -> `Batch` -> dispatch -> scatter path and stamps a
monotonic mark at each stage boundary:

    admitted    request validated and queued (inside submit)
    coalesced   popped off the queue into a micro-batch
    dispatched  batch chosen a bucket / compile key, entering device call
    fenced      `block_until_ready` returned (device work complete)
    scattered   this request's reply sliced out and delivered

Consecutive-mark deltas aggregate into per-stage histograms
(`serve.stage.queue_wait_s`, `.linger_s`, `.device_s`, `.scatter_s`) —
the deltas telescope, so their sum IS the end-to-end latency, which is
what makes the attribution trustworthy — and each completed request
lands one "trace" event on the bus carrying its stage attrs (bucket, k,
probe plan, compile hit/miss, coverage, outcome).

Determinism: trace ids are 64-bit values from a seeded counter run
through a splitmix64 finalizer — no wall-clock, no randomness — so a
replayed drill mints the identical id sequence and tests can pin traces
exactly. `obs.reset()` resets the mint.

Chaos: every stamp passes through `faults.fault_point(STAMP_SITE)`; an
injected corruption marks the ctx dead and the request degrades to
*untraced* — results stay bit-identical, because tracing only ever
observes the request, never steers it.

`to_chrome_trace()` renders trace + span events as Chrome/Perfetto
trace-event JSON (load in https://ui.perfetto.dev): one track per serve
worker thread showing stage segments, one track per bucket ladder entry
showing whole requests. The render is a pure function of the event list
with sorted keys and fixed separators, so two renders of the same bus
are byte-identical.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

from raft_tpu.core import faults
from raft_tpu.obs import bus as _bus_mod
from raft_tpu.obs import registry as _reg_mod

#: fault-injection site guarding every stage stamp (chaos drills corrupt
#: it to prove a broken tracer degrades to untraced, bit-identical serving)
STAMP_SITE = "serve.trace.stamp"

#: stage marks in pipeline order; deltas between consecutive present
#: marks telescope to the end-to-end latency
STAGES = ("admitted", "coalesced", "dispatched", "fenced", "scattered")

#: histogram fed by each consecutive-stage delta
STAGE_HISTOGRAMS = {
    ("admitted", "coalesced"): "serve.stage.queue_wait_s",
    ("coalesced", "dispatched"): "serve.stage.linger_s",
    ("dispatched", "fenced"): "serve.stage.device_s",
    ("fenced", "scattered"): "serve.stage.scatter_s",
}

_MASK64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """Finalizer from splitmix64: bijective on 64-bit ints, so distinct
    (seed, n) pairs give distinct, well-scattered ids."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def trace_id(seed: int, n: int) -> int:
    """The n-th (1-based) id minted under `seed` — a pure function, so
    tests can pin the exact ids a replayed run must produce."""
    return _splitmix64(((int(seed) & _MASK64) << 20) ^ int(n))


class _Mint:
    """Seeded, lock-serialized id source. No wall-clock, no randomness:
    the i-th id after a reset is always `trace_id(seed, i)`."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._n = 0

    def mint(self) -> int:
        with self._lock:
            self._n += 1
            return trace_id(self._seed, self._n)

    def reset(self, seed: Optional[int] = None) -> None:
        with self._lock:
            if seed is not None:
                self._seed = int(seed)
            self._n = 0


_MINT = _Mint()


def reset(seed: Optional[int] = None) -> None:
    """Restart the id mint (wired into `obs.reset()` so a replayed
    drill re-mints the identical id sequence)."""
    _MINT.reset(seed)


class TraceCtx:
    """Per-request trace state riding the `_Request`. Mutated only from
    the single thread currently owning the request (submitter until
    queued, then the worker that popped it), so no lock is needed."""

    __slots__ = ("trace_id", "marks", "attrs", "dead")

    def __init__(self, tid: int):
        self.trace_id = int(tid)
        self.marks: List[tuple] = []  # [(stage, monotonic_t)] in stamp order
        self.attrs: dict = {}
        self.dead = False

    def stamp(self, stage: str, **attrs) -> None:
        """Record one stage mark. An injected fault at STAMP_SITE kills
        the ctx (marks discarded, later stamps no-ops): the request
        degrades to untraced but is otherwise untouched. Dead-check
        BEFORE the fault hook so a dead ctx stops consuming injection
        arms — drills stay deterministic per request, not per stamp."""
        if self.dead:
            return
        try:
            faults.fault_point(STAMP_SITE)
        except faults.FaultInjected:
            self.dead = True  # raftlint: disable=publication-safety  -- TraceCtx is single-owner: exactly one thread holds a request's ctx at a time (class docstring)
            self.marks = []
            self.attrs = {}
            return
        self.marks.append((str(stage), time.monotonic()))  # raftlint: disable=shared-state-race  -- single-owner handoff: the ctx travels with the request, never shared concurrently
        if attrs:
            self.attrs.update(attrs)  # raftlint: disable=shared-state-race  -- single-owner handoff, same contract as marks above


def begin() -> Optional[TraceCtx]:
    """Mint a ctx for one request; None when obs is disabled (the
    untraced fast path costs this one call and a branch)."""
    from raft_tpu import obs

    if not obs.enabled():
        return None
    return TraceCtx(_MINT.mint())


def complete(ctx: Optional[TraceCtx], outcome: str = "ok", **attrs) -> None:
    """Close a request's trace: observe every consecutive-stage delta
    into its histogram and publish one "trace" bus event. Timestamps
    live under the event's "marks" field so replay-identity tests can
    strip them the way they strip "t"/"dur_s"."""
    if ctx is None or ctx.dead:
        return
    if attrs:
        ctx.attrs.update(attrs)
    times = dict(ctx.marks)
    for pair, hist in STAGE_HISTOGRAMS.items():
        a, b = pair
        if a in times and b in times:
            _reg_mod.GLOBAL.histogram(hist).observe(times[b] - times[a])
    _bus_mod.GLOBAL.publish(
        "trace",
        trace_id=ctx.trace_id,
        outcome=str(outcome),
        stages=[s for s, _ in ctx.marks],
        marks={s: t for s, t in ctx.marks},
        worker=threading.current_thread().name,
        **ctx.attrs,
    )


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace-event export


def _us(t: float, t0: float) -> float:
    """Microseconds relative to the window start, rounded so the float
    repr (hence the JSON bytes) is stable."""
    return round((t - t0) * 1e6, 3)


def to_chrome_trace(events: Optional[List[dict]] = None) -> str:
    """Render bus "trace" + "span" events as Chrome trace-event JSON.

    Tracks: pid 1 = serve worker threads (one tid per worker; each
    request's stage segments as complete "X" events), pid 2 = bucket
    ladder (one tid per bucket; one "X" event spanning the whole
    request), pid 3 = spans (one tid per thread nesting by depth).
    Pure function of `events` (defaults to the global bus window) —
    rendering the same window twice yields byte-identical output.
    """
    if events is None:
        events = _bus_mod.GLOBAL.events()
    traces = [e for e in events if e.get("kind") == "trace" and e.get("marks")]
    spans = [e for e in events
             if e.get("kind") == "span" and "dur_s" in e and "t" in e]

    t0 = None
    for e in traces:
        lo = min(e["marks"].values())
        t0 = lo if t0 is None else min(t0, lo)
    for e in spans:
        lo = float(e["t"]) - float(e["dur_s"])
        t0 = lo if t0 is None else min(t0, lo)
    if t0 is None:
        t0 = 0.0

    PID_WORKERS, PID_BUCKETS, PID_SPANS = 1, 2, 3
    workers = sorted({str(e.get("worker", "?")) for e in traces})
    worker_tid = {w: i + 1 for i, w in enumerate(workers)}
    buckets = sorted({int(e.get("bucket", 0)) for e in traces})
    bucket_tid = {b: i + 1 for i, b in enumerate(buckets)}
    span_threads = sorted({str(e.get("thread", e.get("worker", "?")))
                           for e in spans})
    span_tid = {n: i + 1 for i, n in enumerate(span_threads)}

    out: List[dict] = []

    def meta(pid, tid, what, name):
        out.append({"ph": "M", "pid": pid, "tid": tid, "name": what,
                    "args": {"name": name}})

    if traces:
        meta(PID_WORKERS, 0, "process_name", "serve workers")
        for w in workers:
            meta(PID_WORKERS, worker_tid[w], "thread_name", w)
        meta(PID_BUCKETS, 0, "process_name", "bucket ladder")
        for b in buckets:
            meta(PID_BUCKETS, bucket_tid[b], "thread_name", f"bucket={b}")
    if spans:
        meta(PID_SPANS, 0, "process_name", "spans")
        for n in span_threads:
            meta(PID_SPANS, span_tid[n], "thread_name", n)

    for e in traces:
        marks = e["marks"]
        tid = worker_tid[str(e.get("worker", "?"))]
        base_args = {k: v for k, v in sorted(e.items())
                     if k not in ("kind", "seq", "t", "marks", "stages",
                                  "worker")}
        base_args["trace_id"] = f"{int(e['trace_id']):016x}"
        present = [s for s in STAGES if s in marks]
        for a, b in zip(present, present[1:]):
            hist = STAGE_HISTOGRAMS.get((a, b))
            name = hist.rsplit(".", 1)[-1][:-2] if hist else f"{a}->{b}"
            out.append({
                "ph": "X", "pid": PID_WORKERS, "tid": tid, "name": name,
                "ts": _us(marks[a], t0),
                "dur": max(0.0, _us(marks[b], t0) - _us(marks[a], t0)),
                "cat": "serve.stage", "args": base_args,
            })
        if len(present) >= 2:
            out.append({
                "ph": "X", "pid": PID_BUCKETS,
                "tid": bucket_tid[int(e.get("bucket", 0))],
                "name": f"request {base_args['trace_id']}",
                "ts": _us(marks[present[0]], t0),
                "dur": max(0.0, _us(marks[present[-1]], t0)
                           - _us(marks[present[0]], t0)),
                "cat": "serve.request", "args": base_args,
            })

    for e in spans:
        tid = span_tid[str(e.get("thread", e.get("worker", "?")))]
        args = {k: v for k, v in sorted(e.items())
                if k not in ("kind", "seq", "t", "dur_s", "name", "thread")}
        out.append({
            "ph": "X", "pid": PID_SPANS, "tid": tid,
            "name": str(e.get("name", "span")),
            "ts": _us(float(e["t"]) - float(e["dur_s"]), t0),
            "dur": round(float(e["dur_s"]) * 1e6, 3),
            "cat": "span", "args": args,
        })

    payload = {"displayTimeUnit": "ms", "traceEvents": out}
    return json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=repr)
