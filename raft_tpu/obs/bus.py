"""Event bus: the ordered record of what happened in a run.

Spans, collectives, fault injections, compile-cache outcomes, and log
records all publish here as small dicts with a global sequence number.
The bus is the audit trail a chaos drill produces: replay the same
seeded `FaultPlan` and the same event sequence comes back (timestamps
differ; everything else is bit-identical), which is what
`tests/test_obs.py` asserts on.

Publishing is synchronous and lock-serialized: the global `seq` is the
ordering authority, so two events can never race into ambiguous order.
Subscribers run inline under NO lock (a slow subscriber must not block
publishers holding it) and a failing subscriber is dropped from
delivery for that event only — observability must never take down the
serving path it observes.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional


class EventBus:
    """Bounded, ordered event log with synchronous fan-out.

    Events are plain dicts: {"seq": int, "t": monotonic seconds,
    "kind": str, ...fields}. The ring keeps the last `maxlen` events so
    unbounded runs hold constant memory; exporters snapshot the window.
    """

    def __init__(self, maxlen: int = 8192):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(maxlen=int(maxlen))
        self._subscribers: List[Callable[[dict], None]] = []
        self._seq = 0

    def publish(self, kind: str, **fields) -> int:
        """Append one event; returns its sequence number."""
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, "t": time.monotonic(), "kind": str(kind)}
            event.update(fields)
            self._events.append(event)
            subs = tuple(self._subscribers)
        for fn in subs:
            try:
                fn(event)
            except Exception:
                # a broken subscriber must not poison the publisher
                pass
        return event["seq"]

    def subscribe(self, fn: Callable[[dict], None]) -> Callable[[dict], None]:
        with self._lock:
            self._subscribers.append(fn)
        return fn

    def unsubscribe(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Copy of the ringed window, oldest first; `kind` filters."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def clear(self) -> None:
        """Drop ringed events and restart the sequence (test hygiene).
        Subscribers stay attached."""
        with self._lock:
            self._events.clear()
            self._seq = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# the library-wide bus; accessed via raft_tpu.obs.bus()
GLOBAL = EventBus()
