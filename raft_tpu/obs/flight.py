"""Flight recorder: the last few seconds of timeline, crash-survivable.

When a chip-queue session or a churn drill dies — watchdog SIGKILL of a
stalled stage, an unhandled exception in a serve worker, a
`crash_point` drill, SIGTERM from the scheduler — every in-memory
metric dies with it and the post-mortem starts from nothing. The
recorder keeps a bounded ring of the most recent bus events (append to
a bounded deque: no lock beyond the GIL on the hot path) plus, at dump
time, the open-span stack of every live thread and the counter delta
since arming. `dump()` routes through `serialize.atomic_write`, so a
crash mid-dump leaves the previous dump intact, never a torn one — a
flight recorder that tears on the crash it exists for is worse than
none (raftlint's `hygiene-obs-torn-write` rule machine-checks this for
all of obs/).

Arming points (all call `maybe_dump`, which never raises — the
recorder must never take down the path it observes):

  * jobs watchdog, both kill paths — dump BEFORE the SIGKILL
  * `faults.crash_point` — dump before the drill kills the process
  * `SearchServer` worker loop — unhandled-exception hook
  * SIGTERM — via `install_sigterm()` (auto when `RAFT_TPU_FLIGHT_DIR`
    is set and we're on the main thread)

`RAFT_TPU_FLIGHT_DIR=<dir>` auto-installs a recorder when obs is
enabled; dumps land there as `flight-<pid>-<n>.json` (a counter, not
wall-clock, so reruns overwrite rather than accumulate).
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import List, Optional

from raft_tpu.core import faults
from raft_tpu.obs import bus as _bus_mod
from raft_tpu.obs import registry as _reg_mod

#: fault-injection site guarding every dump (chaos drills make it flaky
#: to prove a failing dump never takes down the caller)
DUMP_SITE = "obs.flight.dump"

ENV_DIR = "RAFT_TPU_FLIGHT_DIR"

DEFAULT_RING = 512


class FlightRecorder:
    """Bounded ring of recent bus events + dump machinery. `install()`
    subscribes it to the global bus and snapshots the counter baseline
    the dump's `registry_delta` is computed against."""

    def __init__(self, maxlen: int = DEFAULT_RING):
        # the ring needs a real lock, not just the GIL: deque.append IS
        # atomic, but `list(ring)` iterates — an append landing from
        # another publisher mid-iteration raises "deque mutated during
        # iteration", which used to lose the flight dump exactly when
        # the process was busiest (threadcheck shared-state-race;
        # tests/test_schedfuzz.py reproduces the pre-fix interleaving)
        self._ring_lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=int(maxlen))
        self._baseline: dict = {}
        self._installed = False

    # -- recording --------------------------------------------------------

    def _on_event(self, event: dict) -> None:
        with self._ring_lock:
            self._ring.append(event)

    def install(self) -> "FlightRecorder":
        if not self._installed:
            self._baseline = dict(
                _reg_mod.GLOBAL.snapshot().get("counters", {}))
            _bus_mod.GLOBAL.subscribe(self._on_event)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            _bus_mod.GLOBAL.unsubscribe(self._on_event)
            self._installed = False

    def clear(self) -> None:
        with self._ring_lock:
            self._ring.clear()
        self._baseline = dict(_reg_mod.GLOBAL.snapshot().get("counters", {}))

    def events(self) -> List[dict]:
        """Ring contents, oldest first."""
        with self._ring_lock:
            return list(self._ring)

    # -- dumping ----------------------------------------------------------

    def snapshot(self, reason: str, **fields) -> dict:
        snap = _reg_mod.GLOBAL.snapshot()
        counters = snap.get("counters", {})
        delta = {name: v - self._baseline.get(name, 0)
                 for name, v in sorted(counters.items())
                 if v != self._baseline.get(name, 0)}
        from raft_tpu.obs.spans import open_spans

        return {
            "reason": str(reason),
            **fields,
            "pid": os.getpid(),
            "ring_maxlen": self._ring.maxlen,
            "events": self.events(),
            "open_spans": open_spans(),
            "registry_delta": delta,
            "registry": snap,
        }

    def dump(self, path: str, reason: str, **fields) -> dict:
        """Write the snapshot atomically; returns it. Passes through
        the DUMP_SITE fault hook first, so a drill-injected failure
        surfaces here (callers go through `maybe_dump`, which absorbs
        it)."""
        faults.fault_point(DUMP_SITE)
        snap = self.snapshot(reason, **fields)
        from raft_tpu.core.serialize import atomic_write

        with atomic_write(path) as tmp:
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1, sort_keys=True, default=repr)
        _bus_mod.GLOBAL.publish("flight", action="dump", reason=str(reason),
                                path=os.path.basename(path),
                                events=len(snap["events"]))
        return snap


# ---------------------------------------------------------------------------
# module-level singleton + arming helpers

_LOCK = threading.Lock()
_RECORDER: Optional[FlightRecorder] = None
_DUMP_DIR: Optional[str] = None
_DUMP_N = 0
_PREV_SIGTERM = None


def install(maxlen: int = DEFAULT_RING,
            dump_dir: Optional[str] = None) -> FlightRecorder:
    """Arm the global recorder (idempotent; re-installing just updates
    the dump dir)."""
    global _RECORDER, _DUMP_DIR
    with _LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder(maxlen=maxlen)
        if dump_dir is not None:
            _DUMP_DIR = str(dump_dir)
    return _RECORDER.install()


def installed() -> Optional[FlightRecorder]:
    return _RECORDER if (_RECORDER is not None and _RECORDER._installed) \
        else None


def uninstall() -> None:
    global _RECORDER
    with _LOCK:
        rec, _RECORDER = _RECORDER, None
    if rec is not None:
        rec.uninstall()


def reset() -> None:
    """Clear the armed recorder's ring and rebaseline (test hygiene;
    wired into `obs.reset()`). No-op when nothing is armed."""
    rec = installed()
    if rec is not None:
        rec.clear()


def _next_path() -> str:
    global _DUMP_N
    with _LOCK:
        _DUMP_N += 1
        n = _DUMP_N
    d = _DUMP_DIR or os.environ.get(ENV_DIR) or "."
    return os.path.join(d, f"flight-{os.getpid()}-{n}.json")


def maybe_dump(reason: str, path: Optional[str] = None,
               **fields) -> Optional[str]:
    """Dump if a recorder is armed and obs is enabled; swallow every
    failure (a flaky dump must never take down the worker loop, the
    watchdog, or the crash path that called it). Returns the path
    written, or None."""
    from raft_tpu import obs

    rec = installed()
    if rec is None or not obs.enabled():
        return None
    if path is None:
        path = _next_path()
    try:
        rec.dump(path, reason=reason, **fields)
        return path
    except Exception:
        try:
            _bus_mod.GLOBAL.publish("flight", action="dump_failed",
                                    reason=str(reason))
        except Exception:
            pass
        return None


def install_sigterm() -> bool:
    """Dump on SIGTERM, then chain to the previous handler (or re-raise
    the default). Only possible on the main thread; returns False
    elsewhere."""
    global _PREV_SIGTERM
    import signal

    def _on_sigterm(signum, frame):
        maybe_dump("sigterm")
        prev = _PREV_SIGTERM
        if callable(prev):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    try:
        _PREV_SIGTERM = signal.signal(signal.SIGTERM, _on_sigterm)
        return True
    except ValueError:  # not the main thread
        return False


def maybe_env_install() -> None:
    """Auto-arm from `RAFT_TPU_FLIGHT_DIR` (called by `obs.enable()`)."""
    d = os.environ.get(ENV_DIR, "").strip()
    if d and installed() is None:
        install(dump_dir=d)
        install_sigterm()
