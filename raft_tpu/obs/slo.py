"""SLO watchtower: declarative objectives with multi-window burn rates.

Raw medians in the ledger say what happened; they don't *judge* it. An
`Objective` declares a service-level target ("99% of requests under
50 ms", "99.9% not expired/rejected", "coverage never below 1.0",
"occupancy at least 0.25") and the `Watchtower` evaluates a stream of
per-request / per-batch samples against it over two sliding windows —
a fast window (default 5 min) that reacts, and a slow window (default
1 h) that confirms — using burn rates:

    burn = bad_fraction / error_budget        (budget = 1 - target)

A burn of 1.0 spends the budget exactly; 14 spends a month's budget in
~2 days. An objective **breaches** only when BOTH windows are at or
above `breach_burn` (the fast window alone trips first but a breach
needs the slow window's confirmation — this is the standard
multi-window guard against paging on blips), and **recovers** only when
both fall below `recover_burn` < `breach_burn` (hysteresis, so a burn
hovering at the threshold cannot flap). Transitions publish
`slo.breach` / `slo.recover` bus events and bump matching counters;
`obs.report` renders them as the SLO section, and
`bench_serve.py`/`bench_mutation.py` bank a snapshot judgment
(`judge_serve`) as flat row fields so perfgate gets a verdict signal
beyond medians.

Determinism: the clock is injectable and every `observe`/`evaluate`
takes an explicit `t`, so tests drive the windows with synthetic time.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Dict, List, Optional, Sequence

#: objective kinds and what makes one sample "bad"
#:   latency    latency_s  > threshold
#:   error      outcome not in ("ok", "degraded")
#:   coverage   coverage   < threshold
#:   occupancy  occupancy  < threshold
KINDS = ("latency", "error", "coverage", "occupancy")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declared service-level objective.

    `target` is the required good fraction (0.99 = "99% good"); the
    error budget is `1 - target`. `threshold` parameterizes the
    per-sample good/bad classification for the kinds that need one.
    """

    name: str
    kind: str
    target: float
    threshold: float = 0.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown objective kind {self.kind!r}; "
                             f"expected one of {KINDS}")
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0, 1), got {self.target}")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def serve_objectives(p99_s: float = 0.25, error_target: float = 0.99,
                     coverage_floor: float = 1.0,
                     occupancy_floor: float = 0.05) -> List[Objective]:
    """The default serve-path objective set (tune per deployment)."""
    return [
        Objective("latency_p99", "latency", target=0.99, threshold=p99_s),
        Objective("error_rate", "error", target=error_target),
        Objective("coverage", "coverage", target=0.999,
                  threshold=coverage_floor),
        Objective("occupancy", "occupancy", target=0.95,
                  threshold=occupancy_floor),
    ]


class _Window:
    """Sliding (t, bad) sample window. Pruning amortizes O(1) per add."""

    __slots__ = ("horizon_s", "_dq", "_bad")

    def __init__(self, horizon_s: float):
        self.horizon_s = float(horizon_s)
        self._dq: collections.deque = collections.deque()
        self._bad = 0

    def add(self, t: float, bad: bool) -> None:
        self._dq.append((t, bad))  # raftlint: disable=shared-state-race  -- every live call path holds ServerMetrics._wt_lock (windows are never reached directly)
        if bad:
            self._bad += 1  # raftlint: disable=shared-state-race  -- serialized under ServerMetrics._wt_lock like _dq above
        self._prune(t)

    def _prune(self, now: float) -> None:
        cutoff = now - self.horizon_s
        dq = self._dq
        while dq and dq[0][0] <= cutoff:
            _, b = dq.popleft()
            if b:
                self._bad -= 1

    def bad_fraction(self, now: float) -> float:
        self._prune(now)
        n = len(self._dq)
        return (self._bad / n) if n else 0.0


class Watchtower:
    """Evaluates objectives over fast+slow windows and publishes
    breach/recover transitions. Not thread-safe by itself; the serve
    integration feeds it from under `ServerMetrics`' lock."""

    def __init__(self, objectives: Sequence[Objective],
                 fast_s: float = 300.0, slow_s: float = 3600.0,
                 breach_burn: float = 14.0, recover_burn: float = 1.0,
                 clock=time.monotonic):
        if recover_burn >= breach_burn:
            raise ValueError("recover_burn must be < breach_burn "
                             "(hysteresis)")
        self.objectives = {o.name: o for o in objectives}
        if len(self.objectives) != len(objectives):
            raise ValueError("duplicate objective names")
        self.breach_burn = float(breach_burn)
        self.recover_burn = float(recover_burn)
        self._clock = clock
        self._fast = {o.name: _Window(fast_s) for o in objectives}
        self._slow = {o.name: _Window(slow_s) for o in objectives}
        self._breached: Dict[str, bool] = {o.name: False for o in objectives}

    # -- sample intake ----------------------------------------------------

    def _add(self, name: str, bad: bool, t: float) -> None:
        self._fast[name].add(t, bad)
        self._slow[name].add(t, bad)

    def observe(self, name: str, bad: bool, t: Optional[float] = None) -> None:
        """Record one pre-classified sample for one objective."""
        if name not in self.objectives:
            raise KeyError(name)
        self._add(name, bool(bad), self._clock() if t is None else t)

    def observe_request(self, latency_s: Optional[float] = None,
                        outcome: str = "ok",
                        coverage: Optional[float] = None,
                        t: Optional[float] = None) -> None:
        """Route one request terminal record to every objective whose
        kind it parameterizes. Expired/rejected requests carry no
        latency or coverage — they feed only the error objective, which
        is exactly the truthfulness fix: the killed requests count."""
        if t is None:
            t = self._clock()
        for name, o in self.objectives.items():
            if o.kind == "latency" and latency_s is not None:
                self._add(name, latency_s > o.threshold, t)
            elif o.kind == "error":
                self._add(name, outcome not in ("ok", "degraded"), t)
            elif o.kind == "coverage" and coverage is not None:
                self._add(name, coverage < o.threshold, t)

    def observe_batch(self, occupancy: float,
                      t: Optional[float] = None) -> None:
        if t is None:
            t = self._clock()
        for name, o in self.objectives.items():
            if o.kind == "occupancy":
                self._add(name, occupancy < o.threshold, t)

    # -- evaluation -------------------------------------------------------

    def burns(self, name: str, t: Optional[float] = None) -> tuple:
        """(fast_burn, slow_burn) for one objective at time t."""
        if t is None:
            t = self._clock()
        o = self.objectives[name]
        return (self._fast[name].bad_fraction(t) / o.budget,
                self._slow[name].bad_fraction(t) / o.budget)

    def evaluate(self, t: Optional[float] = None) -> List[dict]:
        """Check every objective; publish and return the transitions
        ([{objective, transition, fast_burn, slow_burn}])."""
        from raft_tpu import obs

        if t is None:
            t = self._clock()
        transitions = []
        for name in sorted(self.objectives):
            fast, slow = self.burns(name, t)
            breached = self._breached[name]
            if (not breached and fast >= self.breach_burn
                    and slow >= self.breach_burn):
                self._breached[name] = True  # raftlint: disable=publication-safety  -- serialized under ServerMetrics._wt_lock; readers see it only via evaluate's snapshot
                transitions.append({"objective": name,
                                    "transition": "breach",
                                    "fast_burn": round(fast, 4),
                                    "slow_burn": round(slow, 4)})
            elif (breached and fast < self.recover_burn
                    and slow < self.recover_burn):
                self._breached[name] = False
                transitions.append({"objective": name,
                                    "transition": "recover",
                                    "fast_burn": round(fast, 4),
                                    "slow_burn": round(slow, 4)})
        for tr in transitions:
            kind = f"slo.{tr['transition']}"
            obs.counter(kind).inc()
            obs.event(kind, objective=tr["objective"],
                      fast_burn=tr["fast_burn"], slow_burn=tr["slow_burn"])
        return transitions

    def state(self, t: Optional[float] = None) -> dict:
        """Current status per objective (for reports/benches)."""
        if t is None:
            t = self._clock()
        out = {}
        for name in sorted(self.objectives):
            fast, slow = self.burns(name, t)
            out[name] = {"breached": self._breached[name],
                         "fast_burn": round(fast, 4),
                         "slow_burn": round(slow, 4)}
        return out


# ---------------------------------------------------------------------------
# snapshot judgment for bench rows


def judge_serve(metrics_snapshot: dict, p99_ms: float = 250.0,
                error_budget: float = 0.01, coverage_floor: float = 1.0,
                occupancy_floor: float = 0.0) -> dict:
    """Judge one `ServerMetrics.snapshot()` against serve objectives,
    returning flat `slo_*` fields for a bench ledger row. NaN stats
    (no traffic) judge as failing — an empty run can't claim its SLOs
    held."""
    def _ok(value, pred):
        try:
            v = float(value)
        except (TypeError, ValueError):
            return False
        return v == v and pred(v)

    snap = metrics_snapshot
    submitted = int(snap.get("submitted") or 0)
    killed = int(snap.get("expired") or 0) + int(snap.get("rejected") or 0) \
        + int(snap.get("failed") or 0)
    error_rate = (killed / submitted) if submitted else 1.0
    verdict = {
        "slo_p99_ms_budget": float(p99_ms),
        "slo_p99_ok": _ok(snap.get("latency_ms_p99"), lambda v: v <= p99_ms),
        "slo_error_rate": round(error_rate, 6),
        "slo_error_ok": submitted > 0 and error_rate <= error_budget,
        "slo_coverage_ok": _ok(snap.get("coverage_min", 1.0),
                               lambda v: v >= coverage_floor),
        "slo_occupancy_ok": _ok(snap.get("batch_occupancy"),
                                lambda v: v >= occupancy_floor),
    }
    verdict["slo_ok"] = all(v for k, v in verdict.items() if k.endswith("_ok"))
    return verdict
