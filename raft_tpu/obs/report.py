"""Run-report renderer: `python -m raft_tpu.obs.report snapshot.json`.

Turns a saved `obs.save_snapshot()` JSON into the human-readable
post-run summary an operator reads after a bench, a chaos drill, or an
incident: where wall-clock went (span totals), what moved over the
interconnect (per-collective calls/bytes), what the serving layer did
(compile-cache hits, warmup compiles), and the fault/health timeline a
degraded run leaves behind.

Also usable as a library: `report.render(snap_dict) -> str`.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def _fmt_s(s) -> str:
    if s is None:
        return "-"
    s = float(s)
    return f"{s * 1e3:.2f} ms" if s < 1.0 else f"{s:.3f} s"


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*header), fmt.format(*["-" * w for w in widths])]
    out += [fmt.format(*[str(c) for c in r]) for r in rows]
    return out


def _span_section(snap: dict) -> List[str]:
    hists = snap.get("metrics", {}).get("histograms", {})
    rows = []
    for name, agg in sorted(hists.items()):
        if not name.startswith("span.") or not agg.get("count"):
            continue
        rows.append([
            name[len("span."):], agg["count"], _fmt_s(agg["total"]),
            _fmt_s(agg["mean"]), _fmt_s(agg["max"]),
        ])
    if not rows:
        return []
    return ["", "## Spans (wall-clock attribution)", ""] + _table(
        rows, ["span", "calls", "total", "mean", "max"])


def _comms_section(snap: dict) -> List[str]:
    counters = snap.get("metrics", {}).get("counters", {})
    ops = sorted({
        name[len("comms."):-len(".calls")]
        for name in counters
        if name.startswith("comms.") and name.endswith(".calls")
    })
    rows = []
    for op in ops:
        calls = counters.get(f"comms.{op}.calls", 0)
        if not calls:
            continue
        rows.append([op, calls, _fmt_bytes(counters.get(f"comms.{op}.bytes", 0))])
    if not rows:
        return []
    lines = ["", "## Collectives (traced ops; bytes = per-rank payload)", ""]
    return lines + _table(rows, ["collective", "calls", "bytes"])


def _serve_section(snap: dict) -> List[str]:
    counters = snap.get("metrics", {}).get("counters", {})
    hists = snap.get("metrics", {}).get("histograms", {})
    lines: List[str] = []
    hit = counters.get("serve.compile_cache.hit", 0)
    miss = counters.get("serve.compile_cache.miss", 0)
    warm = hists.get("serve.warmup_compile_s", {})
    if hit or miss or warm.get("count"):
        lines += ["", "## Serving compile cache", ""]
        total = hit + miss
        rate = f"{hit / total:.1%}" if total else "-"
        lines.append(f"bucket-program hits: {hit}/{total} ({rate})")
        if warm.get("count"):
            lines.append(
                f"warmup compiles: {warm['count']} "
                f"(total {_fmt_s(warm['total'])}, max {_fmt_s(warm['max'])})")
    for cname, section in sorted(
            snap.get("metrics", {}).get("collectors", {}).items()):
        if not isinstance(section, dict):
            continue
        lines += ["", f"## Collector: {cname}", ""]
        for key in sorted(section):
            val = section[key]
            if isinstance(val, float):
                val = f"{val:.6g}"
            lines.append(f"{key}: {val}")
    return lines


def _timeline_section(snap: dict, kinds=("fault", "health", "compile", "log"),
                      limit: int = 60) -> List[str]:
    events = [e for e in snap.get("events", []) if e.get("kind") in kinds]
    if not events:
        return []
    lines = ["", f"## Timeline ({', '.join(kinds)}; last {limit})", ""]
    t0 = snap["events"][0]["t"] if snap.get("events") else 0.0
    for e in events[-limit:]:
        fields = {k: v for k, v in e.items() if k not in ("seq", "t", "kind")}
        detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        lines.append(f"[{e['t'] - t0:+9.3f}s] #{e['seq']:<5d} {e['kind']:<8s} {detail}")
    return lines


def render(snap: dict, title: str = "raft_tpu run report") -> str:
    """Render one snapshot dict (the `obs.snapshot()` shape) as text."""
    n_events = len(snap.get("events", []))
    counters = snap.get("metrics", {}).get("counters", {})
    gauges = snap.get("metrics", {}).get("gauges", {})
    lines = [f"# {title}", "",
             f"events: {n_events}  counters: {len(counters)}  "
             f"gauges: {len(gauges)}"]
    lines += _span_section(snap)
    lines += _comms_section(snap)
    lines += _serve_section(snap)
    misc = {
        name: val for name, val in sorted(counters.items())
        if not name.startswith(("comms.", "serve.compile_cache."))
        and val
    }
    if misc:
        lines += ["", "## Counters", ""] + _table(
            [[n, v] for n, v in misc.items()], ["counter", "value"])
    lines += _timeline_section(snap)
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.report",
        description="Render a human-readable run report from an "
                    "obs.save_snapshot() JSON file ('-' reads stdin).",
    )
    parser.add_argument("snapshot", help="path to snapshot JSON, or '-'")
    parser.add_argument("--title", default="raft_tpu run report")
    args = parser.parse_args(argv)
    if args.snapshot == "-":
        snap = json.load(sys.stdin)
    else:
        with open(args.snapshot) as f:
            snap = json.load(f)
    sys.stdout.write(render(snap, title=args.title))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
