"""Run-report renderer: `python -m raft_tpu.obs.report snapshot.json`.

Turns a saved `obs.save_snapshot()` JSON into the human-readable
post-run summary an operator reads after a bench, a chaos drill, or an
incident: where wall-clock went (span totals), what it *cost* (analytic
FLOPs/bytes per span with derived FLOP/s and MFU against the snapshot's
embedded peak table — nominal CPU peaks clearly tagged), what moved
over the interconnect (per-collective calls/bytes/wire model), what the
serving layer did (compile-cache hits, warmup compiles), and the
fault/health timeline a degraded run leaves behind.

`--merge` takes SEVERAL per-rank snapshots (obs.save_snapshot(path,
rank=..., world=...) from the MNMG drivers) and renders one distributed
view: per-rank span attribution with straggler skew, per-rank collective
calls/bytes (a call-count mismatch is a desync), and the merged
fault/health timeline aligned by each rank's seq-ordered bus.

Also usable as a library: `report.render(snap_dict) -> str` /
`report.render_merged([snap, ...]) -> str`.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def _fmt_s(s) -> str:
    if s is None:
        return "-"
    s = float(s)
    return f"{s * 1e3:.2f} ms" if s < 1.0 else f"{s:.3f} s"


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*header), fmt.format(*["-" * w for w in widths])]
    out += [fmt.format(*[str(c) for c in r]) for r in rows]
    return out


def _span_section(snap: dict) -> List[str]:
    hists = snap.get("metrics", {}).get("histograms", {})
    rows = []
    for name, agg in sorted(hists.items()):
        if not name.startswith("span.") or not agg.get("count"):
            continue
        rows.append([
            name[len("span."):], agg["count"], _fmt_s(agg["total"]),
            _fmt_s(agg["mean"]), _fmt_s(agg["max"]),
        ])
    if not rows:
        return []
    return ["", "## Spans (wall-clock attribution)", ""] + _table(
        rows, ["span", "calls", "total", "mean", "max"])


def _fmt_flops(n: float) -> str:
    n = float(n)
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000.0 or unit == "P":
            return f"{n:.4g} {unit}FLOP".replace("  ", " ")
        n /= 1000.0
    return f"{n:.4g} PFLOP"


def _perf_totals(snap: dict) -> dict:
    """Parse the deterministic perf.<span>.flops.<dtype> /
    perf.<span>.bytes counters back into per-span cost totals."""
    counters = snap.get("metrics", {}).get("counters", {})
    per: dict = {}
    for name, val in counters.items():
        if not name.startswith("perf.") or not val:
            continue
        rest = name[len("perf."):]
        if ".flops." in rest:
            span, dt = rest.rsplit(".flops.", 1)
            row = per.setdefault(span, {"flops": {}, "bytes": 0})
            row["flops"][dt] = row["flops"].get(dt, 0) + val
        elif rest.endswith(".bytes"):
            span = rest[:-len(".bytes")]
            row = per.setdefault(span, {"flops": {}, "bytes": 0})
            row["bytes"] += val
    return per


def _perf_section(snap: dict) -> List[str]:
    """Cost attribution: analytic FLOPs/bytes per span with FLOP/s and
    MFU derived against the snapshot's embedded peak table."""
    per = _perf_totals(snap)
    if not per:
        return []
    hists = snap.get("metrics", {}).get("histograms", {})
    info = snap.get("platform") or {}
    peaks = info.get("peak_flops") or {}
    rows = []
    for span in sorted(per):
        flops_by_dtype = per[span]["flops"]
        flops = sum(flops_by_dtype.values())
        secs = (hists.get(f"span.{span}") or {}).get("total") or 0.0
        gfs = f"{flops / secs / 1e9:.4g}" if secs else "-"
        mfu = "-"
        if secs and peaks:
            peak_s = 0.0
            for dt, fl in flops_by_dtype.items():
                peak = peaks.get(dt)
                if not peak:
                    peak_s = None
                    break
                peak_s += fl / peak
            if peak_s is not None:
                mfu = f"{peak_s / secs:.2%}"
        dts = "+".join(sorted(flops_by_dtype))
        bps = (_fmt_bytes(per[span]["bytes"] / secs) + "/s"
               if secs and per[span]["bytes"] else "-")
        rows.append([span, _fmt_flops(flops), dts, gfs, mfu, bps])
    plat = info.get("platform", "unknown")
    tag = " — NOMINAL peaks, not a hardware claim" if info.get("nominal") else ""
    lines = ["", f"## Cost attribution (analytic model over span "
                 f"host-time; MFU vs {plat} peak{tag})", ""]
    return lines + _table(
        rows, ["span", "flops", "dtype", "GFLOP/s", "MFU", "bytes/s"])


def _comms_section(snap: dict) -> List[str]:
    counters = snap.get("metrics", {}).get("counters", {})
    ops = sorted({
        name[len("comms."):-len(".calls")]
        for name in counters
        if name.startswith("comms.") and name.endswith(".calls")
    })
    rows = []
    any_wire = any(counters.get(f"comms.{op}.wire_bytes") for op in ops)
    for op in ops:
        calls = counters.get(f"comms.{op}.calls", 0)
        if not calls:
            continue
        row = [op, calls, _fmt_bytes(counters.get(f"comms.{op}.bytes", 0))]
        if any_wire:
            row.append(_fmt_bytes(counters.get(f"comms.{op}.wire_bytes", 0)))
        rows.append(row)
    if not rows:
        return []
    header = ["collective", "calls", "bytes"] + (["wire"] if any_wire else [])
    lines = ["", "## Collectives (traced ops; bytes = per-rank payload"
                 + ("; wire = modeled per-rank traffic" if any_wire else "")
                 + ")", ""]
    return lines + _table(rows, header)


def _serve_section(snap: dict) -> List[str]:
    counters = snap.get("metrics", {}).get("counters", {})
    hists = snap.get("metrics", {}).get("histograms", {})
    lines: List[str] = []
    hit = counters.get("serve.compile_cache.hit", 0)
    miss = counters.get("serve.compile_cache.miss", 0)
    warm = hists.get("serve.warmup_compile_s", {})
    if hit or miss or warm.get("count"):
        lines += ["", "## Serving compile cache", ""]
        total = hit + miss
        rate = f"{hit / total:.1%}" if total else "-"
        lines.append(f"bucket-program hits: {hit}/{total} ({rate})")
        if warm.get("count"):
            lines.append(
                f"warmup compiles: {warm['count']} "
                f"(total {_fmt_s(warm['total'])}, max {_fmt_s(warm['max'])})")
    for cname, section in sorted(
            snap.get("metrics", {}).get("collectors", {}).items()):
        if not isinstance(section, dict):
            continue
        lines += ["", f"## Collector: {cname}", ""]
        for key in sorted(section):
            val = section[key]
            if isinstance(val, float):
                val = f"{val:.6g}"
            lines.append(f"{key}: {val}")
    return lines


_STAGE_ORDER = ("serve.stage.queue_wait_s", "serve.stage.linger_s",
                "serve.stage.device_s", "serve.stage.scatter_s")


def _trace_section(snap: dict) -> List[str]:
    """Per-stage request-latency attribution (obs.trace): the stage
    histograms in pipeline order — their deltas telescope, so the
    totals decompose end-to-end latency — plus terminal outcomes and
    the dropped-request queue-wait story."""
    counters = snap.get("metrics", {}).get("counters", {})
    hists = snap.get("metrics", {}).get("histograms", {})
    rows = []
    for name in _STAGE_ORDER:
        agg = hists.get(name) or {}
        if agg.get("count"):
            rows.append([
                name[len("serve.stage."):-len("_s")], agg["count"],
                _fmt_s(agg["total"]), _fmt_s(agg["mean"]), _fmt_s(agg["max"]),
            ])
    outcomes = {name[len("serve.outcome."):]: val
                for name, val in sorted(counters.items())
                if name.startswith("serve.outcome.") and val}
    drop = hists.get("serve.drop_wait_s") or {}
    traces = sum(1 for e in snap.get("events", [])
                 if e.get("kind") == "trace")
    if not rows and not outcomes and not traces:
        return []
    lines = ["", "## Request tracing (per-stage latency attribution)", ""]
    if rows:
        lines += _table(rows, ["stage", "requests", "total", "mean", "max"])
    if outcomes:
        lines += ["", "terminal outcomes: "
                  + "  ".join(f"{k}={v}" for k, v in sorted(outcomes.items()))]
    if drop.get("count"):
        lines.append(
            f"dropped-request queue wait: {drop['count']} requests, "
            f"mean {_fmt_s(drop['mean'])}, max {_fmt_s(drop['max'])}")
    if traces:
        lines.append(f"trace records on bus: {traces}")
    return lines


def _slo_section(snap: dict, limit: int = 40) -> List[str]:
    """SLO watchtower verdicts: breach/recover totals plus the
    transition timeline with both window burns."""
    counters = snap.get("metrics", {}).get("counters", {})
    breaches = counters.get("slo.breach", 0)
    recovers = counters.get("slo.recover", 0)
    events = [e for e in snap.get("events", [])
              if e.get("kind") in ("slo.breach", "slo.recover")]
    if not (breaches or recovers or events):
        return []
    lines = ["", "## SLO watchtower", "",
             f"breaches: {breaches}  recoveries: {recovers}"]
    if events:
        lines.append("")
        t0 = snap["events"][0]["t"] if snap.get("events") else 0.0
        for e in events[-limit:]:
            lines.append(
                f"[{e['t'] - t0:+9.3f}s] #{e['seq']:<5d} {e['kind']:<12s} "
                f"objective={e.get('objective', '-')} "
                f"fast_burn={e.get('fast_burn', '-')} "
                f"slow_burn={e.get('slow_burn', '-')}")
    return lines


def _integrity_section(snap: dict, limit: int = 40) -> List[str]:
    """Integrity watchdog rollup: scrub coverage counters (slices, lists
    re-hashed), detected rot, containment/repair tallies, and the
    mismatch/quarantine/repair/restore timeline — a post-incident read
    of "what rotted, when was it caught, how was it fixed"."""
    counters = snap.get("metrics", {}).get("counters", {})
    stats = {name: counters.get(f"integrity.{name}", 0)
             for name in ("scans", "lists_scanned", "rot_injected",
                          "mismatches", "quarantines", "repairs",
                          "failed_repairs", "restores")}
    events = [e for e in snap.get("events", [])
              if str(e.get("kind", "")).startswith("integrity.")]
    if not (any(stats.values()) or events):
        return []
    lines = ["", "## Integrity", "",
             f"scrub slices: {stats['scans']}  "
             f"lists re-hashed: {stats['lists_scanned']}  "
             f"mismatches: {stats['mismatches']}"
             + (f"  (rot injected: {stats['rot_injected']})"
                if stats["rot_injected"] else ""),
             f"quarantines: {stats['quarantines']}  "
             f"repairs: {stats['repairs']}"
             + (f"  FAILED repairs: {stats['failed_repairs']}"
                if stats["failed_repairs"] else "")
             + (f"  restores: {stats['restores']}"
                if stats["restores"] else "")]
    notable = [e for e in events if e.get("kind") != "integrity.scan"]
    if notable:
        lines.append("")
        t0 = snap["events"][0]["t"] if snap.get("events") else 0.0
        for e in notable[-limit:]:
            fields = {k: v for k, v in e.items()
                      if k not in ("seq", "t", "kind")}
            detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
            kind = e["kind"].split(".", 1)[1]
            lines.append(f"[{e['t'] - t0:+9.3f}s] #{e['seq']:<5d} "
                         f"{kind:<12s} {detail}")
    return lines


def _job_section(snap: dict, limit: int = 80) -> List[str]:
    """The job runner's stage-transition timeline (raft_tpu.jobs): one
    line per kind="job" event — start/skip/resume/commit/failed/blocked/
    preempt plus the streaming checkpoint/resume beats — so a resumed or
    preempted long run reads as a story, not a grep."""
    events = [e for e in snap.get("events", []) if e.get("kind") == "job"]
    if not events:
        return []
    lines = ["", f"## Job timeline (stage transitions; last {limit})", ""]
    t0 = snap["events"][0]["t"] if snap.get("events") else 0.0
    for e in events[-limit:]:
        fields = {k: v for k, v in e.items()
                  if k not in ("seq", "t", "kind", "job", "stage", "action")}
        detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        where = e.get("job", "-")
        if e.get("stage"):
            where += f".{e['stage']}"
        lines.append(f"[{e['t'] - t0:+9.3f}s] #{e['seq']:<5d} "
                     f"{where:<28s} {e.get('action', '-'):<18s} {detail}")
    return lines


def _timeline_section(snap: dict,
                      kinds=("fault", "health", "retry", "compile", "log",
                             "mutation"),
                      limit: int = 60) -> List[str]:
    events = [e for e in snap.get("events", []) if e.get("kind") in kinds]
    if not events:
        return []
    lines = ["", f"## Timeline ({', '.join(kinds)}; last {limit})", ""]
    t0 = snap["events"][0]["t"] if snap.get("events") else 0.0
    for e in events[-limit:]:
        fields = {k: v for k, v in e.items() if k not in ("seq", "t", "kind")}
        detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        lines.append(f"[{e['t'] - t0:+9.3f}s] #{e['seq']:<5d} {e['kind']:<8s} {detail}")
    return lines


def render(snap: dict, title: str = "raft_tpu run report") -> str:
    """Render one snapshot dict (the `obs.snapshot()` shape) as text."""
    n_events = len(snap.get("events", []))
    counters = snap.get("metrics", {}).get("counters", {})
    gauges = snap.get("metrics", {}).get("gauges", {})
    lines = [f"# {title}", "",
             f"events: {n_events}  counters: {len(counters)}  "
             f"gauges: {len(gauges)}"]
    lines += _span_section(snap)
    lines += _perf_section(snap)
    lines += _comms_section(snap)
    lines += _serve_section(snap)
    lines += _trace_section(snap)
    lines += _slo_section(snap)
    lines += _integrity_section(snap)
    misc = {
        name: val for name, val in sorted(counters.items())
        if not name.startswith(("comms.", "integrity.", "perf.",
                                "serve.compile_cache.", "serve.outcome.",
                                "slo."))
        and val
    }
    if misc:
        lines += ["", "## Counters", ""] + _table(
            [[n, v] for n, v in misc.items()], ["counter", "value"])
    lines += _job_section(snap)
    lines += _timeline_section(snap)
    return "\n".join(lines) + "\n"


# -- cross-rank trace merge --------------------------------------------

def _rank_of(snap: dict, fallback: int) -> int:
    rank = snap.get("rank")
    return int(rank) if rank is not None else int(fallback)


def _merged_span_section(snaps: List[dict], ranks: List[int]) -> List[str]:
    names = sorted({
        name[len("span."):]
        for snap in snaps
        for name, agg in snap.get("metrics", {}).get("histograms", {}).items()
        if name.startswith("span.") and agg.get("count")
    })
    if not names:
        return []
    rows = []
    stragglers = []
    for name in names:
        totals = []
        for snap in snaps:
            agg = snap.get("metrics", {}).get("histograms", {}).get(
                f"span.{name}") or {}
            totals.append(float(agg.get("total") or 0.0))
        present = [t for t in totals if t > 0]
        skew = (max(present) / min(present)) if len(present) > 1 else None
        rows.append([name] + [_fmt_s(t) if t else "-" for t in totals]
                    + [f"{skew:.2f}x" if skew else "-"])
        if skew is not None and skew > 1.5:
            worst = ranks[totals.index(max(present))]
            stragglers.append(
                f"straggler: span {name!r} slowest on rank {worst} "
                f"({skew:.2f}x the fastest rank)")
    lines = ["", "## Per-rank span attribution", ""] + _table(
        rows, ["span"] + [f"r{r}" for r in ranks] + ["skew"])
    return lines + ([""] + stragglers if stragglers else [])


def _merged_comms_section(snaps: List[dict], ranks: List[int]) -> List[str]:
    ops = sorted({
        name[len("comms."):-len(".calls")]
        for snap in snaps
        for name in snap.get("metrics", {}).get("counters", {})
        if name.startswith("comms.") and name.endswith(".calls")
    })
    rows = []
    desyncs = []
    for op in ops:
        calls = [snap.get("metrics", {}).get("counters", {}).get(
            f"comms.{op}.calls", 0) for snap in snaps]
        if not any(calls):
            continue
        nbytes = [snap.get("metrics", {}).get("counters", {}).get(
            f"comms.{op}.bytes", 0) for snap in snaps]
        rows.append([op, "/".join(str(c) for c in calls),
                     "/".join(_fmt_bytes(b) for b in nbytes)])
        if len(set(calls)) > 1:
            desyncs.append(
                f"DESYNC: collective {op!r} call counts differ across "
                f"ranks ({'/'.join(str(c) for c in calls)}) — a rank is "
                f"missing collectives (hang risk)")
    if not rows:
        return []
    lines = ["", "## Collective skew (per-rank calls / payload bytes)",
             ""] + _table(rows, ["collective",
                                 "calls " + "/".join(f"r{r}" for r in ranks),
                                 "bytes"])
    return lines + ([""] + desyncs if desyncs else [])


def _merged_timeline(snaps: List[dict], ranks: List[int],
                     kinds=("fault", "health"), limit: int = 60) -> List[str]:
    merged = []
    for snap, rank in zip(snaps, ranks):
        for e in snap.get("events", []):
            if e.get("kind") in kinds:
                merged.append((int(e.get("seq", 0)), rank, e))
    if not merged:
        return []
    merged.sort(key=lambda item: (item[0], item[1]))
    lines = ["", f"## Merged timeline ({', '.join(kinds)}; aligned by "
                 f"per-rank seq; last {limit})", ""]
    for seq, rank, e in merged[-limit:]:
        fields = {k: v for k, v in e.items() if k not in ("seq", "t", "kind")}
        detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        lines.append(f"r{rank} #{seq:<5d} {e['kind']:<8s} {detail}")
    return lines


def render_merged(snaps: List[dict],
                  title: str = "raft_tpu merged rank report") -> str:
    """Render several per-rank snapshots as one distributed view. Ranks
    come from each snapshot's `rank` field (save order otherwise); the
    seq-ordered bus aligns the merged timeline — rank clocks are not
    comparable, sequence positions of the SPMD-identical programs are."""
    order = sorted(range(len(snaps)), key=lambda i: _rank_of(snaps[i], i))
    snaps = [snaps[i] for i in order]
    ranks = [_rank_of(snap, i) for i, snap in enumerate(snaps)]
    world = next((snap.get("world") for snap in snaps
                  if snap.get("world") is not None), None)
    lines = [f"# {title}", "",
             f"ranks merged: {len(snaps)}  world: {world if world else '-'}"]
    lines += _merged_span_section(snaps, ranks)
    lines += _merged_comms_section(snaps, ranks)
    lines += _merged_timeline(snaps, ranks)
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.report",
        description="Render a human-readable run report from an "
                    "obs.save_snapshot() JSON file ('-' reads stdin). "
                    "With --merge, several per-rank snapshots render as "
                    "one distributed timeline.",
    )
    parser.add_argument("snapshot", nargs="+",
                        help="path(s) to snapshot JSON, or '-'")
    parser.add_argument("--title", default=None)
    parser.add_argument("--merge", action="store_true",
                        help="merge several per-rank snapshots into one "
                             "distributed report")
    args = parser.parse_args(argv)

    def load(path):
        if path == "-":
            return json.load(sys.stdin)
        with open(path) as f:
            return json.load(f)

    if args.merge:
        snaps = [load(p) for p in args.snapshot]
        sys.stdout.write(render_merged(
            snaps, title=args.title or "raft_tpu merged rank report"))
        return 0
    if len(args.snapshot) != 1:
        parser.error("multiple snapshots require --merge")
    snap = load(args.snapshot[0])
    sys.stdout.write(render(snap, title=args.title or "raft_tpu run report"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
