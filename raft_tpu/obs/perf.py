"""Analytic cost model: FLOP/byte formulas per span, peaks, MFU.

The TPU-KNN paper (arxiv 2206.14286) frames every kernel decision in
FLOP/s-vs-peak roofline terms; ROADMAP open item 1 ("10x+ on the
pairwise-L2 hot path") is *judged* in those terms. This module is the
accounting half of that judgement: closed-form flops/bytes formulas for
the library's hot paths, registered per span name, so a span can charge
its analytic cost (`obs.span_cost(**perf.cost_for(name, ...))`) and the
report/bench layers can derive FLOP/s, B/s, and MFU against a
per-platform peak table.

Honesty rules, in order:
  - Peaks are *datasheet* numbers for real accelerators (v5e bf16/int8)
    and *nominal placeholders* for the CPU fallback — every CPU entry is
    tagged ``nominal`` and every derived MFU carries that tag through to
    the report, so a CPU rehearsal can never read as a chip roofline
    claim.
  - Formulas are models, not measurements. `xla_cost_analysis()` pulls
    XLA's own per-executable cost analysis so tests can pin the analytic
    formulas against what the compiler actually counted
    (tests/test_perf.py).
  - f32 flops are counted against the bf16 MXU peak (the achievable-rate
    configuration; f32-precision matmuls run *slower*, so the reported
    MFU is a lower bound, never an overclaim).

Pure host-side math: nothing here touches jax at module scope, and
`platform_info()` follows the bench harness's dead-relay discipline
(config string first, never initialize a backend that could hang).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

# -- peak table ---------------------------------------------------------

#: per-platform peaks. flops are per-chip dense peaks by compute dtype;
#: hbm_Bps is peak HBM bandwidth. "nominal" entries are bookkeeping
#: placeholders (an unknown host CPU has no datasheet) — MFU derived
#: from them is tagged and must never be read as a hardware claim.
PEAK_TABLE: Dict[str, dict] = {
    # TPU v5e datasheet: 197 bf16 TFLOP/s, 394 int8 TOPS, 819 GB/s HBM.
    # f32 deliberately shares the bf16 peak (see module docstring).
    # "int" is the VPU integer-op ceiling (popcount/AND/shift-add — the
    # RaBitQ bit-plane scan's op class, which never touches the MXU):
    # no datasheet number exists, so it is an ARCHITECTURAL estimate —
    # 8x128 vector lanes x 4 ALU issue x ~0.94 GHz ≈ 3.9 Tops — kept
    # deliberately on the high side so int-op MFU under-reports rather
    # than flatters (the same honesty direction as f32-at-bf16-peak).
    "tpu-v5e": {
        "peak_flops": {"bf16": 197e12, "f32": 197e12, "int8": 394e12,
                       "int": 3.9e12},
        "hbm_Bps": 819e9,
        "nominal": False,
    },
    # CPU fallback: nominal 200 GFLOP/s / 50 GB/s placeholders (a modern
    # vectorized server core's ballpark) so the arithmetic stays
    # runnable off-chip; honestly tagged. The "int" row is the same
    # NOMINAL class (vectorized popcount ballpark).
    "cpu": {
        "peak_flops": {"bf16": 200e9, "f32": 200e9, "int8": 400e9,
                       "int": 200e9},
        "hbm_Bps": 50e9,
        "nominal": True,
    },
}

_DTYPE_CANON = {
    "float32": "f32", "f32": "f32", "fp32": "f32",
    "bfloat16": "bf16", "bf16": "bf16",
    "float16": "bf16", "f16": "bf16",  # same MXU rate class
    "int8": "int8", "uint8": "int8",
    # 32-bit integer/logical VPU ops (popcount, AND, shift-add): their
    # own peak row — before this entry existed, uint32 popcount spans
    # fell to the f32 fallback and bit-plane MFU was charged against a
    # matmul peak it can never use (ISSUE 11 satellite)
    "int32": "int", "uint32": "int", "int": "int",
}

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "int8": 1, "int": 4}


def canon_dtype(dtype) -> str:
    """Normalize a dtype spelling (str, numpy/jax dtype, or scalar type
    like jnp.bfloat16) onto the peak table's keys; unknown dtypes count
    as f32 (the conservative rate)."""
    name = getattr(dtype, "name", None)
    if name is None and not isinstance(dtype, str):
        try:
            import numpy as _np

            name = _np.dtype(dtype).name
        except Exception:
            pass
    if name is None:
        name = str(dtype)
    return _DTYPE_CANON.get(name.lower(), "f32")


def dtype_bytes(dtype) -> int:
    return _DTYPE_BYTES[canon_dtype(dtype)]


def platform_info() -> dict:
    """Resolve the current platform onto the peak table WITHOUT risking a
    backend init that could hang (dead-relay discipline, bench/common.py):
    the jax config string decides CPU; only an importable live backend is
    consulted for the device kind. Returns a self-contained dict
    (platform / device_kind / peak_flops / hbm_Bps / nominal) that
    `obs.snapshot()` embeds, so a saved snapshot records which peaks its
    MFU numbers were computed against."""
    import jax

    platforms = str(jax.config.jax_platforms or "")
    if platforms.startswith("cpu"):
        return {"platform": "cpu", "device_kind": "cpu", **PEAK_TABLE["cpu"]}
    try:
        from raft_tpu.core.config import relay_transport_down

        if relay_transport_down():
            # chip intent but the transport is dead: probing would hang
            return {"platform": "unknown", "device_kind": "unreachable",
                    "peak_flops": {}, "hbm_Bps": None, "nominal": True}
    except Exception:
        pass
    try:
        dev = jax.devices()[0]
    except Exception:
        return {"platform": "unknown", "device_kind": "uninitialized",
                "peak_flops": {}, "hbm_Bps": None, "nominal": True}
    if dev.platform == "cpu":
        return {"platform": "cpu", "device_kind": "cpu", **PEAK_TABLE["cpu"]}
    kind = str(getattr(dev, "device_kind", dev.platform))
    # every TPU generation this library currently targets is v5e; an
    # unrecognized kind still gets the v5e row, with the kind recorded so
    # a wrong peak is diagnosable from the snapshot itself
    return {"platform": "tpu-v5e", "device_kind": kind,
            **PEAK_TABLE["tpu-v5e"]}


def mfu(flops_by_dtype: Dict[str, float], seconds: float,
        info: Optional[dict] = None) -> Optional[float]:
    """Model FLOP utilization: sum over dtypes of flops_d / peak_d,
    divided by wall seconds. None when no peak covers the dtypes or the
    interval is empty — an unknown platform yields no MFU, not 0%."""
    if seconds <= 0.0 or not flops_by_dtype:
        return None
    info = info if info is not None else platform_info()
    peaks = info.get("peak_flops") or {}
    peak_seconds = 0.0
    for dt, fl in flops_by_dtype.items():
        peak = peaks.get(canon_dtype(dt))
        if not peak:
            return None
        peak_seconds += float(fl) / float(peak)
    return peak_seconds / float(seconds)


# -- analytic formulas --------------------------------------------------
#
# Every formula returns {"flops": int, "bytes": int, "dtype": str} — the
# kwargs shape `obs.span_cost(**...)` takes. flops count multiply+add as
# 2; bytes count the model's unavoidable HBM traffic (operands read once
# per use, outputs written once), not cache behavior.
#
# Composite formulas built with `_add` additionally carry
# "flops_by_dtype": each stage's flops stay attributed to the dtype/peak
# of the unit that executes them (the coarse f32 matmul, the int8 MXU
# scan, the uint32 popcount fold), so a mixed-dtype span's MFU weighs
# every component against ITS OWN peak instead of collapsing onto one —
# the two-peak weighting the integer fused engines need (an int8 scan's
# flops against the bf16 peak would double-report, and popcount ops
# against any matmul peak would be meaningless).


def _cost(flops: float, nbytes: float, dtype) -> dict:
    return {"flops": int(flops), "bytes": int(nbytes),
            "dtype": canon_dtype(dtype)}


def pairwise_l2(n: int, m: int, d: int, dtype="f32") -> dict:
    """Expanded pairwise L2: ||x||^2 + ||y||^2 - 2<x,y> over (n, d) x
    (m, d). Dominant term is the 2nmd matmul; the norm/broadcast adds
    are kept so small shapes cross-check tightly against XLA."""
    b = dtype_bytes(dtype)
    flops = 2.0 * n * m * d          # the -2 x @ y.T matmul
    flops += 2.0 * (n + m) * d       # row norms (mul + add per element)
    flops += 3.0 * n * m             # scale + two broadcast adds
    nbytes = (n * d + m * d) * b + n * m * 4.0  # f32 score matrix out
    return _cost(flops, nbytes, dtype)


def select_k(rows: int, cols: int, k: int, fused: bool = False) -> dict:
    """Top-k selection over a (rows, cols) score matrix: one compare per
    candidate (model of a single-pass partial selection) plus the
    per-row heap/sort tail. `fused=True` models the in-kernel partial
    select (ops/fused_scan.py): the candidates are consumed where they
    are produced, so the (rows, cols) score read never hits HBM — only
    the (rows, k) result does. The flops stay (the compares still
    happen on the VPU); the bytes are what fusion deletes."""
    flops = float(rows) * cols + float(rows) * k * max(_log2(cols), 1.0)
    if fused:
        nbytes = float(rows) * k * 8.0
    else:
        nbytes = float(rows) * cols * 4.0 + float(rows) * k * 8.0
    return _cost(flops, nbytes, "f32")


def knn(n: int, nq: int, d: int, k: int, dtype="f32",
        fused: bool = False) -> dict:
    """Exact brute-force kNN = full pairwise L2 + select-k. With
    `fused=True` (the fused Pallas scan) neither the score-matrix write
    of the pairwise stage nor the score-matrix read of the select stage
    is charged — the fused geometry the banked MFU must reflect."""
    pw = pairwise_l2(n, nq, d, dtype)
    if fused:
        b = dtype_bytes(dtype)
        pw = _cost(pw["flops"], (n * d + nq * d) * b, dtype)
    return _add(pw, select_k(nq, n, k, fused=fused), dtype=dtype)


def ivf_flat_scan(nq: int, n_probes: int, n_lists: int, n_rows: int,
                  dim: int, k: int, dtype="f32",
                  scanned_lists: Optional[int] = None,
                  fused: bool = False) -> dict:
    """Coarse quantizer + list scan + select. `scanned_lists` is the
    number of lists each query's scores actually stream through: the
    query-major engines touch `n_probes` lists (the default), the
    LIST-MAJOR engines stream every list and mask non-probed scores —
    pass `scanned_lists=n_lists` there, or the model undercounts the
    real work by n_lists/n_probes. `n_rows` should be the PADDED slot
    count (n_lists * max_list) when known — pad slots are scored too.
    `fused=True` (the fused Pallas engine) drops the score-matrix
    bytes: the per-chunk scores fold to the candidate buffer in VMEM
    (the scan's own operand-stream bytes stay — they are the store
    read fusion cannot delete)."""
    rows = _probed_rows(n_rows, n_lists,
                        n_probes if scanned_lists is None else scanned_lists)
    coarse = pairwise_l2(nq, n_lists, dim, dtype)
    scan = _cost(2.0 * nq * rows * dim,
                 nq * rows * dim * dtype_bytes(dtype), dtype)
    return _add(coarse, scan, select_k(nq, rows, k, fused=fused),
                dtype=dtype)


def ivf_pq_scan(nq: int, n_probes: int, n_lists: int, n_rows: int,
                dim: int, pq_dim: int, k: int, dtype="bf16",
                scanned_lists: Optional[int] = None,
                fused: bool = False) -> dict:
    """Coarse quantizer + PQ code scoring (reconstruct-and-dot model of
    the recon engines: one fused multiply-add per reconstructed
    dimension) + select. `scanned_lists`/`n_rows` follow the
    `ivf_flat_scan` convention (list-major engines stream EVERY padded
    list). Bytes are dominated by the per-(query, list) code reads —
    1 byte per pq_dim — which is exactly the wire the quantization
    exists to shrink. `fused=True` (the pallas/fused trims) drops the
    score-matrix bytes from the select stage, like `ivf_flat_scan`."""
    rows = _probed_rows(n_rows, n_lists,
                        n_probes if scanned_lists is None else scanned_lists)
    coarse = pairwise_l2(nq, n_lists, dim, "f32")
    scan = _cost(2.0 * nq * rows * dim, nq * rows * float(pq_dim), dtype)
    return _add(coarse, scan, select_k(nq, rows, k, fused=fused),
                dtype=dtype)


def rabitq_scan(nq: int, n_probes: int, n_lists: int, n_rows: int,
                dim: int, k: int, query_bits: int = 8,
                rerank_mult: int = 0, fused: bool = False) -> dict:
    """Binary-code integer scan: per (query, candidate) one AND+popcount
    per 32-bit word per query bit plane — charged as "int" ops (uint32
    VPU popcount/logical class, its own peak row: these ops never touch
    the MXU, so weighing them against a matmul peak would be
    meaningless), plus the exact rerank of rerank_mult*k candidates when
    enabled. `fused=True` (the fused bit-plane kernel) drops the
    score-matrix bytes from the select stage AND the materialized
    bit-plane intersection tensor bytes the XLA reference pays — the
    packed-code stream itself stays (fusion cannot delete the store
    read)."""
    rows = _probed_rows(n_rows, n_lists, n_probes)
    words = (int(dim) + 31) // 32
    bits = max(1, int(query_bits))
    coarse = pairwise_l2(nq, n_lists, dim, "f32")
    # AND + popcount + shift-add per (pair, word, plane): 2 ops modeled,
    # the multiply+add convention applied to the integer unit
    scan_bytes = nq * rows * words * 4.0
    if not fused:
        # the XLA reference materializes the (nq, probes, rows, bits, W)
        # intersection tensor in blocks — charge its dominant write-out
        scan_bytes += nq * rows * bits * words * 4.0
    scan = _cost(2.0 * nq * rows * words * bits, scan_bytes, "int")
    parts = [coarse, scan,
             select_k(nq, rows, max(k, rerank_mult * k or k), fused=fused)]
    if rerank_mult:
        # exact rerank: EVERY query gathers its own distinct
        # rerank_mult*k-row shortlist from the dataset, so the bytes
        # term scales with nq (operands read once per use)
        cand = float(rerank_mult) * k
        parts.append(_cost(2.0 * nq * cand * dim + 3.0 * nq * cand,
                           nq * cand * dim * 4.0 + nq * dim * 4.0, "f32"))
    return _add(*parts, dtype="int")


def refine_rerank(nq: int, n_cand: int, dim: int, k: int, dtype="f32",
                  fused: bool = False) -> dict:
    """Exact re-rank of per-query candidate sets (neighbors/refine):
    every query gathers its own n_cand-row shortlist, one batched
    matvec scores it, select keeps k. `fused=True` (the fused rerank
    kernel) drops the (nq, n_cand) score round-trip from the select
    stage — the gathered candidate stream stays."""
    b = dtype_bytes(dtype)
    flops = 2.0 * nq * n_cand * dim + 3.0 * nq * n_cand
    nbytes = nq * n_cand * dim * b + nq * dim * b
    return _add(_cost(flops, nbytes, dtype),
                select_k(nq, n_cand, k, fused=fused), dtype=dtype)


def kmeans_step(n: int, d: int, n_clusters: int, iters: int = 1,
                dtype="f32") -> dict:
    """One Lloyd iteration: assignment (pairwise L2 vs centers) plus the
    weighted center update (2nd flops)."""
    one = _add(pairwise_l2(n, n_clusters, d, dtype),
               _cost(2.0 * n * d, n * d * dtype_bytes(dtype), dtype),
               dtype=dtype)
    return _cost(one["flops"] * max(1, int(iters)),
                 one["bytes"] * max(1, int(iters)), dtype)


#: per-rank wire-traffic factor by collective op (ring algorithms),
#: RELATIVE TO THE PAYLOAD obs.collective counts for that op — which is
#: the op's per-rank INPUT: the full buffer for allreduce/reducescatter/
#: bcast/barrier, but only the local SHARD for allgather (a ring
#: allgather forwards every other rank's shard through each rank, so
#: its factor is (w-1), not (w-1)/w). The EQuARX-style savings claim
#: (ROADMAP item 3) will be judged against exactly these counters.
WIRE_FACTORS: Dict[str, Callable[[int], float]] = {
    "allreduce": lambda w: 2.0 * (w - 1) / w,
    "allgather": lambda w: float(w - 1),
    "reducescatter": lambda w: float(w - 1) / w,
    "bcast": lambda w: float(w - 1) / w,
    "barrier": lambda w: 2.0 * (w - 1) / w,
    "device_sendrecv": lambda w: 1.0,
    "shift": lambda w: 1.0,
    "device_multicast_sendrecv": lambda w: 1.0,
}


def collective_wire_bytes(op: str, nbytes: int, world: int) -> int:
    """Modeled per-rank bytes on the wire for one collective of per-rank
    payload `nbytes` over `world` ranks (0 for world < 2 — a
    single-rank collective moves nothing)."""
    if world is None or world < 2:
        return 0
    factor = WIRE_FACTORS.get(op, lambda w: float(w - 1) / w)
    return int(float(nbytes) * factor(int(world)))


def _probed_rows(n_rows: int, n_lists: int, n_probes) -> float:
    # n_probes may be FRACTIONAL: adaptive probing charges the actual
    # per-query scanned-list mean, not the worst-case integer knob
    per_list = (float(n_rows) / max(1, int(n_lists)))
    return per_list * min(float(n_probes), float(int(n_lists)))


def _log2(x: float) -> float:
    import math

    return math.log2(max(2.0, float(x)))


def _add(*costs: dict, dtype=None) -> dict:
    flops = sum(c["flops"] for c in costs)
    nbytes = sum(c["bytes"] for c in costs)
    by: Dict[str, int] = {}
    for c in costs:
        sub = c.get("flops_by_dtype") or {c["dtype"]: c["flops"]}
        for dt, fl in sub.items():
            if fl:
                by[dt] = by.get(dt, 0) + int(fl)
    out = _cost(flops, nbytes, dtype if dtype is not None
                else costs[0]["dtype"])
    out["flops_by_dtype"] = by
    return out


# -- the per-span registry ---------------------------------------------

#: span name -> formula. Instrumented entry points resolve their span's
#: formula through here (`cost_for`), so "which spans have a cost
#: model" is one reviewable table, and the report can distinguish
#: "span with no model" from "model says zero".
SPAN_COST_MODEL: Dict[str, Callable[..., dict]] = {
    "neighbors.brute_force.knn": knn,
    "neighbors.ivf_flat.search": ivf_flat_scan,
    "neighbors.ivf_pq.search": ivf_pq_scan,
    "neighbors.refine": refine_rerank,
    "neighbors.ivf_rabitq.search": rabitq_scan,
    "mnmg.knn": knn,
    "mnmg.kmeans_fit": kmeans_step,
    "mnmg.ivf_flat_search": ivf_flat_scan,
    "mnmg.ivf_pq_search": ivf_pq_scan,
    "mnmg.ivf_rabitq_search": rabitq_scan,
}


def register(span_name: str, fn: Callable[..., dict]) -> None:
    """Register (or override) the cost formula for a span name."""
    SPAN_COST_MODEL[str(span_name)] = fn


def cost_for(span_name: str, **shape) -> dict:
    """Evaluate the registered formula for `span_name` with the given
    shape kwargs. KeyError for unregistered spans — a typo'd span name
    must fail loudly in the instrumented code path's tests, not
    silently charge nothing."""
    return SPAN_COST_MODEL[span_name](**shape)


# -- XLA cross-check ----------------------------------------------------

def xla_cost_analysis(fn, *args, **kwargs) -> Optional[dict]:
    """Compile `fn(*args, **kwargs)` and return XLA's own
    {"flops", "bytes"} for the executable, or None when the backend
    doesn't expose cost analysis. This is the ground truth the analytic
    formulas are pinned against (tests/test_perf.py)."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes"] = float(ca["bytes accessed"])
    return out or None
