"""Structured spans: nested, timed scopes over the real hot paths.

The reference annotates every major entry point with NVTX RAII ranges
(`core/nvtx.hpp`); `core/tracing.trace_range` is the TPU analogue for
the *profiler* timeline. Spans are the *accounting* analogue: each one
times a named scope with the monotonic clock, knows its parent (a
per-thread stack), lands one "span" event on the bus at close, and
aggregates its duration into the `span.<name>` histogram — so a run
report can say where wall-clock went without a profiler session.

Timing semantics (important on an async backend): a span measures HOST
wall time of the scope. jax dispatch returns before the device
finishes, so a span around `search(...)` alone measures dispatch. To
charge device time to the span, fence the result inside the scope:

    with obs.span("ivf.search") as sp:
        vals, ids = ivf_flat.search(p, index, q, k)
        sp.fence((vals, ids))      # block_until_ready inside the timer

`fence` returns its argument, so it composes inline. With observability
disabled `span()` yields an inert singleton and touches no clock, no
stack, no lock — the disabled overhead is one module-attribute read and
one branch.
"""

from __future__ import annotations

import contextlib
import threading
import time

from raft_tpu.obs import bus as _bus_mod
from raft_tpu.obs import registry as _reg_mod

_TLS = threading.local()


class Span:
    """One open scope. `set(**attrs)` attaches fields to the close
    event; `fence(x)` blocks on device results inside the timer."""

    __slots__ = ("name", "depth", "parent", "attrs", "t0")

    def __init__(self, name: str, depth: int, parent, attrs: dict):
        self.name = name
        self.depth = depth
        self.parent = parent
        self.attrs = attrs
        self.t0 = time.monotonic()

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def fence(self, value):
        """`jax.block_until_ready(value)` so the span's duration covers
        device execution, not just dispatch. Returns `value`."""
        import jax

        return jax.block_until_ready(value)


class _NullSpan:
    """Inert stand-in yielded when observability is disabled: same
    surface, zero work (fence still blocks — callers rely on the
    synchronization side effect, not just the timing)."""

    __slots__ = ()
    name = None
    depth = 0
    parent = None

    def set(self, **attrs):
        return self

    def fence(self, value):
        import jax

        return jax.block_until_ready(value)


NULL_SPAN = _NullSpan()


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


@contextlib.contextmanager
def span_impl(name: str, **attrs):
    """The enabled-path implementation behind `raft_tpu.obs.span` (the
    public wrapper owns the enabled check so the disabled path never
    enters a generator frame)."""
    st = _stack()
    sp = Span(str(name), depth=len(st), parent=st[-1].name if st else None,
              attrs=attrs)
    st.append(sp)
    try:
        yield sp
    finally:
        st.pop()
        dur = time.monotonic() - sp.t0
        _reg_mod.GLOBAL.histogram(f"span.{sp.name}").observe(dur)
        _bus_mod.GLOBAL.publish(
            "span", name=sp.name, depth=sp.depth, parent=sp.parent,
            dur_s=dur, **sp.attrs,
        )


def current_span():
    """The innermost open span on this thread, or None."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


class SpanCapture:
    """Subscribe-and-aggregate helper: collects span events while
    active and reduces them to per-name totals — the shape
    `bench.common.run_case` banks as per-phase attribution.

        with obs.capture_spans() as cap:
            run_workload()
        cap.totals()  # {"neighbors.ivf_flat.search": {"calls": 5,
                      #   "total_ms": 12.3, "max_ms": 3.1}, ...}
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: dict = {}

    def _on_event(self, event: dict) -> None:
        if event.get("kind") != "span":
            return
        name = event["name"]
        dur_ms = float(event["dur_s"]) * 1e3
        with self._lock:
            row = self._acc.setdefault(
                name, {"calls": 0, "total_ms": 0.0, "max_ms": 0.0})
            row["calls"] += 1
            row["total_ms"] += dur_ms
            row["max_ms"] = max(row["max_ms"], dur_ms)

    def totals(self) -> dict:
        with self._lock:
            return {
                name: {
                    "calls": row["calls"],
                    "total_ms": round(row["total_ms"], 3),
                    "max_ms": round(row["max_ms"], 3),
                }
                for name, row in sorted(self._acc.items())
            }


@contextlib.contextmanager
def capture_spans():
    cap = SpanCapture()
    _bus_mod.GLOBAL.subscribe(cap._on_event)
    try:
        yield cap
    finally:
        _bus_mod.GLOBAL.unsubscribe(cap._on_event)
