"""Structured spans: nested, timed scopes over the real hot paths.

The reference annotates every major entry point with NVTX RAII ranges
(`core/nvtx.hpp`); `core/tracing.trace_range` is the TPU analogue for
the *profiler* timeline. Spans are the *accounting* analogue: each one
times a named scope with the monotonic clock, knows its parent (a
per-thread stack), lands one "span" event on the bus at close, and
aggregates its duration into the `span.<name>` histogram — so a run
report can say where wall-clock went without a profiler session.

Timing semantics (important on an async backend): a span measures HOST
wall time of the scope. jax dispatch returns before the device
finishes, so a span around `search(...)` alone measures dispatch. To
charge device time to the span, fence the result inside the scope:

    with obs.span("ivf.search") as sp:
        vals, ids = ivf_flat.search(p, index, q, k)
        sp.fence((vals, ids))      # block_until_ready inside the timer

`fence` returns its argument, so it composes inline. With observability
disabled `span()` yields an inert singleton and touches no clock, no
stack, no lock — the disabled overhead is one module-attribute read and
one branch.
"""

from __future__ import annotations

import contextlib
import threading
import time

from raft_tpu.obs import bus as _bus_mod
from raft_tpu.obs import registry as _reg_mod

_TLS = threading.local()

# every thread's span stack, registered on first use so the flight
# recorder can enumerate what was OPEN at crash time across all threads
# (entries are tiny and live for the process; the lock is taken once
# per thread lifetime, never per span)
_STACKS_LOCK = threading.Lock()
_ALL_STACKS: dict = {}


class Span:
    """One open scope. `set(**attrs)` attaches fields to the close
    event; `cost()` charges analytic flops/bytes (obs.perf formulas);
    `fence(x)` blocks on device results inside the timer."""

    __slots__ = ("name", "depth", "parent", "attrs", "t0")

    def __init__(self, name: str, depth: int, parent, attrs: dict):
        self.name = name
        self.depth = depth
        self.parent = parent
        self.attrs = attrs
        self.t0 = time.monotonic()

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def cost(self, flops=None, bytes=None, dtype=None,
             flops_by_dtype=None, **attrs) -> "Span":
        """Charge analytic cost to this span (accumulating PER DTYPE —
        a span that charges a bf16 scan and then an f32 rerank keeps
        both sums, so mixed-precision MFU weighs each against its own
        peak). A composite `obs.perf` formula passes the authoritative
        per-dtype split as `flops_by_dtype` (one charge, several peaks
        — the integer fused engines' int8+popcount spans); `flops` then
        only cross-checks the total. On close the totals land in the
        span event (`cost_flops` total, `cost_flops_by_dtype`,
        `cost_bytes`, `cost_dtype` = last charged) and in the
        deterministic `perf.<name>.flops.<dtype>` / `perf.<name>.bytes`
        counters the report and Prometheus exporter read."""
        dt = str(dtype) if dtype is not None else "f32"
        if flops_by_dtype:
            by = self.attrs.setdefault("cost_flops_by_dtype", {})
            total = 0
            for sub_dt, fl in flops_by_dtype.items():
                if fl:
                    by[str(sub_dt)] = by.get(str(sub_dt), 0) + int(fl)
                    total += int(fl)
            self.attrs["cost_flops"] = (
                self.attrs.get("cost_flops", 0) + total)
        elif flops:
            by = self.attrs.setdefault("cost_flops_by_dtype", {})
            by[dt] = by.get(dt, 0) + int(flops)
            self.attrs["cost_flops"] = (
                self.attrs.get("cost_flops", 0) + int(flops))
        if bytes:
            self.attrs["cost_bytes"] = (
                self.attrs.get("cost_bytes", 0) + int(bytes))
        if dtype is not None:
            self.attrs["cost_dtype"] = dt
        self.attrs.update(attrs)
        return self

    def fence(self, value):
        """`jax.block_until_ready(value)` so the span's duration covers
        device execution, not just dispatch. Returns `value`."""
        import jax

        return jax.block_until_ready(value)


class _NullSpan:
    """Inert stand-in yielded when observability is disabled: same
    surface, zero work (fence still blocks — callers rely on the
    synchronization side effect, not just the timing)."""

    __slots__ = ()
    name = None
    depth = 0
    parent = None

    def set(self, **attrs):
        return self

    def cost(self, flops=None, bytes=None, dtype=None,
             flops_by_dtype=None, **attrs):
        return self

    def fence(self, value):
        import jax

        return jax.block_until_ready(value)


NULL_SPAN = _NullSpan()


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
        with _STACKS_LOCK:
            _ALL_STACKS[threading.get_ident()] = (
                threading.current_thread().name, st)
    return st


def open_spans() -> list:
    """Every currently-open span across all threads (the flight
    recorder's 'what was in progress' section): [{"thread", "name",
    "depth", "attrs"}], outermost first per thread, sorted by thread
    name for deterministic dumps."""
    with _STACKS_LOCK:
        stacks = [(name, list(st)) for name, st in _ALL_STACKS.values() if st]
    out = []
    for tname, spans in sorted(stacks, key=lambda x: x[0]):
        for sp in spans:
            out.append({"thread": tname, "name": sp.name, "depth": sp.depth,
                        "attrs": dict(sp.attrs)})
    return out


@contextlib.contextmanager
def span_impl(name: str, **attrs):
    """The enabled-path implementation behind `raft_tpu.obs.span` (the
    public wrapper owns the enabled check so the disabled path never
    enters a generator frame)."""
    st = _stack()
    sp = Span(str(name), depth=len(st), parent=st[-1].name if st else None,
              attrs=attrs)
    st.append(sp)
    try:
        yield sp
    finally:
        st.pop()
        dur = time.monotonic() - sp.t0
        _reg_mod.GLOBAL.histogram(f"span.{sp.name}").observe(dur)
        # charged analytic cost lands in deterministic counters so the
        # report / Prometheus exporter never depend on the bounded event
        # ring keeping the spans around (one counter per charged dtype)
        for dt, fl in sorted((sp.attrs.get("cost_flops_by_dtype")
                              or {}).items()):
            if fl:
                _reg_mod.GLOBAL.counter(
                    f"perf.{sp.name}.flops.{dt}").inc(int(fl))
        by = sp.attrs.get("cost_bytes")
        if by:
            _reg_mod.GLOBAL.counter(f"perf.{sp.name}.bytes").inc(int(by))
        _bus_mod.GLOBAL.publish(
            "span", name=sp.name, depth=sp.depth, parent=sp.parent,
            dur_s=dur, thread=threading.current_thread().name, **sp.attrs,
        )


def current_span():
    """The innermost open span on this thread, or None."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


class SpanCapture:
    """Subscribe-and-aggregate helper: collects span events while
    active and reduces them to per-name totals — the shape
    `bench.common.run_case` banks as per-phase attribution.

        with obs.capture_spans() as cap:
            run_workload()
        cap.totals()  # {"neighbors.ivf_flat.search": {"calls": 5,
                      #   "total_ms": 12.3, "max_ms": 3.1}, ...}
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: dict = {}

    def _on_event(self, event: dict) -> None:
        if event.get("kind") != "span":
            return
        name = event["name"]
        dur_ms = float(event["dur_s"]) * 1e3
        with self._lock:
            row = self._acc.setdefault(
                name, {"calls": 0, "total_ms": 0.0, "max_ms": 0.0,
                       "flops": {}, "bytes": 0})
            row["calls"] += 1
            row["total_ms"] += dur_ms
            row["max_ms"] = max(row["max_ms"], dur_ms)
            for dt, fl in (event.get("cost_flops_by_dtype") or {}).items():
                row["flops"][dt] = row["flops"].get(dt, 0) + int(fl)
            row["bytes"] += int(event.get("cost_bytes", 0) or 0)

    def cost_totals(self) -> dict:
        """Charged cost summed across every captured span:
        {"flops", "by_dtype", "bytes"}. The caller owns the wall-clock
        window to divide by — `bench.common.run_case` divides by its
        FENCED timed loop, which is the honest MFU for a bench row
        (span windows are host dispatch time; see `totals`)."""
        with self._lock:
            by_dtype: dict = {}
            nbytes = 0
            for row in self._acc.values():
                for dt, fl in row["flops"].items():
                    by_dtype[dt] = by_dtype.get(dt, 0) + fl
                nbytes += row["bytes"]
        return {"flops": sum(by_dtype.values()), "by_dtype": by_dtype,
                "bytes": nbytes}

    def totals(self) -> dict:
        """Per-name aggregates. Names whose spans charged an analytic
        cost (obs.perf) additionally carry flops/bytes and the derived
        gflops_per_s / MFU vs the current platform's peak table —
        `mfu_nominal: true` marks a placeholder (CPU) peak.

        Caveat (same as the span timing contract above): a span's
        window is HOST wall time, so for spans that dispatch async
        device work without fencing, the derived rate is per unit of
        dispatch time, not device time. Spans that fence (serve.batch)
        read true; bench rows get an authoritative fenced MFU from
        `run_case` via `cost_totals()`."""
        info = None
        with self._lock:
            acc = {name: dict(row, flops=dict(row["flops"]))
                   for name, row in self._acc.items()}
        out = {}
        for name, row in sorted(acc.items()):
            entry = {
                "calls": row["calls"],
                "total_ms": round(row["total_ms"], 3),
                "max_ms": round(row["max_ms"], 3),
            }
            flops = sum(row["flops"].values())
            if flops:
                entry["flops"] = flops
                if row["bytes"]:
                    entry["bytes"] = row["bytes"]
                secs = row["total_ms"] / 1e3
                if secs > 0:
                    entry["gflops_per_s"] = round(flops / secs / 1e9, 3)
                    try:
                        if info is None:
                            from raft_tpu.obs import perf as _perf

                            info = _perf.platform_info()
                        m = _perf.mfu(row["flops"], secs, info)
                    except Exception:  # attribution must never kill a bench
                        m = None
                    if m is not None:
                        entry["mfu"] = round(m, 6)
                        if info.get("nominal"):
                            entry["mfu_nominal"] = True
            out[name] = entry
        return out


@contextlib.contextmanager
def capture_spans():
    cap = SpanCapture()
    _bus_mod.GLOBAL.subscribe(cap._on_event)
    try:
        yield cap
    finally:
        _bus_mod.GLOBAL.unsubscribe(cap._on_event)
