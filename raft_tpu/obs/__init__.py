"""raft_tpu.obs — library-wide observability.

The cross-cutting layer the ROADMAP's serving/perf work reads its
numbers from: a thread-safe metric registry (counters / gauges /
histograms), structured nested spans, and an ordered event bus that the
comms collectives, MNMG drivers, neighbors entry points, the serving
engine, `core.faults` chaos injections, and `core.logger` all feed.
Exporters render the joined state as a JSON snapshot, Prometheus
exposition text, or a `jax.profiler` trace session;
`python -m raft_tpu.obs.report` turns a snapshot into a human-readable
run report.

Gating: everything is OFF by default. Enable with `RAFT_TPU_OBS=1` in
the environment or `obs.enable()` at runtime. Disabled, every
instrumentation hook is one module-attribute read and a branch —
measured within noise of the pre-instrumentation library (see
docs/observability.md) — and traced programs are byte-identical either
way (instrumentation is host-side only; nothing is ever inserted into
a jaxpr).

Counting semantics under jit: collective instruments count at TRACE
time (XLA owns execution; a cached executable re-runs without
re-tracing), so "comms.allreduce.calls" answers "how many allreduce ops
did the programs traced during this window contain", which is the
deterministic number a test can pin. Spans and serve/fault events are
host-side and count per call.

Public surface:

    obs.enable() / obs.disable() / obs.enabled()
    obs.registry() -> Registry       obs.counter/gauge/histogram(name)
    obs.bus() -> EventBus            obs.event(kind, **fields)
    obs.span(name, **attrs)          obs.capture_spans()
    obs.span_cost(flops=, bytes=)    (analytic-cost hook; obs.perf formulas)
    obs.trace_range / obs.annotate   (re-exported from core.tracing)
    obs.collective(op, x, axis=..., world=...)  (comms hook)
    obs.snapshot() / obs.save_snapshot(path)
    obs.render_prometheus(...) / obs.render_registry_prometheus()
    obs.trace_session(logdir)
    obs.reset()
"""

from __future__ import annotations

import os

# submodule-path imports keep this package safe to import from inside
# raft_tpu.core's own init (core.faults -> obs -> core.tracing)
from raft_tpu.core.tracing import annotate, trace_range  # noqa: F401
from raft_tpu.obs import bus as _bus_mod
from raft_tpu.obs import registry as _reg_mod
from raft_tpu.obs.export import (  # noqa: F401
    prom_name,
    render_prometheus,
    render_registry_prometheus,
    save_snapshot,
    snapshot,
    trace_session,
)
from raft_tpu.obs import flight, ledger, perf, slo, trace  # noqa: F401
from raft_tpu.obs.registry import Counter, Gauge, Histogram, Registry  # noqa: F401
from raft_tpu.obs.spans import (  # noqa: F401
    NULL_SPAN,
    SpanCapture,
    capture_spans,
    current_span,
    open_spans,
    span_impl,
)
from raft_tpu.obs.trace import TraceCtx, to_chrome_trace  # noqa: F401

ENV_FLAG = "RAFT_TPU_OBS"

_ENABLED = False
_LOG_HANDLER = None


def enabled() -> bool:
    return _ENABLED


def enable(flag: bool = True) -> None:
    """Turn observability on (or off with `flag=False`). Enabling also
    bridges `core.logger` records onto the event bus; disabling removes
    the bridge. Idempotent."""
    global _ENABLED
    _ENABLED = bool(flag)
    _bridge_logger(_ENABLED)
    if _ENABLED:
        # RAFT_TPU_FLIGHT_DIR auto-arms the crash flight recorder
        flight.maybe_env_install()


def disable() -> None:
    enable(False)


def _bridge_logger(install: bool) -> None:
    """Install/remove the logging.Handler that routes raft_tpu log
    records to the bus as kind="log" events. Lives here (not in
    core/logger) so the logger has zero obs dependency and the disabled
    path pays nothing."""
    global _LOG_HANDLER
    import importlib
    import logging

    # NOT `import raft_tpu.core.logger as m`: the core package re-binds
    # the attribute `logger` to the Logger OBJECT, shadowing the module
    # for every attribute-based import form
    _logger_mod = importlib.import_module("raft_tpu.core.logger")

    if install:
        if _LOG_HANDLER is None:
            class _BusHandler(logging.Handler):
                def emit(self, record):
                    try:
                        event("log", level=record.levelname,
                              logger=record.name, msg=record.getMessage())
                    except Exception:
                        self.handleError(record)

            _LOG_HANDLER = _BusHandler()
        if _LOG_HANDLER not in _logger_mod.logger.handlers:
            _logger_mod.logger.addHandler(_LOG_HANDLER)
    elif _LOG_HANDLER is not None:
        _logger_mod.logger.removeHandler(_LOG_HANDLER)


def registry() -> Registry:
    return _reg_mod.GLOBAL


def bus() -> _bus_mod.EventBus:
    return _bus_mod.GLOBAL


def counter(name: str) -> Counter:
    return _reg_mod.GLOBAL.counter(name)


def gauge(name: str) -> Gauge:
    return _reg_mod.GLOBAL.gauge(name)


def histogram(name: str) -> Histogram:
    return _reg_mod.GLOBAL.histogram(name)


def event(kind: str, **fields):
    """Publish one event when enabled; returns its seq (None when
    disabled). The one hook every instrumented site calls."""
    if not _ENABLED:
        return None
    return _bus_mod.GLOBAL.publish(kind, **fields)


def span(name: str, **attrs):
    """Nested timed scope (see `obs.spans`). Disabled: yields an inert
    singleton without entering a generator frame."""
    if not _ENABLED:
        return _NULL_CTX
    return span_impl(name, **attrs)


class _ReusableNullCtx:
    """Allocation-free disabled-path context manager (a fresh
    generator per call would dominate the disabled cost)."""

    __slots__ = ()

    def __enter__(self):
        return NULL_SPAN

    def __exit__(self, *exc):
        return False


_NULL_CTX = _ReusableNullCtx()


def spanned(name: str, **attrs):
    """Decorator form of `span` (the obs counterpart of
    `tracing.annotate`): wraps entry points so every call lands one
    timed span. Disabled, the wrapper costs one attribute read and a
    branch before tail-calling the target."""
    import functools

    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return f(*args, **kwargs)
            with span_impl(name, **attrs):
                return f(*args, **kwargs)

        return wrapper

    return deco


def span_cost(flops=None, bytes=None, dtype=None, flops_by_dtype=None,
              **attrs):
    """Charge analytic cost (an `obs.perf` formula's kwargs) to the
    innermost open span on this thread; no-op when disabled or outside
    any span. Composite formulas pass their per-dtype flops split as
    `flops_by_dtype` so mixed-dtype spans (int8 scan + f32 coarse +
    uint32 popcount) weigh each component against its own peak. Returns
    the span (None when nothing was charged)."""
    if not _ENABLED:
        return None
    sp = current_span()
    if sp is not None:
        sp.cost(flops=flops, bytes=bytes, dtype=dtype,
                flops_by_dtype=flops_by_dtype, **attrs)
    return sp


def collective(op: str, x, axis: str = "", world=None, wire_bytes=None,
               wire_dtype=None) -> None:
    """Comms instrumentation hook: account one collective op of payload
    `x` (array or tracer — only .shape/.dtype are touched, so this is
    trace-safe and never materializes anything). With `world`, the
    modeled per-rank wire traffic (obs.perf.collective_wire_bytes) is
    additionally counted — the byte history EQuARX-style wire-savings
    claims are judged against.

    Quantized transports (comms/quantized) pass `wire_bytes` — the
    ACTUAL per-rank bytes moved (quantized payload + scale sidecars,
    summed over ring hops) — overriding the `world` model, plus
    `wire_dtype` naming the wire representation; `x` stays the LOGICAL
    payload, so `comms.<op>.bytes` keeps counting what callers asked to
    move while `comms.<op>.wire_bytes` counts what the wire carried."""
    if not _ENABLED:
        return
    try:
        shape = getattr(x, "shape", ())
        dtype = getattr(x, "dtype", None)
        itemsize = getattr(dtype, "itemsize", None)
        if itemsize is None:
            import numpy as _np

            itemsize = _np.dtype(dtype if dtype is not None else _np.float32).itemsize
        nbytes = int(itemsize)
        for dim in shape:
            nbytes *= int(dim)
    except (TypeError, ValueError):
        nbytes = 0
    _reg_mod.GLOBAL.counter(f"comms.{op}.calls").inc()
    _reg_mod.GLOBAL.counter(f"comms.{op}.bytes").inc(nbytes)
    fields = {}
    if wire_bytes is not None:
        wire = int(wire_bytes)
        _reg_mod.GLOBAL.counter(f"comms.{op}.wire_bytes").inc(wire)
        fields["wire_bytes"] = wire
        if wire_dtype is not None:
            fields["wire_dtype"] = str(wire_dtype)
        if world is not None:
            fields["world"] = int(world)
    elif world is not None:
        wire = perf.collective_wire_bytes(op, nbytes, int(world))
        _reg_mod.GLOBAL.counter(f"comms.{op}.wire_bytes").inc(wire)
        fields["wire_bytes"] = wire
        fields["world"] = int(world)
    _bus_mod.GLOBAL.publish("collective", op=op, bytes=nbytes, axis=axis,
                            **fields)


def reset() -> None:
    """Zero every global metric, clear the event log, restart the
    trace-id mint, and clear the flight ring (test hygiene;
    enabled/disabled state is untouched). The mint reset is what makes
    a replayed drill re-mint the identical trace-id sequence."""
    _reg_mod.GLOBAL.reset()
    _bus_mod.GLOBAL.clear()
    trace.reset()
    flight.reset()


# honor the environment gate at import time so `RAFT_TPU_OBS=1 python
# -m ...` needs no code change to light the whole library up
if os.environ.get(ENV_FLAG, "").strip().lower() not in ("", "0", "false", "off"):
    enable()


__all__ = [
    "ENV_FLAG",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "SpanCapture",
    "annotate",
    "bus",
    "capture_spans",
    "collective",
    "counter",
    "current_span",
    "disable",
    "flight",
    "enable",
    "enabled",
    "event",
    "gauge",
    "histogram",
    "ledger",
    "open_spans",
    "perf",
    "prom_name",
    "registry",
    "render_prometheus",
    "render_registry_prometheus",
    "reset",
    "save_snapshot",
    "slo",
    "snapshot",
    "span",
    "span_cost",
    "spanned",
    "to_chrome_trace",
    "trace",
    "trace_range",
    "trace_session",
    "TraceCtx",
]
