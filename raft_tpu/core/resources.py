"""Resources: the light-weight TPU-native handle.

Reference parity: `raft::resources` (core/resources.hpp:46) is a type-indexed
registry of lazily-created resources (streams, cuBLAS/cuSolver handles,
memory resources, comms); `raft::device_resources` (core/device_resources.hpp:60)
is the ergonomic accessor facade that every public API takes as its first
argument, and pylibraft's `DeviceResources` (common/handle.pyx:34) wraps it.

On TPU the vendor-handle zoo disappears — XLA owns streams, allocation and
BLAS — so `Resources` keeps only what still has meaning:

  - the target `device` (or sharding `mesh` for SPMD execution),
  - a functional RNG key stream (`new_key`),
  - the comms object (`set_comms`/`get_comms`, §2.8 of the survey) and named
    sub-comms (`set_sub_comms`, mirrors core/resource/sub_comms.hpp),
  - a registry for user-defined resources with lazy factories, mirroring
    resources.hpp's `add_resource_factory`/`get_resource`,
  - `sync()` which replaces `sync_stream` (blocks until all dispatched work
    on arrays passed through this handle is done).

Like the reference's shallow-copy semantics, copying a Resources shares the
underlying registry.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Optional

import jax


class ResourceError(RuntimeError):
    """A requested resource (comms, sub-comms, registry entry) is not
    set on this handle. Typed so distributed setup code can distinguish
    "handle not wired yet" from genuine runtime failures (raftlint
    hygiene-untyped-raise)."""


class Resources:
    """TPU-native analogue of ``raft::device_resources``.

    Parameters
    ----------
    device:
        A ``jax.Device`` to place work on. Defaults to ``jax.devices()[0]``.
    mesh:
        Optional ``jax.sharding.Mesh`` for SPMD/multi-chip execution. When
        set, algorithms that support distribution shard over it.
    seed:
        Seed for the handle's RNG key stream.
    """

    def __init__(self, device=None, mesh=None, seed: int = 0):
        self._registry: dict[str, Any] = {}
        self._factories: dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()
        self._device = device
        self._mesh = mesh
        self._key = jax.random.PRNGKey(seed)
        self._pending: list[Any] = []

    # -- device / mesh ---------------------------------------------------
    @property
    def device(self):
        if self._device is None:
            self._device = jax.devices()[0]
        return self._device

    @property
    def mesh(self):
        return self._mesh

    def with_mesh(self, mesh) -> "Resources":
        """Shallow copy sharing the registry, with a different mesh."""
        r = Resources.__new__(Resources)
        r._registry = self._registry
        r._factories = self._factories
        r._lock = self._lock
        r._device = self._device
        r._mesh = mesh
        r._key = self._key
        r._pending = self._pending
        return r

    # -- RNG -------------------------------------------------------------
    def new_key(self) -> jax.Array:
        """Split and return a fresh PRNG key (functional RngState)."""
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- generic resource registry (resources.hpp parity) ----------------
    def add_resource_factory(self, name: str, factory: Callable[[], Any]) -> None:
        with self._lock:
            self._factories[name] = factory
            self._registry.pop(name, None)

    def get_resource(self, name: str) -> Any:
        with self._lock:
            if name not in self._registry:
                if name not in self._factories:
                    raise KeyError(f"no resource or factory registered for {name!r}")
                self._registry[name] = self._factories[name]()
            return self._registry[name]

    def has_resource(self, name: str) -> bool:
        with self._lock:
            return name in self._registry or name in self._factories

    # -- comms (core/resource/comms.hpp, sub_comms.hpp parity) -----------
    def set_comms(self, comms) -> None:
        with self._lock:
            self._registry["comms"] = comms

    def get_comms(self):
        with self._lock:
            if "comms" not in self._registry:
                raise ResourceError(
                    "no comms set on this Resources; call set_comms() or use "
                    "raft_tpu.comms.init_comms()"
                )
            return self._registry["comms"]

    def comms_initialized(self) -> bool:
        with self._lock:
            return "comms" in self._registry

    def set_sub_comms(self, key: str, comms) -> None:
        with self._lock:
            self._registry[f"sub_comms/{key}"] = comms

    def get_sub_comms(self, key: str):
        with self._lock:
            try:
                return self._registry[f"sub_comms/{key}"]
            except KeyError:
                raise ResourceError(
                    f"no sub-comms registered under {key!r}") from None

    # -- synchronization (sync_stream parity) ----------------------------
    def track(self, *arrays) -> None:
        """Remember arrays whose computation `sync()` should wait for."""
        self._pending.extend(a for a in arrays if hasattr(a, "block_until_ready"))

    def sync(self) -> None:
        """Block until all tracked (and given) async work completes.

        Replaces ``device_resources::sync_stream``; dispatch in JAX is async,
        so this drains the handle's pending set.
        """
        pending, self._pending = self._pending, []
        for a in pending:
            a.block_until_ready()


def auto_sync_resources(f: Callable) -> Callable:
    """Decorator mirroring pylibraft's ``@auto_sync_handle`` (handle.pyx:209).

    If the wrapped function is called without ``resources=``, a default
    Resources is created and ``sync()`` is called on it before returning, so
    results are ready when control returns to the caller. When the caller
    passes an explicit handle, syncing is the caller's responsibility (same
    contract as the reference).
    """

    @functools.wraps(f)
    def wrapper(*args, resources: Optional[Resources] = None, **kwargs):
        sync = resources is None
        if resources is None:
            resources = Resources()
        out = f(*args, resources=resources, **kwargs)
        if sync:
            resources.sync()
        return out

    return wrapper
