"""mdarray/mdspan-style factories (reference `core/device_mdarray.hpp`
`make_device_matrix/vector/scalar`, `core/host_mdarray.hpp`, survey §2.1).

On TPU, `jax.Array` subsumes both mdarray (owning) and mdspan (view): XLA
owns the buffers, views are lazy slices. These factories keep the familiar
construction vocabulary; layout is always row-major (XLA's canonical
layout — col-major `layout_f_contiguous` inputs are transposed on ingest).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "make_device_matrix",
    "make_device_vector",
    "make_device_scalar",
    "make_host_matrix",
    "make_host_vector",
    "make_device_matrix_view",
    "make_device_vector_view",
]


def make_device_matrix(n_rows: int, n_cols: int, dtype=jnp.float32,
                       device: Optional[jax.Device] = None) -> jax.Array:
    """Owning zero-initialized device matrix (make_device_matrix)."""
    return jax.device_put(jnp.zeros((n_rows, n_cols), dtype), device)


def make_device_vector(n: int, dtype=jnp.float32,
                       device: Optional[jax.Device] = None) -> jax.Array:
    return jax.device_put(jnp.zeros((n,), dtype), device)


def make_device_scalar(value, dtype=None,
                       device: Optional[jax.Device] = None) -> jax.Array:
    return jax.device_put(jnp.asarray(value, dtype), device)


def make_host_matrix(n_rows: int, n_cols: int, dtype=np.float32) -> np.ndarray:
    return np.zeros((n_rows, n_cols), dtype)


def make_host_vector(n: int, dtype=np.float32) -> np.ndarray:
    return np.zeros((n,), dtype)


def make_device_matrix_view(array, shape: Optional[Tuple[int, int]] = None) -> jax.Array:
    """Non-owning 2-D view (make_device_matrix_view): validates rank/shape
    and returns the (lazily copied-on-ingest) jax.Array."""
    a = jnp.asarray(array)
    if shape is not None:
        a = a.reshape(shape)
    if a.ndim != 2:
        raise ValueError(f"expected a matrix, got ndim={a.ndim}")
    return a


def make_device_vector_view(array) -> jax.Array:
    a = jnp.asarray(array)
    if a.ndim != 1:
        raise ValueError(f"expected a vector, got ndim={a.ndim}")
    return a
