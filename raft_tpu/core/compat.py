"""JAX version compatibility shims.

The library targets the current jax API surface; older runtimes (the CI
image pins one) spell a few entry points differently. Rather than
scattering version probes through every SPMD module, `ensure_jax_compat`
— called once from the package root — installs forward-compatible
aliases so the rest of the codebase writes ONLY the modern spelling:

  - `jax.shard_map(f, mesh=, in_specs=, out_specs=, check_vma=)`:
    older jax keeps it at `jax.experimental.shard_map.shard_map` with
    `check_rep` instead of `check_vma` (same meaning: replication /
    varying-mesh-axes checking).
  - `jax.experimental.pallas.tpu.CompilerParams`: older jax calls it
    `TPUCompilerParams` (same dataclass).

Idempotent and inert on runtimes that already expose the modern names.
"""

from __future__ import annotations

import functools

import jax


def ensure_jax_compat() -> None:
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy_shard_map

        @functools.wraps(_legacy_shard_map)
        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kwargs):
            if check_vma is not None and "check_rep" not in kwargs:
                kwargs["check_rep"] = check_vma
            return _legacy_shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
            )

        jax.shard_map = shard_map

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"
        ):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pallas backend absent from this build
        pass
