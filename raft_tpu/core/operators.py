"""Composable functional operators (reference `core/operators.hpp`,
survey §2.1).

The reference ships a vocabulary of host/device functors (`identity_op`,
`sq_op`, `abs_op`, `add_op`, `mul_op`, `key_op`, `compose_op`, ...) that
parameterize its generic reductions and element-wise kernels. The TPU
equivalents are plain Python callables over jax values — usable as the
`main_op`/`reduce_op`/`final_op` arguments of `raft_tpu.linalg.reduce`,
`map_reduce`, `coalesced_reduction` etc., and fused by XLA at trace time.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "identity_op",
    "void_op",
    "sq_op",
    "abs_op",
    "sqrt_op",
    "nz_op",
    "add_op",
    "sub_op",
    "mul_op",
    "div_op",
    "min_op",
    "max_op",
    "pow_op",
    "mod_op",
    "equal_op",
    "notequal_op",
    "argmin_op",
    "argmax_op",
    "const_op",
    "cast_op",
    "key_op",
    "value_op",
    "compose_op",
    "map_args_op",
    "KeyValuePair",
]


class KeyValuePair(NamedTuple):
    """(key, value) pair (core/kvp.hpp `raft::KeyValuePair`) — carried as a
    pytree through argmin-style reductions."""

    key: jax.Array
    value: jax.Array


# -- unary -------------------------------------------------------------------

def identity_op(x, *args):
    return x


def void_op(*args):
    return None


def sq_op(x, *args):
    return x * x


def abs_op(x, *args):
    return jnp.abs(x)


def sqrt_op(x, *args):
    return jnp.sqrt(x)


def nz_op(x, *args):
    """1 where nonzero else 0 (used by L0 'norm')."""
    return jnp.where(x != 0, jnp.ones_like(x), jnp.zeros_like(x))


# -- binary ------------------------------------------------------------------

def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def pow_op(a, b):
    return a**b


def mod_op(a, b):
    return a % b


def equal_op(a, b):
    return a == b


def notequal_op(a, b):
    return a != b


def argmin_op(a: KeyValuePair, b: KeyValuePair) -> KeyValuePair:
    """KeyValuePair reduction keeping the smaller value (kvp argmin)."""
    take_a = (a.value < b.value) | ((a.value == b.value) & (a.key <= b.key))
    return KeyValuePair(
        jnp.where(take_a, a.key, b.key), jnp.where(take_a, a.value, b.value)
    )


def argmax_op(a: KeyValuePair, b: KeyValuePair) -> KeyValuePair:
    take_a = (a.value > b.value) | ((a.value == b.value) & (a.key <= b.key))
    return KeyValuePair(
        jnp.where(take_a, a.key, b.key), jnp.where(take_a, a.value, b.value)
    )


# -- structural --------------------------------------------------------------

def const_op(c) -> Callable:
    """Returns an op that ignores inputs and yields `c` (const_op<T>)."""

    def op(*args):
        return c

    return op


def cast_op(dtype) -> Callable:
    """Casting op factory (cast_op<T>)."""

    def op(x, *args):
        return jnp.asarray(x).astype(dtype)

    return op


def key_op(kv: KeyValuePair, *args):
    return kv.key


def value_op(kv: KeyValuePair, *args):
    return kv.value


def compose_op(*ops: Callable) -> Callable:
    """compose_op(f, g, h)(x) == f(g(h(x))) (core/operators.hpp compose_op)."""

    def op(x, *args):
        for f in reversed(ops):
            x = f(x, *args)
        return x

    return op


def map_args_op(fn: Callable, *arg_ops: Callable) -> Callable:
    """map_args_op: apply arg_ops[i] to the i-th argument, then fn."""

    def op(*args):
        mapped = [aop(a) for aop, a in zip(arg_ops, args)]
        mapped.extend(args[len(arg_ops):])
        return fn(*mapped)

    return op
