"""Tracing / profiling annotations.

Reference parity: NVTX RAII ranges (core/nvtx.hpp:25-76) annotate every major
entry point, compiled away unless enabled. The TPU equivalents are
`jax.profiler.TraceAnnotation` (host timeline) and `jax.named_scope`
(names carried into the XLA HLO, visible in the TPU profiler). `trace_range`
combines both and is cheap enough to leave on.

These are the *profiler-timeline* scopes; the *accounting* scopes
(timed spans, event bus, metric registry) live in `raft_tpu.obs`, which
re-exports `trace_range`/`annotate` so call sites need one import
surface for both.
"""

from __future__ import annotations

import contextlib
import functools

import jax


_ENABLED = True


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = flag


@contextlib.contextmanager
def trace_range(name: str, **kwargs):
    """RAII-style scope: host trace annotation + HLO named scope.

    Usage (mirrors `common::nvtx::range fun_scope("fn")`):

        with trace_range("raft_tpu.distance.pairwise"):
            ...

    `**kwargs` forward to `jax.profiler.TraceAnnotation` (e.g. trace
    arguments); the disabled path accepts the same signature so
    flipping `enable(False)` can never turn a working call site into a
    TypeError.
    """
    if not _ENABLED:
        yield
        return
    with jax.profiler.TraceAnnotation(name, **kwargs):
        with jax.named_scope(name):
            yield


def annotate(name: str, **kwargs):
    """Decorator form of trace_range; `**kwargs` forward to it."""
    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **fn_kwargs):
            with trace_range(name, **kwargs):
                return f(*args, **fn_kwargs)

        return wrapper

    return deco
