"""Tracing / profiling annotations.

Reference parity: NVTX RAII ranges (core/nvtx.hpp:25-76) annotate every major
entry point, compiled away unless enabled. The TPU equivalents are
`jax.profiler.TraceAnnotation` (host timeline) and `jax.named_scope`
(names carried into the XLA HLO, visible in the TPU profiler). `trace_range`
combines both and is cheap enough to leave on.
"""

from __future__ import annotations

import contextlib

import jax


_ENABLED = True


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = flag


@contextlib.contextmanager
def trace_range(name: str, **kwargs):
    """RAII-style scope: host trace annotation + HLO named scope.

    Usage (mirrors `common::nvtx::range fun_scope("fn")`):

        with trace_range("raft_tpu.distance.pairwise"):
            ...
    """
    if not _ENABLED:
        yield
        return
    with jax.profiler.TraceAnnotation(name, **kwargs):
        with jax.named_scope(name):
            yield


def annotate(name: str):
    """Decorator form of trace_range."""
    def deco(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with trace_range(name):
                return f(*args, **kwargs)

        return wrapper

    return deco
