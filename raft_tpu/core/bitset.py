"""Packed bitset over device memory — the sample-filter primitive.

Forward-parity with RAFT's `core/bitset` + neighbors filtering (the
feature landed after the ~23.02 reference snapshot; `raft::core::bitset`
with `bitset_filter` passed to `ivf_pq::search_with_filtering`). The TPU
design packs 32 samples per lane in a `uint32[(n+31)//32]` jax array and
tests ids with two vector ops (shift + and) — no scalar loops, fully
jit-traceable, so engines can consume it inside their compiled search.

All mutators are FUNCTIONAL (return a new Bitset); the packed `bits`
array is a pytree leaf, so a Bitset can cross jit boundaries as an
argument without recompilation when only bit values change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _words(n: int) -> int:
    return (int(n) + 31) // 32


@jax.tree_util.register_pytree_node_class
class Bitset:
    """`n` logical bits packed little-endian into uint32 words.

    bit i lives at word i >> 5, lane i & 31. Out-of-range tests return
    False; out-of-range or negative ids in mutators are dropped.
    """

    def __init__(self, bits: jax.Array, n: int):
        self.bits = bits
        self.n = int(n)

    # -- pytree protocol (bits is the leaf; n is static aux data) --
    def tree_flatten(self):
        return (self.bits,), self.n

    @classmethod
    def tree_unflatten(cls, n, leaves):
        return cls(leaves[0], n)

    # -- constructors --
    @classmethod
    def full(cls, n: int, value: bool = True) -> "Bitset":
        """All-set (default) or all-clear bitset of `n` bits. The all-set
        form mirrors the reference usage: start from "everything allowed",
        then unset deleted/filtered ids."""
        fill = jnp.uint32(0xFFFFFFFF) if value else jnp.uint32(0)
        bits = jnp.full((_words(n),), fill, jnp.uint32)
        if value:
            # clear the tail beyond n so count() stays exact
            tail = _words(n) * 32 - int(n)
            if tail:
                bits = bits.at[-1].set(
                    jnp.uint32(0xFFFFFFFF >> tail)
                )
        return cls(bits, n)

    @classmethod
    def from_mask(cls, mask) -> "Bitset":
        """Pack a boolean mask (mask[i] == bit i)."""
        mask = jnp.asarray(mask, jnp.bool_)
        n = mask.shape[0]
        pad = _words(n) * 32 - n
        if pad:
            mask = jnp.pad(mask, (0, pad))
        lanes = mask.reshape(-1, 32).astype(jnp.uint32)
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
        return cls(jnp.sum(lanes * weights[None, :], axis=1, dtype=jnp.uint32), n)

    @classmethod
    def excluding(cls, n: int, ids) -> "Bitset":
        """All bits set except `ids` — the deleted-samples filter shape."""
        return cls.full(n, True).set(ids, False)

    # -- queries --
    def test(self, ids) -> jax.Array:
        """Bit value per id (bool, same shape as `ids`). Negative or
        >= n ids test False."""
        ids = jnp.asarray(ids)
        if self.n == 0:
            # zero words: any gather below would index an empty array
            return jnp.zeros(ids.shape, jnp.bool_)
        in_range = (ids >= 0) & (ids < self.n)
        safe = jnp.clip(ids, 0, max(self.n - 1, 0)).astype(jnp.int32)
        word = self.bits[safe >> 5]
        bit = (word >> (safe & 31).astype(jnp.uint32)) & 1
        return (bit == 1) & in_range

    def to_mask(self) -> jax.Array:
        """Unpack to a boolean mask of length n."""
        lanes = (self.bits[:, None] >> jnp.arange(32, dtype=jnp.uint32)[None, :]) & 1
        return lanes.reshape(-1)[: self.n] == 1

    def count(self) -> jax.Array:
        """Number of set bits (int32 scalar, device value)."""
        # 16-entry nibble popcount via two table lookups per byte is
        # overkill; bit-twiddling popcount stays vectorized
        v = self.bits
        v = v - ((v >> 1) & jnp.uint32(0x55555555))
        v = (v & jnp.uint32(0x33333333)) + ((v >> 2) & jnp.uint32(0x33333333))
        v = (v + (v >> 4)) & jnp.uint32(0x0F0F0F0F)
        return jnp.sum((v * jnp.uint32(0x01010101)) >> 24, dtype=jnp.int32)

    def __len__(self) -> int:
        return self.n

    # -- functional mutators --
    def set(self, ids, value: bool = True) -> "Bitset":
        """Return a new Bitset with `ids` set to `value` (duplicates fine;
        out-of-range ids dropped)."""
        ids = jnp.asarray(ids).reshape(-1)
        if self.n == 0:
            return self
        in_range = (ids >= 0) & (ids < self.n)
        safe = jnp.clip(ids, 0, max(self.n - 1, 0)).astype(jnp.int32)
        word = safe >> 5
        lane_bit = jnp.where(
            in_range, (jnp.uint32(1) << (safe & 31).astype(jnp.uint32)), jnp.uint32(0)
        )
        if value:
            bits = _scatter_or(self.bits, word, lane_bit)
        else:
            bits = _scatter_andnot(self.bits, word, lane_bit)
        return Bitset(bits, self.n)

    def flip(self) -> "Bitset":
        b = Bitset(~self.bits, self.n)
        tail = _words(self.n) * 32 - self.n
        if tail:
            b = Bitset(b.bits.at[-1].set(b.bits[-1] & jnp.uint32(0xFFFFFFFF >> tail)), self.n)
        return b

    def __and__(self, other: "Bitset") -> "Bitset":
        if self.n != other.n:
            raise ValueError(f"bitset length mismatch: {self.n} vs {other.n}")
        return Bitset(self.bits & other.bits, self.n)

    def __or__(self, other: "Bitset") -> "Bitset":
        if self.n != other.n:
            raise ValueError(f"bitset length mismatch: {self.n} vs {other.n}")
        return Bitset(self.bits | other.bits, self.n)


def as_bitset(prefilter, n: int) -> Bitset:
    """Coerce a search `prefilter` argument — a Bitset or a boolean mask
    of length `n` (the index's id space) — into a Bitset, validating the
    length (a short filter would silently exclude every tail sample)."""
    if isinstance(prefilter, Bitset):
        if prefilter.n != n:
            raise ValueError(
                f"prefilter covers {prefilter.n} ids but the index has {n}"
            )
        return prefilter
    mask = jnp.asarray(prefilter)
    if mask.dtype != jnp.bool_ or mask.ndim != 1:
        raise ValueError(
            "prefilter must be a Bitset or a 1-D boolean mask, got "
            f"{mask.dtype} ndim={mask.ndim}"
        )
    if mask.shape[0] != n:
        raise ValueError(
            f"prefilter mask has {mask.shape[0]} entries but the index has {n}"
        )
    return Bitset.from_mask(mask)


@jax.jit
def _filter_slot_table_ids(slot_rows, ids, bitset):
    keep = bitset.test(ids) & (slot_rows >= 0)
    return jnp.where(keep, slot_rows, -1).astype(slot_rows.dtype)


def filter_slot_table(slot_rows, source_ids, bitset: Bitset):
    """Slot-table view with filtered-out samples turned into pad (-1).

    This is the ONE filtering mechanism for every ANN engine: all of
    them (query-major, list-major, and the fused Pallas scans) mask
    candidate scores to the worst value wherever the slot table reads
    -1 — *before* any trim or selection — so a filtered view gives the
    same semantics as the reference's in-kernel sample_filter without
    touching a single engine. `source_ids` maps slot values (source
    positions) to the user-visible ids the filter speaks; pass None
    when the table already holds those ids directly."""
    if source_ids is None:
        ids = jnp.maximum(slot_rows, 0)
    else:
        ids = source_ids[jnp.maximum(slot_rows, 0)]
    return _filter_slot_table_ids(slot_rows, ids, bitset)


def make_slot_filter(prefilter, id_bound: int, source_ids, tombstones=None):
    """Coerce a search `prefilter` and bind it to an index's id space:
    returns the `maybe_filter(slot_rows)` callable the search dispatchers
    apply to each engine's slot table (identity when prefilter is None).
    `id_bound` is one past the largest id the index can return —
    `index.id_bound`, NOT `index.size`: extend(new_indices=...) ids live
    beyond size, and a size-bound filter would silently exclude them.

    `tombstones` is the index's optional (n_lists, max_list) dead-row
    mask (`index.tombstones`, any integer/bool dtype; nonzero = dead).
    Tombstones ride the exact same mechanism as the prefilter: the slot
    table reads -1 at dead slots, so every engine — query-major,
    list-major, and the fused Pallas scans — masks their scores to the
    worst value before trim/selection, and refine/regroup_merge never
    see a dead candidate. Applied BEFORE the prefilter, and pad-aware:
    a lane-padded table (`slot_rows_pad`, wider than the mask) keeps
    its pad columns, which already read -1."""
    if prefilter is None and tombstones is None:
        return lambda sr: sr
    bs = as_bitset(prefilter, id_bound) if prefilter is not None else None

    def maybe_filter(slot_rows):
        sr = slot_rows
        if tombstones is not None:
            t = jnp.asarray(tombstones).astype(bool)
            if t.shape[1] < sr.shape[1]:
                t = jnp.pad(t, ((0, 0), (0, sr.shape[1] - t.shape[1])))
            sr = jnp.where(t, jnp.int32(-1), sr).astype(sr.dtype)
        if bs is not None:
            sr = filter_slot_table(sr, source_ids, bs)
        return sr

    return maybe_filter


def carry_tombstones(tombstones, new_width: int):
    """Carry an index's dead-row mask across a store regrow (extend /
    lane padding): new tail columns are live appends by construction,
    so the mask pads with False. None (all-live) stays None — the
    zero-cost fast path must survive every extend."""
    if tombstones is None:
        return None
    t = jnp.asarray(tombstones).astype(bool)
    if new_width > t.shape[1]:
        t = jnp.pad(t, ((0, 0), (0, new_width - t.shape[1])))
    return t


def _touched_word_mask(bits, word_idx, lane_bits):
    """Union of `lane_bits` per word as a full-size uint32 table.

    jax scatter has no bitwise-or mode, and at[].add carries when the
    same (word, lane) repeats — so dedupe the flat bit ids first
    (data-dependent shape: mutators are host-side index-maintenance ops,
    not jit-traceable), after which add accumulates distinct powers of
    two per word with no carries. O(ids + words)."""
    # lane recovery: log2 of a one-hot via popcount(lb - 1)
    v = jnp.maximum(lane_bits, jnp.uint32(1)) - 1
    v = v - ((v >> 1) & jnp.uint32(0x55555555))
    v = (v & jnp.uint32(0x33333333)) + ((v >> 2) & jnp.uint32(0x33333333))
    v = (v + (v >> 4)) & jnp.uint32(0x0F0F0F0F)
    # flat bit index fits int32 for n < 2^31 bits (the id dtype ceiling
    # everywhere else in the package)
    lane = ((v * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)
    flat = word_idx.astype(jnp.int32) * 32 + lane
    flat = jnp.where(lane_bits == 0, -1, flat)  # dropped ids
    uniq = jnp.unique(flat)
    uniq = uniq[uniq >= 0]
    w = (uniq >> 5).astype(jnp.int32)
    lb = jnp.uint32(1) << (uniq & 31).astype(jnp.uint32)
    return jnp.zeros_like(bits).at[w].add(lb)


def _scatter_or(bits, word_idx, lane_bits):
    return bits | _touched_word_mask(bits, word_idx, lane_bits)


def _scatter_andnot(bits, word_idx, lane_bits):
    return bits & ~_touched_word_mask(bits, word_idx, lane_bits)
