"""Measured-on-chip tuned defaults.

`bench/apply_profile_hints.py --apply` turns profiler measurements into
`raft_tpu/tuned_defaults.json` (committed alongside the code), and the
library's `"auto"` dispatch paths consult it here — closing the
measure→flip loop without hand-editing dispatch constants.

Scope is deliberately narrow: only `"auto"` engine selections read tuned
keys, because their contract already lets the library pick among engines
(including approximately-trimming ones). Explicit engine/params choices
are never overridden, so a caller who pinned behavior keeps it.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any

_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tuned_defaults.json",
)

#: Machine-readable registry of every tuned key (the FAULT_SITES
#: pattern): key -> {"kind", "choices", "bench"}. Read BY AST by
#: raftlint's `tuned-key-registry` rule (tools/raftlint/rules/
#: tuned_keys.py) — keep it a literal dict. The rule enforces that
#: every `tuned.get`/`tuned.get_choice` literal and every `*_KEY`
#: constant is registered, every registered key is read somewhere, and
#: every bench --apply writer writes only registered keys with allowed
#: values — so a typo'd key can never silently strand a chip session's
#: measured winner where no reader finds it.
#:
#: kinds: "choice" (enumerated values, validated at write sites),
#: "int" / "float" / "bool" (numeric knobs), "dict" (structured
#: policies), "hints" (the free-form provenance sub-dict, read only
#: through `tuned.hints()`). "bench" names the --apply writer that owns
#: the key (None = hand-set override with no measuring bench).
TUNED_KEYS = {
    "adaptive_probe_policy": {
        "kind": "dict", "choices": None,
        "bench": "bench/bench_adaptive_probes.py"},
    "comms_quant_block": {
        "kind": "choice", "choices": (16, 32, 64, 128),
        "bench": "bench/bench_qcomms.py"},
    "comms_quant_mode": {
        "kind": "choice", "choices": ("off", "int8", "bf16"),
        "bench": "bench/bench_qcomms.py"},
    "flat_auto_engine": {
        "kind": "choice", "choices": ("query", "list", "pallas", "fused"),
        "bench": "bench/apply_profile_hints.py"},
    "grouped_reduce_crossover": {
        "kind": "float", "choices": None, "bench": "bench/bench_comms.py"},
    "grouped_reduce_schedule": {
        "kind": "choice", "choices": ("ring", "planes"),
        "bench": "bench/bench_comms.py"},
    "hints": {
        "kind": "hints", "choices": None, "bench": None},
    "invert_impl": {
        "kind": "choice", "choices": ("sort", "count"),
        "bench": "bench/bench_invert_race.py"},
    "listmajor_chunk": {
        "kind": "int", "choices": None,
        "bench": "bench/apply_profile_hints.py"},
    "listmajor_chunk_block": {
        "kind": "choice", "choices": (0, 8, 16, 32, 64),
        "bench": "bench/apply_profile_hints.py"},
    "listmajor_qs_impl": {
        "kind": "choice", "choices": ("gather", "onehot_bf16",
                                      "onehot_f32h"),
        "bench": None},
    "listmajor_qs_impl_flat": {
        "kind": "choice", "choices": ("gather", "onehot_bf16",
                                      "onehot_f32h"),
        "bench": None},
    "mnmg_query_sharded_min_nq": {
        "kind": "int", "choices": None, "bench": "bench/bench_mnmg_merge.py"},
    "mnmg_query_sharded_min_nq_per_k": {
        "kind": "float", "choices": None,
        "bench": "bench/bench_mnmg_merge.py"},
    "mnmg_replicated_merge_schedule": {
        "kind": "choice", "choices": ("tournament", "allgather"),
        "bench": "bench/bench_comms.py"},
    "pallas_fold": {
        "kind": "choice", "choices": ("exact", "packed"),
        "bench": "bench/bench_pallas_scan.py"},
    "pallas_rot_pad": {
        "kind": "bool", "choices": None, "bench": None},
    "pq_auto_engine": {
        "kind": "choice", "choices": ("lut", "recon8", "recon8_list"),
        "bench": "bench/apply_profile_hints.py"},
    "rabitq_query_bits": {
        "kind": "int", "choices": None, "bench": "bench/bench_ivf_rabitq.py"},
    "rabitq_rerank_mult": {
        "kind": "int", "choices": None, "bench": "bench/bench_ivf_rabitq.py"},
    "select_k_auto_strategy": {
        "kind": "choice", "choices": ("counting",),
        "bench": "bench/bench_select_k_strategies.py"},
    "select_k_chunk_threshold": {
        "kind": "int", "choices": None,
        "bench": "bench/bench_select_k_strategies.py"},
    "select_k_strategy": {
        "kind": "choice", "choices": ("topk", "two_phase", "counting",
                                      "fused"),
        "bench": "bench/bench_select_k_strategies.py"},
    "select_k_strategy_bitplane": {
        "kind": "choice", "choices": ("fused_bitplane", "xla"),
        "bench": "bench/bench_select_k_strategies.py"},
    "select_k_strategy_int8": {
        "kind": "choice", "choices": ("fused_int8",),
        "bench": "bench/bench_select_k_strategies.py"},
}

#: Canonical key-constant spellings (the ONE definition each; the
#: dispatch modules re-export these rather than repeating the literal —
#: `tuned-key-registry` flags any `*_KEY` constant whose value is not
#: registered above).
INT8_SCAN_KEY = "select_k_strategy_int8"
BITPLANE_SCAN_KEY = "select_k_strategy_bitplane"
POLICY_KEY = "adaptive_probe_policy"


def known_keys() -> tuple:
    """Sorted registered key names (mirrors faults.known_sites())."""
    return tuple(sorted(TUNED_KEYS))


@functools.lru_cache(maxsize=1)
def _load() -> dict:
    try:
        with open(_PATH) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def get(key: str, default: Any = None) -> Any:
    """Tuned value for `key`, or `default` when no tuned file exists (the
    state until a chip session has produced measurements)."""
    return _load().get(key, default)


def get_choice(key: str, allowed, default):
    """Tuned value for `key` validated against an allowed set; falls back
    to `default` on a missing or out-of-set value. Shared by dispatch
    sites that must agree on the honored set (e.g. the two list-major
    engines' `listmajor_chunk_block`)."""
    v = get(key, default)
    return v if v in allowed else default


def hints() -> dict:
    """The free-form "hints" sub-dict; {} when the tuned file, the key,
    or the value is missing/null/corrupt. The ONE access path for
    hints — `tuned.get("hints", {})` and `tuned.get("hints") or {}`
    used to coexist and disagreed on a hand-edited `"hints": null`
    (enforced by raftlint's `tuned-key-registry`)."""
    h = get("hints")
    return h if isinstance(h, dict) else {}


def path() -> str:
    return _PATH


def reload() -> None:
    """Drop the cache (tests / after --apply writes a new file)."""
    _load.cache_clear()


def merge(updates: dict) -> None:
    """Merge keys into the tuned file (never clobbers other sessions'
    winners) and reload. The writer every bench --apply mode shares."""
    try:
        with open(_PATH) as f:
            record = json.load(f)
        if not isinstance(record, dict):
            record = {}
    except (OSError, ValueError):
        record = {}
    for k, v in updates.items():
        if k == "hints" and isinstance(v, dict):
            if not isinstance(record.get("hints"), dict):
                record["hints"] = {}  # heal a hand-edited non-dict value
            record["hints"].update(v)
        else:
            record[k] = v
    # atomic replace: a crash mid-write must not leave truncated JSON
    # that silently resets every winner to the heuristics (shared
    # temp-then-rename protocol, which also unlinks the temp on failure)
    from raft_tpu.core.serialize import atomic_write

    with atomic_write(_PATH) as tmp:
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
    reload()
