"""Measured-on-chip tuned defaults.

`bench/apply_profile_hints.py --apply` turns profiler measurements into
`raft_tpu/tuned_defaults.json` (committed alongside the code), and the
library's `"auto"` dispatch paths consult it here — closing the
measure→flip loop without hand-editing dispatch constants.

Scope is deliberately narrow: only `"auto"` engine selections read tuned
keys, because their contract already lets the library pick among engines
(including approximately-trimming ones). Explicit engine/params choices
are never overridden, so a caller who pinned behavior keeps it.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any

_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tuned_defaults.json",
)


@functools.lru_cache(maxsize=1)
def _load() -> dict:
    try:
        with open(_PATH) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def get(key: str, default: Any = None) -> Any:
    """Tuned value for `key`, or `default` when no tuned file exists (the
    state until a chip session has produced measurements)."""
    return _load().get(key, default)


def get_choice(key: str, allowed, default):
    """Tuned value for `key` validated against an allowed set; falls back
    to `default` on a missing or out-of-set value. Shared by dispatch
    sites that must agree on the honored set (e.g. the two list-major
    engines' `listmajor_chunk_block`)."""
    v = get(key, default)
    return v if v in allowed else default


def path() -> str:
    return _PATH


def reload() -> None:
    """Drop the cache (tests / after --apply writes a new file)."""
    _load.cache_clear()


def merge(updates: dict) -> None:
    """Merge keys into the tuned file (never clobbers other sessions'
    winners) and reload. The writer every bench --apply mode shares."""
    try:
        with open(_PATH) as f:
            record = json.load(f)
        if not isinstance(record, dict):
            record = {}
    except (OSError, ValueError):
        record = {}
    for k, v in updates.items():
        if k == "hints" and isinstance(v, dict):
            if not isinstance(record.get("hints"), dict):
                record["hints"] = {}  # heal a hand-edited non-dict value
            record["hints"].update(v)
        else:
            record[k] = v
    # atomic replace: a crash mid-write must not leave truncated JSON
    # that silently resets every winner to the heuristics (shared
    # temp-then-rename protocol, which also unlinks the temp on failure)
    from raft_tpu.core.serialize import atomic_write

    with atomic_write(_PATH) as tmp:
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
    reload()
