"""Cooperative cancellation of device synchronization.

Reference parity: `raft::interruptible` (core/interruptible.hpp:66-100) lets
one CPU thread cancel another thread's stream sync; pylibraft exposes
`cuda_interruptible`/`synchronize` (common/interruptible.pyx).

JAX dispatch is async; the long waits are `block_until_ready` calls. We poll
readiness with a per-thread cancellation flag so another thread can interrupt
a wait. Cancellation is cooperative: the device work itself is not killed
(same semantics as the reference — the stream is not destroyed, the waiting
thread just throws).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

import jax


class InterruptedException(RuntimeError):
    """Raised inside `synchronize` when another thread calls `cancel`."""


_flags: Dict[int, threading.Event] = {}
_flags_lock = threading.Lock()


def _token(tid: int | None = None) -> threading.Event:
    tid = threading.get_ident() if tid is None else tid
    with _flags_lock:
        ev = _flags.get(tid)
        if ev is None:
            ev = _flags[tid] = threading.Event()
        return ev


def cancel(thread_id: int) -> None:
    """Signal the given thread's next/ongoing `synchronize` to abort."""
    _token(thread_id).set()


def synchronize(*arrays, poll_interval_s: float = 0.001) -> None:
    """Wait for arrays to be ready, honoring cancellation from other threads."""
    ev = _token()
    if ev.is_set():
        ev.clear()
        raise InterruptedException("interrupted before synchronize")
    # Fast path: nothing to poll between — use a worker completion check loop.
    remaining = [a for a in arrays if hasattr(a, "block_until_ready")]
    for a in remaining:
        while True:
            if ev.is_set():
                ev.clear()
                raise InterruptedException("synchronize interrupted")
            if _is_ready(a):
                break
            time.sleep(poll_interval_s)


def _is_ready(a) -> bool:
    try:
        return a.is_ready()  # jax.Array exposes is_ready on committed arrays
    except Exception:
        a.block_until_ready()
        return True


@contextlib.contextmanager
def interruptible():
    """Scope marker (parity with `cuda_interruptible`); clears stale flags."""
    ev = _token()
    ev.clear()
    try:
        yield
    finally:
        ev.clear()
