"""Cooperative cancellation of device synchronization.

Reference parity: `raft::interruptible` (core/interruptible.hpp:66-100) lets
one CPU thread cancel another thread's stream sync; pylibraft exposes
`cuda_interruptible`/`synchronize` (common/interruptible.pyx).

JAX dispatch is async; the long waits are `block_until_ready` calls. We poll
readiness with a per-thread cancellation flag so another thread can interrupt
a wait. Cancellation is cooperative: the device work itself is not killed
(same semantics as the reference — the stream is not destroyed, the waiting
thread just throws).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

import jax


class InterruptedException(RuntimeError):
    """Raised inside `synchronize` when another thread calls `cancel`."""


class TimeoutException(RuntimeError):
    """Raised by `synchronize(..., timeout_s=)` when readiness misses the
    deadline. The device work is NOT cancelled (cooperative semantics,
    same as `cancel`); the waiting thread just stops waiting — the
    health-check barrier in `comms/resilience.py` turns this into a
    liveness verdict."""


_flags: Dict[int, threading.Event] = {}
_flags_lock = threading.Lock()


def _token(tid: int | None = None) -> threading.Event:
    tid = threading.get_ident() if tid is None else tid
    with _flags_lock:
        ev = _flags.get(tid)
        if ev is None:
            ev = _flags[tid] = threading.Event()
        return ev


def cancel(thread_id: int) -> None:
    """Signal the given thread's next/ongoing `synchronize` to abort."""
    _token(thread_id).set()


def synchronize(*arrays, poll_interval_s: float = 0.001,
                timeout_s: float | None = None) -> None:
    """Wait for arrays to be ready, honoring cancellation from other
    threads. With `timeout_s`, raise `TimeoutException` once the deadline
    passes while any array is still pending (the deadline covers the
    whole call, not each array). Timeouts only bound arrays exposing
    `is_ready`; the `block_until_ready` fallback blocks uninterruptibly
    (jax.Array always exposes `is_ready`, so the production waits poll)."""
    ev = _token()
    if ev.is_set():
        ev.clear()
        raise InterruptedException("interrupted before synchronize")
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    # Fast path: nothing to poll between — use a worker completion check loop.
    remaining = [a for a in arrays if hasattr(a, "block_until_ready")]
    for a in remaining:
        while True:
            if ev.is_set():
                ev.clear()
                raise InterruptedException("synchronize interrupted")
            if _is_ready(a):
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutException(
                    f"synchronize exceeded timeout_s={timeout_s}"
                )
            time.sleep(poll_interval_s)


def _is_ready(a) -> bool:
    try:
        return a.is_ready()  # jax.Array exposes is_ready on committed arrays
    except Exception:
        a.block_until_ready()
        return True


@contextlib.contextmanager
def interruptible():
    """Scope marker (parity with `cuda_interruptible`); clears stale flags."""
    ev = _token()
    ev.clear()
    try:
        yield
    finally:
        ev.clear()
