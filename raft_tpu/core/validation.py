"""Input validation & conversion.

Reference parity: pylibraft's `cai_wrapper`/`ai_wrapper` (common/cai_wrapper.py)
validate dtype/shape/contiguity of user arrays before building mdspan views.
Here any array-like (numpy, jax.Array, device_ndarray, torch-cpu via
__array__) converts to a `jax.Array`; validators enforce the same dtype/shape
contracts the Cython layer did.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp


def as_array(x) -> jax.Array:
    """Convert any array-like to a jax.Array (zero-copy when possible)."""
    if isinstance(x, jax.Array):
        return x
    if hasattr(x, "__jax_array__"):
        return x.__jax_array__()
    if hasattr(x, "array") and isinstance(getattr(x, "array"), jax.Array):
        return x.array
    return jnp.asarray(x)


def check_array(
    x,
    dtypes: Optional[Sequence] = None,
    ndim: Optional[int] = None,
    name: str = "array",
) -> jax.Array:
    """Validate dtype/ndim and return a jax.Array view of `x`."""
    arr = as_array(x)
    if ndim is not None and arr.ndim != ndim:
        raise ValueError(f"{name}: expected {ndim}-d array, got {arr.ndim}-d")
    if dtypes is not None:
        allowed = tuple(np.dtype(d) for d in dtypes)
        if np.dtype(arr.dtype) not in allowed:
            names = ", ".join(d.name for d in allowed)
            raise ValueError(f"{name}: dtype {np.dtype(arr.dtype).name} not in ({names})")
    return arr


def check_matrix(x, dtypes=None, name: str = "matrix") -> jax.Array:
    return check_array(x, dtypes=dtypes, ndim=2, name=name)


def check_vector(x, dtypes=None, name: str = "vector") -> jax.Array:
    return check_array(x, dtypes=dtypes, ndim=1, name=name)


def check_same_rows(a: jax.Array, b: jax.Array, name_a="a", name_b="b") -> None:
    if a.shape[0] != b.shape[0]:
        raise ValueError(
            f"{name_a} and {name_b} must have the same number of rows "
            f"({a.shape[0]} vs {b.shape[0]})"
        )


def check_same_cols(a: jax.Array, b: jax.Array, name_a="a", name_b="b") -> None:
    if a.shape[1] != b.shape[1]:
        raise ValueError(
            f"{name_a} and {name_b} must have the same number of columns "
            f"({a.shape[1]} vs {b.shape[1]})"
        )


class cai_wrapper:
    """API-compatibility shim for pylibraft.common.cai_wrapper.

    Wraps any array-like and exposes `.shape/.dtype/.c_contiguous`, returning
    device data as a jax.Array. (No CUDA array interface on TPU; duck-typed.)
    """

    def __init__(self, x):
        self._arr = as_array(x)

    @property
    def shape(self):
        return tuple(self._arr.shape)

    @property
    def dtype(self):
        return np.dtype(self._arr.dtype)

    @property
    def c_contiguous(self) -> bool:
        return True  # jax.Arrays are logically row-major

    def validate_shape_dtype(self, expected_dims=None, expected_dtype=None):
        if expected_dims is not None and self._arr.ndim != expected_dims:
            raise ValueError(f"unexpected number of dimensions {self._arr.ndim}")
        if expected_dtype is not None and self.dtype != np.dtype(expected_dtype):
            raise ValueError(f"unexpected dtype {self.dtype}")
        return self

    @property
    def array(self) -> jax.Array:
        return self._arr
