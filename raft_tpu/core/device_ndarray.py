"""device_ndarray: minimal device array helpers.

Reference parity: pylibraft's `device_ndarray` (common/device_ndarray.py) — a
tiny RMM-backed ndarray so pylibraft works without cupy. On TPU, `jax.Array`
IS the device array; this module provides the same convenience constructors
plus host round-trips, and accepts anything implementing `__array__`,
`__cuda_array_interface__`-style wrappers are replaced by duck-typed
conversion through numpy / dlpack.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


class device_ndarray:
    """A thin wrapper holding a `jax.Array`, API-compatible with
    pylibraft.common.device_ndarray where it matters (shape/dtype/copy_to_host).
    """

    def __init__(self, np_ndarray, device=None):
        arr = np.asarray(np_ndarray)
        self._array = jax.device_put(arr, device)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C", device=None):
        self = cls.__new__(cls)
        self._array = jax.device_put(jnp.zeros(shape, dtype=dtype), device)
        return self

    @classmethod
    def zeros(cls, shape, dtype=np.float32, device=None):
        return cls.empty(shape, dtype=dtype, device=device)

    @classmethod
    def from_jax(cls, arr):
        self = cls.__new__(cls)
        self._array = arr
        return self

    @property
    def array(self) -> jax.Array:
        return self._array

    @property
    def shape(self):
        return tuple(self._array.shape)

    @property
    def dtype(self):
        return np.dtype(self._array.dtype)

    @property
    def ndim(self):
        return self._array.ndim

    def copy_to_host(self) -> np.ndarray:
        return np.asarray(self._array)

    def __array__(self, dtype=None):
        out = np.asarray(self._array)
        return out.astype(dtype) if dtype is not None else out

    def __jax_array__(self):
        return self._array

    def __len__(self):
        return self.shape[0] if self.ndim else 0

    def __repr__(self):
        return f"device_ndarray(shape={self.shape}, dtype={self.dtype})"
