"""Output-type configuration (pylibraft parity, survey §2.14).

Reference: pylibraft lets callers choose what array type APIs return
(`pylibraft/common/config.py` `set_output_as`, applied by the
`auto_convert_output` decorator in `pylibraft/common/outputs.py`) — e.g.
cupy/torch views of the RAFT-owned buffer. Here outputs are `jax.Array`s;
supported targets are "jax" (default, zero-copy), "numpy", and "torch"
(CPU torch tensors via dlpack/numpy), or any callable taking a jax.Array.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Union

import jax

_TLS = threading.local()

_OUTPUT_AS: Union[str, Callable[[jax.Array], Any]] = "jax"
_VALID = ("jax", "numpy", "torch")


def set_output_as(output: Union[str, Callable[[jax.Array], Any]]) -> None:
    """Set the global output type for raft_tpu API returns.

    `output` is "jax" | "numpy" | "torch" or a callable jax.Array -> Any.
    """
    global _OUTPUT_AS
    if not callable(output) and output not in _VALID:
        raise ValueError(f"output must be one of {_VALID} or a callable, got {output!r}")
    _OUTPUT_AS = output


def get_output_as() -> Union[str, Callable[[jax.Array], Any]]:
    return _OUTPUT_AS


def _convert_one(x: Any) -> Any:
    if not isinstance(x, jax.Array):
        return x
    out = _OUTPUT_AS
    if callable(out):
        return out(x)
    if out == "jax":
        return x
    import numpy as np

    if out == "numpy":
        return np.asarray(x)
    if out == "torch":
        import torch

        a = np.asarray(x)
        # copy: the numpy view aliases the XLA-owned buffer (read-only);
        # bfloat16 (ml_dtypes) must round-trip through a uint16 view.
        if a.dtype.name == "bfloat16":
            return torch.from_numpy(a.view(np.uint16).copy()).view(torch.bfloat16)
        return torch.from_numpy(a.copy())
    return x


def convert_output(value: Any) -> Any:
    """Convert a return value (array, or tuple/list/dict of arrays) to the
    configured output type. Non-array leaves pass through unchanged."""
    if isinstance(value, tuple):
        converted = [convert_output(v) for v in value]
        if hasattr(value, "_fields"):  # namedtuple: positional construction
            return type(value)(*converted)
        return type(value)(converted)
    if isinstance(value, list):
        return [convert_output(v) for v in value]
    if isinstance(value, dict):
        return {k: convert_output(v) for k, v in value.items()}
    return _convert_one(value)


def auto_convert_output(fn: Callable) -> Callable:
    """Decorator applying `convert_output` to a function's return value
    (pylibraft `auto_convert_output` role).

    Conversion happens only at the OUTERMOST decorated call: library code
    that chains public APIs (fit_predict -> fit/predict, transform ->
    pairwise_distance, ...) sees raw jax.Arrays internally and the caller
    gets exactly one conversion at the boundary."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if getattr(_TLS, "depth", 0):
            return fn(*args, **kwargs)
        _TLS.depth = 1
        try:
            return convert_output(fn(*args, **kwargs))
        finally:
            _TLS.depth = 0

    return wrapper


def is_tpu_backend() -> bool:
    """True when the initialized default backend drives real TPU silicon.

    `jax.default_backend() == "tpu"` alone is wrong under PJRT plugins
    that register a different platform name: the tunneled chip in this
    image registers as "axon" (with MLIR lowering aliased to tpu), so a
    name check silently disables every TPU-default dispatch on the very
    hardware it exists for. Fall back to the device kind, which names
    the silicon ("TPU v5 lite") regardless of plugin platform name.
    Triggers backend init; never raises."""
    try:
        if jax.default_backend() == "tpu":
            return True
        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or "") + " " + (
            getattr(d, "platform", "") or ""
        )
        return "tpu" in kind.lower()
    except Exception:
        return False


def enable_compilation_cache(directory: str = None) -> str:
    """Opt into jax's persistent compilation cache (survey §2.13: the
    reference precompiles template specializations into libraft to cut
    user compile times; on TPU the analogue is caching XLA executables).

    Returns the cache directory in effect. Safe to call repeatedly."""
    import os

    if directory is None:
        directory = os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu_xla")
    jax.config.update("jax_compilation_cache_dir", directory)
    return directory


def enable_compilation_cache_if_tpu(directory: str = None):
    """Enable the persistent cache only when the preferred platform is a
    TPU-ish backend — never for CPU-first runs (reloaded XLA:CPU AOT
    executables are machine-feature sensitive; the loader warns about
    possible SIGILL on mismatch).

    Platform intent comes from JAX_PLATFORMS (env if set, else the jax
    config value, which image-level sitecustomize may force). Caching is
    enabled only when the list is non-empty and names NO cpu entry at
    all: with a "tpu,cpu" fallback list a wedged TPU would silently run —
    and cache — CPU executables. Returns the cache dir, or None when
    caching stays off. Never raises — callers are bench/driver entries
    where a result beats a warm cache."""
    import os

    try:
        platforms = os.environ.get("JAX_PLATFORMS")
        if platforms is None:
            platforms = getattr(jax.config, "jax_platforms", None) or ""
        entries = [p.strip().lower() for p in platforms.split(",") if p.strip()]
        if not entries or "cpu" in entries:
            return None
        return enable_compilation_cache(directory)
    except Exception:
        return None


def is_device_fault(e: BaseException) -> bool:
    """True when an exception reports a TPU device/kernel fault (e.g. the
    runtime's "UNAVAILABLE: TPU device error" after a kernel faults).
    A fault poisons the raising PROCESS's backend permanently — every
    later device op fails the same way; only a fresh process recovers the
    chip — so long bench/profile sessions classify errors with this one
    predicate to decide "bank partial results and stop" vs "config-level
    failure, keep going". One definition shared by bench.py and
    bench/tpu_profile.py so the signature can't drift between them."""
    msg = str(e)
    return "UNAVAILABLE" in msg or "device error" in msg


def relay_transport_down() -> bool:
    """True when this host reaches its chip through a loopback relay
    (PALLAS_AXON_POOL_IPS=127.0.0.1) and no relay port is listening —
    the transport itself is dead, so device RPCs can only hang (a dead
    relay manifests as an infinitely slow compile ending in
    connection-refused ~50 min later, not a clean error). Reads
    /proc/net/tcp{,6} so the check makes NO connection and can never
    touch a chip claim. On plain TPU hosts (no relay env) always False.
    Long-running chip sessions poll this between stages to fail fast
    with partial results instead of hanging out their leash."""
    import os as _os

    if "127.0.0.1" not in _os.environ.get("PALLAS_AXON_POOL_IPS", ""):
        return False
    listening = set()
    found = False
    for table in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            lines = open(table).read().splitlines()[1:]
        except OSError:
            continue
        found = True
        for ln in lines:
            f = ln.split()
            if len(f) > 3 and f[3] == "0A":  # LISTEN
                try:
                    listening.add(int(f[1].split(":")[1], 16))
                except ValueError:
                    continue
    if not found:
        return False  # can't tell; let the caller's normal probing decide
    return not any(p in listening for p in range(8080, 8120))


def chip_probe_would_hang() -> bool:
    """The shared dead-relay LAUNCH gate for scripts about to initialize
    a chip backend: True when the env does not pin CPU and the relay
    transport is structurally dead — i.e. a backend-init probe can only
    hang (~25 min) rather than fail. False whenever JAX_PLATFORMS=cpu
    (CPU smoke/rehearsal runs must proceed with the relay dead) or when
    the check itself cannot tell (fail-open: a broken check must not
    zero out a session's chip work).

    Scope: simple launch gates (run_all, bench_comms, bench_10m_build).
    bench.py and tpu_profile.py keep direct `relay_transport_down()` use
    on purpose — their transport-state machines (leash shortening,
    mid-run bail with partial results) are exercised by tests under the
    CPU env, which this helper's CPU no-op would short-circuit."""
    import os as _os

    if _os.environ.get("JAX_PLATFORMS") == "cpu":
        return False
    try:
        return relay_transport_down()
    except Exception:
        return False
