"""Core runtime: resources handle, validation, logging, tracing, serialization.

TPU-native equivalent of the reference's `cpp/include/raft/core/`
(resources.hpp:46, device_resources.hpp:60, mdspan/mdarray, logger.hpp:118,
nvtx.hpp, interruptible.hpp:66, serialize.hpp:34). On TPU, XLA owns streams,
allocators and BLAS, so the handle shrinks to: mesh + comms, RNG state,
logger, tracing scopes.
"""

from raft_tpu.core.resources import Resources, auto_sync_resources
from raft_tpu.core.device_ndarray import device_ndarray
from raft_tpu.core.validation import check_array, check_matrix, check_vector, cai_wrapper
from raft_tpu.core.logger import logger, set_level
from raft_tpu.core.tracing import trace_range
from raft_tpu.core.serialize import serialize_arrays, deserialize_arrays
from raft_tpu.core.interruptible import (
    synchronize,
    cancel,
    InterruptedException,
    TimeoutException,
)
from raft_tpu.core import faults
from raft_tpu.core.config import (
    set_output_as,
    get_output_as,
    convert_output,
    auto_convert_output,
    enable_compilation_cache,
)
from raft_tpu.core import operators
from raft_tpu.core.operators import KeyValuePair
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.mdarray import (
    make_device_matrix,
    make_device_vector,
    make_device_scalar,
    make_host_matrix,
    make_host_vector,
    make_device_matrix_view,
    make_device_vector_view,
)

__all__ = [
    "operators",
    "KeyValuePair",
    "Bitset",
    "make_device_matrix",
    "make_device_vector",
    "make_device_scalar",
    "make_host_matrix",
    "make_host_vector",
    "make_device_matrix_view",
    "make_device_vector_view",
    "set_output_as",
    "get_output_as",
    "convert_output",
    "auto_convert_output",
    "enable_compilation_cache",
    "Resources",
    "auto_sync_resources",
    "device_ndarray",
    "check_array",
    "check_matrix",
    "check_vector",
    "cai_wrapper",
    "logger",
    "set_level",
    "trace_range",
    "serialize_arrays",
    "deserialize_arrays",
    "synchronize",
    "cancel",
    "InterruptedException",
    "TimeoutException",
    "faults",
]
