"""Deterministic, seedable fault injection for the comms/MNMG stack.

A production MNMG serving path must degrade, not die, when a shard goes
bad (ROADMAP north star; survey §5.8 assumes every rank survives the
whole job). This module is the chaos source that lets tests and drills
*prove* that: a `FaultPlan` describes which faults fire at which named
injection sites, and the comms/MNMG layers consult it at those sites.
No plan installed means every hook is a no-op returning its input
unchanged — the traced programs of a healthy process are byte-identical
to a build of this library without this module.

Fault kinds (the chaos vocabulary):

  kill_rank       rank is declared dead: `resilience.probe_health` masks
                  it out of the liveness mask, and degraded-mode searches
                  merge only the survivors (host-level — a dead rank
                  cannot be simulated inside one SPMD program without
                  deadlocking the collectives, so "dead" means "masked").
  slow_rank       host-side latency injected at a site (`time.sleep`);
                  a latency above a health check's timeout marks the
                  rank unhealthy instead of sleeping (a straggler that
                  missed its deadline).
  corrupt_shard   traced: a seeded fraction of a rank's float payload is
                  replaced with NaN at the site (simulates a shard
                  returning poisoned scores); host variant for loaders.
  drop_collective traced: the rank's contribution to a collective is
                  replaced with the reduction identity (the only
                  non-deadlocking model of "this rank's data never
                  arrived" under XLA collectives).
  flaky_bootstrap host-side: the first `count` executions of a site
                  raise `FaultInjected` (flaky multiprocess init, torn
                  checkpoint reads, ...) — exercised by the
                  retry-with-backoff paths.

Injection sites are a closed, machine-readable registry: `FAULT_SITES`
maps every site name to a one-line description and `known_sites()`
returns the sorted names. The registry is the source of truth that
`tools/raftlint`'s fault-site rules enforce — every site literal passed
to an injection hook must be registered here and every registered site
must have a live call site, so chaos drills can't silently stop
covering a site. The full rendered catalog is appended to this
docstring below (see "Registered injection sites").

Determinism: every random choice derives from (plan.seed, site), so a
replayed plan produces bit-identical corruption; `RAFT_TPU_FAULT_SEED`
seeds plans that don't pass one explicitly (the CI chaos tier pins it).

Trace safety: injection changes the traced program, so every cached SPMD
wrapper key must include `trace_key()` — `mnmg_common._cached_wrapper`
does this for all distributed serving wrappers; ad-hoc jits must either
be rebuilt per call (the k-means closure pattern) or key themselves.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import os
import threading
import time
import zlib
from typing import Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp


KINDS = (
    "kill_rank",
    "slow_rank",
    "corrupt_shard",
    "drop_collective",
    "flaky_bootstrap",
)

ENV_SEED = "RAFT_TPU_FAULT_SEED"

# The machine-readable site registry: every injection hook in the
# library names one of these sites (tools/raftlint rule fault-site-unknown),
# and every entry here is exercised by a live hook and a chaos drill
# (rule fault-site-unused + tests/test_raftlint.py drift test). Keep the
# descriptions one line: the module docstring renders from this dict.
FAULT_SITES = {
    "batch_loader.load": (
        "host loader block fetch (slow_rank latency, flaky reads, "
        "corrupt_host NaNs in a streamed block)"),
    "ckpt.corrupt_file": (
        "post-commit checkpoint sector rot: corrupt_shard flips seeded "
        "bytes of a just-written file's data region (CRC loads heal from "
        "peer mirror slices, comms/mnmg_ckpt)"),
    "comms.allgather": (
        "traced allgather contribution (corrupt_shard NaNs / "
        "drop_collective identity on the faulted rank)"),
    "comms.allreduce": (
        "traced allreduce contribution (corrupt_shard NaNs / "
        "drop_collective identity on the faulted rank)"),
    "comms.bootstrap": (
        "multihost init entry (flaky_bootstrap exercises "
        "retry_with_backoff; slow_rank models a straggling controller)"),
    "comms.quant.decode": (
        "quantized-collective scale sidecar AFTER transport, before "
        "decode (corrupt_shard NaNs the faulted rank's received scales "
        "— its decoded contributions degrade visibly, never a crash; "
        "comms/quantized)"),
    "comms.quant.encode": (
        "quantized-collective scale sidecar AFTER encode, before "
        "transport (corrupt_shard NaNs the faulted rank's outgoing "
        "scales — downstream decodes degrade visibly, never a crash; "
        "comms/quantized)"),
    "fused.scan.scores": (
        "fused scan+select-k kernel's candidate buffer (corrupt_shard "
        "NaNs the selected candidate values in-trace, before callers "
        "merge/finalize — every fused engine flows through it; "
        "ops/fused_scan)"),
    "integrity.scrub.crash": (
        "online-scrub cursor boundary AFTER the scrub-cursor JSON "
        "commits (kill_rank SIGKILLs this process on its count-th visit "
        "— the mid-scrub kill-and-resume drill: the resumed walk "
        "continues from the cursor instead of restarting; "
        "raft_tpu/jobs/streaming resumable_scrub)"),
    "integrity.table.rot": (
        "seeded in-memory rot of a live index table — the HBM/host "
        "analogue of ckpt.corrupt_file (corrupt_shard low-byte-flips a "
        "seeded fraction of a seeded payload list's elements, or a rank "
        "shard under MNMG; detection/containment/repair is "
        "raft_tpu/integrity's whole job)"),
    "ivf.probe_budget": (
        "per-query adaptive probe budgets inside the traced plan "
        "(corrupt_shard NaNs a seeded fraction of the budget vector; "
        "the plan clamps corrupted entries down to min_probes — "
        "SHRUNKEN budgets, visible as recall loss, never a crash; "
        "neighbors/probe_budget)"),
    "ivf_rabitq.build.encode": (
        "host-side RaBitQ encode stage of build/extend (slow_rank "
        "models a slow encode pass — latency only, results untouched; "
        "flaky_bootstrap a transient dispatch failure)"),
    "job.heartbeat.stall": (
        "watchdog heartbeat write inside a supervised stage (slow_rank "
        "here STALLS the first `count` beats for latency_s without "
        "beating — the stall the watchdog must kill + retry; "
        "raft_tpu/jobs/watchdog)"),
    "job.preempt": (
        "job-runner preemption check between stages and at streaming "
        "batch boundaries (flaky_bootstrap simulates a SIGTERM-style "
        "preempt: the runner checkpoints then suspends as JobPreempted; "
        "raft_tpu/jobs/runner)"),
    "job.stage.crash": (
        "streaming-build batch boundary AFTER the checkpoint commits "
        "(kill_rank SIGKILLs this process on its count-th visit — the "
        "kill-and-resume bit-identity drill; flaky_bootstrap a "
        "transient stage failure retried by the supervised runner; "
        "raft_tpu/jobs/streaming)"),
    "mutation.log.commit": (
        "mutation-log batch boundary, visited AFTER each log append and "
        "AFTER each checkpoint commit (kill_rank SIGKILLs this process "
        "on its count-th visit — odd/even counts land in the "
        "log-ahead-of-checkpoint vs just-committed windows of the "
        "kill-and-resume bit-identity drill; neighbors/mutation)"),
    "mutation.rebalance": (
        "tombstone-compaction entry (flaky_bootstrap a transient "
        "rebalance failure retried by the supervised runner; slow_rank "
        "models a long repack; neighbors/mutation)"),
    "mutation.tombstone": (
        "delete/upsert tombstoning entry (flaky_bootstrap a transient "
        "mutation failure surfaced BEFORE any state changes — the index "
        "and log are untouched when it raises; neighbors/mutation)"),
    "mnmg.ivf_flat.scores": (
        "per-rank IVF-Flat candidate scores inside the traced search "
        "(corrupt_shard poisons a shard's contribution pre-merge)"),
    "mnmg.ivf_pq.scores": (
        "per-rank IVF-PQ candidate scores inside the traced search "
        "(corrupt_shard poisons a shard's contribution pre-merge)"),
    "mnmg.kmeans.partials": (
        "per-rank partial EM sums inside the traced k-means step "
        "(corrupt_shard poisons a shard's contribution before the "
        "allreduce)"),
    "mnmg.kmeans.step": (
        "host-side per-iteration k-means driver step (slow_rank models a "
        "straggling rank between collectives)"),
    "mnmg.ivf_rabitq.scores": (
        "per-rank IVF-RaBitQ estimator scores inside the traced search "
        "(corrupt_shard poisons a shard's contribution pre-merge)"),
    "mnmg.knn.scores": (
        "per-rank brute-force scores inside the traced distributed knn "
        "(corrupt_shard poisons a shard's contribution pre-merge)"),
    "mnmg_ckpt.load": (
        "host checkpoint load entry (flaky_bootstrap torn reads retried "
        "by resilience.rehydrate; slow_rank models cold storage)"),
    "obs.flight.dump": (
        "flight-recorder dump entry (flaky_bootstrap a failing dump — "
        "maybe_dump swallows it, so a broken recorder never takes down "
        "the worker loop / watchdog / crash path it observes; slow_rank "
        "models slow crash-time IO; raft_tpu/obs/flight)"),
    "replica.stale": (
        "kill_rank here declares a rank's HOSTED replica copies unusable "
        "without killing the rank — failover elections skip stale "
        "holders (comms/replication)"),
    "resilience.barrier": (
        "health-barrier entry (slow_rank past the deadline marks the "
        "rank unhealthy instead of sleeping it out)"),
    "serve.batch": (
        "serving batch dispatch (slow_rank models slow device work — "
        "the serving analogue of a straggling rank)"),
    "serve.submit": (
        "serving ingress (slow_rank/flaky_bootstrap model slow or flaky "
        "request admission)"),
    "serve.trace.stamp": (
        "request-trace stage stamp (flaky_bootstrap corrupts the stamp: "
        "the TraceCtx goes dead and the request degrades to UNTRACED — "
        "served results stay bit-identical, tracing only observes; "
        "raft_tpu/obs/trace)"),
}


def known_sites() -> Tuple[str, ...]:
    """Sorted tuple of every registered injection site name."""
    return tuple(sorted(FAULT_SITES))


class FaultInjected(RuntimeError):
    """Raised by `fault_point` for an armed flaky fault (distinguishable
    from genuine failures, so retry loops can count chaos separately)."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One fault: `kind` at sites matching the `site` glob, scoped to
    `rank` (-1 = every rank). `latency_s` drives slow_rank, `fraction`
    the corrupted share of a payload, `count` how many times a flaky
    site fails before succeeding."""

    kind: str
    site: str = "*"
    rank: int = -1
    latency_s: float = 0.0
    fraction: float = 1.0
    count: int = 1

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {KINDS}")
        if not (0.0 <= self.fraction <= 1.0):
            raise ValueError(f"fraction must be in [0, 1], got {self.fraction}")

    def key(self) -> tuple:
        return (self.kind, self.site, self.rank, float(self.latency_s),
                float(self.fraction), int(self.count))


class FaultPlan:
    """A deterministic, replayable set of faults.

    Install with `with plan.install(): ...` (re-entrant; inner plans
    shadow outer ones). `reset()` clears the fired-counters so the same
    plan object replays identically; `trace_key()` is the static
    fingerprint cached SPMD wrappers key on.
    """

    def __init__(self, faults: Sequence[Fault] = (), seed: Optional[int] = None):
        if seed is None:
            seed = int(os.environ.get(ENV_SEED, "0"))
        self.seed = int(seed)
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self._fired: dict = {}
        self._lock = threading.Lock()

    # -- queries -------------------------------------------------------
    def matching(self, site: str, kind: str) -> Tuple[Fault, ...]:
        return tuple(
            f for f in self.faults
            if f.kind == kind and fnmatch.fnmatchcase(site, f.site)
        )

    def killed_ranks(self, site: str = "*") -> Tuple[int, ...]:
        """Ranks declared dead by kill_rank faults whose glob matches
        `site` (the conventional probe site is "resilience.barrier")."""
        return tuple(sorted({f.rank for f in self.matching(site, "kill_rank")
                             if f.rank >= 0}))

    def site_seed(self, site: str) -> int:
        """Deterministic per-site PRNG seed: stable across processes and
        runs (crc32, not hash() — PYTHONHASHSEED must not matter)."""
        return (self.seed * 0x9E3779B1 + zlib.crc32(site.encode())) & 0x7FFFFFFF

    def trace_key(self) -> tuple:
        return (self.seed,) + tuple(f.key() for f in self.faults)

    # -- lifecycle -----------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._fired.clear()

    def fire_count(self, site: str, fault: Fault) -> int:
        with self._lock:
            return self._fired.get((site, fault.key()), 0)

    def _next_draw(self, site: str) -> int:
        """Per-site monotone draw counter: successive host corruptions at
        one site sample DIFFERENT positions (a fixed mask would be
        periodic across equally-shaped blocks), while `reset()` — or a
        fresh plan — replays the identical sequence."""
        with self._lock:
            n = self._fired.get(("draw", site), 0)
            self._fired[("draw", site)] = n + 1
            return n

    def _arm(self, site: str, fault: Fault) -> bool:
        """Atomically count one execution of a flaky site; True while the
        fault still has failures left to inject."""
        with self._lock:
            k = (site, fault.key())
            fired = self._fired.get(k, 0)
            if fired >= fault.count:
                return False
            self._fired[k] = fired + 1
            return True

    @contextlib.contextmanager
    def install(self):
        _STACK.append(self)  # raftlint: disable=shared-state-race  -- plans are installed/removed by the drill thread before/after the concurrent phase; workers only read
        try:
            yield self
        finally:
            _STACK.remove(self)


_STACK: list = []  # innermost-active-last plan stack


def _obs_event(**fields) -> None:
    """Publish one kind="fault" event on the obs bus — chaos runs leave
    an auditable timeline. Imported lazily and only on the already-slow
    fired-fault paths, so the no-plan fast path (and module import
    order: core package init -> faults -> obs -> core.tracing) never
    pays for it."""
    from raft_tpu import obs

    obs.event("fault", **fields)


def active_plan() -> Optional[FaultPlan]:
    return _STACK[-1] if _STACK else None


def trace_key() -> Optional[tuple]:
    """Static fingerprint of the active plan (None when chaos is off) —
    appended to every cached SPMD wrapper key so an installed/cleared
    plan can never serve a stale traced program."""
    plan = active_plan()
    return None if plan is None else plan.trace_key()


def active_for(site: str) -> bool:
    """True when the active plan has any TRACED fault for `site` (the
    gate that keeps healthy traces byte-identical to a chaos-free
    build)."""
    plan = active_plan()
    if plan is None:
        return False
    return bool(plan.matching(site, "corrupt_shard")
                or plan.matching(site, "drop_collective"))


# -- host-side hooks ---------------------------------------------------

def _host_rank_matches(fault: Fault, rank: Optional[int]) -> bool:
    """Host-site rank scoping: `rank` is the caller's host identity
    (process index on a multi-controller job). `rank=None` means the
    site has no per-rank identity — the fault fires regardless (the
    single-controller simulation model, where one host stands in for
    every rank)."""
    return fault.rank < 0 or rank is None or fault.rank == rank


def fault_point(site: str, rank: Optional[int] = None) -> None:
    """Host-side injection site: sleeps for matching slow_rank faults,
    raises `FaultInjected` while a matching flaky fault has failures
    left. Call at the top of host entry points (bootstrap, loaders,
    per-iteration driver loops); a no-op without an installed plan.
    Pass `rank` (e.g. `jax.process_index()`) at sites with a real
    per-process identity so rank-scoped faults hit only their target."""
    plan = active_plan()
    if plan is None:
        return
    for f in plan.matching(site, "slow_rank"):
        if f.latency_s > 0 and _host_rank_matches(f, rank):
            _obs_event(site=site, action="slow", rank=f.rank,
                       latency_s=f.latency_s)
            time.sleep(f.latency_s)
    for f in plan.matching(site, "flaky_bootstrap"):
        if _host_rank_matches(f, rank) and plan._arm(site, f):
            _obs_event(site=site, action="flaky", rank=f.rank,
                       fired=plan.fire_count(site, f), count=f.count)
            raise FaultInjected(
                f"injected flaky failure at {site!r} "
                f"({plan.fire_count(site, f)}/{f.count})"
            )


def crash_point(site: str, rank: Optional[int] = None) -> None:
    """Host-side hard-crash site: for each matching kill_rank fault, the
    `count`-th visit to this site SIGKILLs THIS process — no handlers,
    no atexit, no flushing: the preemption model where the machine just
    disappears. Call immediately AFTER a checkpoint commit, so the
    kill-and-resume drills prove the artifact on disk (not process luck)
    carries the resume. Unlike `fault_point`'s flaky arming, `count`
    here selects WHICH visit dies (count=3 -> the third batch boundary),
    because the process does not survive to be armed again. `rank`
    scopes as in `fault_point`; a no-op without an installed plan."""
    plan = active_plan()
    if plan is None:
        return
    import signal

    for f in plan.matching(site, "kill_rank"):
        if not _host_rank_matches(f, rank):
            continue
        with plan._lock:
            k = ("crash", site, f.key())
            n = plan._fired.get(k, 0) + 1
            plan._fired[k] = n
        if n == max(1, f.count):
            _obs_event(site=site, action="crash", rank=f.rank, visit=n)
            # flight-record the pre-crash timeline (atomic write; armed
            # recorders only): the drill's post-mortem survives the kill
            try:
                from raft_tpu.obs import flight as _flight

                _flight.maybe_dump("crash_point", site=site, visit=n)
            except Exception:
                pass  # the crash model must not depend on obs health
            os.kill(os.getpid(), signal.SIGKILL)


def stall_point(site: str, cancelled=None, poll_s: float = 0.01,
                rank: Optional[int] = None) -> bool:
    """Host-side STALL site (watchdog drills): for each matching
    slow_rank fault, the first `count` visits busy-wait `latency_s`
    WITHOUT doing the caller's work — the model of a heartbeat that
    stops arriving, as opposed to `fault_point`'s late-but-delivered
    sleep. The wait polls `cancelled()` (when given) so a supervisor
    that kills the stage unblocks the stall immediately instead of
    serving out the injected latency. Returns True when a stall fired
    (callers treat the visit as a MISSED beat). `rank` scopes as in
    `fault_point`."""
    plan = active_plan()
    if plan is None:
        return False
    stalled = False
    for f in plan.matching(site, "slow_rank"):
        if f.latency_s <= 0 or not _host_rank_matches(f, rank):
            continue
        if not plan._arm(site, f):
            continue
        _obs_event(site=site, action="stall", rank=f.rank,
                   latency_s=f.latency_s)
        stalled = True
        deadline = time.monotonic() + f.latency_s
        while time.monotonic() < deadline:
            if cancelled is not None and cancelled():
                return True
            time.sleep(min(poll_s, max(0.0, deadline - time.monotonic())))
    return stalled


def corrupt_host(site: str, block: np.ndarray,
                 rank: Optional[int] = None) -> np.ndarray:
    """Host-side payload corruption (loaders): NaN a seeded fraction of a
    float block. Non-float payloads pass through untouched (there is no
    NaN to plant; integer ids are validated downstream anyway). Each
    call draws a fresh deterministic mask (`_next_draw`), so repeated
    loads corrupt different positions yet replay identically after
    `reset()`. `rank` scopes as in `fault_point`."""
    plan = active_plan()
    if plan is None or not np.issubdtype(np.asarray(block).dtype, np.floating):
        return block
    out = block
    for i, f in enumerate(plan.matching(site, "corrupt_shard")):
        if not _host_rank_matches(f, rank):
            continue
        rng = np.random.default_rng(
            (plan.site_seed(site), i, plan._next_draw(site)))
        mask = rng.random(out.shape) < f.fraction
        if mask.any():
            out = np.array(out, copy=True)
            out[mask] = np.nan
            _obs_event(site=site, action="corrupt_host", rank=f.rank,
                       cells=int(mask.sum()))
    return out


def corrupt_file(site: str, path: str, start: int = 0,
                 rank: Optional[int] = None,
                 end: Optional[int] = None) -> bool:
    """Host-side FILE corruption (checkpoint bit-rot): for each matching
    corrupt_shard fault, XOR-flip ONE seeded contiguous run of bytes in
    `path` at an offset >= `start` — the bad-sector model, localized so
    per-array checksums attribute the damage to specific fields and the
    mirror-heal paths have something intact to heal FROM (callers pass
    the container's data-region start so headers stay parseable). The
    run length is `fraction` OF the corruptible span (>= 1 byte) — the
    same [0, 1] meaning the field has at every other site. `end` bounds
    the corruptible window from above (default: end of file) — the
    field-targeted drills pass one field's byte range
    (`core.serialize.field_byte_range`) to rot exactly that field and
    prove the load degrades per its `CKPT_SCHEMA` declaration.
    Draws ride `_next_draw`, so successive writes corrupt different
    offsets yet replay identically after `reset()`. Returns True when
    any byte flipped. `rank` scopes as in `fault_point`."""
    plan = active_plan()
    if plan is None:
        return False
    flipped = False
    for i, f in enumerate(plan.matching(site, "corrupt_shard")):
        if not _host_rank_matches(f, rank):
            continue
        size = os.path.getsize(path)
        if end is not None:
            size = min(size, int(end))
        span = size - int(start)
        if span <= 0:
            continue
        rng = np.random.default_rng(
            (plan.site_seed(site), i, plan._next_draw(site)))
        run = max(1, int(span * min(f.fraction, 1.0)))
        off = int(start) + int(rng.integers(0, max(1, span - run + 1)))
        with open(path, "r+b") as fh:
            fh.seek(off)
            blk = fh.read(run)
            fh.seek(off)
            fh.write(bytes(b ^ 0xFF for b in blk))
        flipped = True
        _obs_event(site=site, action="corrupt_file", rank=f.rank,
                   path=os.path.basename(path), offset=off, bytes=run)
    return flipped


# -- traced hooks (inside shard_map bodies) ----------------------------

def corrupt_in_trace(site: str, x, rank):
    """Traced corruption: NaN a seeded fraction of the float payload on
    the fault's rank (`rank` is the traced axis index). Returns `x`
    unchanged — same jaxpr — when no matching fault is installed."""
    plan = active_plan()
    if plan is None:
        return x
    faults_ = plan.matching(site, "corrupt_shard")
    if not faults_ or not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return x
    import jax

    for i, f in enumerate(faults_):
        # trace-time event: counts armed corruptions per traced program
        # (execution is XLA's; see the obs counting-semantics note)
        _obs_event(site=site, action="corrupt_trace", rank=f.rank,
                   fraction=f.fraction)
        key = jax.random.PRNGKey(plan.site_seed(site))
        key = jax.random.fold_in(key, i)
        hit = jax.random.uniform(key, jnp.shape(x)) < f.fraction
        if f.rank >= 0:
            hit = hit & (rank == f.rank)
        x = jnp.where(hit, jnp.nan, x)
    return x


def drop_contribution(site: str, x, rank, identity):
    """Traced drop-collective: replace the fault's rank's contribution
    with the reduction identity (the non-deadlocking model of a lost
    contribution — the collective still runs, the data never arrives)."""
    plan = active_plan()
    if plan is None:
        return x
    for f in plan.matching(site, "drop_collective"):
        _obs_event(site=site, action="drop", rank=f.rank)
        dead = True if f.rank < 0 else (rank == f.rank)
        x = jnp.where(dead, jnp.broadcast_to(jnp.asarray(identity, x.dtype),
                                             jnp.shape(x)), x)
    return x


def _render_sites_doc() -> str:
    """The docstring site catalog, rendered from FAULT_SITES so the two
    can never drift (tests assert every site name appears in __doc__)."""
    import textwrap

    out = []
    for site in known_sites():
        body = textwrap.fill(
            FAULT_SITES[site], width=70, initial_indent="      ",
            subsequent_indent="      ")
        out.append(f"  {site}\n{body}")
    return "\n".join(out)


__doc__ = (__doc__ or "") + (
    "\nRegistered injection sites (rendered from FAULT_SITES):\n\n"
    + _render_sites_doc() + "\n"
)
