"""Logger.

Reference parity: `raft::logger` (core/logger.hpp:118) — an spdlog-backed
singleton with RAFT_LOG_{TRACE..CRITICAL} macros, pattern control and a
callback sink (core/detail/callback_sink.hpp) so Python can capture logs.
Here: stdlib logging with the same level vocabulary and a callback-sink hook.

Observability: when `raft_tpu.obs` is enabled, records emitted through
this logger also land on the obs event bus as kind="log" events (the
bridge handler is installed/removed by `obs.enable()`/`obs.disable()`
so this module keeps zero obs dependency and the disabled path pays
nothing).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

# RAFT level numbers (logger.hpp: RAFT_LEVEL_TRACE=6 .. RAFT_LEVEL_OFF=0)
RAFT_LEVEL_OFF = 0
RAFT_LEVEL_CRITICAL = 1
RAFT_LEVEL_ERROR = 2
RAFT_LEVEL_WARN = 3
RAFT_LEVEL_INFO = 4
RAFT_LEVEL_DEBUG = 5
RAFT_LEVEL_TRACE = 6

_RAFT_TO_PY = {
    RAFT_LEVEL_OFF: logging.CRITICAL + 10,
    RAFT_LEVEL_CRITICAL: logging.CRITICAL,
    RAFT_LEVEL_ERROR: logging.ERROR,
    RAFT_LEVEL_WARN: logging.WARNING,
    RAFT_LEVEL_INFO: logging.INFO,
    RAFT_LEVEL_DEBUG: logging.DEBUG,
    RAFT_LEVEL_TRACE: 5,
}

logger = logging.getLogger("raft_tpu")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[%(levelname)s] [%(asctime)s] %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.WARNING)


def set_level(level: int) -> None:
    """Set verbosity using RAFT level numbers (0=off .. 6=trace)."""
    logger.setLevel(_RAFT_TO_PY.get(level, logging.WARNING))


def set_pattern(fmt: str) -> None:
    """Set the log format string (python logging format syntax)."""
    for h in logger.handlers:
        h.setFormatter(logging.Formatter(fmt))


class _CallbackHandler(logging.Handler):
    def __init__(self, cb: Callable[[int, str], None], flush_cb: Optional[Callable] = None):
        super().__init__()
        self._cb = cb
        self._flush_cb = flush_cb

    def emit(self, record):
        self._cb(record.levelno, self.format(record))

    def flush(self):
        if self._flush_cb is not None:
            self._flush_cb()


def set_callback(cb: Optional[Callable[[int, str], None]], flush_cb=None) -> None:
    """Install a callback sink (parity with callback_sink.hpp); None removes."""
    for h in list(logger.handlers):
        if isinstance(h, _CallbackHandler):
            logger.removeHandler(h)
    if cb is not None:
        logger.addHandler(_CallbackHandler(cb, flush_cb))
