"""THREAD_ROOTS: the machine-readable registry of every thread entry
point in the library — the concurrency counterpart of
``core/faults.FAULT_SITES``.

A *thread root* is a function handed to another execution context:
``threading.Thread(target=...)`` spawns, event-bus fan-out callbacks
(which run inline on whatever thread published), Prometheus collector
callbacks (run on the scraping thread), ``weakref.finalize`` callbacks
(the GC/finalizer context), and installed signal handlers (re-entrant
on the main thread at arbitrary bytecode boundaries — a concurrency
context for data-race purposes even without a second OS thread).

Keys are raftlint scope qnames — ``<repo-relative path>::<qualified
name>`` with nested defs dot-joined (``Watchdog.run.worker`` is the
``worker`` def inside ``Watchdog.run``). raftlint's threadcheck engine
(tools/raftlint/threads.py) reads this dict by AST — never by import —
and enforces the two-way contract:

  - every discovered spawn/registration site must resolve to a
    registered root (``thread-root-unknown`` fires otherwise, and fails
    CLOSED on spawn targets the analysis cannot resolve);
  - every registered root must still be discoverable
    (``thread-root-unused`` fires on stale entries).

So this file cannot drift from reality in either direction, and every
root listed here is an entry point of the shared-state race analysis
(docs/linting.md, "The threadcheck engine").

Runtime code may import :data:`THREAD_ROOTS` freely (it is plain data),
e.g. to label crash dumps, but nothing requires it.
"""

from __future__ import annotations

from typing import Dict

#: registered thread entry points: scope qname -> one-line description
THREAD_ROOTS: Dict[str, str] = {
    "raft_tpu/serve/engine.py::SearchServer._run":
        "serve worker loop: collect/execute batches, between-batch "
        "mutation drain + healing + integrity scrub",
    "raft_tpu/jobs/watchdog.py::Watchdog.run.worker":
        "watchdog stage thread: runs one supervised stage body while "
        "the calling thread monitors heartbeats",
    "raft_tpu/jobs/watchdog.py::run_supervised.pump":
        "supervisor stdout pump: drains the child process pipe so the "
        "child never blocks on a full buffer",
    "raft_tpu/jobs/runner.py::Job.request_preempt":
        "SIGTERM handler (via lambda trampoline): flips the preempt "
        "event re-entrantly on the main thread",
    "raft_tpu/obs/flight.py::FlightRecorder._on_event":
        "event-bus fan-out: appends to the flight ring on whatever "
        "thread published the event",
    "raft_tpu/obs/flight.py::install_sigterm._on_sigterm":
        "SIGTERM handler: dumps the flight recorder before chaining to "
        "the previous handler",
    "raft_tpu/obs/spans.py::SpanCapture._on_event":
        "event-bus fan-out: aggregates span events on the publishing "
        "thread",
    "raft_tpu/serve/metrics.py::ServerMetrics.__init__._collect":
        "Prometheus collector callback: snapshots server metrics on "
        "the scraping thread",
    "raft_tpu/obs/registry.py::Registry.remove_collector":
        "weakref.finalize callback: detaches a dead collector on the "
        "GC/finalizer context",
    "bench/bench_serve.py::main.client":
        "bench client threads: concurrent submit/result against the "
        "serving engine",
}
