"""Versioned binary serialization of named array containers.

Reference parity: `raft::serialize_mdspan` writes numpy .npy-format payloads
into iostreams (core/serialize.hpp:34, detail/mdspan_numpy_serializer.hpp);
index types layer versioned scalar+mdspan streams on top
(detail/ivf_pq_serialize.cuh:36, kSerializationVersion=3).

Here: one container format shared by every index / model:

    magic  8 bytes  b"RAFTTPU\\0"
    u32    container version
    u64    header length
    header JSON: {"meta": {...}, "fields": [{name,dtype,shape,offset,nbytes,
                                             crc32c}]}
    raw little-endian buffers, 64-byte aligned

Integrity: every field carries a CRC-32C (Castagnoli) checksum of its raw
buffer, verified on read (`ChecksumError` names the file and the corrupt
fields) — the detection half of the checkpoint self-healing story
(comms/mnmg_ckpt heals a corrupt shard from a peer's mirror slice).
Containers written before checksums existed simply lack the field and skip
verification. Durability: path writes go through `atomic_write` —
write-to-temp-then-`os.replace` — so a mid-write crash leaves the previous
container intact and never a torn file under the final name.

A native (C++) codec for the same format lives in cpp/raft_tpu_native.cc
(`rt_write_container`) and is used for the write path when built (see
raft_tpu.native); this pure-Python path is the always-available fallback and
the format definition of record.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import struct
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

import numpy as np
import jax

MAGIC = b"RAFTTPU\x00"
CONTAINER_VERSION = 1
_ALIGN = 64


class SerializationError(ValueError):
    """A container could not be decoded: truncated/empty file, bad magic,
    torn header. Subclasses ValueError so pre-existing `except ValueError`
    dispatch still catches it."""


class ChecksumError(SerializationError):
    """One or more field buffers failed CRC-32C verification. `path` names
    the container, `fields` the corrupt field names — the heal paths use
    them to decide which shards to re-materialize from a peer mirror."""

    def __init__(self, path: str, fields: List[str]):
        super().__init__(
            f"checksum mismatch in {path!r}: corrupt fields {fields}"
        )
        self.path = path
        self.fields = list(fields)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


# -- CRC-32C (Castagnoli) ----------------------------------------------
#
# Pure numpy, no dependencies: per-block zero-init CRCs are computed
# VECTORIZED across blocks (the table recurrence runs its _BLOCK steps on
# an (n_blocks,) uint32 register file), then folded left-to-right with the
# precomputed shift-by-one-block linear map (CRC is GF(2)-linear, so
# "append _BLOCK zero bytes" is a 32x32 bit matrix, stored as 4x256
# byte-lookup tables). ~10 ms/MB vs ~1 s/MB for a bytewise Python loop.

_CRC_POLY = np.uint32(0x82F63B78)
_BLOCK = 1024


def _crc_table() -> np.ndarray:
    idx = np.arange(256, dtype=np.uint32)
    crc = idx
    for _ in range(8):
        crc = np.where(crc & 1, (crc >> 1) ^ _CRC_POLY, crc >> 1)
    return crc.astype(np.uint32)


_TBL = _crc_table()
_SHIFT_TBLS: Optional[np.ndarray] = None  # (4, 256) lazy


def _zero_steps(reg: np.ndarray, n: int) -> np.ndarray:
    """Advance CRC registers by n zero bytes (vectorized over registers)."""
    for _ in range(n):
        reg = _TBL[reg & 0xFF] ^ (reg >> np.uint32(8))
    return reg


def _shift_tables() -> np.ndarray:
    """4x256 lookup applying the "append _BLOCK zero bytes" linear map:
    shift(x) = T0[x&FF] ^ T1[(x>>8)&FF] ^ T2[(x>>16)&FF] ^ T3[x>>24]."""
    global _SHIFT_TBLS
    if _SHIFT_TBLS is None:
        basis = _zero_steps(np.uint32(1) << np.arange(32, dtype=np.uint32),
                            _BLOCK)  # (32,) images of each bit
        tbls = np.zeros((4, 256), np.uint32)
        for k in range(4):
            bytes_ = np.arange(256, dtype=np.uint32)
            acc = np.zeros(256, np.uint32)
            for bit in range(8):
                acc ^= np.where(bytes_ & (1 << bit),
                                basis[8 * k + bit], np.uint32(0))
            tbls[k] = acc
        _SHIFT_TBLS = tbls
    return _SHIFT_TBLS


def _shift_block(x: np.ndarray) -> np.ndarray:
    t = _shift_tables()
    return (t[0][x & 0xFF] ^ t[1][(x >> np.uint32(8)) & 0xFF]
            ^ t[2][(x >> np.uint32(16)) & 0xFF] ^ t[3][x >> np.uint32(24)])


def crc32c(data, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) of a bytes-like / numpy buffer. `crc` chains a
    previous call's result. Matches the RFC 3720 reference
    (crc32c(b"123456789") == 0xE3069283)."""
    buf = np.frombuffer(memoryview(data).cast("B"), np.uint8)
    reg = np.uint32(~np.uint32(crc) & np.uint32(0xFFFFFFFF))
    n_blocks = buf.size // _BLOCK
    group = 1 << 16  # ≤64 MiB of payload widened to uint32 at a time
    for g0 in range(0, n_blocks, group):
        gn = min(group, n_blocks - g0)
        data2d = (buf[g0 * _BLOCK:(g0 + gn) * _BLOCK]
                  .reshape(gn, _BLOCK).astype(np.uint32))
        regs = np.zeros(gn, np.uint32)
        for j in range(_BLOCK):
            regs = _TBL[(regs ^ data2d[:, j]) & 0xFF] ^ (regs >> np.uint32(8))
        # affine split: running = shift(running_prev) ^ raw_block; the
        # init register rides the same shifts (f_I(M) = f_0(M) + shift(I))
        for i in range(gn):
            reg = _shift_block(reg) ^ regs[i]
    for b in buf[n_blocks * _BLOCK:]:
        reg = _TBL[(reg ^ b) & 0xFF] ^ (reg >> np.uint32(8))
    return int(~reg & np.uint32(0xFFFFFFFF))


# -- atomic path writes ------------------------------------------------

@contextlib.contextmanager
def atomic_write(path: Union[str, os.PathLike]):
    """Write-to-temp-then-rename protocol for checkpoint files: yields the
    temp path to write, then atomically `os.replace`s it over `path` on
    success (and unlinks it on failure). A crash mid-write leaves the
    previous file intact; readers never observe a torn container. Every
    checkpoint write in the library MUST route through here (ci/
    check_style.sh gates bare `os.rename` / `open(..., "wb")`)."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        yield tmp
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def serialize_arrays(
    f: Union[str, os.PathLike, io.IOBase],
    arrays: Mapping[str, Any],
    meta: Dict[str, Any] | None = None,
) -> None:
    """Write named arrays + JSON-able metadata to a file or stream. Path
    writes are atomic (write-to-temp-then-rename) and every field carries
    a CRC-32C checksum the read path verifies."""
    own = isinstance(f, (str, os.PathLike))
    bufs = []
    fields = []
    offset = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(np.asarray(arr))
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        offset = _align(offset)
        fields.append(
            {
                "name": name,
                "dtype": a.dtype.str,
                "shape": list(a.shape),
                "offset": offset,
                "nbytes": int(a.nbytes),
                "crc32c": crc32c(a.data) if a.nbytes else 0,
            }
        )
        bufs.append((offset, a))
        offset += a.nbytes
    header = json.dumps({"meta": meta or {}, "fields": fields}).encode()

    if own:
        with atomic_write(f) as tmp:
            _write_container(tmp, header, bufs, try_native=True)
        return
    _write_stream(f, header, bufs)


def _write_container(path: str, header: bytes, bufs, try_native: bool) -> None:
    if try_native:
        # native C++ codec path (cpp/raft_tpu_native.cc rt_write_container)
        from raft_tpu import native

        if native.write_container(
            path, header,
            [a for _, a in bufs],
            [a.nbytes for _, a in bufs],
            [off for off, _ in bufs],
        ):
            return
    with open(path, "wb") as fh:
        _write_stream(fh, header, bufs)


def _write_stream(fh, header: bytes, bufs) -> None:
    fh.write(MAGIC)
    fh.write(struct.pack("<IQ", CONTAINER_VERSION, len(header)))
    fh.write(header)
    data_start = _align(fh.tell())
    fh.write(b"\x00" * (data_start - fh.tell()))
    pos = 0
    for off, a in bufs:
        if off > pos:
            fh.write(b"\x00" * (off - pos))
            pos = off
        fh.write(a.tobytes())
        pos += a.nbytes


def _describe(f) -> str:
    if isinstance(f, (str, os.PathLike)):
        return os.fspath(f)
    return getattr(f, "name", "<stream>")


def _read_header(fh, name: str) -> Tuple[int, dict]:
    """Shared magic + version + JSON header decode; raises
    `SerializationError` naming the file on any truncated/torn read
    (instead of the raw struct.error / JSONDecodeError / KeyError a
    short or garbage file used to surface)."""
    magic = fh.read(8)
    if len(magic) < 8:
        raise SerializationError(
            f"truncated container {name!r}: {len(magic)} bytes, expected at "
            f"least the 8-byte magic {MAGIC!r}"
        )
    if magic != MAGIC:
        raise SerializationError(
            f"not a raft_tpu serialized container (bad magic) in {name!r}: "
            f"got {magic!r}, expected {MAGIC!r}"
        )
    lenbytes = fh.read(12)
    if len(lenbytes) < 12:
        raise SerializationError(
            f"truncated container {name!r}: header length fields missing "
            f"(got {8 + len(lenbytes)} bytes)"
        )
    version, hlen = struct.unpack("<IQ", lenbytes)
    if version > CONTAINER_VERSION:
        raise SerializationError(
            f"container version {version} newer than supported "
            f"{CONTAINER_VERSION}"
        )
    raw = fh.read(hlen)
    if len(raw) < hlen:
        raise SerializationError(
            f"truncated container {name!r}: header says {hlen} bytes, file "
            f"holds {len(raw)}"
        )
    try:
        header = json.loads(raw.decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise SerializationError(
            f"torn container header in {name!r}: {e}"
        ) from e
    if not isinstance(header, dict) or "meta" not in header:
        raise SerializationError(
            f"container header in {name!r} lacks the 'meta' section"
        )
    return hlen, header


def peek_meta(f: Union[str, os.PathLike, io.IOBase]) -> Dict[str, Any]:
    """Read ONLY a container's meta dict (magic + header; the data blob
    is never touched) — the cheap dispatch probe for multi-GB
    checkpoints whose kind decides which loader to run."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        return _read_header(fh, _describe(f))[1]["meta"]
    finally:
        if own:
            fh.close()


def container_data_start(f: Union[str, os.PathLike, io.IOBase]) -> int:
    """Byte offset where a container's data region begins (header
    excluded) — chaos hooks corrupt only past here so the header stays
    parseable and the per-array checksums do the detecting."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        hlen, _ = _read_header(fh, _describe(f))
        return _align(8 + 12 + hlen)
    finally:
        if own:
            fh.close()


def deserialize_arrays(
    f: Union[str, os.PathLike, io.IOBase],
    to_device: bool = True,
    verify: bool = True,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Read a container; returns (arrays, meta). Arrays are jax.Arrays when
    `to_device` else numpy. With `verify` (default) every field's CRC-32C
    is checked and a mismatch raises `ChecksumError` naming the corrupt
    fields; pass verify=False only for forensic reads."""
    arrays, meta, bad = deserialize_arrays_checked(f, to_device=to_device,
                                                   verify=verify)
    if bad:
        raise ChecksumError(_describe(f), bad)
    return arrays, meta


def deserialize_arrays_checked(
    f: Union[str, os.PathLike, io.IOBase],
    to_device: bool = True,
    verify: bool = True,
) -> Tuple[Dict[str, Any], Dict[str, Any], List[str]]:
    """Like `deserialize_arrays` but returns (arrays, meta, bad_fields)
    instead of raising on checksum mismatch — corrupt fields still decode
    (garbage bytes) so heal paths can keep the intact fields and
    re-materialize only the bad ones from a peer mirror."""
    own = isinstance(f, (str, os.PathLike))
    name = _describe(f)
    fh = open(f, "rb") if own else f
    try:
        hlen, header = _read_header(fh, name)
        if "fields" not in header:
            raise SerializationError(
                f"container header in {name!r} lacks the 'fields' section"
            )
        data_start = _align(8 + 12 + hlen)
        fh.seek(data_start)
        blob = fh.read()
        arrays: Dict[str, Any] = {}
        bad: List[str] = []
        for field in header["fields"]:
            off, nb = field["offset"], field["nbytes"]
            raw = blob[off: off + nb]
            if len(raw) < nb:
                raise SerializationError(
                    f"truncated container {name!r}: field "
                    f"{field['name']!r} wants {nb} bytes at offset {off}, "
                    f"file holds {len(raw)}"
                )
            if verify and nb and field.get("crc32c") is not None:
                if crc32c(raw) != int(field["crc32c"]):
                    bad.append(field["name"])
            a = np.frombuffer(raw, dtype=np.dtype(field["dtype"]))
            a = a.reshape(field["shape"])
            arrays[field["name"]] = jax.device_put(a) if to_device else a
        return arrays, header["meta"], bad
    finally:
        if own:
            fh.close()
