"""Versioned binary serialization of named array containers.

Reference parity: `raft::serialize_mdspan` writes numpy .npy-format payloads
into iostreams (core/serialize.hpp:34, detail/mdspan_numpy_serializer.hpp);
index types layer versioned scalar+mdspan streams on top
(detail/ivf_pq_serialize.cuh:36, kSerializationVersion=3).

Here: one container format shared by every index / model:

    magic  8 bytes  b"RAFTTPU\\0"
    u32    container version
    u64    header length
    header JSON: {"meta": {...}, "fields": [{name,dtype,shape,offset,nbytes}]}
    raw little-endian buffers, 64-byte aligned

A native (C++) codec for the same format lives in cpp/raft_tpu_native.cc
(`rt_write_container`) and is used for the write path when built (see
raft_tpu.native); this pure-Python path is the always-available fallback and
the format definition of record.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, Mapping, Tuple, Union

import numpy as np
import jax

MAGIC = b"RAFTTPU\x00"
CONTAINER_VERSION = 1
_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def serialize_arrays(
    f: Union[str, os.PathLike, io.IOBase],
    arrays: Mapping[str, Any],
    meta: Dict[str, Any] | None = None,
) -> None:
    """Write named arrays + JSON-able metadata to a file or stream."""
    own = isinstance(f, (str, os.PathLike))
    bufs = []
    fields = []
    offset = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(np.asarray(arr))
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        offset = _align(offset)
        fields.append(
            {
                "name": name,
                "dtype": a.dtype.str,
                "shape": list(a.shape),
                "offset": offset,
                "nbytes": int(a.nbytes),
            }
        )
        bufs.append((offset, a))
        offset += a.nbytes
    header = json.dumps({"meta": meta or {}, "fields": fields}).encode()

    if own:
        # native C++ codec path (cpp/raft_tpu_native.cc rt_write_container)
        from raft_tpu import native

        if native.write_container(
            os.fspath(f), header,
            [a for _, a in bufs],
            [a.nbytes for _, a in bufs],
            [off for off, _ in bufs],
        ):
            return

    fh = open(f, "wb") if own else f
    try:
        fh.write(MAGIC)
        fh.write(struct.pack("<IQ", CONTAINER_VERSION, len(header)))
        fh.write(header)
        data_start = _align(fh.tell())
        fh.write(b"\x00" * (data_start - fh.tell()))
        pos = 0
        for off, a in bufs:
            if off > pos:
                fh.write(b"\x00" * (off - pos))
                pos = off
            fh.write(a.tobytes())
            pos += a.nbytes
    finally:
        if own:
            fh.close()


def peek_meta(f: Union[str, os.PathLike, io.IOBase]) -> Dict[str, Any]:
    """Read ONLY a container's meta dict (magic + header; the data blob
    is never touched) — the cheap dispatch probe for multi-GB
    checkpoints whose kind decides which loader to run."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        magic = fh.read(8)
        if magic != MAGIC:
            raise ValueError("not a raft_tpu serialized container (bad magic)")
        version, hlen = struct.unpack("<IQ", fh.read(12))
        if version > CONTAINER_VERSION:
            raise ValueError(
                f"container version {version} newer than supported "
                f"{CONTAINER_VERSION}"
            )
        return json.loads(fh.read(hlen).decode())["meta"]
    finally:
        if own:
            fh.close()


def deserialize_arrays(
    f: Union[str, os.PathLike, io.IOBase],
    to_device: bool = True,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Read a container; returns (arrays, meta). Arrays are jax.Arrays when
    `to_device` else numpy."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        magic = fh.read(8)
        if magic != MAGIC:
            raise ValueError("not a raft_tpu serialized container (bad magic)")
        version, hlen = struct.unpack("<IQ", fh.read(12))
        if version > CONTAINER_VERSION:
            raise ValueError(f"container version {version} newer than supported {CONTAINER_VERSION}")
        header = json.loads(fh.read(hlen).decode())
        data_start = _align(8 + 12 + hlen)
        fh.seek(data_start)
        blob = fh.read()
        arrays: Dict[str, Any] = {}
        for field in header["fields"]:
            off, nb = field["offset"], field["nbytes"]
            a = np.frombuffer(blob[off : off + nb], dtype=np.dtype(field["dtype"]))
            a = a.reshape(field["shape"])
            arrays[field["name"]] = jax.device_put(a) if to_device else a
        return arrays, header["meta"]
    finally:
        if own:
            fh.close()
