"""Versioned binary serialization of named array containers.

Reference parity: `raft::serialize_mdspan` writes numpy .npy-format payloads
into iostreams (core/serialize.hpp:34, detail/mdspan_numpy_serializer.hpp);
index types layer versioned scalar+mdspan streams on top
(detail/ivf_pq_serialize.cuh:36, kSerializationVersion=3).

Here: one container format shared by every index / model:

    magic  8 bytes  b"RAFTTPU\\0"
    u32    container version
    u64    header length
    header JSON: {"meta": {...}, "fields": [{name,dtype,shape,offset,nbytes,
                                             crc32c}]}
    raw little-endian buffers, 64-byte aligned

Integrity: every field carries a CRC-32C (Castagnoli) checksum of its raw
buffer, verified on read (`ChecksumError` names the file and the corrupt
fields) — the detection half of the checkpoint self-healing story
(comms/mnmg_ckpt heals a corrupt shard from a peer's mirror slice).
Containers written before checksums existed simply lack the field and skip
verification. Durability: path writes go through `atomic_write` —
write-to-temp-then-`os.replace` — so a mid-write crash leaves the previous
container intact and never a torn file under the final name.

A native (C++) codec for the same format lives in cpp/raft_tpu_native.cc
(`rt_write_container`) and is used for the write path when built (see
raft_tpu.native); this pure-Python path is the always-available fallback and
the format definition of record.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import struct
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

import numpy as np
import jax

MAGIC = b"RAFTTPU\x00"
CONTAINER_VERSION = 1
_ALIGN = 64

# -- the checkpoint schema registry ------------------------------------
#
# Machine-readable registry of every checkpoint field, per index kind —
# the FAULT_SITES/TUNED_KEYS pattern, read by AST by tools/raftlint's
# ``ckpt-schema-registry`` rule (never imported there). Every attribute
# a ``*_save*`` path writes must be registered here, and every load path
# must handle a registered field's ABSENCE exactly as declared — so a
# new index-state field (the upsert/delete/tombstone state ROADMAP
# item 5 adds) cannot ship without its forward/backward-compat story.
#
# Shape: kind -> {"version": <current writer version>,
#                 "fields": {name: (category, dtype_class, since, absent)}}
#
#   category     "array" (container payload) | "meta" (header JSON) |
#                "runtime" (never serialized: derived state a load
#                re-creates at its default — documented here so the
#                legacy-load goldens can pin the default)
#   dtype_class  coarse dtype family ("f32", "i32", "u8", "bool",
#                "str", "int", "json", None for runtime) — documentation
#                plus the chaos drill's corruption-target picker; loads
#                do not enforce it (the CRC already detects rot)
#   since        writer version that first emitted the field
#   absent       what a load does when the field is missing (or fails
#                CRC, for arrays):
#                  "refuse"  required: missing -> typed
#                            SerializationError, corrupt -> ChecksumError
#                  "default" optional: load falls back to the documented
#                            default (None / the meta .get default);
#                            corrupt -> dropped, load degrades
#                  "derive"  re-derivable: absence/corruption is healed
#                            or re-computed by shared machinery (mirror
#                            heal, size re-derivation, shape-derived
#                            quantizer state)
#
# "kind" and "version" themselves are consumed by the core gate
# (read_ckpt / check_ckpt_version), not by per-kind load code.
CKPT_SCHEMA = {
    "ivf_flat": {
        "version": 4,
        "fields": {
            "centers": ("array", "f32", 1, "refuse"),
            "list_data": ("array", "f32", 1, "refuse"),
            "slot_rows": ("array", "i32", 1, "refuse"),
            "list_sizes": ("array", "i32", 1, "refuse"),
            "source_ids": ("array", "i32", 1, "refuse"),
            "list_radii": ("array", "f32", 2, "default"),
            # live-mutation era (v3, neighbors/mutation): dead-row mask
            # (absent = all-live), applied-log cursor at the commit,
            # and the mutator's reserved per-list append slack
            "tombstones": ("array", "u8", 3, "default"),
            # integrity era (v4, raft_tpu/integrity): packed per-list
            # CRC-32C sidecar (rows = sorted list-granularity
            # DIGEST_FIELDS) + per-table digests in the header; absent
            # = no sidecar, the scrubber attaches one on first contact
            "list_digests": ("array", "u32", 4, "default"),
            "table_digests": ("meta", "json", 4, "default"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "metric": ("meta", "int", 1, "refuse"),
            "metric_arg": ("meta", "float", 1, "default"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "adaptive_centers": ("meta", "bool", 1, "default"),
            "mut_cursor": ("meta", "int", 3, "default"),
            "append_slack": ("meta", "int", 3, "default"),
            "fused_kb": ("runtime", None, 1, "default"),
        },
    },
    "ivf_pq": {
        "version": 3,
        "fields": {
            "rotation": ("array", "f32", 1, "refuse"),
            "centers": ("array", "f32", 1, "refuse"),
            "pq_centers": ("array", "f32", 1, "refuse"),
            "codes": ("array", "i32", 1, "refuse"),
            "slot_rows": ("array", "i32", 1, "refuse"),
            "list_sizes": ("array", "i32", 1, "refuse"),
            "source_ids": ("array", "i32", 1, "refuse"),
            "list_radii": ("array", "f32", 1, "default"),
            # live-mutation era (v2, neighbors/mutation)
            "tombstones": ("array", "u8", 2, "default"),
            # integrity era (v3, raft_tpu/integrity) — see ivf_flat
            "list_digests": ("array", "u32", 3, "default"),
            "table_digests": ("meta", "json", 3, "default"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "pq_bits": ("meta", "int", 1, "refuse"),
            "codebook_kind": ("meta", "str", 1, "refuse"),
            "mut_cursor": ("meta", "int", 2, "default"),
            "append_slack": ("meta", "int", 2, "default"),
            "fused_kb": ("runtime", None, 1, "default"),
        },
    },
    "ivf_rabitq": {
        "version": 3,
        "fields": {
            "rotation": ("array", "f32", 1, "refuse"),
            "centers": ("array", "f32", 1, "refuse"),
            "codes": ("array", "u32", 1, "refuse"),
            "aux": ("array", "f32", 1, "refuse"),
            "slot_rows": ("array", "i32", 1, "refuse"),
            "list_sizes": ("array", "i32", 1, "refuse"),
            "source_ids": ("array", "i32", 1, "refuse"),
            # live-mutation era (v2, neighbors/mutation)
            "tombstones": ("array", "u8", 2, "default"),
            # integrity era (v3, raft_tpu/integrity) — see ivf_flat
            "list_digests": ("array", "u32", 3, "default"),
            "table_digests": ("meta", "json", 3, "default"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "mut_cursor": ("meta", "int", 2, "default"),
            "append_slack": ("meta", "int", 2, "default"),
            # re-derived from the rotation's shape / process defaults
            "quantizer": ("meta", "str", 1, "derive"),
            "rot_dim": ("meta", "int", 1, "derive"),
            "query_bits": ("meta", "int", 1, "derive"),
            "fused_kb": ("runtime", None, 1, "default"),
            "codes_t": ("runtime", None, 1, "default"),
            "bp_meta": ("runtime", None, 1, "default"),
        },
    },
    "mnmg_ivf_flat": {
        "version": 1,
        "fields": {
            "centers": ("array", "f32", 1, "refuse"),
            "list_data": ("array", "f32", 1, "refuse"),
            "host_gids": ("array", "i32", 1, "refuse"),
            "list_sizes": ("array", "i32", 1, "refuse"),
            "replica_store": ("array", "f32", 1, "derive"),
            "replica_gids": ("array", "i32", 1, "derive"),
            "replica_sizes": ("array", "i32", 1, "derive"),
            # written only when the index carries a correction-table
            # mirror (the shared _replica_arrays helper); registered for
            # every mnmg kind so the shared writer has one contract
            "replica_aux": ("array", "f32", 1, "derive"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "n": ("meta", "int", 1, "refuse"),
            "n_ranks": ("meta", "int", 1, "refuse"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "bridged": ("meta", "bool", 1, "default"),
            "replication": ("meta", "int", 1, "default"),
        },
    },
    "mnmg_ivf_pq": {
        "version": 1,
        "fields": {
            "rotation": ("array", "f32", 1, "refuse"),
            "centers": ("array", "f32", 1, "refuse"),
            "pq_centers": ("array", "f32", 1, "refuse"),
            "codes": ("array", "i32", 1, "refuse"),
            "host_gids": ("array", "i32", 1, "refuse"),
            "list_sizes": ("array", "i32", 1, "refuse"),
            "replica_store": ("array", "i32", 1, "derive"),
            "replica_gids": ("array", "i32", 1, "derive"),
            "replica_sizes": ("array", "i32", 1, "derive"),
            "replica_aux": ("array", "f32", 1, "derive"),  # see mnmg_ivf_flat
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "n": ("meta", "int", 1, "refuse"),
            "n_ranks": ("meta", "int", 1, "refuse"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "pq_dim": ("meta", "int", 1, "refuse"),
            "pq_bits": ("meta", "int", 1, "refuse"),
            "per_cluster": ("meta", "bool", 1, "default"),
            "extended": ("meta", "bool", 1, "default"),
            "bridged": ("meta", "bool", 1, "default"),
            "replication": ("meta", "int", 1, "default"),
        },
    },
    "mnmg_ivf_rabitq": {
        "version": 1,
        "fields": {
            "rotation": ("array", "f32", 1, "refuse"),
            "centers": ("array", "f32", 1, "refuse"),
            "codes": ("array", "u32", 1, "refuse"),
            "aux": ("array", "f32", 1, "refuse"),
            "host_gids": ("array", "i32", 1, "refuse"),
            "list_sizes": ("array", "i32", 1, "refuse"),
            "replica_store": ("array", "u32", 1, "derive"),
            "replica_gids": ("array", "i32", 1, "derive"),
            "replica_sizes": ("array", "i32", 1, "derive"),
            "replica_aux": ("array", "f32", 1, "derive"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "n": ("meta", "int", 1, "refuse"),
            "n_ranks": ("meta", "int", 1, "refuse"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "bridged": ("meta", "bool", 1, "default"),
            "replication": ("meta", "int", 1, "default"),
        },
    },
    "mnmg_ivf_flat_sharded": {
        "version": 1,
        "fields": {
            "centers": ("array", "f32", 1, "refuse"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "n": ("meta", "int", 1, "refuse"),
            "n_ranks": ("meta", "int", 1, "refuse"),
            "n_parts": ("meta", "int", 1, "derive"),
            "parts": ("meta", "json", 1, "refuse"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "replication": ("meta", "int", 1, "default"),
        },
    },
    "mnmg_ivf_pq_sharded": {
        "version": 1,
        "fields": {
            "rotation": ("array", "f32", 1, "refuse"),
            "centers": ("array", "f32", 1, "refuse"),
            "pq_centers": ("array", "f32", 1, "refuse"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "n": ("meta", "int", 1, "refuse"),
            "n_ranks": ("meta", "int", 1, "refuse"),
            "n_parts": ("meta", "int", 1, "derive"),
            "parts": ("meta", "json", 1, "refuse"),
            "metric": ("meta", "int", 1, "refuse"),
            "n_lists": ("meta", "int", 1, "refuse"),
            "pq_dim": ("meta", "int", 1, "refuse"),
            "pq_bits": ("meta", "int", 1, "refuse"),
            "per_cluster": ("meta", "bool", 1, "default"),
            "extended": ("meta", "bool", 1, "default"),
            "replication": ("meta", "int", 1, "default"),
        },
    },
    # one shared schema for every `{kind}_part` per-process part file
    # (the lint rule resolves `kind + "_part"` writes here); reads are
    # the shared `_load_local_tables` assembly, not per-kind load code
    "mnmg_sharded_part": {
        "version": 1,
        "fields": {
            "store": ("array", "f32", 1, "refuse"),
            "gids": ("array", "i32", 1, "refuse"),
            "sizes": ("array", "i32", 1, "derive"),
            "mirror_store": ("array", "f32", 1, "derive"),
            "mirror_gids": ("array", "i32", 1, "derive"),
            "kind": ("meta", "str", 1, "refuse"),
            "ranks": ("meta", "json", 1, "refuse"),
        },
    },
    # one mutation batch's payload container (neighbors/mutation): the
    # CRC'd sidecar a mutlog.jsonl line points at — written atomically
    # BEFORE its line is appended, swept once a checkpoint commit
    # supersedes it
    "mutation_batch": {
        "version": 1,
        "fields": {
            "ids": ("array", "i32", 1, "refuse"),
            # deletes and rebalances carry no vectors
            "vectors": ("array", "f32", 1, "default"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "op": ("meta", "str", 1, "refuse"),
            "seq": ("meta", "int", 1, "refuse"),
        },
    },
}


class SerializationError(ValueError):
    """A container could not be decoded: truncated/empty file, bad magic,
    torn header. Subclasses ValueError so pre-existing `except ValueError`
    dispatch still catches it."""


class ChecksumError(SerializationError):
    """One or more field buffers failed CRC-32C verification. `path` names
    the container, `fields` the corrupt field names — the heal paths use
    them to decide which shards to re-materialize from a peer mirror."""

    def __init__(self, path: str, fields: List[str]):
        super().__init__(
            f"checksum mismatch in {path!r}: corrupt fields {fields}"
        )
        self.path = path
        self.fields = list(fields)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _data_start(hlen: int) -> int:
    """Byte offset of the data region: magic (8) + version/len fields
    (12) + JSON header, aligned — the ONE derivation (readers, the
    chaos offset probe and the field-range helper all call this)."""
    return _align(8 + 12 + hlen)


# -- CRC-32C (Castagnoli) ----------------------------------------------
#
# Pure numpy, no dependencies: per-block zero-init CRCs are computed
# VECTORIZED across blocks (the table recurrence runs its _BLOCK steps on
# an (n_blocks,) uint32 register file), then folded left-to-right with the
# precomputed shift-by-one-block linear map (CRC is GF(2)-linear, so
# "append _BLOCK zero bytes" is a 32x32 bit matrix, stored as 4x256
# byte-lookup tables). ~10 ms/MB vs ~1 s/MB for a bytewise Python loop.

_CRC_POLY = np.uint32(0x82F63B78)
_BLOCK = 1024


def _crc_table() -> np.ndarray:
    idx = np.arange(256, dtype=np.uint32)
    crc = idx
    for _ in range(8):
        crc = np.where(crc & 1, (crc >> 1) ^ _CRC_POLY, crc >> 1)
    return crc.astype(np.uint32)


_TBL = _crc_table()
_SHIFT_TBLS: Optional[np.ndarray] = None  # (4, 256) lazy


def _zero_steps(reg: np.ndarray, n: int) -> np.ndarray:
    """Advance CRC registers by n zero bytes (vectorized over registers)."""
    for _ in range(n):
        reg = _TBL[reg & 0xFF] ^ (reg >> np.uint32(8))
    return reg


def _shift_tables() -> np.ndarray:
    """4x256 lookup applying the "append _BLOCK zero bytes" linear map:
    shift(x) = T0[x&FF] ^ T1[(x>>8)&FF] ^ T2[(x>>16)&FF] ^ T3[x>>24]."""
    global _SHIFT_TBLS
    if _SHIFT_TBLS is None:
        basis = _zero_steps(np.uint32(1) << np.arange(32, dtype=np.uint32),
                            _BLOCK)  # (32,) images of each bit
        tbls = np.zeros((4, 256), np.uint32)
        for k in range(4):
            bytes_ = np.arange(256, dtype=np.uint32)
            acc = np.zeros(256, np.uint32)
            for bit in range(8):
                acc ^= np.where(bytes_ & (1 << bit),
                                basis[8 * k + bit], np.uint32(0))
            tbls[k] = acc
        _SHIFT_TBLS = tbls
    return _SHIFT_TBLS


def _shift_block(x: np.ndarray) -> np.ndarray:
    t = _shift_tables()
    return (t[0][x & 0xFF] ^ t[1][(x >> np.uint32(8)) & 0xFF]
            ^ t[2][(x >> np.uint32(16)) & 0xFF] ^ t[3][x >> np.uint32(24)])


def crc32c(data, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) of a bytes-like / numpy buffer. `crc` chains a
    previous call's result. Matches the RFC 3720 reference
    (crc32c(b"123456789") == 0xE3069283)."""
    buf = np.frombuffer(memoryview(data).cast("B"), np.uint8)
    reg = np.uint32(~np.uint32(crc) & np.uint32(0xFFFFFFFF))
    n_blocks = buf.size // _BLOCK
    group = 1 << 16  # ≤64 MiB of payload widened to uint32 at a time
    for g0 in range(0, n_blocks, group):
        gn = min(group, n_blocks - g0)
        data2d = (buf[g0 * _BLOCK:(g0 + gn) * _BLOCK]
                  .reshape(gn, _BLOCK).astype(np.uint32))
        regs = np.zeros(gn, np.uint32)
        for j in range(_BLOCK):
            regs = _TBL[(regs ^ data2d[:, j]) & 0xFF] ^ (regs >> np.uint32(8))
        # affine split: running = shift(running_prev) ^ raw_block; the
        # init register rides the same shifts (f_I(M) = f_0(M) + shift(I))
        for i in range(gn):
            reg = _shift_block(reg) ^ regs[i]
    for b in buf[n_blocks * _BLOCK:]:
        reg = _TBL[(reg ^ b) & 0xFF] ^ (reg >> np.uint32(8))
    return int(~reg & np.uint32(0xFFFFFFFF))


# -- atomic path writes ------------------------------------------------

@contextlib.contextmanager
def atomic_write(path: Union[str, os.PathLike]):
    """Write-to-temp-then-rename protocol for checkpoint files: yields the
    temp path to write, then atomically `os.replace`s it over `path` on
    success (and unlinks it on failure). A crash mid-write leaves the
    previous file intact; readers never observe a torn container. Every
    checkpoint write in the library MUST route through here (ci/
    check_style.sh gates bare `os.rename` / `open(..., "wb")`)."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        yield tmp
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def serialize_arrays(
    f: Union[str, os.PathLike, io.IOBase],
    arrays: Mapping[str, Any],
    meta: Dict[str, Any] | None = None,
) -> None:
    """Write named arrays + JSON-able metadata to a file or stream. Path
    writes are atomic (write-to-temp-then-rename) and every field carries
    a CRC-32C checksum the read path verifies."""
    own = isinstance(f, (str, os.PathLike))
    bufs = []
    fields = []
    offset = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(np.asarray(arr))
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        offset = _align(offset)
        fields.append(
            {
                "name": name,
                "dtype": a.dtype.str,
                "shape": list(a.shape),
                "offset": offset,
                "nbytes": int(a.nbytes),
                "crc32c": crc32c(a.data) if a.nbytes else 0,
            }
        )
        bufs.append((offset, a))
        offset += a.nbytes
    header = json.dumps({"meta": meta or {}, "fields": fields}).encode()

    if own:
        with atomic_write(f) as tmp:
            _write_container(tmp, header, bufs, try_native=True)
        return
    _write_stream(f, header, bufs)


def _write_container(path: str, header: bytes, bufs, try_native: bool) -> None:
    if try_native:
        # native C++ codec path (cpp/raft_tpu_native.cc rt_write_container)
        from raft_tpu import native

        if native.write_container(
            path, header,
            [a for _, a in bufs],
            [a.nbytes for _, a in bufs],
            [off for off, _ in bufs],
        ):
            return
    with open(path, "wb") as fh:
        _write_stream(fh, header, bufs)


def _write_stream(fh, header: bytes, bufs) -> None:
    fh.write(MAGIC)
    fh.write(struct.pack("<IQ", CONTAINER_VERSION, len(header)))
    fh.write(header)
    data_start = _align(fh.tell())
    fh.write(b"\x00" * (data_start - fh.tell()))
    pos = 0
    for off, a in bufs:
        if off > pos:
            fh.write(b"\x00" * (off - pos))
            pos = off
        fh.write(a.tobytes())
        pos += a.nbytes


def _describe(f) -> str:
    if isinstance(f, (str, os.PathLike)):
        return os.fspath(f)
    return getattr(f, "name", "<stream>")


def _read_header(fh, name: str) -> Tuple[int, dict]:
    """Shared magic + version + JSON header decode; raises
    `SerializationError` naming the file on any truncated/torn read
    (instead of the raw struct.error / JSONDecodeError / KeyError a
    short or garbage file used to surface)."""
    magic = fh.read(8)
    if len(magic) < 8:
        raise SerializationError(
            f"truncated container {name!r}: {len(magic)} bytes, expected at "
            f"least the 8-byte magic {MAGIC!r}"
        )
    if magic != MAGIC:
        raise SerializationError(
            f"not a raft_tpu serialized container (bad magic) in {name!r}: "
            f"got {magic!r}, expected {MAGIC!r}"
        )
    lenbytes = fh.read(12)
    if len(lenbytes) < 12:
        raise SerializationError(
            f"truncated container {name!r}: header length fields missing "
            f"(got {8 + len(lenbytes)} bytes)"
        )
    version, hlen = struct.unpack("<IQ", lenbytes)
    if version > CONTAINER_VERSION:
        raise SerializationError(
            f"container version {version} newer than supported "
            f"{CONTAINER_VERSION}"
        )
    raw = fh.read(hlen)
    if len(raw) < hlen:
        raise SerializationError(
            f"truncated container {name!r}: header says {hlen} bytes, file "
            f"holds {len(raw)}"
        )
    try:
        header = json.loads(raw.decode())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise SerializationError(
            f"torn container header in {name!r}: {e}"
        ) from e
    if not isinstance(header, dict) or "meta" not in header:
        raise SerializationError(
            f"container header in {name!r} lacks the 'meta' section"
        )
    return hlen, header


def peek_meta(f: Union[str, os.PathLike, io.IOBase]) -> Dict[str, Any]:
    """Read ONLY a container's meta dict (magic + header; the data blob
    is never touched) — the cheap dispatch probe for multi-GB
    checkpoints whose kind decides which loader to run."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        return _read_header(fh, _describe(f))[1]["meta"]
    finally:
        if own:
            fh.close()


def container_data_start(f: Union[str, os.PathLike, io.IOBase]) -> int:
    """Byte offset where a container's data region begins (header
    excluded) — chaos hooks corrupt only past here so the header stays
    parseable and the per-array checksums do the detecting."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        hlen, _ = _read_header(fh, _describe(f))
        return _data_start(hlen)
    finally:
        if own:
            fh.close()


def deserialize_arrays(
    f: Union[str, os.PathLike, io.IOBase],
    to_device: bool = True,
    verify: bool = True,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Read a container; returns (arrays, meta). Arrays are jax.Arrays when
    `to_device` else numpy. With `verify` (default) every field's CRC-32C
    is checked and a mismatch raises `ChecksumError` naming the corrupt
    fields; pass verify=False only for forensic reads."""
    arrays, meta, bad = deserialize_arrays_checked(f, to_device=to_device,
                                                   verify=verify)
    if bad:
        raise ChecksumError(_describe(f), bad)
    return arrays, meta


def check_ckpt_version(meta: Dict[str, Any], path: str = "<container>") -> None:
    """The schema version gate: a checkpoint whose kind is registered in
    `CKPT_SCHEMA` but whose declared version is NEWER than this library
    writes carries fields whose semantics this build cannot know — loading
    it by best effort would silently mis-read index state, so refuse,
    typed. Unregistered kinds pass (generic containers gate elsewhere)."""
    kind = meta.get("kind")
    spec = CKPT_SCHEMA.get(kind)
    if spec is None:
        return
    version = int(meta.get("version", 1))
    if version > int(spec["version"]):
        raise SerializationError(
            f"checkpoint {path!r} declares {kind!r} version {version}, "
            f"newer than the library's supported version "
            f"{spec['version']} — refusing to load fields whose "
            f"semantics this build cannot know (upgrade raft_tpu)"
        )


def read_ckpt(
    f: Union[str, os.PathLike, io.IOBase],
    kind: str,
    to_device: bool = True,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Schema-checked checkpoint read — the single-file load path of the
    `CKPT_SCHEMA` contract. Returns (arrays, meta) after enforcing, in
    order:

      1. the container's declared kind matches `kind` (typed mismatch);
      2. the version gate (`check_ckpt_version`: newer-than-library
         checkpoints refuse, typed);
      3. required ("refuse") array fields of the file's version are
         present — a truncated writer cannot produce a half-index that
         explodes three layers later;
      4. corrupt (CRC-failed) fields degrade per their declared
         absent-on-load behavior: "default"/"derive" fields are DROPPED
         (the load falls back exactly as if the writer had never emitted
         them, with an obs `ckpt.degrade` event) while a corrupt
         "refuse" field raises `ChecksumError` naming it.
    """
    spec = CKPT_SCHEMA.get(kind)
    if spec is None:
        raise SerializationError(f"no CKPT_SCHEMA entry for kind {kind!r}")
    name = _describe(f)
    arrays, meta, bad = deserialize_arrays_checked(f, to_device=to_device)
    got = meta.get("kind")
    if got != kind:
        raise SerializationError(
            f"not a {kind} container: {name!r} declares kind {got!r}"
        )
    check_ckpt_version(meta, name)
    version = int(meta.get("version", 1))
    fields = spec["fields"]
    missing = [
        fname for fname, (cat, _dt, since, absent) in sorted(fields.items())
        if absent == "refuse" and since <= version
        and fname not in (arrays if cat == "array"
                          else meta if cat == "meta" else (fname,))
    ]
    if missing:
        raise SerializationError(
            f"checkpoint {name!r} ({kind} v{version}) is missing required "
            f"field(s) {missing} — torn or foreign writer"
        )
    if bad:
        required_bad = []
        for fname in bad:
            cat_spec = fields.get(fname)
            if cat_spec is not None and cat_spec[3] in ("default", "derive"):
                # registered-optional: degrade exactly as the schema
                # declares for absence — drop the field, load falls back
                arrays.pop(fname, None)
                from raft_tpu import obs

                obs.event("ckpt.degrade", file=name, field=fname,
                          action="dropped", absent=cat_spec[3])
            else:
                required_bad.append(fname)
        if required_bad:
            raise ChecksumError(name, required_bad)
    return arrays, meta


def field_byte_range(
    f: Union[str, os.PathLike, io.IOBase], name: str
) -> Tuple[int, int]:
    """Absolute (start, end) byte range of one named field's buffer in a
    container file — the chaos drills' targeted-rot helper (rot exactly
    one registered field and prove the load degrades per its schema)."""
    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "rb") if own else f
    try:
        hlen, header = _read_header(fh, _describe(f))
        data_start = _data_start(hlen)
        for field in header.get("fields", ()):
            if field["name"] == name:
                start = data_start + int(field["offset"])
                return start, start + int(field["nbytes"])
        raise SerializationError(
            f"container {_describe(f)!r} has no field {name!r}"
        )
    finally:
        if own:
            fh.close()


def deserialize_arrays_checked(
    f: Union[str, os.PathLike, io.IOBase],
    to_device: bool = True,
    verify: bool = True,
) -> Tuple[Dict[str, Any], Dict[str, Any], List[str]]:
    """Like `deserialize_arrays` but returns (arrays, meta, bad_fields)
    instead of raising on checksum mismatch — corrupt fields still decode
    (garbage bytes) so heal paths can keep the intact fields and
    re-materialize only the bad ones from a peer mirror."""
    own = isinstance(f, (str, os.PathLike))
    name = _describe(f)
    fh = open(f, "rb") if own else f
    try:
        hlen, header = _read_header(fh, name)
        if "fields" not in header:
            raise SerializationError(
                f"container header in {name!r} lacks the 'fields' section"
            )
        data_start = _data_start(hlen)
        fh.seek(data_start)
        blob = fh.read()
        arrays: Dict[str, Any] = {}
        bad: List[str] = []
        for field in header["fields"]:
            off, nb = field["offset"], field["nbytes"]
            raw = blob[off: off + nb]
            if len(raw) < nb:
                raise SerializationError(
                    f"truncated container {name!r}: field "
                    f"{field['name']!r} wants {nb} bytes at offset {off}, "
                    f"file holds {len(raw)}"
                )
            if verify and nb and field.get("crc32c") is not None:
                if crc32c(raw) != int(field["crc32c"]):
                    bad.append(field["name"])
            a = np.frombuffer(raw, dtype=np.dtype(field["dtype"]))
            a = a.reshape(field["shape"])
            arrays[field["name"]] = jax.device_put(a) if to_device else a
        return arrays, header["meta"], bad
    finally:
        if own:
            fh.close()
