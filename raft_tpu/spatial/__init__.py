"""Legacy spatial namespace (reference `raft/spatial/`, survey §2.9).

The reference keeps `spatial/knn/*` as deprecated forwarding aliases of
`neighbors/*` for cuML compatibility (e.g. spatial/knn/ivf_flat.cuh,
spatial/knn/knn.cuh). This package mirrors that: same symbols, re-exported
from `raft_tpu.neighbors`, with a DeprecationWarning on import.
"""

from raft_tpu.spatial import knn

__all__ = ["knn"]
