"""Deprecated aliases of raft_tpu.neighbors (reference spatial/knn/knn.cuh:
`#pragma message` deprecation shims kept for cuML)."""

import warnings

warnings.warn(
    "raft_tpu.spatial.knn is deprecated; use raft_tpu.neighbors",
    DeprecationWarning,
    stacklevel=2,
)

from raft_tpu.neighbors import ball_cover, brute_force, ivf_flat, ivf_pq
from raft_tpu.neighbors.brute_force import knn, knn_merge_parts
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors

__all__ = [
    "ball_cover",
    "brute_force",
    "ivf_flat",
    "ivf_pq",
    "knn",
    "knn_merge_parts",
    "eps_neighbors",
]
