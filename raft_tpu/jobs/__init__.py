"""raft_tpu.jobs — durable, resumable job running for long work.

TPU fleets make preemption the NORMAL failure mode: multi-hour streaming
builds and bench sessions must survive SIGTERM, SIGKILL, hung children,
and stalled device waits, or the 100M-row regime is unreachable
(ROADMAP item 5). This subpackage turns the fault-injection (PR 1) and
replication/recovery (PR 4) machinery into survivable long-running
work:

- `JobDir` (jobs.jobdir): one job's durable directory — CRC-32C-
  verified artifacts, an append-only stage manifest with input
  fingerprints + provenance, per-stage scratch for intra-stage
  checkpoints.
- `Job` (jobs.runner): a named DAG of stages; re-running skips
  completed stages and resumes the first incomplete one. SIGTERM (or
  an injected ``job.preempt`` fault) is a graceful suspend
  (`JobPreempted`), not a failure.
- `Watchdog` / `run_supervised` (jobs.watchdog): heartbeat + wall-clock
  supervision; a stalled stage or silent child is killed as a typed
  `StageTimeout` and retried through the seeded
  `resilience.retry_with_backoff`.
- streaming helpers (jobs.streaming): batch-boundary checkpoints for
  `extend_from_file`-driven IVF-Flat/PQ/RaBitQ builds (SIGKILL
  mid-stream resumes to a bit-identical index), chunked resumable
  dataset synthesis, `mnmg_ckpt`-backed distributed build stages
  resuming through the PR-4 `rehydrate` path, and crash-atomic online
  mutation stages (`resumable_mutate`, riding `neighbors.mutation`'s
  log — a rebalance-only sequence is the background compaction job),
  and cursor-checkpointed integrity sweeps (`resumable_scrub`, walking
  the `raft_tpu.integrity` digest sidecar in bounded slices).

Layering: jobs may import core/io/comms/obs at module scope (the
raftlint ``ALLOWED`` map); index modules resolve lazily at call time.

Quickstart (docs/jobs.md has the full walkthrough)::

    from raft_tpu import jobs

    job = jobs.Job("my_build", "/data/jobs/my_build")

    @job.stage("make_data", inputs={"rows": N})
    def make_data(ctx): ...

    @job.stage("train", deps=("make_data",), retries=2,
               stall_timeout_s=600)
    def train(ctx): ...

    job.run()   # killed? run it again — completed stages skip
"""

from raft_tpu.jobs.jobdir import JobDir, fingerprint_of
from raft_tpu.jobs.runner import (
    Job,
    JobPreempted,
    StageContext,
    StageFailed,
    StageSpec,
)
from raft_tpu.jobs.streaming import (
    STREAM_KINDS,
    checkpointed_mnmg_build,
    resumable_extend_from_file,
    resumable_extend_local_from_file,
    resumable_mutate,
    resumable_scrub,
    resumable_write_npy,
)
from raft_tpu.jobs.watchdog import (
    Heartbeat,
    StageCancelled,
    StageTimeout,
    Watchdog,
    run_supervised,
)

__all__ = [
    "Heartbeat",
    "Job",
    "JobDir",
    "JobPreempted",
    "STREAM_KINDS",
    "StageCancelled",
    "StageContext",
    "StageFailed",
    "StageSpec",
    "StageTimeout",
    "Watchdog",
    "checkpointed_mnmg_build",
    "fingerprint_of",
    "resumable_extend_from_file",
    "resumable_extend_local_from_file",
    "resumable_mutate",
    "resumable_scrub",
    "resumable_write_npy",
    "run_supervised",
]
