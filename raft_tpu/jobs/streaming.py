"""Intra-stage resume for streaming work: the batch-boundary protocol.

The runner's manifest makes whole STAGES resumable; this module makes
the long stages resumable INSIDE themselves, at batch boundaries — the
difference between "a preempted 100M build restarts its 3-hour extend"
and "it loses at most one batch". Two helpers, one discipline:

- `resumable_extend_from_file`: the streaming IVF build loop
  (`io.FileBatchLoader` → repeated `ivf_*.extend`). Every
  `checkpoint_every` batches it commits the WHOLE index (kmeans
  centers + partially-filled list tables + slot ids, via the index's
  own CRC'd `save`) plus a cursor sidecar (batch number, id offset)
  into the stage's scratch dir; a killed run reloads the checkpoint,
  re-opens the loader at the cursor (`FileBatchLoader(start_batch=)`
  yields a bit-identical tail), and produces a **bit-identical** index
  to an uninterrupted build.
- `resumable_write_npy`: chunked dataset synthesis (the
  `BENCH_10M_PARTIAL` failure class): the `.npy` grows chunk by chunk
  behind a durable progress marker; a resume truncates any torn tail
  back to the last committed chunk and continues — given a
  deterministic per-chunk generator, the finished file is byte-equal
  to a one-shot write.

MNMG variants (`checkpointed_mnmg_build`, `resumable_extend_local_from_
file`) ride the PR-4 machinery: checkpoints go through `mnmg_ckpt`
saves and resumes through `resilience.rehydrate`, so a preempted
distributed build re-enters via the same verified/healing load path a
crashed rank does.

Chaos: every checkpoint commit is followed by
`faults.crash_point("job.stage.crash")` — an injected kill_rank fault
SIGKILLs the process on its count-th boundary, which is how the drills
prove the artifact on disk (not process luck) carries the resume. The
same site doubles as a flaky transient (`fault_point`) the supervised
runner retries.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core import faults

from raft_tpu.io import FileBatchLoader, probe_file
from raft_tpu.jobs.jobdir import JobDir, fingerprint_of

STREAM_CRASH_SITE = "job.stage.crash"

#: index kinds the streaming-build checkpoint protocol understands
STREAM_KINDS = ("ivf_flat", "ivf_pq", "ivf_rabitq")


def _index_module(kind: str):
    """The `neighbors` module for a streamable index kind (lazy: jobs'
    layer allowance is core/io/comms/obs, so neighbors resolves at call
    time like every sanctioned upward reference)."""
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as mod
    elif kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as mod
    elif kind == "ivf_rabitq":
        from raft_tpu.neighbors import ivf_rabitq as mod
    else:
        raise ValueError(
            f"unknown streamable index kind {kind!r}; one of {STREAM_KINDS}")
    return mod


def _ctx_hooks(ctx, scratch, heartbeat, preempt):
    """Resolve (scratch, heartbeat, preempt) from an optional
    `StageContext` — streaming helpers run identically under the runner
    or standalone (tests, ad-hoc scripts)."""
    if ctx is not None:
        scratch = scratch or ctx.scratch()
        heartbeat = heartbeat or ctx.heartbeat
        preempt = preempt or ctx.preempt_point
    if scratch is None:
        raise ValueError("need a scratch dir: pass ctx= or scratch=")
    os.makedirs(scratch, exist_ok=True)
    return scratch, heartbeat or (lambda: None), preempt or (lambda: None)


def resumable_extend_from_file(
    kind: str,
    index,
    path: str,
    batch_rows: int,
    *,
    ctx=None,
    scratch: Optional[str] = None,
    start_id: int = 0,
    checkpoint_every: int = 1,
    depth: int = 3,
    heartbeat: Optional[Callable[[], None]] = None,
    preempt: Optional[Callable[[], None]] = None,
    on_batch: Optional[Callable[[int, int, float], None]] = None,
) -> Tuple[object, dict]:
    """Stream an on-disk dataset into `index` via repeated
    `ivf_<kind>.extend`, checkpointing at batch boundaries so a killed
    run resumes bit-identically (module docstring). `index` is the
    freshly-trained (empty-table) index; on resume it is REPLACED by the
    checkpointed one — the caller's trained state is only the cold-start
    seed. `on_batch(batch_no, valid_rows, extend_seconds)` is the bench
    timing hook (the extend is fenced before the clock stops). Returns
    (index, stats)."""
    mod = _index_module(kind)
    scratch, heartbeat, preempt = _ctx_hooks(ctx, scratch, heartbeat, preempt)
    import jax.numpy as jnp

    cursor_path = os.path.join(scratch, "stream_cursor.json")
    # per-batch checkpoint names + cursor-written-LAST make the two-file
    # commit crash-atomic: a kill between the index save and the cursor
    # write leaves the cursor pointing at the PREVIOUS (intact, matching)
    # checkpoint, so the resume re-extends from exactly that state — the
    # orphan newer save is swept at the next commit. A shared mutable
    # checkpoint name would instead pair a new index with an old cursor
    # and double-ingest a batch.
    ckpt_of = lambda n: os.path.join(scratch, f"stream_index.{n}.ckpt")  # noqa: E731
    probe_rows = int(probe_file(path)[1][0])
    config = fingerprint_of({
        "kind": kind, "path": os.path.abspath(path), "n_rows": probe_rows,
        "batch_rows": int(batch_rows), "start_id": int(start_id),
    })

    b0, offset = 0, int(start_id)
    cur = JobDir.read_json(cursor_path)
    if (cur and cur.get("config") == config and int(cur.get("batch", 0)) > 0
            and os.path.exists(ckpt_of(int(cur["batch"])))):
        # a stale cursor (changed file/geometry) fails this gate and the
        # build starts over — never resumes into different inputs
        b0, offset = int(cur["batch"]), int(cur["offset"])
        index = mod.load(ckpt_of(b0))
        obs.event("job", action="stream_resume", index_kind=kind, batch=b0,
                  offset=offset)
    run_start_offset = offset

    def commit(batch_no: int, id_offset: int) -> None:
        mod.save(ckpt_of(batch_no), index)  # CRC'd atomic container write
        JobDir.write_json(cursor_path, {"config": config, "batch": batch_no,
                                        "offset": id_offset})
        # cursor is durable: superseded checkpoints are now unreachable
        keep = os.path.basename(ckpt_of(batch_no))
        for name in os.listdir(scratch):
            if (name.startswith("stream_index.") and name.endswith(".ckpt")
                    and name != keep):
                try:
                    os.remove(os.path.join(scratch, name))
                except OSError:
                    pass  # a sweep miss only costs disk, never correctness
        obs.event("job", action="stream_checkpoint", index_kind=kind,
                  batch=batch_no, offset=id_offset)
        # AFTER the commit: the kill-and-resume drills must prove the
        # artifact on disk carries the resume, not in-process luck
        faults.crash_point(STREAM_CRASH_SITE)

    loader = FileBatchLoader(path, batch_rows, depth=depth, copy=False,
                             start_batch=b0)
    n_batches, b = loader.n_batches, b0
    for batch, valid in loader:
        # transient-failure flavor of the site: an armed flaky fault
        # raises FaultInjected here and the supervised runner retries
        # the stage, which re-enters through the cursor
        faults.fault_point(STREAM_CRASH_SITE)
        ids = jnp.arange(offset, offset + valid, dtype=jnp.int32)
        t0 = time.perf_counter() if on_batch is not None else 0.0
        index = mod.extend(index, batch[:valid], ids)
        if on_batch is not None:
            import jax

            jax.block_until_ready(
                index.codes if hasattr(index, "codes") else index.list_data)
            on_batch(b, int(valid), time.perf_counter() - t0)
        offset += valid
        b += 1
        if b == n_batches or (checkpoint_every > 0
                              and b % checkpoint_every == 0):
            commit(b, offset)
            preempt()  # a pending SIGTERM suspends here, state durable
        heartbeat()
    # rows_ingested is CUMULATIVE (everything the index now holds from
    # this stream); rows_this_run is what THIS invocation ingested —
    # throughput must divide by the latter, or a resumed tail run banks
    # the whole file's rows against the tail's wall clock
    return index, {"batches": int(b - b0), "resumed_from_batch": int(b0),
                   "rows_ingested": int(offset - start_id),
                   "rows_this_run": int(offset - run_start_offset),
                   "total_rows": int(probe_rows)}


def resumable_mutate(
    kind: str,
    index,
    ops,
    *,
    ctx=None,
    scratch: Optional[str] = None,
    ckpt_every: int = 8,
    slack: int = 0,
    heartbeat: Optional[Callable[[], None]] = None,
    preempt: Optional[Callable[[], None]] = None,
    on_op: Optional[Callable[[int, str], None]] = None,
) -> Tuple[object, dict]:
    """Apply a scripted mutation sequence to `index` through a
    crash-atomic `neighbors.mutation.Mutator` rooted in the stage
    scratch, under the runner's supervision. `ops` is a sequence of
    `apply_batch` shapes: ``("upsert", vectors, ids)``, ``("delete",
    ids)``, ``("rebalance",)`` — a rebalance-only sequence IS the
    background compaction stage.

    Resume contract: the mutator's log dedupes re-issued ops by
    sequence number, so a killed/preempted run re-enters with the SAME
    `ops` list and converges on the bit-identical committed state
    (`index` is only the cold-start seed — a committed checkpoint in
    scratch replaces it, the `resumable_extend_from_file` contract).
    Preemption suspends at commit boundaries, where state is durable.
    Returns (index, stats)."""
    from raft_tpu.neighbors import mutation

    scratch, heartbeat, preempt = _ctx_hooks(ctx, scratch, heartbeat, preempt)
    mut = mutation.Mutator(scratch, index, kind=kind,
                           ckpt_every=ckpt_every, slack=slack)
    resumed_at = mut.applied
    for i, op in enumerate(ops):
        before = mut.index
        if op[0] == "upsert":
            mut.upsert(op[1], op[2])
        elif op[0] == "delete":
            mut.delete(op[1])
        elif op[0] == "rebalance":
            mut.rebalance()
        else:
            raise ValueError(f"unknown mutation op {op[0]!r}")
        # transient-failure flavor: an armed flaky fault aborts the
        # stage BETWEEN ops — everything up to here is logged, so the
        # supervised retry re-enters through the log and skips it
        faults.fault_point(mutation.TOMBSTONE_SITE)
        if on_op is not None and mut.index is not before:
            on_op(i, op[0])
        heartbeat()
        if int(mut.index.mut_cursor) == mut.applied:
            preempt()  # just committed: a pending SIGTERM suspends here
    index = mut.commit()
    obs.event("job", action="mutation_commit", index_kind=kind,
              ops=len(ops), cursor=mut.applied)
    return index, {"ops": int(len(ops)), "resumed_at": int(resumed_at),
                   "applied": int(mut.applied),
                   "live_rows": int(mutation.live_rows(index)),
                   "tombstones": int(index.n_tombstones)}


def resumable_scrub(
    kind: str,
    index,
    *,
    ctx=None,
    scratch: Optional[str] = None,
    budget_lists: int = 8,
    laps: int = 1,
    skip=(),
    heartbeat: Optional[Callable[[], None]] = None,
    preempt: Optional[Callable[[], None]] = None,
    on_slice: Optional[Callable[[int, list], None]] = None,
) -> Tuple[list, dict]:
    """Walk `laps` full integrity passes over a live index in bounded
    `budget_lists` slices (raft_tpu/integrity Scrubber), under the
    runner's supervision. Scrubbing is read-only, so the ONLY durable
    state is the scrub cursor — committed to `scrub_cursor.json` after
    every slice (cursor-written-LAST, the batch-boundary discipline),
    then `faults.crash_point("integrity.scrub.crash")`: a SIGKILL at
    any point resumes from the committed cursor and re-hashes at most
    one slice twice (at-least-once scanning — a repeated slice costs
    time, never correctness). The cursor is fingerprint-gated on
    (kind, geometry, committed mut_cursor), so a scrub never resumes
    into a different index state.

    Returns (mismatches, stats): mismatches are (field, list_id) pairs
    (list_id -1 = a table-granularity field), stats carries
    lists_scanned/mismatches/laps plus the resume point."""
    from raft_tpu.integrity.scrub import SCRUB_CRASH_SITE, Scrubber

    scratch, heartbeat, preempt = _ctx_hooks(ctx, scratch, heartbeat, preempt)
    cursor_path = os.path.join(scratch, "scrub_cursor.json")
    n_lists = int(index.n_lists)
    config = fingerprint_of({"kind": kind, "n_lists": n_lists,
                             "width": int(np.asarray(index.slot_rows).shape[1]),
                             "mut_cursor": int(index.mut_cursor)})
    sc = Scrubber(kind, budget_lists=budget_lists)
    lap = 0
    cur = JobDir.read_json(cursor_path)
    if cur and cur.get("config") == config:
        # a stale cursor (different index state) fails the gate and the
        # walk starts over — never resumes into other content
        sc.cursor = int(cur.get("cursor", 0)) % max(n_lists, 1)
        lap = int(cur.get("lap", 0))
        obs.event("job", action="scrub_resume", index_kind=kind,
                  cursor=sc.cursor, lap=lap)
    resumed_at = int(lap * n_lists + sc.cursor)
    bad: list = []
    while lap < int(laps):
        # transient-failure flavor: an armed flaky fault raises here
        # and the supervised runner retries through the cursor
        faults.fault_point(SCRUB_CRASH_SITE)
        laps_before = sc.laps
        hits = sc.slice_scan(index, skip=skip)
        bad.extend(hits)
        if sc.laps > laps_before:
            lap += 1
        JobDir.write_json(cursor_path, {"config": config,
                                        "cursor": sc.cursor, "lap": lap})
        # AFTER the cursor commit: the kill-and-resume drill must prove
        # the cursor on disk carries the walk, not in-process luck
        faults.crash_point(SCRUB_CRASH_SITE)
        if on_slice is not None:
            on_slice(sc.cursor, hits)
        heartbeat()
        if sc.cursor == 0:
            preempt()  # lap boundary: a pending SIGTERM suspends here
    obs.event("job", action="scrub_done", index_kind=kind,
              lists_scanned=sc.lists_scanned, mismatches=len(bad))
    return bad, {"lists_scanned": int(sc.lists_scanned),
                 "mismatches": int(len(bad)),
                 "laps": int(lap), "resumed_at": resumed_at}


def resumable_write_npy(
    path: str,
    rows: int,
    dim: int,
    chunk_rows: int,
    make_chunk: Callable[[int, int], np.ndarray],
    *,
    ctx=None,
    scratch: Optional[str] = None,
    dtype=np.float32,
    heartbeat: Optional[Callable[[], None]] = None,
    preempt: Optional[Callable[[], None]] = None,
) -> dict:
    """Write a (rows, dim) `.npy` in chunks behind a durable progress
    marker; a killed run resumes from the last committed chunk instead
    of rewriting the file (the `BENCH_10M_PARTIAL` root fix).

    `make_chunk(lo, hi)` must be DETERMINISTIC in (lo, hi) — seed a
    fresh rng per chunk, not a sequential stream — so the resumed file
    is byte-identical to a one-shot write. Commits go fsync-then-marker:
    the marker only advances past bytes that are durable, and a resume
    truncates anything past the marker (a torn tail chunk)."""
    scratch, heartbeat, preempt = _ctx_hooks(ctx, scratch, heartbeat, preempt)
    dtype = np.dtype(dtype)
    marker_path = os.path.join(scratch, "datagen_progress.json")
    config = fingerprint_of({
        "path": os.path.abspath(path), "rows": int(rows), "dim": int(dim),
        "chunk_rows": int(chunk_rows), "dtype": dtype.str,
    })
    row_bytes = int(dim) * dtype.itemsize

    header = np.lib.format.header_data_from_array_1_0(
        np.empty((0, dim), dtype))
    header["shape"] = (int(rows), int(dim))

    def checked_chunk(lo: int, hi: int) -> np.ndarray:
        blk = np.ascontiguousarray(make_chunk(lo, hi), dtype=dtype)
        if blk.shape != (hi - lo, int(dim)):
            raise ValueError(
                f"make_chunk({lo},{hi}) returned {blk.shape}, "
                f"expected {(hi - lo, int(dim))}")
        return blk

    done = 0
    marker = JobDir.read_json(marker_path)
    if marker and marker.get("config") == config and os.path.exists(path):
        done = min(int(marker.get("rows_done", 0)), int(rows))
    pending = None
    if done == 0 and rows > 0:
        # produce + validate the FIRST chunk before the header lands: a
        # broken make_chunk must raise with no bytes on disk, not leave
        # a torn header-only .npy behind
        pending = checked_chunk(0, min(int(chunk_rows), int(rows)))
    if done == 0:
        # fresh start: header + no rows. Deliberately NOT atomic_write —
        # this file grows in place behind the marker; torn tails are
        # dropped by the truncate below, which is this protocol's
        # durability discipline.
        with open(path, "wb") as fh:  # raftlint: disable=hygiene-raw-write
            np.lib.format.write_array_header_1_0(fh, header)
            data_off = fh.tell()
        JobDir.write_json(marker_path, {"config": config, "rows_done": 0,
                                  "data_off": data_off})
    else:
        data_off = int(marker["data_off"])
        obs.event("job", action="datagen_resume", rows_done=done)

    with open(path, "r+b") as fh:
        fh.truncate(data_off + done * row_bytes)  # drop any torn tail
        fh.seek(data_off + done * row_bytes)
        while done < rows:
            hi = min(done + int(chunk_rows), int(rows))
            blk = pending if pending is not None else checked_chunk(done, hi)
            pending = None
            fh.write(blk.tobytes())
            fh.flush()
            os.fsync(fh.fileno())  # marker must never outrun durability
            done = hi
            JobDir.write_json(marker_path, {"config": config, "rows_done": done,
                                      "data_off": data_off})
            faults.crash_point(STREAM_CRASH_SITE)  # post-commit kill site
            preempt()
            heartbeat()
    return {"rows": int(rows), "dim": int(dim),
            "nbytes": os.path.getsize(path)}


# -- MNMG: checkpointed distributed build stages ------------------------

def _agreed_on_all_hosts(flag: bool) -> bool:
    """Agree a per-host boolean across every controller: True iff EVERY
    process passes True (minimum wins). Collective decisions must never
    ride a raw per-host predicate — on a non-shared filesystem one
    controller can see a checkpoint while another doesn't, and the two
    would then enter different collective programs (rehydrate vs build)
    and deadlock the mesh (raftlint: collective-divergence). Single-
    process worlds pass through."""
    import jax

    if jax.process_count() <= 1:
        return bool(flag)
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    votes = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([1 if flag else 0]), tiled=True))
    return bool(votes.min())


def _mnmg_save(kind: str, filename: str, index) -> None:
    """Checkpoint a distributed index through the layout-appropriate
    `mnmg_ckpt` save: driver-built indexes (host mirrors present) use
    the single-controller save; `*_build_local` indexes use the
    collective sharded save (whose `kind` tag still re-enters through
    the same `rehydrate` load)."""
    from raft_tpu.comms import mnmg_ckpt

    saves = {"ivf_flat": (mnmg_ckpt.ivf_flat_save,
                          mnmg_ckpt.ivf_flat_save_local),
             "ivf_pq": (mnmg_ckpt.ivf_pq_save, mnmg_ckpt.ivf_pq_save_local),
             "ivf_rabitq": (mnmg_ckpt.ivf_rabitq_save, None)}.get(kind)
    if saves is None:
        raise ValueError(f"unknown MNMG index kind {kind!r}")
    save, save_local = saves
    if getattr(index, "host_gids", None) is None:
        if save_local is None:
            raise ValueError(
                f"{kind!r} has no collective sharded checkpoint yet; "
                f"stream through a driver-built index")
        save_local(filename, index)
    else:
        save(filename, index)


def checkpointed_mnmg_build(
    comms,
    kind: str,
    build_fn: Callable[[], object],
    ckpt_path: str,
):
    """Run a distributed build as a resumable stage: when `ckpt_path`
    already holds a checkpoint, skip the build and re-enter through the
    PR-4 `resilience.rehydrate` path (verified CRC load, replica-mirror
    healing, seeded retry on flaky reads) — so a preempted MNMG build
    run resumes instead of rebuilding. Otherwise run `build_fn()` and
    commit its result through the matching `mnmg_ckpt` save. Returns
    (index, RankHealth, resumed: bool)."""
    from raft_tpu.comms.resilience import RankHealth, rehydrate

    # the resume decision is AGREED (min over an allgather), never a raw
    # per-host os.path.exists: the divergence audit (ISSUE 9) caught the
    # original form — controllers disagreeing on the checkpoint's
    # existence would split between rehydrate's collective load and the
    # build's collectives and wedge the mesh
    resume = _agreed_on_all_hosts(os.path.exists(ckpt_path))
    if resume:
        index, health = rehydrate(comms, ckpt_path)
        obs.event("job", action="mnmg_resume", index_kind=kind, ckpt=ckpt_path)
        return index, health, True
    index = build_fn()
    _mnmg_save(kind, ckpt_path, index)
    faults.crash_point(STREAM_CRASH_SITE)  # post-commit kill site
    return index, RankHealth.all_healthy(comms.get_size()), False


def resumable_extend_local_from_file(
    comms,
    kind: str,
    index,
    extend_local_fn,
    path: str,
    batch_rows: int,
    *,
    ctx=None,
    scratch: Optional[str] = None,
    ckpt_path: Optional[str] = None,
    checkpoint_every: int = 1,
    depth: int = 3,
    heartbeat: Optional[Callable[[], None]] = None,
    preempt: Optional[Callable[[], None]] = None,
) -> Tuple[object, dict]:
    """Collective twin of `resumable_extend_from_file` for the
    multi-controller ingest path (`io.extend_from_file_local`): every
    controller streams its own file partition through
    `extend_local_fn(index, rows)` (a collective), checkpointing the
    distributed index through `mnmg_ckpt` every `checkpoint_every`
    batches. The resume cursor is AGREED across controllers (host
    allgather of per-rank cursors, minimum wins) so the collective
    extend schedule stays aligned; resume re-enters through
    `rehydrate`'s verified/healing load. Single-controller worlds (the
    in-process test mesh) degrade to the local protocol."""
    scratch, heartbeat, preempt = _ctx_hooks(ctx, scratch, heartbeat, preempt)
    import jax

    from raft_tpu.comms.resilience import rehydrate

    cursor_path = os.path.join(scratch, "mnmg_stream_cursor.json")
    base = ckpt_path or os.path.join(scratch, "mnmg_stream.ckpt")
    # deterministic per-batch checkpoint names + cursor-written-LAST
    # (crash-atomicity, as in resumable_extend_from_file) — and every
    # controller derives the SAME name from the agreed batch count, so
    # the min-cursor resume loads one shared file. The previous
    # checkpoint is kept alongside the current one: on a shared fs a
    # controller killed between the collective save and its own cursor
    # write is one batch behind, and the min-cursor file must still
    # exist when the world resumes at it.
    ckpt_of = lambda n: f"{base}.{n}"  # noqa: E731
    probe_rows = int(probe_file(path)[1][0])
    my_nb = -(-probe_rows // int(batch_rows)) if probe_rows else 0
    config = fingerprint_of({
        "kind": kind, "path": os.path.abspath(path), "n_rows": probe_rows,
        "batch_rows": int(batch_rows), "world": int(comms.get_size()),
    })

    my_cursor = 0
    cur = JobDir.read_json(cursor_path)
    if (cur and cur.get("config") == config
            and os.path.exists(ckpt_of(int(cur.get("batch", 0))))):
        my_cursor = int(cur.get("batch", 0))

    # agree the resume point: the slowest controller's durable cursor
    # (collectives past it would desynchronize the extend schedule)
    if jax.process_count() > 1:
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        all_cur = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([my_cursor]), tiled=True))
        b0 = int(all_cur.min())
    else:
        b0 = my_cursor
    if b0 > 0:
        index, _health = rehydrate(comms, ckpt_of(b0))
        obs.event("job", action="mnmg_stream_resume", index_kind=kind, batch=b0)

    # b0 is the WORLD's agreed step, which can exceed this controller's
    # own batch count (shorter file partition): clamp the local cursor
    loader = FileBatchLoader(path, batch_rows, depth=depth, copy=False,
                             start_batch=min(b0, my_nb))
    my_batches = my_nb
    # total collective steps: agreed once, as in extend_from_file_local
    if jax.process_count() > 1:
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        all_b = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([my_batches]), tiled=True))
        total_batches = int(all_b.max())
    else:
        total_batches = my_batches
    empty = np.zeros((0,) + tuple(loader.shape[1:]), loader.dtype)
    prev_done = b0 if b0 > 0 else None
    it = iter(loader)
    for b in range(b0, total_batches):
        faults.fault_point(STREAM_CRASH_SITE)
        try:
            batch, valid = next(it)
            rows = batch[:valid]
        except StopIteration:
            rows = empty
        index = extend_local_fn(index, rows)
        done = b + 1
        if done == total_batches or (checkpoint_every > 0
                                     and done % checkpoint_every == 0):
            _mnmg_save(kind, ckpt_of(done), index)
            JobDir.write_json(cursor_path, {"config": config, "batch": done})
            # keep current + previous (see the naming comment above);
            # sweep anything older, parts files included
            keep = {os.path.basename(ckpt_of(done))}
            if prev_done is not None:
                keep.add(os.path.basename(ckpt_of(prev_done)))
            stem, cdir = os.path.basename(base), os.path.dirname(base)
            for name in os.listdir(cdir or "."):
                if (name.startswith(stem + ".")
                        and name.split(".part")[0] not in keep
                        and name not in keep):
                    try:
                        os.remove(os.path.join(cdir, name))
                    except OSError:
                        pass  # sweep misses only cost disk
            prev_done = done
            obs.event("job", action="mnmg_stream_checkpoint", index_kind=kind,
                      batch=done)
            faults.crash_point(STREAM_CRASH_SITE)
            preempt()
        heartbeat()
    return index, {"batches": int(total_batches - b0),
                   "resumed_from_batch": int(b0),
                   "ckpt": ckpt_of(total_batches) if total_batches else None}
