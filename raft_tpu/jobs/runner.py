"""The durable, resumable job runner: a `Job` is a named DAG of stages.

Each stage runs once, commits its artifacts + a manifest line into the
job's `JobDir`, and is SKIPPED by every future run whose fingerprint
(stage name + declared inputs + upstream fingerprints) still matches —
so rerunning a killed job resumes at the first incomplete stage instead
of row zero. Intra-stage resume (streaming builds checkpointing at
batch boundaries) lives in `jobs.streaming`; the runner provides the
scratch dir and clears it whenever a stage starts over with a CHANGED
fingerprint (a stale cursor must never resume into new inputs).

Supervision: every stage runs under a `Watchdog` (heartbeat +
wall-clock deadline); a stall-kill surfaces as a typed `StageTimeout`
and is retried through the seeded `resilience.retry_with_backoff`
(`FaultInjected` transients retry the same way). Preemption is a
first-class outcome, not a failure: SIGTERM (or an injected
``job.preempt`` fault) sets a flag the runner checks between stages and
streaming loops check between batches; the in-flight checkpoint state
is already durable, so the job raises `JobPreempted` — a graceful
suspend — and the next run resumes.

Observability: every stage transition lands a kind="job" event
(start/skip/resume/commit/failed/preempt) and runs inside an obs span
``job.<job>.<stage>``, so `python -m raft_tpu.obs.report` renders a job
timeline for any instrumented run.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.logger import logger
from raft_tpu.comms.resilience import retry_with_backoff
from raft_tpu.jobs.jobdir import JobDir, fingerprint_of
from raft_tpu.jobs.watchdog import Heartbeat, StageTimeout, Watchdog

PREEMPT_SITE = "job.preempt"


class JobPreempted(RuntimeError):
    """The job suspended gracefully (SIGTERM or injected preempt): every
    completed stage is committed, the interrupted stage's intra-stage
    checkpoints are durable, and re-running the same job resumes. Not a
    failure — callers typically exit with a distinct code and let the
    scheduler restart them."""


class StageFailed(RuntimeError):
    """A stage exhausted its retry budget (or raised a non-retryable
    error). Chains the underlying cause as `__cause__`."""


@dataclasses.dataclass
class StageSpec:
    """One node of the DAG. `fn(ctx)` does the work; `inputs` is the
    JSON-able parameter dict that joins the fingerprint (geometry,
    seeds, source paths — anything whose change must re-run the
    stage)."""

    name: str
    fn: Callable[["StageContext"], Optional[dict]]
    deps: Tuple[str, ...] = ()
    inputs: Optional[dict] = None
    retries: int = 0
    stall_timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None


class StageContext:
    """What a stage fn sees: its JobDir paths, liveness + preempt hooks,
    and upstream results."""

    def __init__(self, job: "Job", spec: StageSpec, fingerprint: str,
                 heartbeat: Heartbeat):
        self.job = job
        self.jobdir = job.jobdir
        self.stage = spec.name
        self.fingerprint = fingerprint
        self._heartbeat = heartbeat

    def heartbeat(self) -> None:
        """Beat the watchdog; call at least once per `stall_timeout_s`
        of work (streaming helpers take this as their `heartbeat=`)."""
        self._heartbeat.beat()

    def preempt_point(self) -> None:
        """Honor a pending preemption at a safe point (durable state
        just committed). Streaming helpers call this at batch
        boundaries; raises `JobPreempted` when one is pending."""
        self.job.check_preempt()

    def scratch(self) -> str:
        return self.jobdir.scratch(self.stage)

    def artifact_path(self, name: str = "artifact") -> str:
        return self.jobdir.artifact_path(self.stage, name)

    def dep_meta(self, stage: str) -> dict:
        """The committed `meta` dict of a dependency stage."""
        return dict(self.job.results.get(stage) or {})

    def dep_artifact(self, stage: str, name: str = "artifact") -> str:
        """Absolute path of a dependency's committed artifact."""
        return self.jobdir.artifact_path(stage, name)


def _git_sha(repo_dir: Optional[str] = None) -> str:
    from raft_tpu.obs.ledger import git_sha

    return git_sha(repo_dir)


class Job:
    """A named DAG of stages over one `JobDir`; see module docstring.

    Build with `add_stage` (or the `stage` decorator), then `run()`.
    `results` maps stage name -> committed meta dict after a run,
    whether the stage ran or was skipped."""

    def __init__(self, name: str, jobdir, repo_dir: Optional[str] = None):
        self.name = str(name)
        self.jobdir = jobdir if isinstance(jobdir, JobDir) else JobDir(jobdir)
        self.repo_dir = repo_dir
        self._stages: Dict[str, StageSpec] = {}
        self._order: List[str] = []
        self.results: Dict[str, dict] = {}
        self.statuses: Dict[str, str] = {}
        self._preempt = threading.Event()

    # -- building ------------------------------------------------------
    def add_stage(self, name: str,
                  fn: Callable[[StageContext], Optional[dict]],
                  deps: Sequence[str] = (), inputs: Optional[dict] = None,
                  retries: int = 0, stall_timeout_s: Optional[float] = None,
                  deadline_s: Optional[float] = None) -> StageSpec:
        if name in self._stages:
            raise ValueError(f"duplicate stage {name!r}")
        for d in deps:
            if d not in self._stages:
                raise ValueError(
                    f"stage {name!r} depends on unknown stage {d!r} — "
                    f"declare stages in dependency order")
        spec = StageSpec(name, fn, tuple(deps), inputs, int(retries),
                         stall_timeout_s, deadline_s)
        self._stages[name] = spec
        self._order.append(name)
        return spec

    def stage(self, name: str, **kwargs):
        """Decorator form of `add_stage`."""

        def deco(fn):
            self.add_stage(name, fn, **kwargs)
            return fn

        return deco

    # -- fingerprints --------------------------------------------------
    def fingerprint(self, name: str) -> str:
        spec = self._stages[name]
        return fingerprint_of({
            "stage": spec.name,
            "inputs": spec.inputs or {},
            "deps": {d: self.fingerprint(d) for d in spec.deps},
        })

    def _provenance(self) -> dict:
        import time as _time

        plan = faults.active_plan()
        return {
            "job": self.name,
            "git_sha": _git_sha(self.repo_dir),
            "fault_plan": (fingerprint_of(repr(plan.trace_key()))
                           if plan is not None and plan.faults else None),
            "utc": _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime()),
        }

    # -- preemption ----------------------------------------------------
    def request_preempt(self) -> None:
        """Ask the job to suspend at the next safe point (the SIGTERM
        handler's body; safe from any thread/signal context)."""
        self._preempt.set()

    def check_preempt(self) -> None:
        """Raise `JobPreempted` when a preemption is pending — called
        between stages and (via `StageContext.preempt_point`) at
        streaming batch boundaries. Also the injected-chaos hook: a
        flaky fault at ``job.preempt`` simulates the SIGTERM."""
        if not self._preempt.is_set():
            try:
                faults.fault_point(PREEMPT_SITE)
            except faults.FaultInjected:
                self._preempt.set()
        self._raise_pending()

    def _raise_pending(self) -> None:
        if self._preempt.is_set():
            obs.event("job", job=self.name, action="preempt")
            raise JobPreempted(
                f"job {self.name!r} preempted — durable state committed, "
                f"re-run to resume")

    # -- running -------------------------------------------------------
    def run(self, resume: bool = True,
            continue_on_error: bool = False) -> Dict[str, str]:
        """Run the DAG in declaration (= dependency) order. Returns
        {stage: status}, statuses in {"skipped", "ran", "failed",
        "blocked", "preempted"}. A failed stage raises `StageFailed`
        immediately unless `continue_on_error` (the independent-suites
        queue mode): then the failure is recorded, its dependents go
        "blocked", the sweep continues, and callers inspect the
        returned statuses. `JobPreempted` always propagates — a suspend
        must reach the caller's exit path."""
        self.statuses = {}
        old_handler = None
        handler_installed = False
        if threading.current_thread() is threading.main_thread():
            try:
                old_handler = signal.signal(
                    signal.SIGTERM,
                    lambda signum, frame: self.request_preempt())
                handler_installed = True
            except ValueError:
                pass  # non-main interpreter contexts
        try:
            for name in self._order:
                self.check_preempt()
                spec = self._stages[name]
                if any(self.statuses.get(d) in ("failed", "blocked")
                       for d in spec.deps):
                    self.statuses[name] = "blocked"
                    obs.event("job", job=self.name, stage=name,
                              action="blocked")
                    continue
                fp = self.fingerprint(name)
                entry = (self.jobdir.is_complete(name, fp)
                         if resume else None)
                if entry is not None:
                    self.results[name] = entry.get("meta") or {}
                    self.statuses[name] = "skipped"
                    obs.event("job", job=self.name, stage=name,
                              action="skip", fingerprint=fp)
                    logger.info("job %s: stage %s complete — skipping",
                                self.name, name)
                    continue
                try:
                    self._run_stage(spec, fp)
                    self.statuses[name] = "ran"
                except JobPreempted:
                    self.statuses[name] = "preempted"
                    raise
                except Exception as e:
                    self.statuses[name] = "failed"
                    obs.event("job", job=self.name, stage=name,
                              action="failed", error=repr(e)[:200])
                    if not continue_on_error:
                        raise StageFailed(
                            f"job {self.name!r} stage {name!r} failed: {e}"
                        ) from e
                    logger.warning("job %s: stage %s failed (%s); "
                                   "continuing", self.name, name, e)
            # a preempt requested DURING the final stage (SIGTERM, or a
            # bench's --stop-after on the last stage) has no next-stage
            # check to land on — honor it here so the caller still exits
            # through its suspend path. No fault_point: an injected
            # preempt after all stages committed would prove nothing.
            self._raise_pending()
            return dict(self.statuses)
        finally:
            if handler_installed:
                signal.signal(signal.SIGTERM, old_handler)  # raftlint: disable=thread-root-unknown  -- restores the handler captured at install; not a new thread entry point

    def _run_stage(self, spec: StageSpec, fp: str) -> None:
        jd = self.jobdir
        prior = jd.committed(spec.name)
        if prior is not None and prior.get("fingerprint") != fp:
            # starting OVER, not resuming: a stale intra-stage cursor
            # from different inputs must never carry into this attempt —
            # and neither may a stale artifact, which auto-discovery
            # would re-commit under the new fingerprint
            jd.clear_scratch(spec.name)
            jd.clear_artifacts(spec.name)
            obs.event("job", job=self.name, stage=spec.name,
                      action="invalidate", was=prior.get("fingerprint"),
                      now=fp)
        resumable = os.path.isdir(
            os.path.join(jd.root, "scratch", spec.name)) and bool(
            os.listdir(jd.scratch(spec.name)))
        obs.event("job", job=self.name, stage=spec.name,
                  action=("resume" if resumable else "start"),
                  fingerprint=fp)
        hb = Heartbeat(jd.heartbeat_path)
        ctx = StageContext(self, spec, fp, hb)
        dog = Watchdog(hb, stall_timeout_s=spec.stall_timeout_s,
                       deadline_s=spec.deadline_s)

        def attempt():
            with obs.span(f"job.{self.name}.{spec.name}"):
                return dog.run(lambda: spec.fn(ctx),
                               describe=f"{self.name}.{spec.name}")

        if spec.retries > 0:
            meta = retry_with_backoff(
                attempt, max_retries=spec.retries,
                retry_on=(StageTimeout, faults.FaultInjected),
                describe=f"job.{self.name}.{spec.name}",
            )
        else:
            meta = attempt()
        meta = meta if isinstance(meta, dict) else {}
        arts = meta.pop("_artifacts", None)
        if arts is None:
            default = jd.artifact_path(spec.name)
            arts = ({"artifact": default} if os.path.exists(default)
                    else {})
        jd.commit(spec.name, fp, artifacts=arts, meta=meta,
                  provenance=self._provenance())
        # intra-stage cursors/checkpoints are superseded by the commit —
        # a committed stage never re-enters them, and at 100M scale the
        # final streaming checkpoint is a full second copy of the index
        jd.clear_scratch(spec.name)
        self.results[spec.name] = meta
        obs.event("job", job=self.name, stage=spec.name, action="commit",
                  fingerprint=fp)
