"""Watchdog supervision for long-running job stages.

TPU fleets make stalls a normal failure mode: a relay transport dies
under a device wait, a child bench hangs in a cold compile, a loader
blocks on cold storage. The watchdog turns "hung forever" into a typed
`StageTimeout` the runner can retry: a stage keeps a `Heartbeat`
beating; a background monitor kills the stage when the heartbeat goes
stale past `stall_timeout_s` or the wall clock passes `deadline_s`.

Two kill models:

- **in-process stages** (`Watchdog.run(fn)`): the stage runs on a
  worker thread. Python cannot kill a thread, so the kill is
  cooperative on two fronts: `interruptible.cancel` breaks any device
  wait the stage is blocked in, and the next `Heartbeat.beat()` raises
  `StageCancelled`. A stage that neither beats nor syncs can outlive
  its supervisor (the abandoned daemon thread is documented behavior —
  same cooperative semantics as `core.interruptible`).
- **child processes** (`run_supervised(cmd)`): a real `SIGKILL`. Output
  lines are echoed through and double as heartbeats, so "produces no
  output for stall_timeout_s" is the hang definition — exactly the
  failure shape of the dead-relay bench children (BENCH_r01–r05).

Chaos: `Heartbeat.beat` visits the registered site
``job.heartbeat.stall`` through `faults.stall_point` — an injected
slow_rank fault STALLS the first `count` beats (no beat is written),
which is how the drills prove a stall is killed, retried, and visible
in `obs.report`.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.interruptible import cancel as _cancel_thread
from raft_tpu.obs import flight as _flight

HEARTBEAT_SITE = "job.heartbeat.stall"


class StageTimeout(RuntimeError):
    """A supervised stage was killed by the watchdog: heartbeat stale
    past `stall_timeout_s`, or wall clock past `deadline_s`. Typed so
    the runner's retry policy can distinguish a stall-kill (retryable)
    from a genuine stage error (not)."""


class StageCancelled(RuntimeError):
    """Raised inside the stage (by `Heartbeat.beat`) after the watchdog
    killed it — unwinds the worker promptly once the stall clears."""


class Heartbeat:
    """Liveness signal a supervised stage must keep beating.

    `beat()` records a monotonic timestamp and touches the heartbeat
    FILE (when a path is given) so an external supervisor — or a human
    with `stat` — sees the same signal. The file write is best-effort;
    the in-memory timestamp is the watchdog's source of truth."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._last = time.monotonic()
        self._cancelled = threading.Event()
        self._owner: Optional[int] = None

    def adopt(self) -> None:
        """Bind the heartbeat to the CALLING thread — the current
        attempt's worker. From then on a beat from any OTHER thread
        raises `StageCancelled`: a killed-but-unjoinable previous
        attempt (blocked in plain IO, where the cooperative cancel
        can't reach) must never be revived by the next attempt's
        re-arm, or two attempts would run the stage concurrently
        against the same scratch state."""
        with self._lock:
            self._owner = threading.get_ident()
            self._last = time.monotonic()
        self._cancelled.clear()

    def beat(self) -> None:
        with self._lock:
            owner = self._owner
        if owner is not None and threading.get_ident() != owner:
            raise StageCancelled(
                "beat from a superseded attempt's thread — a newer "
                "attempt owns this stage")
        if faults.stall_point(HEARTBEAT_SITE, cancelled=self.cancelled):
            # the injected stall consumed the beat: it never lands, and
            # if the watchdog killed us meanwhile, unwind right here
            if self.cancelled():
                raise StageCancelled("stage killed by watchdog mid-stall")
            return
        if self.cancelled():
            raise StageCancelled("stage killed by watchdog")
        with self._lock:
            self._last = time.monotonic()
        if self.path is not None:
            try:
                with open(self.path, "a"):
                    os.utime(self.path)
            except OSError:
                pass  # a full/readonly disk must not kill a live stage

    def beat_raw(self) -> None:
        """Beat without chaos hooks, cancellation, or file IO — for
        supervisor-internal liveness pumps (child-output readers)."""
        with self._lock:
            self._last = time.monotonic()

    def age_s(self) -> float:
        with self._lock:
            return time.monotonic() - self._last

    def rearm(self) -> None:
        """Re-stamp liveness WITHOUT clearing cancellation — the
        supervisor calls this before starting a new attempt's worker so
        the monitor doesn't insta-kill on a stale age; only the new
        worker's own `adopt()` clears the cancel flag (a zombie stays
        cancelled throughout)."""
        with self._lock:
            self._last = time.monotonic()

    def reset(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._owner = None
        self._cancelled.clear()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def _kill(self) -> None:
        self._cancelled.set()


class Watchdog:
    """Supervise an in-process stage callable (see module docstring).

    `stall_timeout_s` bounds heartbeat age, `deadline_s` the whole
    attempt; either alone is fine, neither means `run` degrades to a
    plain call. On kill: a kind="fault" event (action="watchdog_kill")
    lands on the obs bus — stall-kills belong in the same fault/health
    timeline `obs.report` renders for chaos drills."""

    def __init__(self, heartbeat: Optional[Heartbeat] = None,
                 stall_timeout_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 poll_s: float = 0.02):
        self.heartbeat = heartbeat if heartbeat is not None else Heartbeat()
        self.stall_timeout_s = stall_timeout_s
        self.deadline_s = deadline_s
        self.poll_s = float(poll_s)

    def _verdict(self, t0: float) -> Optional[str]:
        if (self.stall_timeout_s is not None
                and self.heartbeat.age_s() > self.stall_timeout_s):
            return (f"heartbeat stale {self.heartbeat.age_s():.2f}s "
                    f"(> stall_timeout_s={self.stall_timeout_s})")
        if (self.deadline_s is not None
                and time.monotonic() - t0 > self.deadline_s):
            return f"wall clock past deadline_s={self.deadline_s}"
        return None

    def run(self, fn: Callable[[], object], describe: str = "stage"):
        """Run `fn()` under supervision; returns its result, re-raises
        its exception, or raises `StageTimeout` after a kill."""
        if self.stall_timeout_s is None and self.deadline_s is None:
            return fn()
        self.heartbeat.rearm()
        result: list = []
        error: list = []
        tid: list = []

        def worker():
            tid.append(threading.get_ident())
            # take ownership FIRST: beats from a previous attempt's
            # zombie thread raise from here on (see Heartbeat.adopt)
            self.heartbeat.adopt()
            try:
                result.append(fn())
            except BaseException as e:  # noqa: BLE001 — relayed below
                error.append(e)

        th = threading.Thread(target=worker, daemon=True,
                              name=f"jobs-stage-{describe}")
        t0 = time.monotonic()
        th.start()
        while True:
            th.join(self.poll_s)
            if not th.is_alive():
                break
            why = self._verdict(t0)
            if why is None:
                continue
            self.heartbeat._kill()
            if tid:
                _cancel_thread(tid[0])  # break any device wait
            obs.event("fault", action="watchdog_kill", stage=describe,
                      reason=why,
                      elapsed_s=round(time.monotonic() - t0, 3))
            # flight-record the kill's preceding timeline BEFORE the
            # stage is abandoned — the dump is the stall's post-mortem
            _flight.maybe_dump("watchdog_kill", stage=describe, why=why)
            th.join(max(1.0, 10 * self.poll_s))
            raise StageTimeout(f"watchdog killed {describe!r}: {why}")
        if error:
            if isinstance(error[0], StageCancelled):
                # the worker noticed the kill after we already raised on
                # a previous attempt's supervisor — surface as timeout
                raise StageTimeout(
                    f"{describe!r} unwound after watchdog kill"
                ) from error[0]
            raise error[0]
        return result[0] if result else None


def run_supervised(
    cmd: List[str],
    describe: Optional[str] = None,
    stall_timeout_s: Optional[float] = None,
    deadline_s: Optional[float] = None,
    echo: bool = True,
    env: Optional[dict] = None,
    cwd: Optional[str] = None,
) -> int:
    """Run a child process under watchdog supervision; returns its exit
    code, or raises `StageTimeout` after killing a hung child.

    Each line the child writes (stdout+stderr merged) is echoed through
    to our stdout AND beats the heartbeat — a bench that streams JSON
    rows stays alive indefinitely; one that goes silent for
    `stall_timeout_s` is declared hung and SIGKILLed. This is the
    supervision `bench/run_all.py` wraps every suite in, so one hung
    bench no longer takes the whole session down."""
    if describe is None:
        # name the child by its script, not cmd[-1]: with CLI args the
        # last element is a flag, and a kill would surface as
        # StageTimeout("... child '--apply' ...")
        describe = next(
            (os.path.basename(c) for c in cmd
             if c.endswith((".py", ".sh")) and not c.startswith("-")),
            os.path.basename(cmd[0]) if cmd else "child")
    hb = Heartbeat()
    # the child leads its own process group so a kill reaches its WHOLE
    # tree: a hung bench whose grandchild holds the single-client chip
    # lease must not leave that grandchild alive to wedge every later
    # suite in the sweep
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=env, cwd=cwd, start_new_session=True)

    def pump():
        assert proc.stdout is not None
        for raw in proc.stdout:
            hb.beat_raw()
            if echo:
                sys.stdout.buffer.write(raw)
                sys.stdout.buffer.flush()
        proc.stdout.close()

    reader = threading.Thread(target=pump, daemon=True,
                              name=f"jobs-pump-{describe}")
    t0 = time.monotonic()
    reader.start()
    dog = Watchdog(hb, stall_timeout_s=stall_timeout_s,
                   deadline_s=deadline_s)
    try:
        while True:
            try:
                rc = proc.wait(timeout=dog.poll_s)
                reader.join(5.0)
                return rc
            except subprocess.TimeoutExpired:
                pass
            why = dog._verdict(t0)
            if why is None:
                continue
            # event first, then the flight dump (so the dump's ring
            # CONTAINS the watchdog_kill event), then the SIGKILL — a
            # crash-time recorder that dumps after the kill records a
            # timeline missing its own cause
            obs.event("fault", action="watchdog_kill", stage=describe,
                      reason=why, elapsed_s=round(time.monotonic() - t0, 3))
            _flight.maybe_dump("watchdog_kill", stage=describe, why=why)
            _kill_tree(proc)
            reader.join(5.0)
            raise StageTimeout(f"watchdog killed child {describe!r}: {why}")
    except BaseException:
        # KeyboardInterrupt / preemption in the supervisor must not
        # orphan the (session-detached) child tree
        if proc.poll() is None:
            _kill_tree(proc)
        raise


def _kill_tree(proc) -> None:
    """SIGKILL the supervised child's process group (it leads its own
    session); fall back to the direct child if the group is gone."""
    import signal as _signal

    try:
        os.killpg(proc.pid, _signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        proc.kill()
    proc.wait()
