"""Durable on-disk state of one job: the JobDir layout + stage manifest.

A `JobDir` is the unit of resumability. Everything a killed run needs to
continue lives under one directory:

    <root>/
      MANIFEST.jsonl        one line per committed stage (append-only)
      artifacts/            committed stage outputs (CRC-verified on skip)
      scratch/<stage>/      intra-stage checkpoints (stream cursor, ...)
      heartbeat             watchdog heartbeat file

Manifest lines are the commit protocol: a stage is COMPLETE iff its
latest manifest line carries the stage's current fingerprint AND every
artifact it names still matches its recorded whole-file CRC-32C. Lines
carry provenance — git SHA and the active fault-plan fingerprint — so a
resumed chaos drill is auditable, but provenance does NOT join the
fingerprint: re-running the same job at a new commit (or without the
drill's plan installed) must SKIP completed stages, not redo them.
The fingerprint is (stage name, declared inputs, dependency
fingerprints), so changing an input or any upstream stage re-runs the
stage and everything downstream.

Durability discipline matches the rest of the library: artifacts are
written via `core.serialize.atomic_write` (temp-then-rename, so SIGKILL
never leaves a torn artifact under a committed name), and the manifest
append terminates a torn final line first (the `obs.ledger` pattern) so
a crash mid-append can't swallow the next commit. Reads skip
unparseable lines.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from raft_tpu.core.serialize import atomic_write, crc32c

MANIFEST_NAME = "MANIFEST.jsonl"

#: manifest schema version (bump on incompatible line-shape changes)
MANIFEST_VERSION = 1


def fingerprint_of(payload: Any) -> str:
    """Deterministic fingerprint of a JSON-able payload: CRC-32C of its
    canonical (sorted-keys, compact) JSON encoding, as 8 hex chars.
    Collisions only cost a spurious re-run, never a wrong skip-decision
    on unrelated STAGES (the stage name is always part of the payload)."""
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()
    return f"{crc32c(blob):08x}"


def file_crc32c(path: str, chunk_bytes: int = 1 << 22) -> int:
    """Whole-file CRC-32C, streamed (artifacts can be multi-GB)."""
    crc = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(chunk_bytes)
            if not chunk:
                return crc
            crc = crc32c(chunk, crc)


class JobDir:
    """One job's durable directory (layout in the module docstring)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(os.fspath(root))
        os.makedirs(self.artifacts_dir, exist_ok=True)
        os.makedirs(os.path.join(self.root, "scratch"), exist_ok=True)

    # -- layout --------------------------------------------------------
    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    @property
    def artifacts_dir(self) -> str:
        return os.path.join(self.root, "artifacts")

    @property
    def heartbeat_path(self) -> str:
        return os.path.join(self.root, "heartbeat")

    def artifact_path(self, stage: str, name: str = "artifact") -> str:
        """Canonical path for a stage's committed artifact. Stage fns
        write here (through `serialize` / `atomic_write`) and name it in
        their commit; the path is stable so a resumed downstream stage
        finds it without re-running the producer."""
        return os.path.join(self.artifacts_dir, f"{stage}.{name}")

    def scratch(self, stage: str) -> str:
        """Per-stage scratch dir for INTRA-stage checkpoints (stream
        cursors, partial tables). Never committed; cleared by the runner
        when a stage starts over with a changed fingerprint."""
        d = os.path.join(self.root, "scratch", stage)
        os.makedirs(d, exist_ok=True)
        return d

    def clear_scratch(self, stage: str) -> None:
        import shutil

        shutil.rmtree(os.path.join(self.root, "scratch", stage),
                      ignore_errors=True)

    def clear_artifacts(self, stage: str) -> None:
        """Delete a stage's committed artifact files. Invalidation must
        call this alongside `clear_scratch`: the runner's default-artifact
        auto-discovery (`os.path.exists(artifact_path)`) would otherwise
        re-commit a previous fingerprint's leftover file — with a freshly
        computed CRC, so it verifies forever — as the new run's output."""
        d = self.artifacts_dir
        if not os.path.isdir(d):
            return
        prefix = f"{stage}."
        for name in os.listdir(d):
            if name.startswith(prefix):
                try:
                    os.remove(os.path.join(d, name))
                except OSError:
                    pass  # a locked file resurfaces as a CRC/size mismatch

    # -- manifest ------------------------------------------------------
    def read_manifest(self) -> List[dict]:
        """All parseable manifest lines, in append order. Unparseable
        (torn) lines are skipped — a killed append never poisons the
        job."""
        if not os.path.exists(self.manifest_path):
            return []
        out: List[dict] = []
        with open(self.manifest_path, "r", encoding="utf-8",
                  errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(entry, dict):
                    out.append(entry)
        return out

    def committed(self, stage: str) -> Optional[dict]:
        """The LATEST manifest entry for `stage` (later lines win: a
        re-run after an input change appends a fresh commit)."""
        entry = None
        for e in self.read_manifest():
            if e.get("stage") == stage:
                entry = e
        return entry

    def commit(
        self,
        stage: str,
        fingerprint: str,
        artifacts: Optional[Dict[str, str]] = None,
        meta: Optional[dict] = None,
        provenance: Optional[dict] = None,
    ) -> dict:
        """Append one commit line for `stage`. `artifacts` maps artifact
        names to paths (absolute or JobDir-relative); each is recorded
        with its whole-file CRC-32C + size, verified again before any
        future run skips the stage. `meta` is the stage's JSON-able
        result (handed to dependents on skip)."""
        arts = {}
        for name, path in (artifacts or {}).items():
            full = path if os.path.isabs(path) else os.path.join(self.root,
                                                                 path)
            crc = file_crc32c(full)
            st = os.stat(full)
            arts[name] = {
                "path": os.path.relpath(full, self.root),
                "crc32c": crc,
                "nbytes": st.st_size,
                "mtime_ns": st.st_mtime_ns,
            }
        entry = {
            "v": MANIFEST_VERSION,
            "stage": stage,
            "fingerprint": fingerprint,
            "artifacts": arts,
            "meta": meta or {},
        }
        entry.update(provenance or {})
        self._append(entry)
        return entry

    def _append(self, entry: dict) -> None:
        """Torn-line-terminating append (the `obs.ledger` discipline): a
        previous process SIGKILLed mid-append leaves an unterminated
        line; terminating it first keeps this entry parseable."""
        line = json.dumps(entry, sort_keys=True)
        with open(self.manifest_path, "a+b") as fh:
            fh.seek(0, os.SEEK_END)
            if fh.tell() > 0:
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) != b"\n":
                    fh.write(b"\n")
            fh.write(line.encode() + b"\n")

    # -- completion / verification -------------------------------------
    def artifact_ok(self, entry: dict) -> bool:
        """True when every artifact a manifest entry names still exists
        with its recorded CRC — the gate between 'skip' and 're-run'.
        A deleted or rotted artifact fails closed (re-run the stage).

        Fast path: a file whose (size, mtime_ns) still equal the values
        recorded at commit time is accepted without re-reading it — the
        make/bazel up-to-date contract, without which every resume of a
        100M-scale job would re-CRC hundreds of GB just to decide
        'skip'. Any metadata change falls back to the full streamed CRC
        (which remains the ground truth: a CRC match with a changed
        mtime still passes)."""
        for art in (entry.get("artifacts") or {}).values():
            full = os.path.join(self.root, art["path"])
            try:
                st = os.stat(full)
            except OSError:
                return False
            if st.st_size != int(art["nbytes"]):
                return False
            rec_mtime = art.get("mtime_ns")
            if rec_mtime is not None and st.st_mtime_ns == int(rec_mtime):
                continue
            if file_crc32c(full) != int(art["crc32c"]):
                return False
        return True

    def is_complete(self, stage: str, fingerprint: str) -> Optional[dict]:
        """The committed entry when `stage` is complete at this
        fingerprint (artifacts verified), else None."""
        entry = self.committed(stage)
        if entry is None or entry.get("fingerprint") != fingerprint:
            return None
        if not self.artifact_ok(entry):
            return None
        return entry

    def resolve(self, entry_path: str) -> str:
        """JobDir-relative artifact path -> absolute."""
        return os.path.join(self.root, entry_path)

    # -- small durable sidecars ----------------------------------------
    @staticmethod
    def write_json(path: str, payload: dict) -> None:
        """Atomic JSON sidecar write (cursors, progress markers) — the
        ONE writer for every sidecar in the subsystem, so durability
        policy can't drift between the manifest and the cursors."""
        with atomic_write(path) as tmp:
            with open(tmp, "w") as fh:
                json.dump(payload, fh, sort_keys=True)

    @staticmethod
    def read_json(path: str) -> Optional[dict]:
        """Read a sidecar; None when missing or torn (fail open to a
        fresh start, never to a wrong resume)."""
        try:
            with open(path) as fh:
                out = json.load(fh)
            return out if isinstance(out, dict) else None
        except (OSError, json.JSONDecodeError):
            return None
