// raft_tpu native runtime ops.
//
// TPU-native counterpart of the reference's C++ host layer: the growable
// IVF list bookkeeping (cpp/include/raft/neighbors/ivf_list.hpp) and the
// binary serialization codec (core/serialize.hpp:34,
// core/detail/mdspan_numpy_serializer.hpp). Device compute stays in
// XLA/Pallas; these are the host-side O(n) paths that Python loops make
// slow at 100M-vector scale (slot-table packing during index build/extend,
// container codec during save/load).
//
// C ABI, consumed via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// slot-table packing (ivf_flat/_pack_lists equivalent)
// ---------------------------------------------------------------------------

// Returns the padded max list size (multiple of `group`), or -1 on error.
int64_t rt_max_list_size(const int64_t* labels, int64_t n, int64_t n_lists,
                         int64_t group) {
  if (n_lists <= 0 || group <= 0) return -1;
  std::vector<int64_t> sizes(n_lists, 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t l = labels[i];
    if (l < 0 || l >= n_lists) return -1;
    sizes[l]++;
  }
  int64_t mx = 1;
  for (int64_t l = 0; l < n_lists; ++l)
    if (sizes[l] > mx) mx = sizes[l];
  return (mx + group - 1) / group * group;
}

// Fill row_ids (n_lists x max_sz, pre-sized) with stable per-list row order;
// empty slots get -1. sizes_out receives per-list counts. Returns 0 on ok.
int32_t rt_pack_lists(const int64_t* labels, int64_t n, int64_t n_lists,
                      int64_t max_sz, int32_t* row_ids, int32_t* sizes_out) {
  std::vector<int64_t> cursor(n_lists, 0);
  for (int64_t i = 0; i < n_lists * max_sz; ++i) row_ids[i] = -1;
  for (int64_t i = 0; i < n; ++i) {
    int64_t l = labels[i];
    if (l < 0 || l >= n_lists) return -1;
    int64_t c = cursor[l]++;
    if (c >= max_sz) return -2;
    row_ids[l * max_sz + c] = static_cast<int32_t>(i);
  }
  for (int64_t l = 0; l < n_lists; ++l)
    sizes_out[l] = static_cast<int32_t>(cursor[l]);
  return 0;
}

// ---------------------------------------------------------------------------
// container codec (magic | u32 version | u64 header_len | json | payload)
// ---------------------------------------------------------------------------

static const char kMagic[8] = {'R', 'A', 'F', 'T', 'T', 'P', 'U', '\0'};
static const uint32_t kVersion = 1;
static const int64_t kAlign = 64;

static int64_t align_up(int64_t v) { return (v + kAlign - 1) / kAlign * kAlign; }

// Write a container: header json (bytes) + n_fields buffers at the given
// offsets (relative to payload start). Offsets must be kAlign-aligned and
// consistent with the header's field table (Python composes the header).
// Returns 0 on success.
int32_t rt_write_container(const char* path, const uint8_t* header,
                           int64_t header_len, int64_t n_fields,
                           const void** bufs, const int64_t* nbytes,
                           const int64_t* offsets) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint64_t hlen = static_cast<uint64_t>(header_len);
  if (fwrite(kMagic, 1, 8, f) != 8 ||
      fwrite(&kVersion, 4, 1, f) != 1 ||
      fwrite(&hlen, 8, 1, f) != 1 ||
      (header_len && fwrite(header, 1, header_len, f) != (size_t)header_len)) {
    fclose(f);
    return -2;
  }
  int64_t data_start = align_up(8 + 12 + header_len);
  int64_t pos = 8 + 12 + header_len;
  static const char zeros[kAlign] = {0};
  while (pos < data_start) {
    int64_t chunk = data_start - pos < kAlign ? data_start - pos : kAlign;
    if (fwrite(zeros, 1, chunk, f) != (size_t)chunk) { fclose(f); return -2; }
    pos += chunk;
  }
  int64_t payload_pos = 0;
  for (int64_t i = 0; i < n_fields; ++i) {
    while (payload_pos < offsets[i]) {
      int64_t chunk = offsets[i] - payload_pos < kAlign ? offsets[i] - payload_pos : kAlign;
      if (fwrite(zeros, 1, chunk, f) != (size_t)chunk) { fclose(f); return -2; }
      payload_pos += chunk;
    }
    if (nbytes[i] && fwrite(bufs[i], 1, nbytes[i], f) != (size_t)nbytes[i]) {
      fclose(f);
      return -2;
    }
    payload_pos += nbytes[i];
  }
  fclose(f);
  return 0;
}

// Read an entire container file into a malloc'd buffer. Returns the buffer
// (caller frees with rt_free) or nullptr; *out_size receives the size.
// Validation of magic/version happens Python-side over the returned bytes.
uint8_t* rt_read_file(const char* path, int64_t* out_size) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 0) { fclose(f); return nullptr; }
  uint8_t* buf = static_cast<uint8_t*>(malloc(sz ? sz : 1));
  if (!buf) { fclose(f); return nullptr; }
  size_t got = fread(buf, 1, sz, f);
  fclose(f);
  if (got != (size_t)sz) { free(buf); return nullptr; }
  *out_size = sz;
  return buf;
}

void rt_free(void* p) { free(p); }

uint32_t rt_abi_version() { return 1; }

}  // extern "C"
