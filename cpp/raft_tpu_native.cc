// raft_tpu native runtime ops.
//
// TPU-native counterpart of the reference's C++ host layer: the growable
// IVF list bookkeeping (cpp/include/raft/neighbors/ivf_list.hpp) and the
// binary serialization codec (core/serialize.hpp:34,
// core/detail/mdspan_numpy_serializer.hpp). Device compute stays in
// XLA/Pallas; these are the host-side O(n) paths that Python loops make
// slow at 100M-vector scale (slot-table packing during index build/extend,
// container codec during save/load).
//
// C ABI, consumed via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <vector>
#include <algorithm>
#include <condition_variable>
#include <mutex>
#include <thread>

#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// slot-table packing (ivf_flat/_pack_lists equivalent)
// ---------------------------------------------------------------------------

// Returns the padded max list size (multiple of `group`), or -1 on error.
int64_t rt_max_list_size(const int64_t* labels, int64_t n, int64_t n_lists,
                         int64_t group) {
  if (n_lists <= 0 || group <= 0) return -1;
  std::vector<int64_t> sizes(n_lists, 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t l = labels[i];
    if (l < 0 || l >= n_lists) return -1;
    sizes[l]++;
  }
  int64_t mx = 1;
  for (int64_t l = 0; l < n_lists; ++l)
    if (sizes[l] > mx) mx = sizes[l];
  return (mx + group - 1) / group * group;
}

// Fill row_ids (n_lists x max_sz, pre-sized) with stable per-list row order;
// empty slots get -1. sizes_out receives per-list counts. Returns 0 on ok.
int32_t rt_pack_lists(const int64_t* labels, int64_t n, int64_t n_lists,
                      int64_t max_sz, int32_t* row_ids, int32_t* sizes_out) {
  std::vector<int64_t> cursor(n_lists, 0);
  for (int64_t i = 0; i < n_lists * max_sz; ++i) row_ids[i] = -1;
  for (int64_t i = 0; i < n; ++i) {
    int64_t l = labels[i];
    if (l < 0 || l >= n_lists) return -1;
    int64_t c = cursor[l]++;
    if (c >= max_sz) return -2;
    row_ids[l * max_sz + c] = static_cast<int32_t>(i);
  }
  for (int64_t l = 0; l < n_lists; ++l)
    sizes_out[l] = static_cast<int32_t>(cursor[l]);
  return 0;
}

// ---------------------------------------------------------------------------
// container codec (magic | u32 version | u64 header_len | json | payload)
// ---------------------------------------------------------------------------

static const char kMagic[8] = {'R', 'A', 'F', 'T', 'T', 'P', 'U', '\0'};
static const uint32_t kVersion = 1;
static const int64_t kAlign = 64;

static int64_t align_up(int64_t v) { return (v + kAlign - 1) / kAlign * kAlign; }

// Write a container: header json (bytes) + n_fields buffers at the given
// offsets (relative to payload start). Offsets must be kAlign-aligned and
// consistent with the header's field table (Python composes the header).
// Returns 0 on success.
int32_t rt_write_container(const char* path, const uint8_t* header,
                           int64_t header_len, int64_t n_fields,
                           const void** bufs, const int64_t* nbytes,
                           const int64_t* offsets) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint64_t hlen = static_cast<uint64_t>(header_len);
  if (fwrite(kMagic, 1, 8, f) != 8 ||
      fwrite(&kVersion, 4, 1, f) != 1 ||
      fwrite(&hlen, 8, 1, f) != 1 ||
      (header_len && fwrite(header, 1, header_len, f) != (size_t)header_len)) {
    fclose(f);
    return -2;
  }
  int64_t data_start = align_up(8 + 12 + header_len);
  int64_t pos = 8 + 12 + header_len;
  static const char zeros[kAlign] = {0};
  while (pos < data_start) {
    int64_t chunk = data_start - pos < kAlign ? data_start - pos : kAlign;
    if (fwrite(zeros, 1, chunk, f) != (size_t)chunk) { fclose(f); return -2; }
    pos += chunk;
  }
  int64_t payload_pos = 0;
  for (int64_t i = 0; i < n_fields; ++i) {
    while (payload_pos < offsets[i]) {
      int64_t chunk = offsets[i] - payload_pos < kAlign ? offsets[i] - payload_pos : kAlign;
      if (fwrite(zeros, 1, chunk, f) != (size_t)chunk) { fclose(f); return -2; }
      payload_pos += chunk;
    }
    if (nbytes[i] && fwrite(bufs[i], 1, nbytes[i], f) != (size_t)nbytes[i]) {
      fclose(f);
      return -2;
    }
    payload_pos += nbytes[i];
  }
  fclose(f);
  return 0;
}

// Read an entire container file into a malloc'd buffer. Returns the buffer
// (caller frees with rt_free) or nullptr; *out_size receives the size.
// Validation of magic/version happens Python-side over the returned bytes.
uint8_t* rt_read_file(const char* path, int64_t* out_size) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz < 0) { fclose(f); return nullptr; }
  uint8_t* buf = static_cast<uint8_t*>(malloc(sz ? sz : 1));
  if (!buf) { fclose(f); return nullptr; }
  size_t got = fread(buf, 1, sz, f);
  fclose(f);
  if (got != (size_t)sz) { free(buf); return nullptr; }
  *out_size = sz;
  return buf;
}

void rt_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// sparse format conversion (sparse/convert/csr.cuh host-side role): sorted
// COO rows -> CSR indptr, and the counting-sort permutation for unsorted COO.
// ---------------------------------------------------------------------------

// rows: (nnz,) COO row ids in [0, n_rows). indptr_out: (n_rows+1,) int64.
// Rows need NOT be sorted (counting pass). Returns 0 on ok.
int32_t rt_coo_rows_to_indptr(const int64_t* rows, int64_t nnz, int64_t n_rows,
                              int64_t* indptr_out) {
  if (n_rows < 0) return -1;
  for (int64_t i = 0; i <= n_rows; ++i) indptr_out[i] = 0;
  for (int64_t i = 0; i < nnz; ++i) {
    int64_t r = rows[i];
    if (r < 0 || r >= n_rows) return -1;
    indptr_out[r + 1]++;
  }
  for (int64_t r = 0; r < n_rows; ++r) indptr_out[r + 1] += indptr_out[r];
  return 0;
}

// Stable counting-sort permutation ordering COO entries by row:
// perm_out[k] = original position of the k-th entry in row-major order.
int32_t rt_coo_sort_perm(const int64_t* rows, int64_t nnz, int64_t n_rows,
                         int64_t* perm_out) {
  std::vector<int64_t> indptr(n_rows + 1, 0);
  if (rt_coo_rows_to_indptr(rows, nnz, n_rows, indptr.data()) != 0) return -1;
  std::vector<int64_t> cursor(indptr.begin(), indptr.end() - 1);
  for (int64_t i = 0; i < nnz; ++i) perm_out[cursor[rows[i]]++] = i;
  return 0;
}

// ---------------------------------------------------------------------------
// label compaction (label/classlabels.cuh host-side role): map arbitrary
// int labels onto the dense range [0, n_unique) preserving first-seen order
// of the SORTED unique values (make_monotonic semantics).
// ---------------------------------------------------------------------------

namespace {
int64_t uf_find(int64_t* parent, int64_t x) {
  int64_t root = x;
  while (parent[root] != root) root = parent[root];
  while (parent[x] != root) {
    int64_t nxt = parent[x];
    parent[x] = root;
    x = nxt;
  }
  return root;
}

// Map values onto [0, n_unique) in sorted-unique order (the shared core of
// rt_make_monotonic and rt_cut_tree; np.unique return_inverse semantics).
int64_t densify_sorted(const int64_t* vals, int64_t n, int64_t* out,
                       int64_t* unique_out, int64_t capacity) {
  std::vector<int64_t> uniq(vals, vals + n);
  std::sort(uniq.begin(), uniq.end());
  uniq.erase(std::unique(uniq.begin(), uniq.end()), uniq.end());
  int64_t nu = static_cast<int64_t>(uniq.size());
  if (unique_out) {
    if (nu > capacity) return -2;
    for (int64_t i = 0; i < nu; ++i) unique_out[i] = uniq[i];
  }
  for (int64_t i = 0; i < n; ++i) {
    const int64_t* it =
        std::lower_bound(uniq.data(), uniq.data() + nu, vals[i]);
    out[i] = it - uniq.data();
  }
  return nu;
}
}  // namespace

// labels: (n,). out: (n,) dense ids. unique_out: (capacity) receives the
// sorted unique values; *n_unique_out their count. Returns 0 on ok, -2 if
// capacity is too small.
int32_t rt_make_monotonic(const int64_t* labels, int64_t n, int64_t* out,
                          int64_t* unique_out, int64_t capacity,
                          int64_t* n_unique_out) {
  int64_t nu = densify_sorted(labels, n, out, unique_out, capacity);
  if (nu < 0) return static_cast<int32_t>(nu);
  *n_unique_out = nu;
  return 0;
}

// ---------------------------------------------------------------------------
// agglomerative dendrogram (cluster/detail/agglomerative.cuh host-side role):
// union-find merge of weight-sorted MST edges into the scipy children
// convention, and the flat cut. O(E alpha(n)) — the Python-loop version
// interprets ~10 ops per edge and crawls at 100k+ rows.
// ---------------------------------------------------------------------------

// Edges MUST already be sorted by weight (caller does the argsort — numpy's
// C sort is fine; the Python cost was the merge loop). children_out is
// (n-1, 2) int64, deltas_out (n-1) double, sizes_out (n-1) int64.
// Returns the number of merges m (m <= n-1), or -1 on bad input.
int64_t rt_mst_linkage(const int32_t* src, const int32_t* dst, const float* w,
                       int64_t n_edges, int64_t n, int64_t* children_out,
                       double* deltas_out, int64_t* sizes_out) {
  if (n <= 0) return -1;
  std::vector<int64_t> parent(2 * n - 1);
  std::vector<int64_t> size(2 * n - 1, 1);
  for (int64_t i = 0; i < 2 * n - 1; ++i) parent[i] = i;
  int64_t nxt = n, m = 0;
  for (int64_t e = 0; e < n_edges && m < n - 1; ++e) {
    int64_t a = src[e], b = dst[e];
    if (a < 0 || a >= n || b < 0 || b >= n) return -1;
    int64_t ra = uf_find(parent.data(), a);
    int64_t rb = uf_find(parent.data(), b);
    if (ra == rb) continue;
    children_out[2 * m] = ra;
    children_out[2 * m + 1] = rb;
    deltas_out[m] = static_cast<double>(w[e]);
    size[nxt] = size[ra] + size[rb];
    sizes_out[m] = size[nxt];
    parent[ra] = parent[rb] = nxt;
    ++nxt;
    ++m;
  }
  return m;
}

// Flat labels from the first (m - (n_clusters - 1)) merges of a children
// table (m rows). labels_out (n,) int32 gets dense ids in [0, k).
// Returns the number of distinct labels, or -1 on bad input.
int64_t rt_cut_tree(const int64_t* children, int64_t m, int64_t n,
                    int64_t n_clusters, int32_t* labels_out) {
  if (n <= 0 || n_clusters < 1 || m < 0 || m > n - 1) return -1;
  std::vector<int64_t> parent(2 * n - 1);
  for (int64_t i = 0; i < 2 * n - 1; ++i) parent[i] = i;
  int64_t keep = m - (n_clusters - 1);
  if (keep < 0) keep = 0;
  for (int64_t e = 0; e < keep; ++e) {
    int64_t a = children[2 * e], b = children[2 * e + 1];
    if (a < 0 || a >= 2 * n - 1 || b < 0 || b >= 2 * n - 1) return -1;
    int64_t nxt = n + e;
    parent[uf_find(parent.data(), a)] = nxt;
    parent[uf_find(parent.data(), b)] = nxt;
  }
  // remap roots to dense ids in sorted-unique order
  // (np.unique(..., return_inverse=True) semantics)
  std::vector<int64_t> roots(n);
  for (int64_t i = 0; i < n; ++i) roots[i] = uf_find(parent.data(), i);
  std::vector<int64_t> dense(n);
  int64_t nu = densify_sorted(roots.data(), n, dense.data(), nullptr, 0);
  for (int64_t i = 0; i < n; ++i)
    labels_out[i] = static_cast<int32_t>(dense[i]);
  return nu;
}

uint32_t rt_abi_version() { return 4; }

}  // extern "C"

// ---------------------------------------------------------------------------
// prefetching batch file loader (batch_load_iterator host-IO role,
// spatial/knn/detail/ann_utils.cuh:388): a reader thread pread()s fixed-row
// batches of a row-major on-disk array into a ring of `depth` buffers ahead
// of the consumer, so disk/page-cache latency overlaps the device work of
// streamed index builds. The consumer acquires batches strictly in order
// and each buffer stays valid until `depth - 1` further acquires.
// ---------------------------------------------------------------------------

namespace {

struct RtLoader {
  int fd = -1;
  int64_t data_off = 0, row_bytes = 0, n_rows = 0, batch_rows = 0;
  int64_t depth = 0, n_batches = 0;
  std::vector<std::vector<uint8_t>> bufs;
  std::vector<int64_t> slot_batch;  // batch FILLED in each slot; -1 = free
  int64_t next_acquire = 0;  // next batch the consumer gets
  int64_t next_release = 0;  // oldest unreleased batch
  bool stop = false;
  int32_t err = 0;
  std::mutex mu;
  std::condition_variable cv;
  std::thread th;
};

void rt_loader_run(RtLoader* L) {
  for (int64_t b = 0; b < L->n_batches; ++b) {
    int64_t slot = b % L->depth;
    {
      std::unique_lock<std::mutex> lk(L->mu);
      // wait until the slot's previous occupant (batch b - depth) is
      // released; reader stays exactly `depth` batches ahead at most
      L->cv.wait(lk, [&] { return L->stop || b - L->next_release < L->depth; });
      if (L->stop) return;
    }
    int64_t lo = b * L->batch_rows;
    int64_t rows = std::min(L->batch_rows, L->n_rows - lo);
    int64_t want = rows * L->row_bytes;
    int64_t off = L->data_off + lo * L->row_bytes;
    uint8_t* dst = L->bufs[slot].data();
    int64_t got = 0;
    while (got < want) {
      ssize_t r = pread(L->fd, dst + got, want - got, off + got);
      if (r <= 0) {
        std::lock_guard<std::mutex> lk(L->mu);
        L->err = -2;  // short read / IO error
        L->cv.notify_all();
        return;
      }
      got += r;
    }
    {
      std::lock_guard<std::mutex> lk(L->mu);
      L->slot_batch[slot] = b;
      L->cv.notify_all();
    }
  }
}

}  // namespace

extern "C" {

// Open a loader over a row-major array stored at `data_off` in `path`.
// Returns an opaque handle (close with rt_loader_close) or nullptr.
void* rt_loader_open(const char* path, int64_t data_off, int64_t row_bytes,
                     int64_t n_rows, int64_t batch_rows, int64_t depth) {
  if (row_bytes <= 0 || n_rows < 0 || batch_rows <= 0 || data_off < 0)
    return nullptr;
  if (depth < 2) depth = 2;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  RtLoader* L = new RtLoader();
  L->fd = fd;
  L->data_off = data_off;
  L->row_bytes = row_bytes;
  L->n_rows = n_rows;
  L->batch_rows = batch_rows;
  L->depth = depth;
  L->n_batches = n_rows ? (n_rows + batch_rows - 1) / batch_rows : 0;
  L->bufs.assign(depth, {});
  for (auto& b : L->bufs) b.resize(static_cast<size_t>(batch_rows * row_bytes));
  L->slot_batch.assign(depth, -1);
  L->th = std::thread(rt_loader_run, L);
  return L;
}

// Blocks until the next batch is resident; *ptr_out receives its buffer.
// Returns the batch's valid row count, 0 past the last batch, or a
// negative error. The buffer stays valid until the consumer releases it
// (rt_loader_release frees oldest-first) AND the reader laps the ring;
// the Python wrapper holds depth-1 slots so views outlive the current
// iteration by depth-2 more. All buffers die at rt_loader_close.
int64_t rt_loader_acquire(void* handle, uint8_t** ptr_out) {
  RtLoader* L = static_cast<RtLoader*>(handle);
  if (!L || !ptr_out) return -1;
  if (L->next_acquire >= L->n_batches) return 0;
  int64_t b = L->next_acquire;
  int64_t slot = b % L->depth;
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv.wait(lk, [&] { return L->err != 0 || L->slot_batch[slot] == b; });
  if (L->err != 0) return L->err;
  L->next_acquire = b + 1;
  *ptr_out = L->bufs[slot].data();
  return std::min(L->batch_rows, L->n_rows - b * L->batch_rows);
}

// Releases the oldest unreleased batch's slot back to the reader.
int32_t rt_loader_release(void* handle) {
  RtLoader* L = static_cast<RtLoader*>(handle);
  if (!L) return -1;
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->next_release >= L->next_acquire) return -1;  // nothing outstanding
  L->slot_batch[L->next_release % L->depth] = -1;
  L->next_release++;
  L->cv.notify_all();
  return 0;
}

void rt_loader_close(void* handle) {
  RtLoader* L = static_cast<RtLoader*>(handle);
  if (!L) return;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
    L->cv.notify_all();
  }
  if (L->th.joinable()) L->th.join();
  if (L->fd >= 0) close(L->fd);
  delete L;
}

}  // extern "C"
