"""Headline benchmark: brute-force k-NN QPS (1M x 128, k=64) on one chip.

Mirrors the reference bench config `cpp/bench/neighbors/knn.cuh` (1M-row
brute-force) / BASELINE.md config 2. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md), so vs_baseline is reported
against the north-star derived floor of 10k QPS for exact 1M x 128 k=64
search on a single chip (value/floor; >1 is better than target).

Data is generated ON DEVICE (jax.random) — no host->device transfer of the
1M-row dataset, which matters when the chip sits behind a network tunnel.
"""

import json
import time

import jax
import jax.numpy as jnp


def main():
    n, dim, k, nq = 1_000_000, 128, 64, 8192

    from raft_tpu.neighbors.brute_force import _bf_knn_impl
    from raft_tpu.distance.distance_types import DistanceType

    key = jax.random.PRNGKey(0)
    kd, kq = jax.random.split(key)
    dataset = jax.random.uniform(kd, (n, dim), jnp.float32)
    queries = jax.random.uniform(kq, (nq, dim), jnp.float32)
    jax.block_until_ready((dataset, queries))

    def run():
        d, i = _bf_knn_impl(dataset, queries, k, DistanceType.L2Expanded)
        jax.block_until_ready((d, i))
        return d, i

    run()  # compile + warmup
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        run()
    dt = (time.perf_counter() - t0) / iters
    qps = nq / dt

    floor = 10_000.0
    print(
        json.dumps(
            {
                "metric": "bf_knn_qps_1Mx128_k64",
                "value": round(qps, 1),
                "unit": "qps",
                "vs_baseline": round(qps / floor, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
