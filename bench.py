"""Headline benchmark: ANN search QPS @ recall@10 >= 0.95 on one chip.

The north-star task (BASELINE.md: "ANN QPS @ recall@10"): 1M x 96, 4096
queries, k=10. The headline is the fastest gate-clearing config with the
algorithm recorded in "algo": an IVF-PQ ladder (refined n_probes ramp,
recon8_list/recon8 engines; lut is excluded — its gather kernel-faulted
the device 2026-08-01) raced against exact tiled brute force, which wins
at this geometry on the MXU (measured 17.4k qps @ recall 1.0 vs 5.3k @
0.9965). The IVF-PQ winner is always reported alongside ("ivf_pq_best",
falling back to the floor-gated best when nothing clears 0.95). Prints
ONE JSON line:

  {"metric": ..., "value": N, "unit": "qps", "vs_baseline": N,
   "recall@10": r, ...}

vs_baseline: the reference publishes no numbers (BASELINE.md), so the value
is reported against a derived floor of 10k QPS @ recall>=0.8 for this
config on a single chip. If the IVF-PQ path fails for any reason, falls
back to the exact brute-force 1M x 128 k=64 bench (config 2) so the driver
always records a number.

Data is generated ON DEVICE (jax.random) — no host->device transfer of the
1M-row dataset, which matters when the chip sits behind a network tunnel.
"""

import json
import os
import time

import jax

# Honor an explicit CPU request (same pin as bench/common.py): the
# image's sitecustomize force-appends the axon platform to jax_platforms
# AFTER env processing, so without this a JAX_PLATFORMS=cpu smoke run
# silently dials the tunneled single-client chip — and contends with
# whatever queue currently holds the claim.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

# Persist compiled programs across bench processes/rounds: the 1M-row
# build+search pipeline costs minutes of XLA compile cold; with the cache
# warm, retries and the driver's end-of-round run skip straight to compute.
# The gate (TPU-intent only, never CPU-first) and the shared default dir
# both live in core.config so bench and the driver entry cannot drift.
try:
    from raft_tpu.core.config import enable_compilation_cache_if_tpu

    enable_compilation_cache_if_tpu()
except Exception:
    pass  # a bench record beats a warm cache

import jax.numpy as jnp
import numpy as np

# "ann": the headline is the fastest gate-clearing ANN config at this
# geometry with the algorithm recorded in "algo" — on the MXU, exact
# tiled brute force beats IVF-PQ at 1M×96 (measured 2026-08-01:
# 17.4k qps @ recall 1.0 vs 5.3k @ 0.9965), mirroring how the
# reference's own bench suite races brute force against the IVF
# methods at a recall target (cpp/bench/neighbors/knn.cuh). The
# IVF-PQ winner is always reported alongside in "ivf_pq_best".
_HEADLINE_METRIC = "ann_qps_1Mx96_k10_recall95"

# Every measured ladder config is appended here as it lands, so a bench
# killed by the driver's outer timeout still leaves its numbers in the
# repo (same rationale as TPU_PROFILE_RESULTS.json).
_PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_PARTIAL.jsonl")

# The last successful non-smoke headline record, written on every
# success — WRITE-ONLY provenance of the most recent real chip headline.
# It is deliberately never re-reported: the old 72-hour recovery path
# recycled it into BENCH_r04/r05 as if it were fresh trajectory, which
# is exactly the blindness ROADMAP item 5a calls out. History now lives
# in the append-only BENCH_LEDGER.jsonl (raft_tpu.obs.ledger), where
# every row keeps its own SHA and a dead round shows up as a 0.0 row.
_LAST_GOOD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_LAST_GOOD.json"
)


def _record_partial(rec: dict) -> None:
    # smoke rehearsals tag their rows: a CPU-scale measurement appended
    # while a real chip session owns the file must never be recoverable
    # as that session's best (this happened 2026-08-01 — a 16.7k qps
    # smoke row landed in a live chip ladder's partial file)
    if os.environ.get("RAFT_TPU_BENCH_SMOKE") == "1":
        rec = dict(rec, smoke=True)
    try:
        with open(_PARTIAL_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def _best_partial():
    """Best previously-measured ladder entry (gate-clearing first, then
    floor-clearing by QPS) from this round's partial file, if any."""
    rows = []
    try:
        with open(_PARTIAL_PATH) as f:
            for l in f:
                # per-line parse: a SIGKILL mid-append leaves one truncated
                # line, which must not discard the valid entries before it
                try:
                    rows.append(json.loads(l))
                except json.JSONDecodeError:
                    continue
    except OSError:
        return None
    rows = [
        r for r in rows
        if isinstance(r, dict) and "qps" in r and "recall" in r
        and not r.get("smoke") and not r.get("suspect")
    ]
    gated = [r for r in rows if r["recall"] >= _RECALL_GATE]
    # the floor pool mirrors the in-process fallback, which never admits
    # a sub-gate brute-force row (exact search below the gate means the
    # engine is broken, not that the config needs tuning) — recovery
    # must not disagree with the normal path on the same measurements
    pool = gated or [
        r for r in rows
        if r["recall"] >= _RECALL_FLOOR
        and not str(r.get("mode", "")).startswith("bf_")
    ]
    return max(pool, key=lambda r: r["qps"]) if pool else None

# BASELINE.md north star: QPS counted only at recall@10 >= 0.95 (the
# reference-grade gate, ann_ivf_pq.cuh:257-265); the secondary floor is
# recorded when nothing clears the primary one (still a perf signal on a
# config that needs tuning, and the record says which gate it cleared).
_RECALL_GATE = 0.95
_RECALL_FLOOR = 0.80

# Derived single-chip floor used for vs_baseline everywhere (the reference
# publishes no numbers — see module docstring); keep as the one constant so
# the success, fallback, and partial-recovery paths can't drift.
_BASELINE_FLOOR_QPS = 10_000.0


def _dual_time(run_nosync, iters=3, iters_pipe=None):
    """Synced + pipelined timing pair shared by every measurement in
    this file (the headline protocol AND the TFLOPS probe), so the
    methodology cannot drift between them. Returns (iter_ms, dt_pipe):
    per-call wall times with a sync each (each pays the tunnel
    round-trip), and the per-call seconds of a back-to-back loop with
    ONE final sync — same-stream device order serializes the calls, so
    that is the sustained rate with queued work; methodology parity
    with the reference's loop_on_state fixture
    (cpp/bench/common/benchmark.hpp:113), which also syncs once per
    measurement loop. A failure inside the extra pipelined loop yields
    dt_pipe=inf rather than raising — the synced measurements are
    complete and valid, and a tunnel blip must not cost them. The
    caller is responsible for one warmup call first."""
    iter_ms = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(run_nosync())
        iter_ms.append((time.perf_counter() - t0) * 1e3)
    try:
        n = iters if iters_pipe is None else iters_pipe
        t0 = time.perf_counter()
        last = None
        for _ in range(n):
            last = run_nosync()
        jax.block_until_ready(last)
        dt_pipe = (time.perf_counter() - t0) / n
    except Exception:
        dt_pipe = float("inf")
    return iter_ms, dt_pipe


def _measure_protocol(run_nosync, nq, k, truth, mode, n_probes, refine,
                      smoke):
    """The one measurement protocol for every headline candidate (IVF
    ladder configs and the exact-BF racer), so the methodology cannot
    drift between them: warmup, the _dual_time synced+pipelined timing
    pair (see its docstring for the methodology), recall vs the exact
    truth, and the sub-floor plausibility gate. Appends the row to the
    partial file and returns it; a row flagged "suspect" must not be
    tallied.

    run_nosync must return a (distances, indices) pair without forcing a
    device sync. Exceptions from warmup or the synced loop propagate to
    the caller."""
    import sys

    res = run_nosync()  # compile + warmup
    jax.block_until_ready(res)
    iter_ms, dt_pipe = _dual_time(run_nosync)
    dt = sum(iter_ms) / len(iter_ms) / 1e3
    # plausibility floor for each clock independently: at the 1M-row
    # geometry no real config completes a batch faster than the relay
    # dispatch floor (~66 ms measured 2026-08-01); a sub-floor wall time
    # means the backend returned without doing the work (observed once
    # under client contention: np16 refined "measured" 1.7 ms/batch =
    # 2.2M qps, correct results, absurd clock). A bogus pipelined clock
    # alone must not void the row's valid synced measurement — fall back
    # to it; only a sub-floor synced clock marks the row suspect
    # (recorded for diagnosis, excluded from tally and partial
    # recovery). Smoke scale legitimately runs sub-10ms batches — no
    # gate there.
    min_ms = float(os.environ.get("RAFT_TPU_BENCH_MIN_BATCH_MS",
                                  "0" if smoke else "10"))
    pipe_ok = 1e3 * dt_pipe >= min_ms
    # headline QPS = pipelined throughput (never worse than the synced
    # per-batch rate, by at most one sync round-trip per batch);
    # per-batch latency stays recorded alongside
    qps = nq / (min(dt, dt_pipe) if pipe_ok else dt)
    got = np.asarray(res[1])
    recall = float(
        np.mean([len(set(got[j]) & set(truth[j])) / k for j in range(nq)])
    )
    rec = {
        "qps": qps, "recall": recall, "mode": mode,
        "n_probes": n_probes, "refine": refine,
        "qps_synced": round(nq / dt, 1),
        # per-batch wall times: best/worst spread is the serving-tail
        # signal (retrace/transfer hiccups show as a worst outlier the
        # mean QPS alone would hide)
        "batch_ms_best": round(min(iter_ms), 2),
        "batch_ms_worst": round(max(iter_ms), 2),
    }
    if not pipe_ok:
        rec["pipelined_suspect"] = True  # synced clock carried the row
    if 1e3 * dt < min_ms:
        rec["suspect"] = True
        print(f"suspect measurement excluded from tally: {rec}",
              file=sys.stderr, flush=True)
    _record_partial(rec)
    return rec


def _race_bf(best, best_floor, bf_rec, extra):
    """Race the exact-BF candidate against the IVF-PQ winner: the
    headline is the fastest gate-clearing config, algorithm recorded;
    the IVF-PQ number stays in the record either way (it is the
    north-star algo and the round-over-round comparison point — and it
    must survive a BF headline even when IVF only cleared the 0.80
    floor, because that regression is exactly what the round-over-round
    comparison needs to see). Mutates `extra`; returns the headline
    config (None if neither candidate cleared the primary gate)."""
    if bf_rec is None or bf_rec["recall"] < _RECALL_GATE:
        return best
    if best is not None and best["qps"] >= bf_rec["qps"]:
        # mode is recorded because the racer may be the bf16 variant,
        # whose sub-1.0 recall must not read as a broken exact engine
        extra["bf_best"] = {
            "qps": round(bf_rec["qps"], 1), "recall": bf_rec["recall"],
            "mode": bf_rec["mode"],
        }
        return best
    ivf_best = best if best is not None else best_floor
    if ivf_best is not None:
        extra["ivf_pq_best"] = {
            "qps": round(ivf_best["qps"], 1),
            "recall": round(ivf_best["recall"], 4),
            "mode": ivf_best["mode"],
            "n_probes": ivf_best["n_probes"],
            "refine": ivf_best["refine"],
        }
    if "ladder_validation" in extra:
        # overall_true_best must agree with the headline when the BF
        # racer wins (it raced every measured config)
        extra["ladder_validation"]["overall_true_best"] = bf_rec
    return bf_rec


def _headline_record(cfg: dict, gate: float, **extra) -> dict:
    """The one shape of the headline JSON record, shared by the success
    path and the partial-recovery path so the two can't drift."""
    rec = {
        "metric": _HEADLINE_METRIC,
        "value": round(cfg["qps"], 1),
        "unit": "qps",
        "vs_baseline": round(cfg["qps"] / _BASELINE_FLOOR_QPS, 3),
        "recall@10": round(cfg["recall"], 4),
        "recall_gate": gate,
        "algo": ("brute_force" if str(cfg.get("mode", "")).startswith("bf_")
                 else "ivf_pq"),
        "score_mode": cfg.get("mode"),
        "n_probes": cfg.get("n_probes"),
        "refine": cfg.get("refine"),
    }
    rec.update(extra)
    return rec


def _bank_ledger(rec: dict) -> None:
    """Append the session's headline record to the append-only bench
    ledger (BENCH_LEDGER.jsonl; see raft_tpu.obs.ledger) so the perf
    trajectory has one honest row per bench session — measured, partial,
    or failed (a 0.0 row is SIGNAL: the trajectory must show the outage,
    not hide it). Never raises."""
    try:
        from raft_tpu.obs import ledger
    except Exception:
        return
    here = os.path.dirname(os.path.abspath(__file__))
    ledger.bank_row(
        bench="bench_headline", row=rec, repo_dir=here, ledger_dir=here,
        smoke=True if rec.get("smoke") else None,
        partial=True if rec.get("partial") else None)


class DeterministicBenchFailure(RuntimeError):
    """Algorithm-level failure that would recur identically on retry
    (distinct from transient TPU/runtime errors, which DO deserve a fresh
    process — jax's runtime errors subclass RuntimeError, so the child
    must only short-circuit retries on this exact type)."""


def _pairwise_tflops_probe():
    """Measured pairwise-L2 TFLOPS/chip at a BASELINE-ish shape, reported
    beside the QPS headline (BASELINE.md: 'pairwise-distance TFLOPS/chip';
    v5e bf16 MXU peak = 197 TFLOP/s). bf16 inputs: the achievable-rate
    configuration (the f32 default runs HIGHEST precision, ~6 passes)."""
    from raft_tpu.distance import pairwise_distance
    from raft_tpu.distance.distance_types import DistanceType

    m = n = 16384
    d = 768
    if os.environ.get("RAFT_TPU_BENCH_SMOKE") == "1":
        m = n = 512
        d = 128
    kx, ky = jax.random.split(jax.random.PRNGKey(7))
    x = jax.random.uniform(kx, (m, d), jnp.bfloat16)
    y = jax.random.uniform(ky, (n, d), jnp.bfloat16)
    fn = lambda: pairwise_distance(x, y, metric=DistanceType.L2Expanded)
    jax.block_until_ready(fn())
    # the synced rate pays one tunnel round-trip per dispatch (~66 ms
    # measured 2026-08-01, vs ~4 ms device compute at this shape), so the
    # pipelined rate is the headline — see _dual_time. References are
    # dropped each iteration, so at most one (m, n) f32 output is live
    # on device at a time.
    iter_ms, dt_pipe = _dual_time(fn, iters=3, iters_pipe=6)
    dt_synced = sum(iter_ms) / len(iter_ms) / 1e3
    flop = 2.0 * m * n * d
    # plausibility: a clock implying more than the v5e bf16 MXU peak is
    # physically impossible — the backend returned without doing the
    # work (the 10 ms QPS floor does not transfer here: a legitimate
    # pipelined per-call time at this shape is ~4-8 ms). Fall back to
    # the synced clock; if that is also super-peak, publish no TFLOPS
    # rather than a bogus number.
    peak = 197.0
    dt = min(dt_synced, dt_pipe)
    if flop / dt / 1e12 > peak:
        dt = dt_synced
    if flop / dt / 1e12 > peak:
        return {"pairwise_l2_bf16_tflops_suspect": True}
    tflops = flop / dt / 1e12
    return {
        "pairwise_l2_bf16_tflops": round(tflops, 2),
        "pairwise_l2_bf16_tflops_synced": round(flop / dt_synced / 1e12, 2),
        "pairwise_mfu_vs_v5e_bf16_peak": round(tflops / peak, 4),
    }


_TFLOPS_MEMO = None


def _with_tflops(rec: dict) -> dict:
    global _TFLOPS_MEMO
    if _TFLOPS_MEMO is None:
        try:
            _TFLOPS_MEMO = _pairwise_tflops_probe()
        except Exception as e:
            import sys

            print(f"pairwise tflops probe failed: {e}", file=sys.stderr)
            _TFLOPS_MEMO = {}
    rec.update(_TFLOPS_MEMO)
    return rec


def _bench_ivf_pq():
    from raft_tpu.neighbors import brute_force, ivf_pq

    n, dim, nq, k = 1_000_000, 96, 4096, 10
    n_lists = 1024
    smoke = os.environ.get("RAFT_TPU_BENCH_SMOKE") == "1"
    if smoke:
        # CPU-rehearsable geometry: the ENTIRE ladder/tally/fault logic
        # runs end-to-end in ~a minute, so a first chip session never
        # executes this function's control flow for the first time
        n, dim, nq, k, n_lists = 20_000, 32, 256, 10, 64
    k1, k2, k3, k4, kc = jax.random.split(jax.random.PRNGKey(0), 5)
    # clustered data (blobs): representative of ANN corpora and gives the
    # coarse quantizer real structure, like the reference's make_blobs benches
    n_blobs = n_lists
    centers = jax.random.uniform(kc, (n_blobs, dim), jnp.float32, -5.0, 5.0)
    assign = jax.random.randint(k1, (n,), 0, n_blobs)
    dataset = centers[assign] + jax.random.normal(k2, (n, dim), jnp.float32)
    qassign = jax.random.randint(k3, (nq,), 0, n_blobs)
    queries = centers[qassign] + jax.random.normal(k4, (nq, dim), jnp.float32)
    jax.block_until_ready((dataset, queries))

    import sys

    t0 = time.perf_counter()
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=n_lists, pq_dim=dim // 2, kmeans_n_iters=10),
        dataset
    )
    jax.block_until_ready(index.codes)
    build_s = time.perf_counter() - t0
    # stage markers: the parent's timed-out-child heuristic reads these to
    # tell a slow-but-computing child from one hung in backend reconnect
    print(f"stage: build done in {build_s:.1f}s", file=sys.stderr, flush=True)

    # exact ground truth for the recall gate
    _, bt_i = brute_force.knn(dataset, queries, k=k)
    truth = np.asarray(bt_i)
    print("stage: ground truth done", file=sys.stderr, flush=True)

    # Independent truth validation: scored against its own output, the
    # BF racer's recall gate would be vacuous (a deterministic bug in
    # brute_force.knn corrupts truth and candidate identically — and
    # every IVF recall would be scored against the same wrong truth).
    # Cross-check numpy float64 exact kNN on a slice (16 queries vs a
    # 100k-row window; ~38 MB host pull, seconds through the tunnel).
    # The 0.95 agreement bar tolerates f32-vs-f64 near-tie flips at
    # rank k on random data; a real tile/boundary bug scores far lower.
    truth_ok = True
    try:
        ns = min(100_000, n)
        sub = np.asarray(dataset[:ns], np.float64)
        qs = np.asarray(queries[:16], np.float64)
        d2 = ((qs * qs).sum(1)[:, None] + (sub * sub).sum(1)[None, :]
              - 2.0 * qs @ sub.T)
        ref_i = np.argsort(d2, axis=1, kind="stable")[:, :k]
        _, sl_i = brute_force.knn(dataset[:ns], queries[:16], k=k)
        sl_i = np.asarray(sl_i)
        agree = float(np.mean([len(set(ref_i[j]) & set(sl_i[j])) / k
                               for j in range(ref_i.shape[0])]))
        truth_ok = agree >= 0.95
        if not truth_ok:
            print(f"stage: truth validation FAILED (numpy agreement "
                  f"{agree:.3f}) — BF candidate disabled, recalls "
                  f"suspect", file=sys.stderr, flush=True)
        else:
            print(f"stage: truth validated (numpy agreement {agree:.3f})",
                  file=sys.stderr, flush=True)
    except Exception as e:
        print(f"truth validation skipped: {e}", file=sys.stderr, flush=True)

    # Exact tiled brute force IS a headline candidate at this geometry:
    # the MXU turns the full 1M scan into one big bf16 matmul stream, and
    # the measured crossover where IVF-PQ starts winning sits above 1M×96
    # on this chip (TPU_PROFILE_RESULTS.json 2026-08-01: bf_tiled 17.4k
    # qps @ recall 1.0). The truth stage just compiled and warmed the
    # exact same call, so measuring it costs ~1 s. Recall vs the truth
    # array is 1.0 by construction (same exact algorithm); the gate check
    # stays so a future engine change that breaks exactness can't ride in.
    faulted = [False]  # device fault observed: backend is dead process-wide
    bf_rec = None
    try:
        if not truth_ok:
            raise RuntimeError(
                "truth validation failed; BF self-recall would be vacuous"
            )
        bf_rec = _measure_protocol(
            lambda: brute_force.knn(dataset, queries, k=k),
            nq, k, truth, "bf_tiled", None, False, smoke,
        )
        print(f"stage: bf_tiled candidate {bf_rec['qps']:.0f} qps "
              f"recall {bf_rec['recall']:.4f}", file=sys.stderr, flush=True)
        if bf_rec.get("suspect"):
            bf_rec = None  # recorded, but out of the headline race
        # bf16-compute variant: f32 inputs run the distance matmul at
        # Precision.HIGHEST (six bf16 MXU passes — see
        # distance/pairwise.py:_MATMUL_PRECISION); casting the operands
        # takes one pass with f32 accumulation. The ranking is then of
        # the bf16-rounded points, so the recall gate (scored against
        # the f32 truth, itself numpy-validated) decides whether the
        # speed is real at this geometry.
        ds16 = dataset.astype(jnp.bfloat16)
        qs16 = queries.astype(jnp.bfloat16)
        jax.block_until_ready((ds16, qs16))
        bf16_rec = _measure_protocol(
            lambda: brute_force.knn(ds16, qs16, k=k),
            nq, k, truth, "bf_tiled_bf16", None, False, smoke,
        )
        print(f"stage: bf_tiled_bf16 candidate {bf16_rec['qps']:.0f} qps "
              f"recall {bf16_rec['recall']:.4f}", file=sys.stderr,
              flush=True)
        if (not bf16_rec.get("suspect")
                and bf16_rec["recall"] >= _RECALL_GATE
                and (bf_rec is None or bf16_rec["qps"] > bf_rec["qps"])):
            bf_rec = bf16_rec
        # release the ~200 MB of bf16 copies before the IVF builds (the
        # most memory-hungry phase) — nothing below reads them
        del ds16, qs16, bf16_rec
    except Exception as e:
        print(f"bf_tiled candidate failed: {e}", file=sys.stderr, flush=True)
        from raft_tpu.core.config import is_device_fault

        if is_device_fault(e):
            # same classification as measure_config: a kernel fault
            # poisons this process's backend for good — don't burn the
            # ladder's configs discovering that one by one
            faulted[0] = True

    # NB: the package re-exports the refine *function* under this name
    # (from raft_tpu.neighbors import refine == the callable, not the module)
    from raft_tpu.neighbors import refine as refine_fn

    best = None  # first config clearing the 0.95 primary gate
    best_floor = None  # best seen clearing only the 0.80 floor
    # Full-ladder validation mode (RAFT_TPU_BENCH_FULL_LADDER=1): measure
    # EVERY config instead of early-exiting, then report the true QPS
    # winner plus a ladder_validation record comparing it against the
    # early-exit choice — the on-chip check of the ordering assumption
    # below. Run it cache-warm (the queue runs it right after the normal
    # bench) so the extra configs are compute-only.
    full_ladder = os.environ.get("RAFT_TPU_BENCH_FULL_LADDER") == "1"
    gated_all = []  # every gate-clearing config (full-ladder mode)
    # ladder of (n_probes, refine?) configs: refined configs run the PQ
    # search for a 4k shortlist then re-rank exactly against the original
    # vectors (the reference's high-recall pipeline, neighbors/refine.cuh) —
    # fewer probes at the same recall gate = higher QPS. The ladder is
    # ordered by expected DECREASING QPS (probes only go up; refined
    # configs lead because pure-PQ recall plateaus below the 0.95 gate),
    # so the first config that clears the gate is the winner — stopping
    # there keeps chip time bounded on flaky-tunnel days.
    configs = [
        (8, True), (16, True), (32, True), (64, True),
        (32, False), (64, False),
    ]
    def measure_config(idx, n_probes, use_refine, mode, tag=""):
        params = ivf_pq.SearchParams(n_probes=n_probes, score_mode=mode)

        def run_nosync():
            if use_refine:
                _, cand = ivf_pq.search(params, idx, queries, 4 * k)
                return refine_fn(dataset, queries, cand, k)
            return ivf_pq.search(params, idx, queries, k)

        try:
            rec = _measure_protocol(
                run_nosync, nq, k, truth, tag + mode, n_probes, use_refine,
                smoke,
            )
        except Exception as e:
            import sys
            import traceback

            print(f"score_mode={mode} n_probes={n_probes} failed:", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            from raft_tpu.core.config import is_device_fault

            if is_device_fault(e):
                # a TPU kernel fault poisons this process's backend for
                # good; every further attempt fails identically — stop
                # burning configs and report from what's banked
                faulted[0] = True
            return None
        return None if rec.get("suspect") else rec

    def tally(rec):
        nonlocal best, best_floor
        if rec["recall"] >= _RECALL_GATE:
            gated_all.append(rec)
            if best is None:
                best = rec
            return True
        if rec["recall"] >= _RECALL_FLOOR and (
            best_floor is None or rec["qps"] > best_floor["qps"]
        ):
            best_floor = rec
        return False

    # engine candidates: lut is EXCLUDED — its gather kernel-faulted the
    # device at this geometry on 2026-08-01 (one fault kills every later
    # config in the process; recon8 covers the same recall at lower QPS)
    for n_probes, use_refine in configs:
        if faulted[0] or (best is not None and not full_ladder):
            break
        for mode in ("recon8_list", "recon8"):
            rec = measure_config(index, n_probes, use_refine, mode)
            if faulted[0]:
                break
            # the first engine that passes the primary gate is enough for
            # this config; skip the slower engines
            if rec is not None and tally(rec) and not full_ladder:
                break

    # Unrefined variants (VERDICT r2 #6 + r3 #6): extra index builds cost
    # real chip minutes, so they run only when the refined ladder failed
    # the gate — or in full-ladder validation mode, where their
    # QPS-vs-refined comparison is the point. Ordered by expected
    # decreasing QPS:
    #   mid  (pq_dim = 2*dim/3): ~2/3 the scan bytes of fine; the test
    #        geometry's dim/2 analogue measures 0.894 unrefined, so this
    #        rung targets the 0.80 floor with a shot at the 0.95 gate —
    #        the headline no longer depends solely on refine-or-fine;
    #   fine (pq_dim == dim): 8 rotated bits per input dim, 0.976
    #        unrefined at the test geometry — the high-fidelity fallback.
    variant_build_s = {}
    for tag, vdim in (("mid_", (2 * dim + 2) // 3), ("fine_", dim)):
        if faulted[0] or (best is not None and not full_ladder):
            break
        import sys

        t0 = time.perf_counter()
        vidx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=n_lists, pq_dim=vdim, kmeans_n_iters=10),
            dataset,
        )
        jax.block_until_ready(vidx.codes)
        variant_build_s[tag] = time.perf_counter() - t0
        print(f"stage: {tag}build (pq_dim={vdim}) done in "
              f"{variant_build_s[tag]:.1f}s", file=sys.stderr, flush=True)
        for n_probes in (32, 64):
            rec = measure_config(vidx, n_probes, False, "recon8_list", tag=tag)
            if faulted[0]:
                break
            if rec is not None and tally(rec) and not full_ladder:
                break

    extra = {}
    if not truth_ok:
        # every recall in this record was scored against a truth array
        # that disagreed with the independent numpy check
        extra["truth_suspect"] = True
    if full_ladder and gated_all:
        # ordering validation covers only the `configs` ladder (mid_/fine_
        # records come from different index builds — no reordering of
        # `configs` could ever select one, so they must not fail it)
        ladder_gated = [r for r in gated_all
                        if not r["mode"].startswith(("mid_", "fine_"))]
        ladder_best = (max(ladder_gated, key=lambda r: r["qps"])
                       if ladder_gated else None)
        true_best = max(gated_all, key=lambda r: r["qps"])
        extra["ladder_validation"] = {
            "early_exit_choice": best,
            "ladder_true_best": ladder_best,
            # ordering_ok: the early-exit choice is the ladder's true
            # winner (within noise) — if False, reorder `configs`
            "ordering_ok": ladder_best is None or best is ladder_best
            or best["qps"] >= 0.95 * ladder_best["qps"],
            "overall_true_best": true_best,
        }
        best = true_best  # report the real winner when we measured them all
    gate = _RECALL_GATE
    best = _race_bf(best, best_floor, bf_rec, extra)
    if best is None and best_floor is not None:
        best, gate = best_floor, _RECALL_FLOOR
    if best is None:
        if faulted[0]:
            # a fresh process recovers the chip, so a fault before any
            # config banked deserves the parent's transient-error retry —
            # NOT the deterministic short-circuit
            raise RuntimeError("device fault before any config banked")
        raise DeterministicBenchFailure("no scoring mode met the recall gate")
    if faulted[0]:
        # mark truncated coverage: a fault cut the ladder short, so
        # downstream readers (ladder_validation consumers, next-round
        # tuning) must not treat this record as a completed sweep
        extra["faulted"] = True
        if "ladder_validation" in extra:
            extra["ladder_validation"]["ordering_ok"] = None
    # build_s describes the index that produced the headline config;
    # exact brute force builds nothing, so a BF headline reports 0 with
    # the IVF-PQ build time preserved alongside
    chosen_build_s = build_s
    for tag, vbs in variant_build_s.items():
        if best["mode"].startswith(tag):
            chosen_build_s = vbs
        extra[f"{tag}build_s"] = round(vbs, 1)
    if str(best.get("mode", "")).startswith("bf_"):
        extra["ivf_pq_build_s"] = round(build_s, 1)
        chosen_build_s = 0.0
    extra["build_s"] = round(chosen_build_s, 1)
    if smoke:
        # a rehearsal record must never pass for a chip measurement (the
        # metric name and vs_baseline otherwise look identical)
        extra["smoke"] = True
    return _with_tflops(_headline_record(best, gate, **extra))


def _bench_bf_fallback():
    from raft_tpu.neighbors.brute_force import _bf_knn_impl
    from raft_tpu.distance.distance_types import DistanceType

    n, dim, k, nq = 1_000_000, 128, 64, 8192
    key = jax.random.PRNGKey(0)
    kd, kq = jax.random.split(key)
    dataset = jax.random.uniform(kd, (n, dim), jnp.float32)
    queries = jax.random.uniform(kq, (nq, dim), jnp.float32)
    jax.block_until_ready((dataset, queries))

    def run():
        d, i = _bf_knn_impl(dataset, queries, k, DistanceType.L2Expanded)
        jax.block_until_ready((d, i))

    run()
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        run()
    dt = (time.perf_counter() - t0) / iters
    qps = nq / dt
    return _with_tflops({
        "metric": "bf_knn_qps_1Mx128_k64",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / _BASELINE_FLOOR_QPS, 3),
    })


def _axon_relay_down() -> bool:
    """Shared side-effect-free dead-transport check (see
    raft_tpu.core.config.relay_transport_down); falls back to 'up' if
    the library import itself fails so the normal probe still decides."""
    try:
        from raft_tpu.core.config import relay_transport_down

        return relay_transport_down()
    except Exception:
        return False


def _wait_for_backend(max_wait_s: float = 1800.0) -> bool:
    """Check the TPU backend initializes and answers a trivial op; returns
    False if it doesn't within max_wait_s.

    The tunneled chip is single-client, and killing a process mid-init can
    leave the remote claim held for hours (the round-1 outage). So: probe
    in throwaway subprocesses; clean fast failures (transient UNAVAILABLE
    while a previous holder releases) are retried — retrying kills nothing
    — and the only kill ever issued is once, at the overall deadline,
    which exceeds any realistic cold init (a wedged backend fails on its
    own at ~25 min, well inside it). A failed init in the subprocess also
    keeps it from poisoning any real process's backend."""
    import os
    import subprocess
    import sys

    # the probe child needs the same explicit CPU pin as the top of this
    # file: the env var alone is overridden by the image's sitecustomize
    # force-appending the axon platform, and a CPU-intent probe that
    # dials the tunneled chip contends with whoever holds the claim
    probe = (
        "import os, jax;"
        "os.environ.get('JAX_PLATFORMS') == 'cpu' and "
        "jax.config.update('jax_platforms', 'cpu');"
        "import jax.numpy as jnp;"
        "jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))"
    )
    deadline = time.monotonic() + max_wait_s
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        if _axon_relay_down():
            # give a restarting relay one short grace period, then bail:
            # with the transport gone the probe child would hang its full
            # leash dialing dead ports
            time.sleep(30.0)
            if _axon_relay_down():
                print("relay transport down; backend unreachable", file=sys.stderr)
                return False
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                timeout=remaining,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if r.returncode == 0:
                return True
            # clean non-zero exit (transient UNAVAILABLE while a previous
            # holder releases): retrying kills nothing — keep waiting
        except subprocess.TimeoutExpired:
            break  # the only kill: once, at the overall deadline
        time.sleep(min(20.0, max(0.0, deadline - time.monotonic())))
    print("backend probe never came up; proceeding anyway", file=sys.stderr)
    return False


def _run_child(which: str, timeout_s: float):
    """Run one bench attempt in a fresh interpreter and parse its JSON line.

    A TPU worker crash mid-run poisons the crashing process's backend for
    good — only a new process recovers the chip — so each attempt gets its
    own interpreter."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, RAFT_TPU_BENCH_CHILD=which)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        print(f"bench child {which!r} timed out", file=sys.stderr)
        err = e.stderr or b""
        err = err if isinstance(err, str) else err.decode(errors="replace")
        sys.stderr.write(err[-8000:])
        # a child can hang in backend teardown AFTER printing its record;
        # recover it from the partial stdout rather than retrying
        out = e.stdout or b""
        out = out if isinstance(out, str) else out.decode(errors="replace")
        # "progressed" distinguishes a slow-but-computing child from one
        # hung in backend init/reconnect: the latter produces no stdout and
        # no per-config stderr markers, and deserves short leashes after
        progressed = (
            bool(out.strip()) or ("stage:" in err)
            or ("score_mode=" in err) or ("tflops" in err)
        )
        return _parse_child_record(out), progressed
    sys.stderr.write(r.stderr[-8000:])
    return _parse_child_record(r.stdout), True


def _parse_child_record(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and ("metric" in rec or "deterministic_failure" in rec):
            return rec
    return None


def main():
    import os
    import sys

    which = os.environ.get("RAFT_TPU_BENCH_CHILD")
    if which:  # child: one attempt, print one JSON line, no recursion
        # The env-intent cache gate stays off when JAX_PLATFORMS is unset
        # or a "tpu,cpu" fallback list (the common plain-TPU-host state).
        # The child is about to claim the backend anyway, so resolve the
        # ambiguity from the actual backend: not-cpu => enable the cache.
        try:
            if jax.config.jax_compilation_cache_dir is None and (
                jax.default_backend() != "cpu"
            ):
                from raft_tpu.core.config import enable_compilation_cache

                enable_compilation_cache()
        except Exception:
            pass
        try:
            rec = _bench_ivf_pq() if which == "ivf" else _bench_bf_fallback()
        except DeterministicBenchFailure as e:
            # deterministic algorithm-level failure (e.g. recall gate):
            # rerunning the same attempt would fail identically, so tell
            # the parent not to burn another full attempt on it.
            # flush: the record must reach the pipe even if interpreter
            # teardown hangs afterwards (the timeout-recovery path reads it)
            print(json.dumps({"deterministic_failure": str(e)}), flush=True)
            raise
        print(json.dumps(rec), flush=True)
        return
    # fresh partial file per bench session so a previous round's entries
    # can't masquerade as this run's measurements; if the reset fails, the
    # stale file must also be unusable for final-record recovery.
    # KEEP_PARTIAL=1 (the queue's end-of-session tuned-keys re-run): the
    # re-run belongs to the same session — truncating here would erase
    # every gate-clearing row the session banked if the relay dies
    # before this run lands one.
    partial_reset_ok = True
    if os.environ.get("RAFT_TPU_BENCH_KEEP_PARTIAL") != "1":
        try:
            open(_PARTIAL_PATH, "w").close()
        except OSError:
            partial_reset_ok = False
    rec = None
    attempts = [("ivf", 3600), ("ivf", 3600), ("bf", 1200)]
    # probe up front and reuse the verdict: a dead backend takes the full
    # ~30 min leash to answer, so probing before EVERY attempt would burn
    # hours flailing at a wedged chip. One re-probe is allowed after a
    # failed short-leashed child, so a chip released mid-run gets its
    # full leash back on the next attempt.
    backend_up = _wait_for_backend()
    reprobes_left = 1
    i = 0
    while i < len(attempts):
        attempt_kind, timeout_s = attempts[i]
        if not backend_up:
            # chip never answered the probe: a child would just block in
            # backend init — give it a short leash instead of a full hour
            timeout_s = min(timeout_s, 600)
            if _axon_relay_down():
                # transport structurally dead: the child exists only to
                # catch a relay restart, so keep the leash minimal
                timeout_s = 120
        try:
            partial_size_before = os.path.getsize(_PARTIAL_PATH)
        except OSError:
            partial_size_before = 0
        rec, progressed = _run_child(attempt_kind, timeout_s)
        try:
            # a healthy-but-slow child is silent on stdout/stderr while it
            # works through passing configs, but it appends each measured
            # config here — file growth is the reliable progress signal
            if os.path.getsize(_PARTIAL_PATH) > partial_size_before:
                progressed = True
        except OSError:
            pass
        if rec is None and not progressed:
            # the child hung without doing any work — a flapping/lost
            # backend mid-session; stop burning full-hour leashes on it
            backend_up = False
        if rec is None and not backend_up and reprobes_left > 0 and i + 1 < len(attempts):
            # reprobe only when another attempt remains to use the verdict
            reprobes_left -= 1
            backend_up = _wait_for_backend()
        if rec is not None and "metric" in rec:
            break
        if rec is not None and "deterministic_failure" in rec:
            # skip identical retries of an algorithmic failure; jump to the
            # next different attempt kind
            print(
                f"bench attempt {attempt_kind!r} failed deterministically "
                f"({rec['deterministic_failure']}); skipping identical retries",
                file=sys.stderr,
            )
            while i + 1 < len(attempts) and attempts[i + 1][0] == attempt_kind:
                i += 1
        elif rec is None and i + 1 < len(attempts):
            print(f"bench attempt {attempt_kind!r} failed; retrying", file=sys.stderr)
        rec = None
        i += 1
        if i < len(attempts):
            time.sleep(30)
    if rec is not None and "metric" in rec and rec.get("value", 0) > 0 \
            and not rec.get("smoke"):
        # bank the real headline durably (see _LAST_GOOD_PATH rationale);
        # atomic replace — a crash mid-write must not destroy the
        # previously banked record this file exists to preserve
        try:
            tmp = _LAST_GOOD_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dict(rec, measured_unix=round(time.time(), 1)), f)
                f.write("\n")
            os.replace(tmp, _LAST_GOOD_PATH)
        except OSError:
            pass
    if rec is None:
        partial = _best_partial() if partial_reset_ok else None
        if partial is not None:
            # a killed/timed-out child still measured something: report the
            # best persisted ladder entry rather than zero, marked partial;
            # recall_gate records which gate it actually cleared, same as
            # the success path, so a floor-only number can't pass for a
            # recall95 result across rounds
            gate = _RECALL_GATE if partial["recall"] >= _RECALL_GATE else _RECALL_FLOOR
            rec = _headline_record(partial, gate, partial=True)
        else:
            # Total failure reports 0.0 + error — the last-good RECYCLING
            # path that used to live here (re-reporting BENCH_LAST_GOOD
            # within 72 h, marked "recovered_from") is deliberately gone:
            # it produced BENCH_r04/r05, two rounds of the same 5,315 QPS
            # row masquerading as trajectory while every real measurement
            # failed. A dead transport must surface as a dead transport;
            # fresh fallback numbers come from the survivable bench path
            # (bench/run_all.py + ensure_survivable_backend), not from
            # re-banking old ones. _LAST_GOOD_PATH remains write-only
            # provenance of the last real chip headline.
            rec = {
                "metric": _HEADLINE_METRIC,
                "value": 0.0,
                "unit": "qps",
                "vs_baseline": 0.0,
                "error": "all bench attempts failed",
            }
    _bank_ledger(rec)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
