"""Refinement tests (mirrors cpp/test/neighbors/refine.cu)."""

import numpy as np
import pytest
from scipy.spatial import distance as spdist

from raft_tpu.neighbors import refine, brute_force


def test_refine_recovers_exact_topk(rng):
    data = rng.random((2000, 24), dtype=np.float32)
    q = rng.random((30, 24), dtype=np.float32)
    # candidates: exact top-20 (superset of top-5) plus noise ordering
    _, cand = brute_force.knn(data, q, 20)
    d, i = refine(data, q, np.asarray(cand), 5)
    _, want = brute_force.knn(data, q, 5)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(want))
    full = spdist.cdist(q, data, "sqeuclidean")
    np.testing.assert_allclose(
        np.asarray(d), np.sort(full, axis=1)[:, :5], rtol=2e-3, atol=2e-3
    )


def test_refine_handles_invalid_ids(rng):
    data = rng.random((100, 8), dtype=np.float32)
    q = rng.random((4, 8), dtype=np.float32)
    cand = np.full((4, 10), -1, np.int32)
    cand[:, :3] = np.array([[0, 1, 2]] * 4)
    d, i = refine(data, q, cand, 3)
    assert set(np.asarray(i).ravel().tolist()) <= {0, 1, 2}


def test_refine_inner_product(rng):
    data = rng.random((500, 16), dtype=np.float32)
    q = rng.random((10, 16), dtype=np.float32)
    _, cand = brute_force.knn(data, q, 30, metric="inner_product")
    d, i = refine(data, q, np.asarray(cand), 5, metric="inner_product")
    _, want = brute_force.knn(data, q, 5, metric="inner_product")
    np.testing.assert_array_equal(np.asarray(i), np.asarray(want))


def test_refine_validation(rng):
    data = rng.random((100, 8), dtype=np.float32)
    q = rng.random((4, 8), dtype=np.float32)
    with pytest.raises(ValueError):
        refine(data, q, np.zeros((4, 3), np.int32), 5)  # k > n_candidates
    with pytest.raises(ValueError):
        refine(data, q, np.zeros((5, 3), np.int32), 2)  # row mismatch
