"""Refinement tests (mirrors cpp/test/neighbors/refine.cu)."""

import numpy as np
import pytest
from scipy.spatial import distance as spdist

from raft_tpu.neighbors import refine, brute_force


def test_refine_recovers_exact_topk(rng):
    data = rng.random((2000, 24), dtype=np.float32)
    q = rng.random((30, 24), dtype=np.float32)
    # candidates: exact top-20 (superset of top-5) plus noise ordering
    _, cand = brute_force.knn(data, q, 20)
    d, i = refine(data, q, np.asarray(cand), 5)
    _, want = brute_force.knn(data, q, 5)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(want))
    full = spdist.cdist(q, data, "sqeuclidean")
    np.testing.assert_allclose(
        np.asarray(d), np.sort(full, axis=1)[:, :5], rtol=2e-3, atol=2e-3
    )


def test_refine_host_matches_device(rng):
    """Host-dataset refine (detail/refine.cuh host overload): identical
    results to the device path, dataset never uploaded wholesale."""
    from raft_tpu.neighbors.refine import refine_host

    data = rng.random((2000, 24), dtype=np.float32)
    q = rng.random((30, 24), dtype=np.float32)
    _, cand = brute_force.knn(data, q, 20)
    cand = np.asarray(cand)
    dv, iv = refine(data, q, cand, 5)
    dh, ih = refine_host(data, q, cand, 5)
    np.testing.assert_array_equal(np.asarray(ih), np.asarray(iv))
    np.testing.assert_allclose(np.asarray(dh), np.asarray(dv), rtol=1e-5, atol=1e-5)
    # invalid ids skipped identically
    cand2 = cand.copy()
    cand2[:, 10:] = -1
    dh2, ih2 = refine_host(data, q, cand2, 5)
    assert np.asarray(ih2).min() >= 0
    # IP metric
    dhi, ihi = refine_host(data, q, cand, 5, metric="inner_product")
    dvi, ivi = refine(data, q, cand, 5, metric="inner_product")
    np.testing.assert_array_equal(np.asarray(ihi), np.asarray(ivi))


def test_streamed_build_path(rng):
    """The 10M bench's exact pipeline at CPU scale: train-only build ->
    extend_batched streaming -> search + host refine, recall-gated."""
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.batch_loader import extend_batched
    from raft_tpu.neighbors.refine import refine_host

    data = rng.random((30_000, 32), dtype=np.float32)
    q = rng.random((64, 32), dtype=np.float32)
    params = ivf_pq.IndexParams(
        n_lists=32, pq_dim=16, kmeans_n_iters=6, add_data_on_build=False
    )
    index = ivf_pq.build(params, data[:8_000])
    index = extend_batched(ivf_pq.extend, index, data, batch_size=7_000)
    assert index.size == len(data)
    _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), index, q, 40)
    d, i = refine_host(data, q, np.asarray(cand), 10)
    _, truth = brute_force.knn(data, q, 10)
    truth, got = np.asarray(truth), np.asarray(i)
    rec = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(got, truth)) / truth.size
    assert rec >= 0.7, rec


def test_refine_handles_invalid_ids(rng):
    data = rng.random((100, 8), dtype=np.float32)
    q = rng.random((4, 8), dtype=np.float32)
    cand = np.full((4, 10), -1, np.int32)
    cand[:, :3] = np.array([[0, 1, 2]] * 4)
    d, i = refine(data, q, cand, 3)
    assert set(np.asarray(i).ravel().tolist()) <= {0, 1, 2}


def test_refine_inner_product(rng):
    data = rng.random((500, 16), dtype=np.float32)
    q = rng.random((10, 16), dtype=np.float32)
    _, cand = brute_force.knn(data, q, 30, metric="inner_product")
    d, i = refine(data, q, np.asarray(cand), 5, metric="inner_product")
    _, want = brute_force.knn(data, q, 5, metric="inner_product")
    np.testing.assert_array_equal(np.asarray(i), np.asarray(want))


def test_refine_validation(rng):
    data = rng.random((100, 8), dtype=np.float32)
    q = rng.random((4, 8), dtype=np.float32)
    with pytest.raises(ValueError):
        refine(data, q, np.zeros((4, 3), np.int32), 5)  # k > n_candidates
    with pytest.raises(ValueError):
        refine(data, q, np.zeros((5, 3), np.int32), 2)  # row mismatch
